"""Inference-graph optimization: BatchNorm folding (r5 MFU work).

Eval-mode BatchNorm is a per-channel affine ``y = x·a + b`` with
``a = scale/√(var+ε)``, ``b = bias − mean·a`` — EXACTLY absorbable into
a preceding Conv2D/Dense: ``conv(x; K)·a + b = conv(x; K·a) + b``.
Folding removes every BN's elementwise pass (and its params/state) from
the serving graph; the training graph is untouched (training BN uses
batch statistics, where folding is not exact — reference point:
standard deployment practice, e.g. TF's fold_batch_norms).

    model2, variables2 = fold_batchnorm(model, variables)
    y, _ = model2.apply(variables2, x)            # == model.apply eval

Handles Sequential stacks recursively, including the ``Residual``
combinator's inner/shortcut branches (the ResNet zoo's conv-bn shape).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .layers import (BatchNorm, Conv2D, Dense, Embedding, Layer,
                     Residual, Sequential, register)
from .model import Model

__all__ = ["fold_batchnorm", "zigzag_wrap", "ZigzagStripe"]


def _affine(bn: BatchNorm, bn_params, bn_state):
    inv = 1.0 / np.sqrt(np.asarray(bn_state["var"], np.float64)
                        + bn.epsilon)
    a = np.asarray(bn_params["scale"], np.float64) * inv
    b = np.asarray(bn_params["bias"], np.float64) \
        - np.asarray(bn_state["mean"], np.float64) * a
    return a, b


def _fold_into(lyr, p, a, b):
    """Return (new_layer, new_params) with the BN affine absorbed."""
    if isinstance(lyr, Conv2D):
        new = Conv2D(lyr.filters, lyr.kernel_size, lyr.strides,
                     lyr.padding, lyr.activation, use_bias=True)
        kernel = np.asarray(p["kernel"], np.float64) * a  # (...,I,O)·(O,)
        bias = np.asarray(p.get("bias", 0.0), np.float64) * a + b
    else:  # Dense
        new = Dense(lyr.units, lyr.activation, use_bias=True)
        kernel = np.asarray(p["kernel"], np.float64) * a
        bias = np.asarray(p.get("bias", 0.0), np.float64) * a + b
    return new, {"kernel": jnp.asarray(kernel, jnp.float32),
                 "bias": jnp.asarray(bias, jnp.float32)}


def _foldable(lyr):
    # the affine must commute with everything between the kernel op and
    # the BN: fold only the DIRECTLY adjacent pair, and only when the
    # kernel op applies no nonlinearity of its own
    return isinstance(lyr, (Conv2D, Dense)) and lyr.activation is None


def _fold_layer(lyr, p, s):
    """Recursive single-layer fold; returns (layer, params, state)."""
    if isinstance(lyr, Sequential):
        return _fold_sequential(lyr.layers, p, s)
    if isinstance(lyr, Residual):
        inner, pi, si = _fold_layer(lyr.inner, p["inner"], s["inner"])
        params = {"inner": pi}
        state = {"inner": si}
        shortcut = None
        if lyr.shortcut is not None:
            shortcut, ps, ss = _fold_layer(lyr.shortcut, p["shortcut"],
                                           s["shortcut"])
            params["shortcut"] = ps
            state["shortcut"] = ss
        return Residual(inner, shortcut, lyr.activation), params, state
    return lyr, p, s


def _fold_sequential(layers, params, state):
    out_l, out_p, out_s = [], [], []
    i = 0
    while i < len(layers):
        lyr, p, s = _fold_layer(layers[i], params[i], state[i])
        nxt = layers[i + 1] if i + 1 < len(layers) else None
        if _foldable(lyr) and isinstance(nxt, BatchNorm):
            a, b = _affine(nxt, params[i + 1], state[i + 1])
            lyr, p = _fold_into(lyr, p, a, b)
            s = {}
            i += 2  # consume the BN
        else:
            i += 1
        out_l.append(lyr)
        out_p.append(p)
        out_s.append(s)
    return Sequential(out_l), out_p, out_s


@register
class ZigzagStripe(Layer):
    """Re-stripe the token axis into the P-way zigzag ring layout
    (device d's shard = chunks (d, 2P−1−d)); ``inverse=True`` restores
    natural order.  Parameter-free and shape-preserving — the once-per-
    batch boundary layers :func:`zigzag_wrap` inserts."""

    #: permutes the TIME axis: the decode protocol must not apply it
    #: pointwise to per-token input (generation falls back to the
    #: full-context recompute path, which runs the whole wrapped forward)
    time_mixing = True

    def __init__(self, p_size: int, inverse: bool = False):
        self.p_size = int(p_size)
        self.inverse = bool(inverse)

    def init(self, rng, in_shape):
        return {}, {}, tuple(in_shape)

    def out_shape(self, in_shape):
        return tuple(in_shape)

    def apply(self, params, state, x, *, train=False, rng=None):
        from ..parallel.ring import zigzag_shuffle, zigzag_unshuffle
        f = zigzag_unshuffle if self.inverse else zigzag_shuffle
        return f(x, self.p_size), state

    def get_config(self):
        return {"p_size": self.p_size, "inverse": self.inverse}


def _clone_for_wrap(layer, mha_cls):
    """Shallow-copy ``layer`` iff it is (or contains) a ``mha_cls``
    attention layer, rebuilding the container spine (``layers`` /
    ``inner`` / ``shortcut``) down to fresh attention objects; everything
    attention-free is shared.  Hyperparameters copy over, params stay
    positional — the clones run the original variables unchanged."""
    import copy
    if isinstance(layer, mha_cls):
        return copy.copy(layer)
    if not any(isinstance(s, mha_cls) for s in layer.iter_layers()):
        return layer
    clone = copy.copy(layer)
    if getattr(clone, "layers", None):
        clone.layers = [_clone_for_wrap(l, mha_cls) for l in clone.layers]
    for attr in ("inner", "shortcut"):
        sub = getattr(clone, attr, None)
        if isinstance(sub, Layer):
            setattr(clone, attr, _clone_for_wrap(sub, mha_cls))
    return clone


class _ZigzagWrappedModel(Model):
    """A zigzag-wrapped model is a RUNTIME artifact: its mesh attachment
    and ``ring_pre_shuffled`` flags are trace-time layer attributes that
    do not serialize — a config round-trip would restore the stripe
    boundary layers but run DENSE attention over the permuted order
    (silently wrong).  Refuse serialization; serialize the ORIGINAL
    model and re-wrap after loading."""

    def config(self) -> dict:
        raise ValueError(
            "cannot serialize a zigzag_wrap'ed model (its mesh "
            "attachment is runtime-only and a reload would compute "
            "wrong attention over the striped order); serialize the "
            "original model and re-apply zigzag_wrap after loading")


def zigzag_wrap(model: Model, mesh, *, axis: str = "sp",
                batch_axis=None, impl=None):
    """Sequence-parallel CAUSAL training with the zigzag stripe paid
    ONCE per batch (r5).

    Attaching a mesh to each ``MultiHeadAttention`` runs the balanced
    zigzag ring, but every attention call then re-stripes its inputs and
    un-stripes its output — 2 gathers per layer per step.  This wrapper
    returns a NEW model that stripes the token axis once after the
    position-dependent embedding layers and un-stripes once at the
    output head, with every attention layer told its activations are
    already zigzag (``ring_pre_shuffled``): between the two boundary
    layers all non-attention compute is token-pointwise, so it runs
    identically on the striped order.

    Returns ``(wrapped_model, insert_positions)`` — the positions let a
    caller map variables between the two stacks (the wrapped Sequential
    has two extra parameter-free layers).  Train the wrapped model from
    scratch or adapt existing variables by inserting empty ``{}``
    param/state entries at those positions.

    The attention layers in the wrapped stack are SHALLOW COPIES of the
    original's (ADVICE r5): the mesh attachment and ``ring_pre_shuffled``
    land on the copies only, so the ORIGINAL model stays runnable (dense
    attention, natural token order) while the wrap is active.  Params are
    positional — both stacks accept the same variables (modulo the two
    empty boundary inserts).  Non-attention layers are shared, as are
    container layers without nested attention.
    """
    from ..ops.attention import MultiHeadAttention, PositionalEmbedding
    if not isinstance(model.layer, Sequential):
        raise ValueError("zigzag_wrap needs a Sequential model")
    p = mesh.shape[axis]
    t = model.input_shape[0]
    if t % (2 * p):
        raise ValueError(f"sequence length {t} must be divisible by "
                         f"2×|{axis}| ({2 * p}) for the zigzag stripe")
    layers = list(model.layer.layers)
    mhas = [l for l in model.iter_layers()
            if isinstance(l, MultiHeadAttention)]
    if not mhas:
        raise ValueError("zigzag_wrap needs attention layers")
    for l in mhas:
        if not l.causal:
            raise ValueError("zigzag_wrap is for CAUSAL attention stacks "
                             "(non-causal rings don't use the stripe)")
        if l.rope:
            raise ValueError("rope positions are applied inside the "
                             "attention layer from PHYSICAL indices; "
                             "zigzag_wrap supports learned positional "
                             "embeddings only")
    # stripe boundary: after the last position-SENSITIVE pointwise layer
    # (token/positional embeddings, NESTED occurrences included — a
    # positional table applied to striped activations would silently
    # corrupt the model); everything after must be attention or
    # token-pointwise
    emb_types = (Embedding, PositionalEmbedding)
    idx = [i for i, l in enumerate(layers)
           if any(isinstance(sub, emb_types) for sub in l.iter_layers())]
    start = (max(idx) + 1) if idx else 0
    for lyr in layers[:start]:
        if any(isinstance(sub, MultiHeadAttention)
               for sub in lyr.iter_layers()):
            raise ValueError(
                "attention appears before (or interleaved with) the "
                "embedding layers: the stripe boundary cannot sit after "
                "the embeddings without leaving that attention on "
                "un-striped input; zigzag_wrap cannot wrap this stack")
    for lyr in layers[start:]:
        for sub in lyr.iter_layers():
            if getattr(sub, "time_mixing", False) and \
                    not isinstance(sub, MultiHeadAttention):
                raise ValueError(
                    f"{type(sub).__name__} mixes the time axis and is "
                    f"not attention: it would read the striped order; "
                    f"zigzag_wrap cannot wrap this stack")
    if impl == "ulysses" or (impl is None and
                             any(l.ring_impl == "ulysses" for l in mhas)):
        raise ValueError("ulysses is the all-to-all formulation — "
                         "already balanced, no stripe to amortize; "
                         "zigzag_wrap is for the ring impls (unset "
                         "layer.ring_impl or pass impl='flash'/"
                         "'blockwise')")
    # clone the stack so the runtime placement below mutates COPIES; the
    # original model keeps running dense attention (ADVICE r5)
    layers = [_clone_for_wrap(l, MultiHeadAttention) for l in layers]
    for l in (s for lyr in layers for s in lyr.iter_layers()
              if isinstance(s, MultiHeadAttention)):
        l.mesh = mesh
        l.ring_axis = axis
        if batch_axis is not None:  # preserve an existing dp attachment
            l.batch_axis = batch_axis
        if impl is not None:
            l.ring_impl = impl
        l.ring_pre_shuffled = True
    wrapped = layers[:start] + [ZigzagStripe(p)] + layers[start:] \
        + [ZigzagStripe(p, inverse=True)]
    m2 = _ZigzagWrappedModel(Sequential(wrapped),
                             input_shape=model.input_shape,
                             name=model.name + "_zigzag")
    return m2, (start, len(wrapped) - 1)


def fold_batchnorm(model: Model, variables: dict):
    """(model, variables) → (folded_model, folded_variables); exact for
    EVAL-mode forward passes.  Raises if the top layer is not
    Sequential."""
    if not isinstance(model.layer, Sequential):
        raise ValueError("fold_batchnorm needs a Sequential model, got "
                         f"{type(model.layer).__name__}")
    seq, params, state = _fold_sequential(
        model.layer.layers, variables["params"], variables["state"])
    folded = Model(seq, input_shape=model.input_shape,
                   name=model.name + "_bnfold")
    return folded, {"params": params, "state": state}

"""Inference-graph optimization: BatchNorm folding (r5 MFU work).

Eval-mode BatchNorm is a per-channel affine ``y = x·a + b`` with
``a = scale/√(var+ε)``, ``b = bias − mean·a`` — EXACTLY absorbable into
a preceding Conv2D/Dense: ``conv(x; K)·a + b = conv(x; K·a) + b``.
Folding removes every BN's elementwise pass (and its params/state) from
the serving graph; the training graph is untouched (training BN uses
batch statistics, where folding is not exact — reference point:
standard deployment practice, e.g. TF's fold_batch_norms).

    model2, variables2 = fold_batchnorm(model, variables)
    y, _ = model2.apply(variables2, x)            # == model.apply eval

Handles Sequential stacks recursively, including the ``Residual``
combinator's inner/shortcut branches (the ResNet zoo's conv-bn shape).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .layers import BatchNorm, Conv2D, Dense, Residual, Sequential
from .model import Model


def _affine(bn: BatchNorm, bn_params, bn_state):
    inv = 1.0 / np.sqrt(np.asarray(bn_state["var"], np.float64)
                        + bn.epsilon)
    a = np.asarray(bn_params["scale"], np.float64) * inv
    b = np.asarray(bn_params["bias"], np.float64) \
        - np.asarray(bn_state["mean"], np.float64) * a
    return a, b


def _fold_into(lyr, p, a, b):
    """Return (new_layer, new_params) with the BN affine absorbed."""
    if isinstance(lyr, Conv2D):
        new = Conv2D(lyr.filters, lyr.kernel_size, lyr.strides,
                     lyr.padding, lyr.activation, use_bias=True)
        kernel = np.asarray(p["kernel"], np.float64) * a  # (...,I,O)·(O,)
        bias = np.asarray(p.get("bias", 0.0), np.float64) * a + b
    else:  # Dense
        new = Dense(lyr.units, lyr.activation, use_bias=True)
        kernel = np.asarray(p["kernel"], np.float64) * a
        bias = np.asarray(p.get("bias", 0.0), np.float64) * a + b
    return new, {"kernel": jnp.asarray(kernel, jnp.float32),
                 "bias": jnp.asarray(bias, jnp.float32)}


def _foldable(lyr):
    # the affine must commute with everything between the kernel op and
    # the BN: fold only the DIRECTLY adjacent pair, and only when the
    # kernel op applies no nonlinearity of its own
    return isinstance(lyr, (Conv2D, Dense)) and lyr.activation is None


def _fold_layer(lyr, p, s):
    """Recursive single-layer fold; returns (layer, params, state)."""
    if isinstance(lyr, Sequential):
        return _fold_sequential(lyr.layers, p, s)
    if isinstance(lyr, Residual):
        inner, pi, si = _fold_layer(lyr.inner, p["inner"], s["inner"])
        params = {"inner": pi}
        state = {"inner": si}
        shortcut = None
        if lyr.shortcut is not None:
            shortcut, ps, ss = _fold_layer(lyr.shortcut, p["shortcut"],
                                           s["shortcut"])
            params["shortcut"] = ps
            state["shortcut"] = ss
        return Residual(inner, shortcut, lyr.activation), params, state
    return lyr, p, s


def _fold_sequential(layers, params, state):
    out_l, out_p, out_s = [], [], []
    i = 0
    while i < len(layers):
        lyr, p, s = _fold_layer(layers[i], params[i], state[i])
        nxt = layers[i + 1] if i + 1 < len(layers) else None
        if _foldable(lyr) and isinstance(nxt, BatchNorm):
            a, b = _affine(nxt, params[i + 1], state[i + 1])
            lyr, p = _fold_into(lyr, p, a, b)
            s = {}
            i += 2  # consume the BN
        else:
            i += 1
        out_l.append(lyr)
        out_p.append(p)
        out_s.append(s)
    return Sequential(out_l), out_p, out_s


def fold_batchnorm(model: Model, variables: dict):
    """(model, variables) → (folded_model, folded_variables); exact for
    EVAL-mode forward passes.  Raises if the top layer is not
    Sequential."""
    if not isinstance(model.layer, Sequential):
        raise ValueError("fold_batchnorm needs a Sequential model, got "
                         f"{type(model.layer).__name__}")
    seq, params, state = _fold_sequential(
        model.layer.layers, variables["params"], variables["state"])
    folded = Model(seq, input_shape=model.input_shape,
                   name=model.name + "_bnfold")
    return folded, {"params": params, "state": state}

"""Autoregressive generation for causal LMs (``zoo.gpt_lm``).

The reference has no generative models (its inference surface is
``ModelPredictor`` classification, reference ``distkeras/predictors.py``);
this completes the long-context family with a TPU-idiomatic decode loop:
one ``lax.scan`` over positions, static shapes throughout.

Two decode strategies, both ONE compiled program:

* **KV-cached** (default when the model supports it): a batched prefill
  (one full forward that also records every layer's K/V —
  ``Layer.apply_prefill``) followed by per-token decode steps
  (``Layer.apply_decode``; ``MultiHeadAttention`` appends this
  position's K/V and attends a single query) — O(T·D) per generated
  token, time-to-first-token = one forward.  Covers stacks of
  time-pointwise layers (Dense, LayerNorm, Embedding, MoE FF) + causal
  attention, dense or flash impl.
* **Full-context recompute** (fallback, ``use_cache=False``): rerun the
  training forward on the whole buffer each step — O(T²·D) per token
  but correct for ANY causal model, because it reuses the exact training
  forward.  Auto-selected for mesh-attached (ring-sharded) attention
  (per-chip full-length caches would defeat the sharding), for hybrid
  stacks containing a time-mixing layer without its own decode rule
  (``Layer.time_mixing``), and for RAGGED prompt batches.

Sampling controls: ``temperature`` (0 → greedy), ``top_k``, ``top_p``
(nucleus), composable.  ``eos_id`` freezes a row once it emits EOS
(masked continue inside the scan — static shapes, rows finish
independently).  Ragged prompts: pass right-padded ``prompt`` plus
``prompt_lengths``; each row's continuation is written at its own
positions (causal attention ignores the right padding, so content keeps
its physical positions 0..len-1 and the training forward stays exact —
no position-id plumbing needed).

With ``temperature > 0`` the two strategies consume PRNG splits in the
same order, so a given seed yields the same continuation on either path.
"""

from __future__ import annotations

from collections import OrderedDict

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..obs.profile import RetraceSentinel
from .layers import Layer

#: compiled decode runners kept per model (LRU): eval loops over many
#: distinct (prompt_len, num_steps, ...) shapes would otherwise retain one
#: executable EACH for the model's lifetime (ADVICE r3)
_RUNNER_CACHE_MAX = 16

# -- recompilation accounting (ISSUE 7) -------------------------------------
# One sentinel per decode entry point, observed by (model identity, runner
# cache key) — the key bakes in everything the compiled scan specializes
# on (shapes AND values like temperature), so decode recompiles count into
# ``jit.compiles``/``jit.retraces`` like every other jit entry point.
# ``warn=False``: many keys are a LEGITIMATE workload here (eval sweeps,
# decode_bench's config matrix) — the counters still feed the drift gate,
# and the serve engine's per-bucket sentinels do warn, because a serving
# bucket that re-traces is a real bug.

_SENTINELS: dict = {}
_SENTINEL_REGISTRY: list = [None]


def set_decode_registry(registry) -> None:
    """Route the decode entry points' ``jit.compiles``/``jit.retraces``
    counters into ``registry`` for this process (None restores the
    default registry) — how ``scripts/decode_bench.py`` and tests scope
    decode recompile accounting to their own snapshot."""
    _SENTINEL_REGISTRY[0] = registry


def _decode_registry():
    return _SENTINEL_REGISTRY[0]


def _observe_decode(entry: str, model, key) -> None:
    s = _SENTINELS.get(entry)
    if s is None:
        s = _SENTINELS[entry] = RetraceSentinel(
            f"decode.{entry}", registry=_decode_registry, warn=False)
    # id(model) scopes keys per live model instance (in-process counting
    # only — two models legitimately compile the same key once each)
    s.observe_key((id(model), key))

# plain Python float: a module-level jnp scalar would initialize the XLA
# backend at import time, breaking jax.distributed.initialize for any
# program that imports the package first (multihost bring-up contract)
_NEG = -1e30


def _model_cache(model, batch):
    """The model's decode-cache pytree, or None when the cached path is
    unsupported: no ``init_cache`` protocol, a mesh-attached (sharded)
    layer, a time-mixing layer without its own decode rule, or simply
    nothing in the stack that caches."""
    init = getattr(model.layer, "init_cache", None)
    if init is None:
        return None
    for lyr in model.iter_layers():
        if getattr(lyr, "mesh", None) is not None:
            return None
        if getattr(lyr, "time_mixing", False) and \
                type(lyr).apply_decode is Layer.apply_decode:
            return None
    cache = init(batch, model.input_shape)
    # None leaves vanish from pytrees: empty => nothing in the stack caches
    return cache if jax.tree_util.tree_leaves(cache) else None


def _write_at(buf, tok, pos, t):
    """Write ``tok`` (B,) into ``buf[:, pos]``; ``pos`` scalar or (B,)
    (one-hot update — no gather/scatter shape surprises on TPU)."""
    w = jax.nn.one_hot(pos, t, dtype=jnp.int32)
    if w.ndim == 1:
        w = w[None, :]
    return buf * (1 - w) + tok[:, None] * w


def _cached_runner(model, key):
    """Per-model bounded-LRU of compiled decode runners: returns
    ``(runners, run_or_None)`` with the LRU order already refreshed."""
    runners = getattr(model, "_generate_cache", None)
    if runners is None:
        runners = model._generate_cache = OrderedDict()
    run = runners.get(key)
    if run is not None:
        runners.move_to_end(key)
    return runners, run


def _cache_runner(runners, key, run):
    runners[key] = run
    if len(runners) > _RUNNER_CACHE_MAX:
        runners.popitem(last=False)
    return run


def _filter_logits(logits, top_k, top_p):
    """top-k / nucleus (top-p) filtering; composable, batch-wise."""
    if top_k is not None:
        kth = lax.top_k(logits, int(top_k))[0][..., -1:]
        logits = jnp.where(logits < kth, _NEG, logits)
    if top_p is not None:
        sorted_desc = -jnp.sort(-logits, axis=-1)
        probs = jax.nn.softmax(sorted_desc, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # smallest prefix with mass >= top_p: keep entries whose EXCLUSIVE
        # cumulative mass is still below the threshold
        keep = (cum - probs) < top_p
        thresh = jnp.min(jnp.where(keep, sorted_desc, jnp.inf),
                         axis=-1, keepdims=True)
        logits = jnp.where(logits < thresh, _NEG, logits)
    return logits


def filter_logits_rowwise(logits, top_k, top_p):
    """Per-row top-k / nucleus filtering with TRACED (B,) parameters —
    the per-request sampling primitive (ISSUE 14): unlike
    :func:`_filter_logits`, whose knobs are Python constants baked into
    the trace, these ride as device arrays, so ONE compiled program
    serves every sampling configuration without re-tracing.
    ``top_k[r] == 0`` disables top-k for row r; ``top_p[r] >= 1``
    disables nucleus filtering.  ``logits`` is (B, V)."""
    v = logits.shape[-1]
    top_k = jnp.asarray(top_k, jnp.int32)
    top_p = jnp.asarray(top_p, logits.dtype)
    sorted_desc = -jnp.sort(-logits, axis=-1)
    kth = jnp.take_along_axis(
        sorted_desc, jnp.clip(top_k - 1, 0, v - 1)[:, None], axis=-1)
    logits = jnp.where((top_k > 0)[:, None] & (logits < kth), _NEG, logits)
    # nucleus over the (possibly top-k-filtered) distribution, same
    # exclusive-mass rule as the batch-wise version
    sorted_desc = -jnp.sort(-logits, axis=-1)
    probs = jax.nn.softmax(sorted_desc, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep = (cum - probs) < top_p[:, None]
    thresh = jnp.min(jnp.where(keep, sorted_desc, jnp.inf),
                     axis=-1, keepdims=True)
    return jnp.where((top_p < 1.0)[:, None] & (logits < thresh), _NEG,
                     logits)


def rowwise_dist(logits, temperature, top_k, top_p):
    """The per-row SAMPLING distribution: softmax of the tempered,
    filtered logits (rows with ``temperature == 0`` divide by 1 — their
    value is never used by callers, which take the exact argmax path
    instead).  Returns (B, V) probabilities."""
    temperature = jnp.asarray(temperature, logits.dtype)
    scaled = logits / jnp.where(temperature > 0.0, temperature,
                                1.0)[:, None]
    return jax.nn.softmax(filter_logits_rowwise(scaled, top_k, top_p),
                          axis=-1)


def sample_rowwise(rng_key, logits, temperature, top_k, top_p):
    """One next-token draw per row under per-row sampling params: rows
    with ``temperature[r] == 0`` take the EXACT argmax (the sampled
    branch's value is discarded for them, never approximated — greedy
    parity with :func:`generate_tokens` holds row by row), others sample
    from the filtered, tempered distribution.  Returns int32 (B,)."""
    temperature = jnp.asarray(temperature, logits.dtype)
    greedy = temperature <= 0.0
    scaled = logits / jnp.where(greedy, 1.0, temperature)[:, None]
    filtered = filter_logits_rowwise(scaled, top_k, top_p)
    sampled = jax.random.categorical(rng_key, filtered, axis=-1)
    return jnp.where(greedy, jnp.argmax(logits, axis=-1),
                     sampled).astype(jnp.int32)


def decode_window(layer, params, state, tokens, cache, start, limit=None):
    """Cached multi-token decode window: feed ``tokens`` (B, K) through
    ``layer.apply_decode`` sequentially at positions ``start + i``
    (``start`` scalar or (B,) — per-row ragged windows work), returning
    the per-position logits (B, K, V) and the advanced cache.

    This is the chunked-decode primitive both serving accelerators build
    on (ISSUE 11): the prefix cache's *suffix prefill* (re-play only the
    uncached tail of a prompt over a cached KV prefix) and the
    speculative-decode *batched verify step* (K proposed tokens through
    the target in ONE compiled program instead of K dispatches).  Each
    position's K/V is written before it is attended, so the window is
    exact wherever a position-by-position decode would be.

    ``limit`` (the model's seq_len) clamps every write position to
    ``limit - 1``: callers may pad the window past a row's real content,
    and a clamped slot is placeholder-overwritten by a later real write
    before any kept logit attends it — same contract as prefill padding.
    Trace-safe: call inside jit (it compiles a ``lax.scan``)."""
    k = int(tokens.shape[1])
    start = jnp.asarray(start, jnp.int32)
    cap = None if limit is None else int(limit) - 1

    def step(c, i):
        pos = start + i
        if cap is not None:
            pos = jnp.minimum(pos, cap)
        logits, c = layer.apply_decode(params, state, tokens[:, i], c, pos)
        return c, logits

    cache, ls = lax.scan(step, cache, jnp.arange(k))
    return jnp.moveaxis(ls, 0, 1), cache


def generate_tokens(model, variables, prompt, num_steps: int,
                    temperature: float = 0.0, seed: int = 0,
                    use_cache=None, top_k=None, top_p=None,
                    eos_id=None, prompt_lengths=None):
    """Generate ``num_steps`` tokens after ``prompt``.

    model: a causal LM whose ``apply(variables, x)`` maps (B, T) int
    tokens → (B, T, V) logits, T = ``model.input_shape[0]``.
    prompt: (B, P) int array, 1 <= P, P + num_steps <= T.
    temperature: 0.0 → greedy argmax; > 0 → categorical sampling.
    top_k / top_p: sampling filters (applied in that order); only
    meaningful with temperature > 0 (argmax is unaffected by filtering).
    eos_id: once a row samples this token its continuation freezes
    (further positions repeat ``eos_id``) while other rows continue.
    prompt_lengths: (B,) true lengths for RIGHT-padded ragged prompts;
    row b's content is ``prompt[b, :prompt_lengths[b]]`` and its
    continuation lands at positions ``len_b .. len_b+num_steps-1``.
    Ragged batches run KV-cached too (r5): one padded prefill, then each
    row reads/writes its cache at its OWN position (the one-hot decode
    write takes (B,) positions) — padding K/V recorded by the prefill
    sits beyond every row's mask horizon and is overwritten as that
    row's continuation reaches it.
    use_cache: None → auto (KV-cached when the model supports it);
    True forces the cached path (raises if unsupported); False forces
    full-context recompute.

    Returns (B, P + num_steps) int32 — prompt + continuation (ragged
    rows keep their right padding; content ends at len_b + num_steps).
    The whole loop is jit-compiled (scan over positions, one-hot position
    read/write — no gather/scatter shape surprises on TPU).
    """
    t = int(model.input_shape[0])
    prompt = jnp.asarray(prompt, jnp.int32)
    b, p = prompt.shape
    num_steps = int(num_steps)
    if num_steps < 0:
        raise ValueError(f"num_steps must be >= 0, got {num_steps}")
    if top_k is not None and int(top_k) < 1:
        raise ValueError(f"top_k must be >= 1, got {top_k}")
    if top_p is not None and not 0.0 < float(top_p) <= 1.0:
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")
    if not 1 <= p <= t - num_steps:
        raise ValueError(f"prompt length {p} + {num_steps} steps exceeds "
                         f"the model's seq_len {t}")
    if num_steps == 0:
        # the degenerate call is the prompt itself on BOTH strategies
        # (ADVICE r3: the cached runner's trailing sample would otherwise
        # corrupt the last prompt token); validation above still applies
        return prompt

    ragged = False
    lengths = None
    if prompt_lengths is not None:
        lengths = np.asarray(prompt_lengths, np.int32)
        if lengths.shape != (b,):
            raise ValueError(f"prompt_lengths shape {lengths.shape} != "
                             f"({b},)")
        if lengths.min() < 1 or lengths.max() > p:
            raise ValueError(f"prompt_lengths must lie in [1, {p}]")
        if int(lengths.max()) + num_steps > t:
            raise ValueError(
                f"longest prompt {int(lengths.max())} + {num_steps} steps "
                f"exceeds the model's seq_len {t}")
        ragged = bool((lengths != lengths.max()).any()) or int(
            lengths.max()) != p

    cache = None
    if use_cache in (None, True):
        cache = _model_cache(model, b)
    if use_cache is True and cache is None:
        raise ValueError(
            "use_cache=True but the cached decode path is unsupported "
            "here: the model has no caching layer / init_cache protocol, "
            "a mesh-attached (ring-sharded) attention layer, or a "
            "time-mixing layer without a decode rule; use "
            "use_cache=False (full-context recompute)")

    buf = jnp.zeros((b, t), jnp.int32).at[:, :p].set(prompt)
    eos = None if eos_id is None else jnp.int32(int(eos_id))

    # compiled runners are cached ON the model (bounded LRU), keyed by
    # everything the closure bakes in — repeated generate_tokens calls
    # (eval loops, different seeds) reuse one compiled scan per shape
    key = (p, num_steps, float(temperature), cache is not None, b,
           None if top_k is None else int(top_k),
           None if top_p is None else float(top_p),
           None if eos_id is None else int(eos_id), ragged)
    _observe_decode("generate_tokens", model, key)
    runners, run = _cached_runner(model, key)

    if run is None:
        def sample(next_logits, rng, done):
            if temperature > 0.0:
                rng, sub = jax.random.split(rng)
                filtered = _filter_logits(next_logits / temperature,
                                          top_k, top_p)
                nxt = jax.random.categorical(sub, filtered, axis=-1)
            else:
                nxt = jnp.argmax(next_logits, axis=-1)
            nxt = nxt.astype(jnp.int32)
            if eos is not None:
                # masked continue: finished rows repeat EOS; the done flag
                # latches on the first EOS emission
                nxt = jnp.where(done, eos, nxt)
                done = done | (nxt == eos)
            return nxt, rng, done

        def write_at(buf, nxt, pos):
            return _write_at(buf, nxt, pos, t)

        done0 = jnp.zeros((b,), bool)

        if cache is not None:
            def _run(variables, buf, cache, rng, lens):
                params, state = variables["params"], variables["state"]
                # batched prefill: one forward fills every layer's cache
                # (entries past the prompt are masked placeholders,
                # overwritten as decoding advances)
                y, cache = model.layer.apply_prefill(params, state, buf,
                                                     cache)
                if lens is None:
                    logits0 = y[:, p - 1]
                else:
                    # per-row read: row b's first continuation follows
                    # position len_b - 1
                    sel = jax.nn.one_hot(lens - 1, t, dtype=y.dtype)
                    logits0 = jnp.einsum("btv,bt->bv", y, sel)

                def step(carry, i):
                    buf, cache, rng, logits_prev, done = carry
                    nxt, rng, done = sample(logits_prev, rng, done)
                    # scalar positions for uniform prompts (cheap
                    # dynamic-slice cache writes); (B,) per-row positions
                    # for ragged (one-hot cache writes)
                    pos = (p - 1 + i) if lens is None else (lens - 1 + i)
                    buf = write_at(buf, nxt, pos + 1)
                    logits_t, cache = model.layer.apply_decode(
                        params, state, nxt, cache, pos + 1)
                    return (buf, cache, rng, logits_t, done), None

                # num_steps-1 decode forwards (logits0 covers the first
                # token); the last token needs only a sample + write
                (buf, _, rng, logits_prev, done), _ = lax.scan(
                    step, (buf, cache, rng, logits0, done0),
                    jnp.arange(num_steps - 1))
                last, _, _ = sample(logits_prev, rng, done)
                last_pos = (p - 1 + num_steps if lens is None
                            else lens - 1 + num_steps)
                return write_at(buf, last, last_pos)
        else:
            def _run(variables, buf, cache, rng, lens):
                # per-row positions: uniform prompts degenerate to a
                # broadcast scalar; ragged rows each read/write their own
                # slot (right padding sits in the causal FUTURE of every
                # written position, so it never leaks into the content)
                base = (jnp.full((b,), p, jnp.int32) if lens is None
                        else lens)

                def step(carry, i):
                    buf, rng, done = carry
                    logits, _ = model.apply(variables, buf, train=False)
                    pos = base - 1 + i          # (B,) read position
                    sel = jax.nn.one_hot(pos, t, dtype=logits.dtype)
                    next_logits = jnp.einsum("btv,bt->bv", logits, sel)
                    nxt, rng, done = sample(next_logits, rng, done)
                    return (write_at(buf, nxt, pos + 1), rng, done), None

                (buf, _, _), _ = lax.scan(step, (buf, rng, done0),
                                          jnp.arange(num_steps))
                return buf

        run = _cache_runner(runners, key, jax.jit(_run))

    lens_arg = None if (not ragged or lengths is None) \
        else jnp.asarray(lengths)
    out = run(variables, buf, cache, jax.random.PRNGKey(seed), lens_arg)
    return out[:, :p + num_steps]


def generate_beam(model, variables, prompt, num_steps: int,
                  num_beams: int = 4, eos_id=None,
                  length_penalty: float = 0.0, use_cache=None,
                  return_scores: bool = False, prompt_lengths=None):
    """Deterministic beam search: ``num_beams`` hypotheses per row, the
    highest-(length-normalized)-log-probability continuation returned.

    Beams flatten into the batch dimension (B·K rows), so BOTH decode
    strategies work unchanged — the KV cache is per-row and beam
    reindexing is a batch gather inside the scan.  ``eos_id`` freezes a
    hypothesis at its first EOS (its score stops accumulating);
    ``length_penalty`` α divides final scores by (generated length)^α.
    ``prompt_lengths``: (B,) true lengths for RIGHT-padded ragged
    prompts (r5) — each row's hypotheses extend from its own length, on
    either decode strategy.  Returns (B, P + num_steps) int32, plus
    per-row best scores when ``return_scores``.
    """
    t = int(model.input_shape[0])
    prompt = jnp.asarray(prompt, jnp.int32)
    b, p = prompt.shape
    num_steps = int(num_steps)
    k_beams = int(num_beams)
    if k_beams < 1:
        raise ValueError(f"num_beams must be >= 1, got {num_beams}")
    if num_steps < 0:
        raise ValueError(f"num_steps must be >= 0, got {num_steps}")
    if not 1 <= p <= t - num_steps:
        raise ValueError(f"prompt length {p} + {num_steps} steps exceeds "
                         f"the model's seq_len {t}")
    ragged = False
    lengths = None
    if prompt_lengths is not None:
        lengths = np.asarray(prompt_lengths, np.int32)
        if lengths.shape != (b,):
            raise ValueError(f"prompt_lengths shape {lengths.shape} != "
                             f"({b},)")
        if lengths.min() < 1 or lengths.max() > p:
            raise ValueError(f"prompt_lengths must lie in [1, {p}]")
        if int(lengths.max()) + num_steps > t:
            raise ValueError(
                f"longest prompt {int(lengths.max())} + {num_steps} steps "
                f"exceeds the model's seq_len {t}")
        ragged = bool((lengths != lengths.max()).any()) or int(
            lengths.max()) != p
    if num_steps == 0:
        out = prompt
        return (out, jnp.zeros((b,), jnp.float32)) if return_scores else out

    bk = b * k_beams
    cache = _model_cache(model, bk) if use_cache in (None, True) else None
    if use_cache is True and cache is None:
        raise ValueError(
            "use_cache=True but the cached decode path is unsupported "
            "here (see generate_tokens); use use_cache=False")

    flat_prompt = jnp.repeat(prompt, k_beams, axis=0)      # (B*K, P)
    buf = jnp.zeros((bk, t), jnp.int32).at[:, :p].set(flat_prompt)
    eos = None if eos_id is None else jnp.int32(int(eos_id))

    key = ("beam", p, num_steps, k_beams, cache is not None, b,
           None if eos_id is None else int(eos_id), float(length_penalty),
           ragged)
    _observe_decode("generate_beam", model, key)
    runners, run = _cached_runner(model, key)

    if run is None:
        def expand(scores, done, gen_len, logits_prev):
            """One beam-search selection: (B·K, V) logits → per-row top-K
            of the K·V continuations → (tokens, source beam rows, ...)."""
            logp = jax.nn.log_softmax(
                logits_prev.astype(jnp.float32), axis=-1)
            v = logp.shape[-1]
            if eos is not None:
                # finished beams may only "continue" with EOS at no cost:
                # the hypothesis is frozen but stays selectable
                frozen = jnp.full_like(logp, _NEG).at[:, eos].set(0.0)
                logp = jnp.where(done[:, None], frozen, logp)
            total = scores[:, None] + logp                  # (B*K, V)
            total = total.reshape(b, k_beams * v)
            top, idx = lax.top_k(total, k_beams)            # (B, K)
            beam = idx // v                                 # source beam
            tok = (idx % v).astype(jnp.int32)
            rows = (jnp.arange(b)[:, None] * k_beams + beam).reshape(-1)
            tok = tok.reshape(-1)
            new_done = done[rows]
            new_len = gen_len[rows] + jnp.where(new_done, 0, 1)
            if eos is not None:
                new_done = new_done | (tok == eos)
            return top.reshape(-1), new_done, new_len, tok, rows

        def first_scores():
            # beam 0 live, beams 1..K-1 at -inf so the FIRST expansion
            # takes the top-K tokens of the prompt row, not K duplicates
            s = jnp.full((b, k_beams), _NEG).at[:, 0].set(0.0)
            return s.reshape(-1)

        def finalize(buf, scores, gen_len):
            if length_penalty:
                norm = jnp.maximum(gen_len.astype(jnp.float32), 1.0) \
                    ** length_penalty
                scores = scores / norm
            scores = scores.reshape(b, k_beams)
            best = jnp.argmax(scores, axis=-1)              # (B,)
            rows = jnp.arange(b) * k_beams + best
            return buf[rows], jnp.max(scores, axis=-1)

        done0 = jnp.zeros((bk,), bool)
        len0 = jnp.zeros((bk,), jnp.int32)

        def write_at(buf, tok, pos):
            return _write_at(buf, tok, pos, t)

        if cache is not None:
            def _run(variables, buf, cache, lens):
                params, state = variables["params"], variables["state"]
                y, cache = model.layer.apply_prefill(params, state, buf,
                                                     cache)
                if lens is None:
                    logits0 = y[:, p - 1]
                else:
                    sel = jax.nn.one_hot(lens - 1, t, dtype=y.dtype)
                    logits0 = jnp.einsum("btv,bt->bv", y, sel)

                def step(carry, i):
                    buf, cache, scores, done, gen_len, logits_prev = carry
                    scores, done, gen_len, tok, rows = expand(
                        scores, done, gen_len, logits_prev)
                    # generated position: p+i uniform, len_b+i ragged
                    # (lens is constant within a row's beam group, so
                    # beam regathering never changes it)
                    pos = (p + i) if lens is None else (lens + i)
                    buf = write_at(buf[rows], tok, pos)
                    cache = jax.tree_util.tree_map(lambda c: c[rows],
                                                   cache)
                    logits_t, cache = model.layer.apply_decode(
                        params, state, tok, cache, pos)
                    return (buf, cache, scores, done, gen_len,
                            logits_t), None

                (buf, _, scores, done, gen_len, logits_prev), _ = lax.scan(
                    step, (buf, cache, first_scores(), done0, len0,
                           logits0), jnp.arange(num_steps - 1))
                scores, done, gen_len, tok, rows = expand(
                    scores, done, gen_len, logits_prev)
                last_pos = (p + num_steps - 1 if lens is None
                            else lens + num_steps - 1)
                buf = write_at(buf[rows], tok, last_pos)
                return finalize(buf, scores, gen_len)
        else:
            def _run(variables, buf, cache, lens):
                def step(carry, i):
                    buf, scores, done, gen_len = carry
                    logits, _ = model.apply(variables, buf, train=False)
                    if lens is None:
                        sel = jax.nn.one_hot(p - 1 + i, t,
                                             dtype=logits.dtype)
                        logits_prev = jnp.einsum("btv,t->bv", logits, sel)
                    else:
                        sel = jax.nn.one_hot(lens - 1 + i, t,
                                             dtype=logits.dtype)
                        logits_prev = jnp.einsum("btv,bt->bv", logits, sel)
                    scores, done, gen_len, tok, rows = expand(
                        scores, done, gen_len, logits_prev)
                    pos = (p + i) if lens is None else (lens + i)
                    buf = write_at(buf[rows], tok, pos)
                    return (buf, scores, done, gen_len), None

                (buf, scores, _, gen_len), _ = lax.scan(
                    step, (buf, first_scores(), done0, len0),
                    jnp.arange(num_steps))
                return finalize(buf, scores, gen_len)

        run = _cache_runner(runners, key, jax.jit(_run))

    lens_arg = None if not ragged else jnp.repeat(jnp.asarray(lengths),
                                                  k_beams, axis=0)
    out, best_scores = run(variables, buf, cache, lens_arg)
    out = out[:, :p + num_steps]
    return (out, best_scores) if return_scores else out

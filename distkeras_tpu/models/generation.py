"""Autoregressive generation for causal LMs (``zoo.gpt_lm``).

The reference has no generative models (its inference surface is
``ModelPredictor`` classification, reference ``distkeras/predictors.py``);
this completes the long-context family with a TPU-idiomatic decode loop:
one ``lax.scan`` over positions, static shapes throughout.

Two decode strategies, both ONE compiled program:

* **KV-cached** (default when the model supports it): a batched prefill
  (one full forward that also records every layer's K/V —
  ``Layer.apply_prefill``) followed by per-token decode steps
  (``Layer.apply_decode``; ``MultiHeadAttention`` appends this
  position's K/V and attends a single query) — O(T·D) per generated
  token, time-to-first-token = one forward.  Covers stacks of
  time-pointwise layers (Dense, LayerNorm, Embedding, MoE FF) + causal
  attention, dense or flash impl.
* **Full-context recompute** (fallback, ``use_cache=False``): rerun the
  training forward on the whole buffer each step — O(T²·D) per token
  but correct for ANY causal model, because it reuses the exact training
  forward.  Auto-selected for mesh-attached (ring-sharded) attention
  (per-chip full-length caches would defeat the sharding) and for
  hybrid stacks containing a time-mixing layer without its own decode
  rule (``Layer.time_mixing``).

With ``temperature > 0`` the two strategies consume PRNG splits in the
same order, so a given seed yields the same continuation on either path.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .layers import Layer


def _model_cache(model, batch):
    """The model's decode-cache pytree, or None when the cached path is
    unsupported: no ``init_cache`` protocol, a mesh-attached (sharded)
    layer, a time-mixing layer without its own decode rule, or simply
    nothing in the stack that caches."""
    init = getattr(model.layer, "init_cache", None)
    if init is None:
        return None
    for lyr in model.iter_layers():
        if getattr(lyr, "mesh", None) is not None:
            return None
        if getattr(lyr, "time_mixing", False) and \
                type(lyr).apply_decode is Layer.apply_decode:
            return None
    cache = init(batch, model.input_shape)
    # None leaves vanish from pytrees: empty => nothing in the stack caches
    return cache if jax.tree_util.tree_leaves(cache) else None


def generate_tokens(model, variables, prompt, num_steps: int,
                    temperature: float = 0.0, seed: int = 0,
                    use_cache=None):
    """Generate ``num_steps`` tokens after ``prompt``.

    model: a causal LM whose ``apply(variables, x)`` maps (B, T) int
    tokens → (B, T, V) logits, T = ``model.input_shape[0]``.
    prompt: (B, P) int array, 1 <= P, P + num_steps <= T.
    temperature: 0.0 → greedy argmax; > 0 → categorical sampling.
    use_cache: None → auto (KV-cached when the model supports it);
    True forces the cached path (raises if unsupported); False forces
    full-context recompute.

    Returns (B, P + num_steps) int32 — prompt + continuation.  The whole
    loop is jit-compiled (scan over positions, one-hot position
    read/write — no gather/scatter shape surprises on TPU).
    """
    t = int(model.input_shape[0])
    prompt = jnp.asarray(prompt, jnp.int32)
    b, p = prompt.shape
    if not 1 <= p <= t - num_steps:
        raise ValueError(f"prompt length {p} + {num_steps} steps exceeds "
                         f"the model's seq_len {t}")

    cache = _model_cache(model, b) if use_cache in (None, True) else None
    if use_cache is True and cache is None:
        raise ValueError(
            "use_cache=True but the cached decode path is unsupported "
            "here: the model has no caching layer / init_cache protocol, "
            "a mesh-attached (ring-sharded) attention layer, or a "
            "time-mixing layer without a decode rule; use "
            "use_cache=False (full-context recompute)")

    buf = jnp.zeros((b, t), jnp.int32).at[:, :p].set(prompt)

    # compiled runners are cached ON the model, keyed by everything the
    # closure bakes in — repeated generate_tokens calls (eval loops,
    # different seeds) reuse one compiled scan instead of retracing
    key = (p, int(num_steps), float(temperature), cache is not None, b)
    runners = getattr(model, "_generate_cache", None)
    if runners is None:
        runners = model._generate_cache = {}
    run = runners.get(key)

    if run is None:
        def sample(next_logits, rng):
            if temperature > 0.0:
                rng, sub = jax.random.split(rng)
                nxt = jax.random.categorical(
                    sub, next_logits / temperature, axis=-1)
            else:
                nxt = jnp.argmax(next_logits, axis=-1)
            return nxt.astype(jnp.int32), rng

        def write_after(buf, nxt, pos):
            """Write ``nxt`` into buf[:, pos+1] (one-hot update)."""
            w = jax.nn.one_hot(pos + 1, t, dtype=jnp.int32)
            return buf * (1 - w)[None, :] + nxt[:, None] * w[None, :]

        if cache is not None:
            def _run(variables, buf, cache, rng):
                params, state = variables["params"], variables["state"]
                # batched prefill: one forward fills every layer's cache
                # (entries past the prompt are masked placeholders,
                # overwritten as decoding advances)
                y, cache = model.layer.apply_prefill(params, state, buf,
                                                     cache)
                logits0 = y[:, p - 1]

                def step(carry, i):
                    buf, cache, rng, logits_prev = carry
                    nxt, rng = sample(logits_prev, rng)
                    pos = p - 1 + i
                    buf = write_after(buf, nxt, pos)
                    logits_t, cache = model.layer.apply_decode(
                        params, state, nxt, cache, pos + 1)
                    return (buf, cache, rng, logits_t), None

                # num_steps-1 decode forwards (logits0 covers the first
                # token); the last token needs only a sample + write
                (buf, _, rng, logits_prev), _ = lax.scan(
                    step, (buf, cache, rng, logits0),
                    jnp.arange(num_steps - 1))
                last, _ = sample(logits_prev, rng)
                return write_after(buf, last, p - 2 + num_steps)
        else:
            def _run(variables, buf, cache, rng):
                def step(carry, i):
                    buf, rng = carry
                    logits, _ = model.apply(variables, buf, train=False)
                    pos = p - 1 + i
                    sel = jax.nn.one_hot(pos, t, dtype=logits.dtype)
                    next_logits = jnp.einsum("btv,t->bv", logits, sel)
                    nxt, rng = sample(next_logits, rng)
                    return (write_after(buf, nxt, pos), rng), None

                (buf, _), _ = lax.scan(step, (buf, rng),
                                       jnp.arange(num_steps))
                return buf

        run = runners[key] = jax.jit(_run)

    out = run(variables, buf, cache, jax.random.PRNGKey(seed))
    return out[:, :p + num_steps]

"""Autoregressive generation for causal LMs (``zoo.gpt_lm``).

The reference has no generative models (its inference surface is
``ModelPredictor`` classification, reference ``distkeras/predictors.py``);
this completes the long-context family with a TPU-idiomatic decode loop:
one ``lax.scan`` over positions, static shapes throughout (the token
buffer is the model's full ``seq_len``; each step recomputes the causal
forward and samples at the current position).

Full-context recompute keeps the loop correct for ANY causal model —
dense, flash (Pallas), ring-sharded, MoE, or a Keras-adapted decoder —
because it reuses the exact training forward instead of a separate
cached-decode path.  Cost is O(steps · T²) attention; for the sequence
lengths the zoo trains on one chip this is dominated by dispatch, and the
whole generation is ONE compiled program.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def generate_tokens(model, variables, prompt, num_steps: int,
                    temperature: float = 0.0, seed: int = 0):
    """Generate ``num_steps`` tokens after ``prompt``.

    model: a causal LM whose ``apply(variables, x)`` maps (B, T) int
    tokens → (B, T, V) logits, T = ``model.input_shape[0]``.
    prompt: (B, P) int array, 1 <= P, P + num_steps <= T.
    temperature: 0.0 → greedy argmax; > 0 → categorical sampling.

    Returns (B, P + num_steps) int32 — prompt + continuation.  The whole
    loop is jit-compiled (scan over positions, dynamic position indexing
    via one-hot contractions — no gather/scatter shape surprises on TPU).
    """
    t = int(model.input_shape[0])
    prompt = jnp.asarray(prompt, jnp.int32)
    b, p = prompt.shape
    if not 1 <= p <= t - num_steps:
        raise ValueError(f"prompt length {p} + {num_steps} steps exceeds "
                         f"the model's seq_len {t}")

    buf = jnp.zeros((b, t), jnp.int32).at[:, :p].set(prompt)

    # compiled runners are cached ON the model, keyed by everything the
    # closure bakes in — repeated generate_tokens calls (eval loops,
    # different seeds) reuse one compiled scan instead of retracing
    key = (p, int(num_steps), float(temperature))
    cache = getattr(model, "_generate_cache", None)
    if cache is None:
        cache = model._generate_cache = {}
    run = cache.get(key)
    if run is None:
        def _run(variables, buf, rng):
            def step(carry, i):
                buf, rng = carry
                logits, _ = model.apply(variables, buf, train=False)
                # logits at position p-1+i (the last valid token) via
                # one-hot contraction: TPU-friendly dynamic indexing
                pos = p - 1 + i
                sel = jax.nn.one_hot(pos, t, dtype=logits.dtype)
                next_logits = jnp.einsum("btv,t->bv", logits, sel)
                if temperature > 0.0:
                    rng, sub = jax.random.split(rng)
                    nxt = jax.random.categorical(
                        sub, next_logits / temperature, axis=-1)
                else:
                    nxt = jnp.argmax(next_logits, axis=-1)
                # write the sampled token at position pos+1
                write = jax.nn.one_hot(pos + 1, t, dtype=jnp.int32)
                buf = buf * (1 - write)[None, :] \
                    + nxt[:, None] * write[None, :]
                return (buf, rng), nxt

            (buf, _), _ = lax.scan(step, (buf, rng),
                                   jnp.arange(num_steps))
            return buf

        run = cache[key] = jax.jit(_run)

    out = run(variables, buf, jax.random.PRNGKey(seed))
    return out[:, :p + num_steps]

from .layers import (
    Layer, Dense, Activation, Flatten, Reshape, Dropout, Conv2D, MaxPool2D,
    AvgPool2D, GlobalAvgPool2D, BatchNorm, Embedding, LSTM, Sequential,
    register, layer_from_config, LAYER_REGISTRY,
)
from .model import Model, num_params
from .generation import generate_beam, generate_tokens
from .optimize import fold_batchnorm, zigzag_wrap

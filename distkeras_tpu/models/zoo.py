"""Model zoo — the five benchmark model families from BASELINE.json.

The reference defines its models ad hoc in notebooks (``examples/
mnist.ipynb`` builds a Keras Sequential MLP/convnet inline; the workflow
notebook reuses them).  We ship them as constructors so trainers, tests and
benchmarks share one definition:

1. ``mlp_mnist``       — SingleTrainer MLP on MNIST (the 99%-acc anchor)
2. ``convnet_cifar10`` — ADAG ConvNet on CIFAR-10
3. ``resnet20``        — DOWNPOUR ResNet-20 on CIFAR-10 (He et al. 2015,
                         the CIFAR variant: 3 stages × 3 blocks, 16/32/64)
4. ``lstm_imdb``       — AEASGD/EAMSGD LSTM sentiment on IMDB
5. ``resnet50``        — DynSGD ResNet-50 on ImageNet-subset (bottleneck
                         blocks, 4 stages × [3,4,6,3])

All are NHWC / channels-last, end in softmax (the reference's Keras
convention — trainers swap in the on-probs loss), and lower to MXU-friendly
convs/matmuls with static shapes.
"""

from __future__ import annotations

from .layers import (Activation, AvgPool2D, BatchNorm, Conv2D, Dense, Dropout,
                     Embedding, Flatten, GlobalAvgPool2D, LSTM, MaxPool2D,
                     Residual, Sequential)
from .model import Model


def mlp_mnist(hidden: int = 500, num_classes: int = 10) -> Model:
    """MLP for flat 784-dim MNIST (reference ``examples/mnist.ipynb``
    architecture scale: Dense(500) stacks + softmax head)."""
    return Model(Sequential([
        Dense(hidden, "relu"),
        Dense(hidden, "relu"),
        Dense(num_classes, "softmax"),
    ]), input_shape=(784,), name="mlp_mnist")


def convnet_mnist(num_classes: int = 10) -> Model:
    """Small convnet for 28×28×1 MNIST (the reference notebook's convnet
    variant: conv-pool-conv-pool-dense)."""
    return Model(Sequential([
        Conv2D(32, 3, activation="relu"),
        MaxPool2D(2),
        Conv2D(64, 3, activation="relu"),
        MaxPool2D(2),
        Flatten(),
        Dense(128, "relu"),
        Dense(num_classes, "softmax"),
    ]), input_shape=(28, 28, 1), name="convnet_mnist")


def convnet_cifar10(num_classes: int = 10) -> Model:
    """VGG-ish ConvNet for 32×32×3 CIFAR-10 (ADAG benchmark config)."""
    return Model(Sequential([
        Conv2D(32, 3, activation="relu"),
        Conv2D(32, 3, activation="relu"),
        MaxPool2D(2),
        Conv2D(64, 3, activation="relu"),
        Conv2D(64, 3, activation="relu"),
        MaxPool2D(2),
        Flatten(),
        Dense(256, "relu"),
        Dropout(0.5),
        Dense(num_classes, "softmax"),
    ]), input_shape=(32, 32, 3), name="convnet_cifar10")


def _basic_block(filters: int, stride: int = 1, in_filters: int = None):
    """ResNet v1 basic block: conv-bn-relu-conv-bn (+shortcut) -relu."""
    inner = Sequential([
        Conv2D(filters, 3, strides=stride, use_bias=False),
        BatchNorm(),
        Activation("relu"),
        Conv2D(filters, 3, use_bias=False),
        BatchNorm(),
    ])
    shortcut = None
    if stride != 1 or (in_filters is not None and in_filters != filters):
        shortcut = Sequential([
            Conv2D(filters, 1, strides=stride, use_bias=False),
            BatchNorm(),
        ])
    return Residual(inner, shortcut, activation="relu")


def resnet20(num_classes: int = 10, width: int = 16) -> Model:
    """ResNet-20 for CIFAR-10 (He et al. 2015 §4.2: n=3 → 6n+2=20 layers,
    widths 16/32/64).  The DOWNPOUR benchmark config and the headline
    samples/sec/chip model.

    ``width`` scales the stage widths ``[w, 2w, 4w]`` (16 = the standard
    model).  Wider variants put MXU-granular channel counts (≥128 lanes)
    on the matmul dimensions — the scripts/mfu.py utilization ladder."""
    layers = [Conv2D(width, 3, use_bias=False), BatchNorm(),
              Activation("relu")]
    widths = [width, 2 * width, 4 * width]
    in_f = width
    for si, f in enumerate(widths):
        for bi in range(3):
            stride = 2 if (si > 0 and bi == 0) else 1
            layers.append(_basic_block(f, stride, in_f))
            in_f = f
    layers += [GlobalAvgPool2D(), Dense(num_classes, "softmax")]
    return Model(Sequential(layers), input_shape=(32, 32, 3), name="resnet20")


def _bottleneck(filters: int, stride: int = 1, in_filters: int = None):
    """ResNet v1.5 bottleneck: 1×1 reduce, 3×3 (strided), 1×1 expand ×4."""
    out_f = filters * 4
    inner = Sequential([
        Conv2D(filters, 1, use_bias=False),
        BatchNorm(),
        Activation("relu"),
        Conv2D(filters, 3, strides=stride, use_bias=False),
        BatchNorm(),
        Activation("relu"),
        Conv2D(out_f, 1, use_bias=False),
        BatchNorm(),
    ])
    shortcut = None
    if stride != 1 or (in_filters is not None and in_filters != out_f):
        shortcut = Sequential([
            Conv2D(out_f, 1, strides=stride, use_bias=False),
            BatchNorm(),
        ])
    return Residual(inner, shortcut, activation="relu")


def resnet50(num_classes: int = 1000, input_size: int = 224,
             stem: str = "conv7") -> Model:
    """ResNet-50 (DynSGD / ImageNet-subset benchmark config): stem +
    [3,4,6,3] bottleneck stages, widths 64/128/256/512.

    ``stem``: ``"conv7"`` is the classic 7×7/s2 conv + 3×3/s2 maxpool.
    ``"s2d"`` is the TPU space-to-depth stem: a 4×4 patchify
    (``SpaceToDepth``) feeding a stride-1 3×3 conv — same ×4
    downsampling and output shape, but the first contraction runs at 48
    input channels instead of 3, filling the MXU's lanes (the 7×7/s2
    stem + maxpool bound ResNet-50/96px MFU at 26%, VERDICT r3 weak #2;
    the standard MLPerf-era TPU stem rewrite)."""
    if stem == "s2d":
        from .layers import SpaceToDepth
        layers = [
            SpaceToDepth(4),
            Conv2D(64, 3, strides=1, use_bias=False),
            BatchNorm(),
            Activation("relu"),
        ]
    elif stem == "conv7":
        layers = [
            Conv2D(64, 7, strides=2, use_bias=False),
            BatchNorm(),
            Activation("relu"),
            MaxPool2D(3, strides=2, padding="SAME"),
        ]
    else:
        raise ValueError(f"stem must be 'conv7' or 's2d', got {stem!r}")
    in_f = 64
    for si, (f, blocks) in enumerate(zip([64, 128, 256, 512], [3, 4, 6, 3])):
        for bi in range(blocks):
            stride = 2 if (si > 0 and bi == 0) else 1
            layers.append(_bottleneck(f, stride, in_f))
            in_f = f * 4
    layers += [GlobalAvgPool2D(), Dense(num_classes, "softmax")]
    return Model(Sequential(layers), input_shape=(input_size, input_size, 3),
                 name="resnet50")


def lstm_imdb(vocab_size: int = 20000, embed_dim: int = 128,
              lstm_units: int = 128, seq_len: int = 200) -> Model:
    """LSTM sentiment classifier for IMDB (AEASGD/EAMSGD benchmark config):
    embed → LSTM → dense sigmoid.  Sequences are padded/bucketed to
    ``seq_len`` for static shapes (XLA recompilation trap, SURVEY.md §7)."""
    return Model(Sequential([
        Embedding(vocab_size, embed_dim),
        LSTM(lstm_units),
        Dropout(0.5),
        Dense(1, "sigmoid"),
    ]), input_shape=(seq_len,), name="lstm_imdb")


def _ff_block(dim: int, ff_mult: int, moe_experts: int):
    """Transformer FF block: pre-LN residual around dense-gelu-dense, or a
    switch-MoE FF when ``moe_experts > 0`` (shared by
    ``transformer_classifier`` and ``gpt_lm``)."""
    from ..ops.attention import LayerNorm
    if moe_experts:
        from ..ops.moe import MoEDense
        ff: list = [MoEDense(moe_experts, d_hidden=dim * ff_mult)]
    else:
        ff = [Dense(dim * ff_mult, "gelu"), Dense(dim)]
    return Residual(Sequential([LayerNorm(), *ff]))


def transformer_classifier(vocab_size: int = 20000, dim: int = 128,
                           num_heads: int = 4, num_blocks: int = 2,
                           seq_len: int = 200, num_classes: int = 2,
                           ff_mult: int = 4,
                           moe_experts: int = 0) -> Model:
    """Pre-LN transformer encoder classifier — the long-context model
    family the reference never had (its sequence ceiling was one worker's
    LSTM, SURVEY.md §5.7).  Attention lowers to
    ``ops.attention.MultiHeadAttention``; for sequences sharded over an
    ``sp`` mesh axis the same math runs as ring attention
    (``parallel.ring``).

    ``moe_experts > 0`` swaps the dense FF block for a switch-MoE FF
    (``ops.moe.MoEDense`` — per-token top-1 routing; expert-sharded
    execution over an ``ep`` mesh via ``switch_moe_sharded``)."""
    from ..ops.attention import (GlobalAvgPool1D, LayerNorm,
                                 MultiHeadAttention)
    layers = [Embedding(vocab_size, dim)]
    for _ in range(num_blocks):
        layers.append(Residual(Sequential([
            LayerNorm(), MultiHeadAttention(num_heads)])))
        layers.append(_ff_block(dim, ff_mult, moe_experts))
    layers += [LayerNorm(), GlobalAvgPool1D(),
               Dense(num_classes, "softmax")]
    return Model(Sequential(layers), input_shape=(seq_len,),
                 name="transformer_classifier")


def gpt_lm(vocab_size: int = 256, dim: int = 128, num_heads: int = 4,
           num_blocks: int = 2, seq_len: int = 256, ff_mult: int = 4,
           attention_impl: str = "dense", moe_experts: int = 0,
           num_kv_heads=None, positional: str = "learned") -> Model:
    """Decoder-only causal language model (GPT-style) — the canonical
    long-context workload, beyond the reference's LSTM ceiling
    (SURVEY.md §5.7).

    Pre-LN blocks of causal ``MultiHeadAttention`` + gelu FF; ends in a
    vocab-logits Dense (no softmax — pair with
    ``loss='sparse_categorical_crossentropy'``, which averages per-token).
    Targets are the input sequence shifted left by one.

    ``attention_impl='flash'`` lowers attention to the Pallas
    VMEM-resident kernels (O(T·D) HBM fwd+bwd); for sequences past one
    chip, attach an ``sp`` mesh to every ``MultiHeadAttention`` found via
    ``model.iter_layers()`` (set ``layer.mesh = mesh``; see
    ``examples/longcontext.py``) to run ring attention over the
    sequence shards.

    ``moe_experts > 0`` swaps each dense FF block for a switch-MoE FF
    (``ops.moe.MoEDense``) — same option as
    ``transformer_classifier``."""
    from ..ops.attention import (LayerNorm, MultiHeadAttention,
                                 PositionalEmbedding)
    if positional not in ("learned", "rope"):
        raise ValueError(f"positional must be 'learned' or 'rope', got "
                         f"{positional!r}")
    rope = positional == "rope"
    layers = [Embedding(vocab_size, dim)]
    if not rope:  # rope lives inside the attention layers instead
        layers.append(PositionalEmbedding(seq_len))
    for _ in range(num_blocks):
        layers.append(Residual(Sequential([
            LayerNorm(),
            MultiHeadAttention(num_heads, causal=True,
                               impl=attention_impl,
                               num_kv_heads=num_kv_heads,
                               rope=rope)])))
        layers.append(_ff_block(dim, ff_mult, moe_experts))
    layers += [LayerNorm(), Dense(vocab_size)]
    return Model(Sequential(layers), input_shape=(seq_len,), name="gpt_lm")


def draft_lm(target: Model, dim: int = 32, num_heads: int = 2,
             num_blocks: int = 1, ff_mult: int = 4,
             positional: str = "learned") -> Model:
    """A small **draft** model for speculative decoding (ISSUE 11),
    shape-compatible with a ``gpt_lm`` ``target`` by construction: same
    vocab (proposals are verified token-by-token in one shared id
    space) and same ``seq_len`` (the draft's KV cache tracks the same
    absolute positions as the target's), everything else scaled down.
    ``DecodeEngine(..., draft_model=..., draft_variables=...)`` verifies
    exactly these two invariants at construction — this helper makes
    them impossible to get wrong.

    The draft's *weights* are the caller's problem (typically a
    distillation of the target): speculative decoding is greedy-exact at
    ANY draft quality, a bad draft only costs accept rate."""
    return gpt_lm(vocab_size=int(target.output_shape[-1]), dim=dim,
                  num_heads=num_heads, num_blocks=num_blocks,
                  seq_len=int(target.input_shape[0]), ff_mult=ff_mult,
                  positional=positional)


ZOO = {
    "mlp_mnist": mlp_mnist,
    "convnet_mnist": convnet_mnist,
    "convnet_cifar10": convnet_cifar10,
    "resnet20": resnet20,
    "resnet50": resnet50,
    "lstm_imdb": lstm_imdb,
    "transformer_classifier": transformer_classifier,
    "gpt_lm": gpt_lm,
}

"""Keras-3 model ingestion — train *actual Keras models* on this framework.

The reference's entire API is Keras-model-in, Keras-model-out (its trainers
pickle ``keras.Model`` objects to Spark executors).  Keras 3 ships a JAX
backend with a stateless functional API, which lets us compile unmodified
Keras models straight into our jit-compiled train steps:

    kmodel = keras.Sequential([...])        # any Keras 3 model
    model = KerasAdapter(kmodel)
    SingleTrainer(model, "sgd", "categorical_crossentropy").train(ds)

``KerasAdapter`` implements the same protocol as ``models.Model`` (init /
apply / layer.apply / config), so every trainer, predictor and serde path
accepts it unchanged.  Under the hood ``apply`` is
``keras.Model.stateless_call`` — pure, jit-safe, differentiable.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import numpy as np

_KERAS = None


def _keras():
    """Import keras lazily with the JAX backend enforced."""
    global _KERAS
    if _KERAS is not None:
        return _KERAS
    os.environ.setdefault("KERAS_BACKEND", "jax")
    import keras
    if keras.backend.backend() != "jax":
        raise RuntimeError(
            f"keras backend is {keras.backend.backend()!r}; distkeras_tpu "
            f"needs the JAX backend (set KERAS_BACKEND=jax before importing "
            f"keras)")
    _KERAS = keras
    return keras


class _KerasLayerShim:
    """Adapts ``stateless_call`` to the ``Layer.apply`` signature trainers
    compile against."""

    def __init__(self, adapter: "KerasAdapter"):
        self._adapter = adapter

    def apply(self, params, state, x, *, train: bool = False, rng=None):
        outputs, new_state = self._adapter.keras_model.stateless_call(
            params, state, x, training=train)
        return outputs, new_state


class KerasAdapter:
    """Wrap a built Keras 3 model into the ``Model`` protocol."""

    def __init__(self, keras_model, input_shape: Optional[Sequence[int]] = None):
        keras = _keras()
        if not keras_model.built:
            if input_shape is None:
                raise ValueError("pass input_shape= for an unbuilt model")
            keras_model.build((None, *input_shape))
        self.keras_model = keras_model
        shape = keras_model.input_shape
        self.input_shape = tuple(int(s) for s in shape[1:])
        self.output_shape = tuple(
            int(s) for s in keras_model.output_shape[1:])
        self.name = keras_model.name
        self.variables: Optional[dict] = None

        self.layer = _KerasLayerShim(self)

    # -- Model protocol -----------------------------------------------------
    def init(self, rng=0) -> dict:
        """Snapshot the model's variables as a pytree.

        ``rng`` is accepted for signature parity but IGNORED: the wrapped
        model's weights (possibly pretrained) are the init — trainers pass
        their seed here and must never silently discard a pretrained
        snapshot.  For deliberately decorrelated fresh inits (ensembles)
        use :meth:`reinit`."""
        return {
            "params": [np.asarray(v) for v in
                       self.keras_model.trainable_variables],
            "state": [np.asarray(v) for v in
                      self.keras_model.non_trainable_variables],
        }

    def reinit(self, rng: int) -> dict:
        """Deterministic FRESH initialization keyed on ``rng`` (a seeded
        clone re-init; used by EnsembleTrainer for decorrelated members).
        Note: seeds Keras' global RNG as a side effect of cloning."""
        keras = _keras()
        keras.utils.set_random_seed(int(rng) & 0x7FFFFFFF)
        model = keras.models.model_from_json(self.keras_model.to_json())
        model.build((None, *self.input_shape))
        return {
            "params": [np.asarray(v) for v in model.trainable_variables],
            "state": [np.asarray(v) for v in model.non_trainable_variables],
        }

    def apply(self, variables: dict, x, *, train: bool = False, rng=None):
        return self.layer.apply(variables["params"], variables["state"], x,
                                train=train, rng=rng)

    def iter_layers(self):
        """Model-protocol parity (``Model.iter_layers``).  An ingested
        Keras graph has no native-layer internals to traverse — callers
        get the shim only (no MoEDense/MultiHeadAttention instances to
        configure; do that on the Keras side instead)."""
        yield self.layer

    def predict_fn(self):
        def fn(variables, x):
            y, _ = self.apply(variables, x, train=False)
            return y
        return fn

    # -- serde ---------------------------------------------------------------
    def config(self) -> dict:
        return {"keras_json": self.keras_model.to_json(),
                "input_shape": list(self.input_shape)}

    @classmethod
    def from_config(cls, cfg: dict) -> "KerasAdapter":
        keras = _keras()
        kmodel = keras.models.model_from_json(cfg["keras_json"])
        return cls(kmodel, input_shape=cfg.get("input_shape"))

    def __repr__(self):
        return (f"KerasAdapter({self.name!r}, in={self.input_shape}, "
                f"out={self.output_shape})")

"""TPU-native layer/model API.

This is the replacement for the reference's reliance on Keras model objects
(dist-keras ships Keras models to Spark executors and calls
``model.train_on_batch``; see reference ``distkeras/workers.py`` and
``distkeras/utils.py:serialize_keras_model``).  Here a model is a pure
function pair:

    variables = model.init(rng)                     # {'params': ..., 'state': ...}
    y, new_state = model.apply(variables, x, train=True, rng=rng)

``params`` are trainable pytrees (differentiated through), ``state`` holds
non-trainable mutables (BatchNorm running statistics).  Everything lowers to
jit-friendly JAX: static shapes, ``lax.scan`` recurrence, no Python control
flow on traced values — so the whole train step compiles onto the TPU MXU.

Layer configs are JSON-serializable (``get_config``/``from_config``) which
gives us the reference's architecture-JSON + weight-list serialization
contract (reference ``distkeras/utils.py:serialize_keras_model``).
"""

from __future__ import annotations

import math
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

LAYER_REGISTRY: dict[str, type] = {}


def register(cls):
    """Register a layer class for config-based (de)serialization."""
    LAYER_REGISTRY[cls.__name__] = cls
    return cls


def layer_from_config(cfg: dict) -> "Layer":
    cls = LAYER_REGISTRY[cfg["class"]]
    return cls.from_config(cfg["config"])


# ---------------------------------------------------------------------------
# initializers
# ---------------------------------------------------------------------------

def glorot_uniform(rng, shape, dtype=jnp.float32, fan_in=None, fan_out=None):
    if fan_in is None or fan_out is None:
        receptive = math.prod(shape[:-2]) if len(shape) > 2 else 1
        fan_in = shape[-2] * receptive if len(shape) >= 2 else shape[-1]
        fan_out = shape[-1] * receptive if len(shape) >= 2 else shape[-1]
    limit = math.sqrt(6.0 / (fan_in + fan_out))
    return jax.random.uniform(rng, shape, dtype, -limit, limit)


def he_normal(rng, shape, dtype=jnp.float32):
    receptive = math.prod(shape[:-2]) if len(shape) > 2 else 1
    fan_in = (shape[-2] * receptive) if len(shape) >= 2 else shape[-1]
    std = math.sqrt(2.0 / fan_in)
    return jax.random.normal(rng, shape, dtype) * std


def uniform_scale(rng, shape, scale=0.05, dtype=jnp.float32):
    return jax.random.uniform(rng, shape, dtype, -scale, scale)


ACTIVATIONS: dict[str, Callable] = {
    "linear": lambda x: x,
    "relu": jax.nn.relu,
    "gelu": jax.nn.gelu,
    "tanh": jnp.tanh,
    "sigmoid": jax.nn.sigmoid,
    "softmax": lambda x: jax.nn.softmax(x, axis=-1),
    "log_softmax": lambda x: jax.nn.log_softmax(x, axis=-1),
    "elu": jax.nn.elu,
    "silu": jax.nn.silu,
    "leaky_relu": jax.nn.leaky_relu,
}


def get_activation(name_or_fn):
    if name_or_fn is None:
        return ACTIVATIONS["linear"]
    if callable(name_or_fn):
        return name_or_fn
    return ACTIVATIONS[name_or_fn]


def activation_config(name_or_fn):
    """Serializable form of an activation spec; refuses silent loss."""
    if name_or_fn is None or isinstance(name_or_fn, str):
        return name_or_fn
    for name, fn in ACTIVATIONS.items():
        if fn is name_or_fn:
            return name
    raise ValueError(
        f"cannot serialize custom activation {name_or_fn!r}; use a registered "
        f"name ({', '.join(ACTIVATIONS)}) or an Activation layer subclass")


# ---------------------------------------------------------------------------
# base
# ---------------------------------------------------------------------------

class Layer:
    """Base layer: pure-functional init/apply with explicit shapes.

    ``init(rng, in_shape) -> (params, state, out_shape)`` where shapes
    exclude the leading batch dimension.  ``apply(params, state, x, ...)``
    returns ``(y, new_state)``.  Shapes are static so XLA traces once.
    """

    def init(self, rng, in_shape: tuple) -> tuple[Any, Any, tuple]:
        return {}, {}, self.out_shape(in_shape)

    def out_shape(self, in_shape: tuple) -> tuple:
        return in_shape

    def apply(self, params, state, x, *, train: bool = False, rng=None):
        raise NotImplementedError

    # -- cached autoregressive decode (causal LMs) --------------------------

    #: layers that mix information ACROSS the time axis set this True;
    #: the decode protocol refuses stacks containing a time-mixing layer
    #: without its own apply_decode override (the pointwise default would
    #: silently compute the wrong thing on per-token input)
    time_mixing = False

    #: layers whose training forward consumes randomness (Dropout) set
    #: this True; contexts that cannot thread per-layer rng (the GPipe
    #: stage schedule) refuse them instead of silently running eval-mode
    rng_in_train = False

    def init_cache(self, batch: int, in_shape: tuple):
        """Decode-cache pytree for one-position-at-a-time generation
        (``models.generation``), or None for cache-free layers.
        ``in_shape`` is the layer's input shape INCLUDING the time axis
        (same walk as ``init``); the time extent bounds the cache."""
        return None

    def apply_decode(self, params, state, x, cache, pos):
        """One-token decode step: ``x`` is (B, ...) for position ``pos``
        (no time axis) → ``(y, cache)``.  Default covers time-pointwise
        layers (Dense, LayerNorm, Embedding, activations, MoE FF — their
        ``apply`` treats the time axis elementwise, so per-token input is
        just a batch); time-MIXING layers must override (see
        ``MultiHeadAttention``) — ``models.generation`` enforces this via
        ``time_mixing`` and falls back to full-context recompute."""
        y, _ = self.apply(params, state, x, train=False)
        return y, cache

    def apply_prefill(self, params, state, x, cache):
        """Batched prefill: run the FULL-sequence forward (x has its time
        axis) while filling the decode cache → ``(y, cache)``.  Default
        (cache-free layers) is the ordinary inference apply; caching
        layers override to also record K/V (one batched forward instead
        of per-token prefill steps)."""
        y, _ = self.apply(params, state, x, train=False)
        return y, cache

    def iter_layers(self):
        """Yield this layer and every nested layer (depth-first through
        the composition attributes: ``layers``, ``inner``, ``shortcut``).
        The public way to find/configure layers inside a built model —
        e.g. attaching a mesh to every ``MoEDense``."""
        yield self
        for sub in getattr(self, "layers", None) or []:
            yield from sub.iter_layers()
        for attr in ("inner", "shortcut"):
            sub = getattr(self, attr, None)
            if isinstance(sub, Layer):
                yield from sub.iter_layers()

    # -- config serde -------------------------------------------------------
    def get_config(self) -> dict:
        return {}

    @classmethod
    def from_config(cls, cfg: dict) -> "Layer":
        return cls(**cfg)

    def config(self) -> dict:
        return {"class": type(self).__name__, "config": self.get_config()}

    def __repr__(self):
        args = ", ".join(f"{k}={v!r}" for k, v in self.get_config().items())
        return f"{type(self).__name__}({args})"


# ---------------------------------------------------------------------------
# core layers
# ---------------------------------------------------------------------------

@register
class Dense(Layer):
    def __init__(self, units: int, activation=None, use_bias: bool = True):
        self.units = int(units)
        self.activation = activation
        self.use_bias = use_bias
        self._act = get_activation(activation)

    def init(self, rng, in_shape):
        (d,) = in_shape[-1:]
        kr, _ = jax.random.split(rng)
        params = {"kernel": glorot_uniform(kr, (d, self.units))}
        if self.use_bias:
            params["bias"] = jnp.zeros((self.units,))
        return params, {}, self.out_shape(in_shape)

    def out_shape(self, in_shape):
        return (*in_shape[:-1], self.units)

    def apply(self, params, state, x, *, train=False, rng=None):
        y = x @ params["kernel"].astype(x.dtype)
        if self.use_bias:
            y = y + params["bias"].astype(x.dtype)
        return self._act(y), state

    def get_config(self):
        return {
            "units": self.units,
            "activation": activation_config(self.activation),
            "use_bias": self.use_bias,
        }


@register
class Activation(Layer):
    def __init__(self, activation: str):
        self.activation = activation
        self._act = get_activation(activation)

    def apply(self, params, state, x, *, train=False, rng=None):
        return self._act(x), state

    def get_config(self):
        return {"activation": self.activation}


@register
class Flatten(Layer):
    def out_shape(self, in_shape):
        return (math.prod(in_shape),)

    def apply(self, params, state, x, *, train=False, rng=None):
        return x.reshape(x.shape[0], -1), state


@register
class Reshape(Layer):
    def __init__(self, target_shape: Sequence[int]):
        self.target_shape = tuple(int(s) for s in target_shape)

    def out_shape(self, in_shape):
        return self.target_shape

    def apply(self, params, state, x, *, train=False, rng=None):
        return x.reshape(x.shape[0], *self.target_shape), state

    def get_config(self):
        return {"target_shape": list(self.target_shape)}


@register
class Dropout(Layer):
    rng_in_train = True

    def __init__(self, rate: float):
        self.rate = float(rate)

    def apply(self, params, state, x, *, train=False, rng=None):
        if not train or self.rate == 0.0:
            return x, state
        if rng is None:
            raise ValueError("Dropout needs an rng when train=True")
        keep = 1.0 - self.rate
        mask = jax.random.bernoulli(rng, keep, x.shape)
        return jnp.where(mask, x / keep, 0.0).astype(x.dtype), state

    def get_config(self):
        return {"rate": self.rate}


@register
class Conv2D(Layer):
    """NHWC conv lowering to ``lax.conv_general_dilated`` (MXU-tiled by XLA)."""
    time_mixing = True

    def __init__(self, filters: int, kernel_size, strides=1, padding="SAME",
                 activation=None, use_bias: bool = True):
        self.filters = int(filters)
        self.kernel_size = (kernel_size, kernel_size) if isinstance(kernel_size, int) else tuple(kernel_size)
        self.strides = (strides, strides) if isinstance(strides, int) else tuple(strides)
        self.padding = padding
        self.activation = activation
        self.use_bias = use_bias
        self._act = get_activation(activation)

    def init(self, rng, in_shape):
        h, w, c = in_shape
        kh, kw = self.kernel_size
        params = {"kernel": he_normal(rng, (kh, kw, c, self.filters))}
        if self.use_bias:
            params["bias"] = jnp.zeros((self.filters,))
        return params, {}, self.out_shape(in_shape)

    def out_shape(self, in_shape):
        h, w, c = in_shape
        sh, sw = self.strides
        if self.padding == "SAME":
            oh, ow = -(-h // sh), -(-w // sw)
        else:
            kh, kw = self.kernel_size
            oh, ow = (h - kh) // sh + 1, (w - kw) // sw + 1
        return (oh, ow, self.filters)

    def apply(self, params, state, x, *, train=False, rng=None):
        y = lax.conv_general_dilated(
            x, params["kernel"].astype(x.dtype),
            window_strides=self.strides, padding=self.padding,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        if self.use_bias:
            y = y + params["bias"].astype(x.dtype)
        return self._act(y), state

    def get_config(self):
        return {
            "filters": self.filters, "kernel_size": list(self.kernel_size),
            "strides": list(self.strides), "padding": self.padding,
            "activation": activation_config(self.activation),
            "use_bias": self.use_bias,
        }


class _Pool2D(Layer):
    """Pooling via stacked strided slices instead of ``lax.reduce_window``.

    One static slice per (i, j) window offset (p² slices, e.g. 9 for 3×3),
    reduced with max/mean.  Equivalent math, but differentiable everywhere
    reverse-mode runs — ``reduce_window`` fails to linearize inside
    ``shard_map`` (jax 0.9), which the distributed conv trainers hit —
    and XLA fuses the slices back into one windowed reduction.
    """
    time_mixing = True

    def __init__(self, pool_size=2, strides=None, padding="VALID"):
        self.pool_size = (pool_size, pool_size) if isinstance(pool_size, int) else tuple(pool_size)
        self.strides = self.pool_size if strides is None else (
            (strides, strides) if isinstance(strides, int) else tuple(strides))
        self.padding = padding

    def out_shape(self, in_shape):
        h, w, c = in_shape
        ph, pw = self.pool_size
        sh, sw = self.strides
        if self.padding == "SAME":
            return (-(-h // sh), -(-w // sw), c)
        return ((h - ph) // sh + 1, (w - pw) // sw + 1, c)

    def _pads(self, h, w):
        if self.padding != "SAME":
            return (0, 0), (0, 0)
        ph, pw = self.pool_size
        sh, sw = self.strides
        oh, ow = -(-h // sh), -(-w // sw)
        dh = max(0, (oh - 1) * sh + ph - h)
        dw = max(0, (ow - 1) * sw + pw - w)
        return (dh // 2, dh - dh // 2), (dw // 2, dw - dw // 2)

    def _patches(self, x):
        """(p²,) list of (B, OH, OW, C) strided slices of padded input."""
        _, h, w, _ = x.shape
        ph, pw = self.pool_size
        sh, sw = self.strides
        oh = (h - ph) // sh + 1
        ow = (w - pw) // sw + 1
        return [x[:, i: i + (oh - 1) * sh + 1: sh,
                  j: j + (ow - 1) * sw + 1: sw, :]
                for i in range(ph) for j in range(pw)]

    def get_config(self):
        return {"pool_size": list(self.pool_size), "strides": list(self.strides),
                "padding": self.padding}


@register
class SpaceToDepth(Layer):
    """(H, W, C) → (H/b, W/b, C·b²): each b×b spatial patch becomes one
    pixel's channel stack.  The standard TPU stem transform: a conv on
    tiny-channel inputs (RGB C=3) underfills the MXU's 128 lanes, so the
    stem patchifies first and feeds a stride-1 conv at C·b² channels —
    same downsampling, MXU-shaped contraction (``zoo.resnet50(stem=
    "s2d")``; SURVEY.md §6 perf north star, VERDICT r3 weak #2)."""

    def __init__(self, block_size: int):
        self.block_size = int(block_size)

    def out_shape(self, in_shape):
        h, w, c = in_shape
        b = self.block_size
        if h % b or w % b:
            raise ValueError(f"spatial extent ({h}, {w}) not divisible by "
                             f"block_size {b}")
        return (h // b, w // b, c * b * b)

    def apply(self, params, state, x, *, train=False, rng=None):
        n, h, w, c = x.shape
        b = self.block_size
        x = x.reshape(n, h // b, b, w // b, b, c)
        x = x.transpose(0, 1, 3, 2, 4, 5)  # (N, H/b, W/b, b, b, C)
        return x.reshape(n, h // b, w // b, b * b * c), state

    def get_config(self):
        return {"block_size": self.block_size}


@register
class MaxPool2D(_Pool2D):
    def apply(self, params, state, x, *, train=False, rng=None):
        (pt, pb), (pl, pr) = self._pads(x.shape[1], x.shape[2])
        if pt or pb or pl or pr:
            neg = jnp.asarray(jnp.finfo(x.dtype).min, x.dtype)
            x = jnp.pad(x, ((0, 0), (pt, pb), (pl, pr), (0, 0)),
                        constant_values=neg)
        patches = self._patches(x)
        out = patches[0]
        for p in patches[1:]:
            out = jnp.maximum(out, p)
        return out, state


@register
class AvgPool2D(_Pool2D):
    def apply(self, params, state, x, *, train=False, rng=None):
        (pt, pb), (pl, pr) = self._pads(x.shape[1], x.shape[2])
        if pt or pb or pl or pr:
            # average over valid (unpadded) elements only, like Keras:
            # zero-pad the values, divide by the per-window valid count
            mask = jnp.ones((1, x.shape[1], x.shape[2], 1), x.dtype)
            x = jnp.pad(x, ((0, 0), (pt, pb), (pl, pr), (0, 0)))
            mask = jnp.pad(mask, ((0, 0), (pt, pb), (pl, pr), (0, 0)))
            total = sum(self._patches(x))
            counts = sum(self._patches(mask))
            return total / counts, state
        return sum(self._patches(x)) / math.prod(self.pool_size), state


@register
class GlobalAvgPool2D(Layer):
    time_mixing = True
    def out_shape(self, in_shape):
        return (in_shape[-1],)

    def apply(self, params, state, x, *, train=False, rng=None):
        return jnp.mean(x, axis=(1, 2)), state


@register
class BatchNorm(Layer):
    """Batch normalization with running statistics kept in ``state``.

    During distributed (SPMD) training the batch statistics are per-shard;
    trainers that need cross-replica stats psum them via ``axis_name`` — we
    follow the simpler per-shard convention (matches the reference, where
    each Spark worker batch-norms its own minibatch independently).
    """

    def __init__(self, momentum: float = 0.9, epsilon: float = 1e-5,
                 axis_name: Optional[str] = None):
        self.momentum = float(momentum)
        self.epsilon = float(epsilon)
        self.axis_name = axis_name

    def init(self, rng, in_shape):
        c = in_shape[-1]
        params = {"scale": jnp.ones((c,)), "bias": jnp.zeros((c,))}
        state = {"mean": jnp.zeros((c,)), "var": jnp.ones((c,))}
        return params, state, in_shape

    def apply(self, params, state, x, *, train=False, rng=None):
        reduce_axes = tuple(range(x.ndim - 1))
        if train:
            # SIBLING reduces (mean and mean-of-squares over the same
            # read) fuse into ONE pass over the activations, where
            # jnp.var's (x − mean)² formulation needs a second,
            # dependent pass — one full HBM read saved per BN per step
            # on the conv families (r5 MFU work).  Accumulation is f32
            # even for bf16 activations.
            mean = jnp.mean(x, axis=reduce_axes, dtype=jnp.float32)
            mean2 = jnp.mean(lax.square(x), axis=reduce_axes,
                             dtype=jnp.float32)
            if self.axis_name is not None:
                mean = lax.pmean(mean, self.axis_name)
                mean2 = lax.pmean(mean2, self.axis_name)
            var = jnp.maximum(mean2 - lax.square(mean), 0.0)
            m = self.momentum
            new_state = {"mean": m * state["mean"] + (1 - m) * mean,
                         "var": m * state["var"] + (1 - m) * var}
        else:
            mean, var = state["mean"], state["var"]
            new_state = state
        # per-CHANNEL affine precompute: the (B, H, W, C)-wide loop is
        # y = x·a + b (one fused multiply-add) instead of the 4-op
        # subtract/scale/shift chain
        inv = lax.rsqrt(var.astype(jnp.float32) + self.epsilon)
        a = (inv * params["scale"].astype(jnp.float32)).astype(x.dtype)
        b = (params["bias"].astype(jnp.float32)
             - mean.astype(jnp.float32) * inv
             * params["scale"].astype(jnp.float32)).astype(x.dtype)
        return x * a + b, new_state

    def get_config(self):
        return {"momentum": self.momentum, "epsilon": self.epsilon,
                "axis_name": self.axis_name}


@register
class Embedding(Layer):
    def __init__(self, vocab_size: int, dim: int):
        self.vocab_size = int(vocab_size)
        self.dim = int(dim)

    def init(self, rng, in_shape):
        params = {"table": uniform_scale(rng, (self.vocab_size, self.dim))}
        return params, {}, (*in_shape, self.dim)

    def out_shape(self, in_shape):
        return (*in_shape, self.dim)

    def apply(self, params, state, x, *, train=False, rng=None):
        return jnp.take(params["table"], x.astype(jnp.int32), axis=0), state

    def get_config(self):
        return {"vocab_size": self.vocab_size, "dim": self.dim}


@register
class LSTM(Layer):
    """LSTM over the time axis via ``lax.scan`` (static-shape recurrence).

    Replaces the reference's Keras LSTM layers (IMDB sentiment config in
    BASELINE.json).  Gates are fused into one (in+h, 4h) matmul so each scan
    step is a single MXU-shaped GEMM.
    """
    time_mixing = True

    def __init__(self, units: int, return_sequences: bool = False):
        self.units = int(units)
        self.return_sequences = bool(return_sequences)

    def init(self, rng, in_shape):
        t, d = in_shape
        k1, k2 = jax.random.split(rng)
        h = self.units
        params = {
            "kernel": glorot_uniform(k1, (d, 4 * h)),
            "recurrent": glorot_uniform(k2, (h, 4 * h)),
            "bias": jnp.zeros((4 * h,)).at[h:2 * h].set(1.0),  # forget-gate bias 1
        }
        return params, {}, self.out_shape(in_shape)

    def out_shape(self, in_shape):
        t, d = in_shape
        return (t, self.units) if self.return_sequences else (self.units,)

    def apply(self, params, state, x, *, train=False, rng=None):
        b, t, d = x.shape
        h = self.units
        wk = params["kernel"].astype(x.dtype)
        wr = params["recurrent"].astype(x.dtype)
        bias = params["bias"].astype(x.dtype)
        x_proj = x @ wk + bias  # (b, t, 4h): hoist input projection out of scan

        def step(carry, xp):
            hprev, cprev = carry
            z = xp + hprev @ wr
            i, f, g, o = jnp.split(z, 4, axis=-1)
            c = jax.nn.sigmoid(f) * cprev + jax.nn.sigmoid(i) * jnp.tanh(g)
            hnew = jax.nn.sigmoid(o) * jnp.tanh(c)
            return (hnew, c), hnew

        h0 = jnp.zeros((b, h), x.dtype)
        (hT, _), hs = lax.scan(step, (h0, h0), jnp.swapaxes(x_proj, 0, 1))
        if self.return_sequences:
            return jnp.swapaxes(hs, 0, 1), state
        return hT, state

    def get_config(self):
        return {"units": self.units, "return_sequences": self.return_sequences}


# ---------------------------------------------------------------------------
# composition
# ---------------------------------------------------------------------------

@register
class Residual(Layer):
    """Residual block: ``y = act(inner(x) + shortcut(x))``.

    The combinator the reference never needed (its era's models were plain
    Sequential stacks) but ResNet-20/50 (BASELINE.json configs) require.
    ``shortcut`` defaults to identity; pass a layer (e.g. a 1×1 strided
    Conv2D) when shapes change.  XLA fuses the add into the adjacent convs.
    """

    def __init__(self, inner: "Layer", shortcut: Optional["Layer"] = None,
                 activation=None):
        self.inner = inner
        self.shortcut = shortcut
        self.activation = activation
        self._act = get_activation(activation)

    def init(self, rng, in_shape):
        r1, r2 = jax.random.split(rng)
        p_in, s_in, out_shape = self.inner.init(r1, in_shape)
        params = {"inner": p_in}
        state = {"inner": s_in}
        if self.shortcut is not None:
            p_sc, s_sc, sc_shape = self.shortcut.init(r2, in_shape)
            if tuple(sc_shape) != tuple(out_shape):
                raise ValueError(
                    f"shortcut shape {sc_shape} != inner shape {out_shape}")
            params["shortcut"] = p_sc
            state["shortcut"] = s_sc
        elif tuple(out_shape) != tuple(in_shape):
            raise ValueError(
                f"identity shortcut needs matching shapes, got {in_shape} -> "
                f"{out_shape}; pass a projection shortcut")
        return params, state, out_shape

    def out_shape(self, in_shape):
        return self.inner.out_shape(in_shape)

    def apply(self, params, state, x, *, train=False, rng=None):
        r1 = r2 = None
        if rng is not None:
            r1, r2 = jax.random.split(rng)
        y, new_inner = self.inner.apply(params["inner"], state["inner"], x,
                                        train=train, rng=r1)
        new_state = {"inner": new_inner}
        if self.shortcut is not None:
            sc, new_sc = self.shortcut.apply(params["shortcut"],
                                             state["shortcut"], x,
                                             train=train, rng=r2)
            new_state["shortcut"] = new_sc
        else:
            sc = x
        return self._act(y + sc), new_state

    def init_cache(self, batch, in_shape):
        cache = {"inner": self.inner.init_cache(batch, in_shape)}
        if self.shortcut is not None:
            cache["shortcut"] = self.shortcut.init_cache(batch, in_shape)
        return cache

    def apply_decode(self, params, state, x, cache, pos):
        y, ci = self.inner.apply_decode(params["inner"], state["inner"],
                                        x, cache["inner"], pos)
        new_cache = {"inner": ci}
        if self.shortcut is not None:
            sc, cs = self.shortcut.apply_decode(
                params["shortcut"], state["shortcut"], x,
                cache["shortcut"], pos)
            new_cache["shortcut"] = cs
        else:
            sc = x
        return self._act(y + sc), new_cache

    def apply_prefill(self, params, state, x, cache):
        y, ci = self.inner.apply_prefill(params["inner"], state["inner"],
                                         x, cache["inner"])
        new_cache = {"inner": ci}
        if self.shortcut is not None:
            sc, cs = self.shortcut.apply_prefill(
                params["shortcut"], state["shortcut"], x,
                cache["shortcut"])
            new_cache["shortcut"] = cs
        else:
            sc = x
        return self._act(y + sc), new_cache

    def get_config(self):
        return {"inner": self.inner.config(),
                "shortcut": self.shortcut.config() if self.shortcut else None,
                "activation": activation_config(self.activation)}

    @classmethod
    def from_config(cls, cfg):
        return cls(layer_from_config(cfg["inner"]),
                   layer_from_config(cfg["shortcut"]) if cfg["shortcut"] else None,
                   activation=cfg.get("activation"))


@register
class Sequential(Layer):
    """Keras-Sequential-style composition; the standard model container.

    Parity surface for the reference's use of ``keras.models.Sequential`` in
    its examples (``examples/mnist.ipynb``): same mental model, but lowering
    to one pure jit-able function.
    """

    def __init__(self, layers: Sequence[Layer], input_shape: Optional[Sequence[int]] = None):
        self.layers = list(layers)
        self.input_shape = tuple(input_shape) if input_shape is not None else None

    def init(self, rng, in_shape=None):
        in_shape = tuple(in_shape) if in_shape is not None else self.input_shape
        if in_shape is None:
            raise ValueError("Sequential needs input_shape (constructor or init arg)")
        params, state = [], []
        shape = in_shape
        for lyr in self.layers:
            rng, sub = jax.random.split(rng)
            p, s, shape = lyr.init(sub, shape)
            params.append(p)
            state.append(s)
        return params, state, shape

    def out_shape(self, in_shape):
        shape = tuple(in_shape)
        for lyr in self.layers:
            shape = lyr.out_shape(shape)
        return shape

    def apply(self, params, state, x, *, train=False, rng=None):
        new_state = []
        for i, lyr in enumerate(self.layers):
            sub = None
            if rng is not None:
                rng, sub = jax.random.split(rng)
            x, s = lyr.apply(params[i], state[i], x, train=train, rng=sub)
            new_state.append(s)
        return x, new_state

    def init_cache(self, batch, in_shape):
        caches, shape = [], tuple(in_shape)
        for lyr in self.layers:
            caches.append(lyr.init_cache(batch, shape))
            shape = lyr.out_shape(shape)
        return caches

    def apply_decode(self, params, state, x, cache, pos):
        new_cache = []
        for i, lyr in enumerate(self.layers):
            x, c = lyr.apply_decode(params[i], state[i], x, cache[i], pos)
            new_cache.append(c)
        return x, new_cache

    def apply_prefill(self, params, state, x, cache):
        new_cache = []
        for i, lyr in enumerate(self.layers):
            x, c = lyr.apply_prefill(params[i], state[i], x, cache[i])
            new_cache.append(c)
        return x, new_cache

    def get_config(self):
        return {"layers": [l.config() for l in self.layers],
                "input_shape": list(self.input_shape) if self.input_shape else None}

    @classmethod
    def from_config(cls, cfg):
        return cls([layer_from_config(c) for c in cfg["layers"]],
                   input_shape=cfg.get("input_shape"))

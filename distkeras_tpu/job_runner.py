"""Job-package executor: ``python -m distkeras_tpu.job_runner PKG OUT``.

The remote half of ``job_deployment`` (the reference's ``spark-submit``\\ ed
script): load the package, rebuild model + trainer + dataset, train, write
the trained model blob (+ history) to OUT.
"""

from __future__ import annotations

import json
import sys

import numpy as np

from . import trainers as trainers_mod
from .data import datasets as datasets_mod
from .data.dataset import Dataset
from .obs import emit
from .utils import serde


def _load_dataset(spec: dict) -> Dataset:
    if "loader" in spec:
        loader = getattr(datasets_mod, spec["loader"])
        train, _test, _meta = loader(**spec.get("kwargs", {}))
        return train
    if "npz" in spec:
        with np.load(spec["npz"]) as d:
            return Dataset({k: d[k] for k in d.files})
    raise ValueError(f"unrecognized dataset spec {spec!r}")


def run_package(pkg_path: str, out_path: str) -> None:
    with open(pkg_path, "rb") as f:
        pkg = serde.tree_from_bytes(f.read())

    # serde's dispatch: native Model configs AND ingested KerasAdapter
    # configs both rebuild correctly
    model = serde.model_from_config(json.loads(pkg["model_config"]))
    cls = getattr(trainers_mod, pkg["trainer"]["class"])
    trainer = cls(model, **pkg["trainer"].get("kwargs", {}))
    ds = _load_dataset(pkg["dataset"])
    trained = trainer.train(ds, shuffle=pkg.get("shuffle", False))
    if isinstance(trained, list):  # EnsembleTrainer returns a list
        trained = trained[0]

    payload = {
        "model": serde.serialize_model(trained, trained.variables),
        "history": [np.asarray(h) for h in trainer.get_history()],
        "training_time": trainer.get_training_time(),
    }
    with open(out_path, "wb") as f:
        f.write(serde.tree_to_bytes(payload))


def main(argv=None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    if len(argv) != 2:
        emit("usage: python -m distkeras_tpu.job_runner PKG OUT", err=True)
        return 2
    run_package(argv[0], argv[1])
    return 0


if __name__ == "__main__":
    sys.exit(main())

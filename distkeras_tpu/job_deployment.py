"""Remote job deployment — parity with reference ``distkeras/job_deployment.py``.

The reference (experimental) packages a training job, copies it to a Spark
cluster's head node over SSH, ``spark-submit``\\ s it, and fetches the
trained model back; a ``Punchcard`` file holds the credentials.  TPU-native
equivalent: the job package is a msgpack blob (model config + trainer spec
+ dataset spec), executed by ``python -m distkeras_tpu.job_runner`` on the
target host (a TPU VM) via ssh/scp, and the trained model blob is fetched
back.  ``host=None`` runs the same package in a local subprocess — the
test story, and the moral equivalent of Spark ``local[*]``.
"""

from __future__ import annotations

import json
import os
import shlex
import subprocess
import sys
import tempfile
from typing import Optional

from .models.model import Model
from .utils import serde


class Punchcard:
    """Credentials/targets file (parity: reference ``Punchcard``): JSON with
    ``host``, ``username``, ``key_file`` (optional), ``remote_dir``
    (optional), ``python`` (optional remote interpreter)."""

    def __init__(self, path: str):
        with open(path) as f:
            d = json.load(f)
        self.host: Optional[str] = d.get("host")
        self.username: Optional[str] = d.get("username")
        self.key_file: Optional[str] = d.get("key_file")
        self.remote_dir: str = d.get("remote_dir", "/tmp")
        self.python: str = d.get("python", "python3")

    @property
    def target(self) -> str:
        return f"{self.username}@{self.host}" if self.username else self.host


class Job:
    """A packaged training job (parity: reference ``Job``).

    ``trainer_spec``: ``{"class": "ADAG", "kwargs": {...}}`` — any trainer
    from ``distkeras_tpu.trainers``.  ``dataset_spec``: either
    ``{"loader": "load_mnist", "kwargs": {...}}`` (a
    ``distkeras_tpu.data.datasets`` loader) or ``{"npz": path,
    "features_col": ..., "label_col": ...}``.
    """

    def __init__(self, job_name: str, model: Model, trainer_spec: dict,
                 dataset_spec: dict, punchcard: Optional[Punchcard] = None,
                 shuffle: bool = False):
        self.job_name = job_name
        self.model = model
        self.trainer_spec = trainer_spec
        self.dataset_spec = dataset_spec
        self.punchcard = punchcard
        self.shuffle = shuffle
        self.result_model: Optional[Model] = None
        self.result_history = None

    # -- packaging ----------------------------------------------------------
    def package(self) -> bytes:
        return serde.tree_to_bytes({
            "job_name": self.job_name,
            "model_config": json.dumps(self.model.config()),
            "trainer": self.trainer_spec,
            "dataset": self.dataset_spec,
            "shuffle": bool(self.shuffle),
        })

    # -- execution ----------------------------------------------------------
    def run(self, timeout: Optional[float] = 3600) -> Model:
        """Ship, execute, fetch.  Returns the trained Model (also kept on
        ``self.result_model``)."""
        with tempfile.TemporaryDirectory() as td:
            pkg = os.path.join(td, f"{self.job_name}.job")
            out = os.path.join(td, f"{self.job_name}.result")
            with open(pkg, "wb") as f:
                f.write(self.package())
            if self.punchcard is None or self.punchcard.host is None:
                self._run_local(pkg, out, timeout)
            else:
                self._run_ssh(pkg, out, timeout)
            with open(out, "rb") as f:
                payload = serde.tree_from_bytes(f.read())
        model, variables = serde.deserialize_model(payload["model"])
        model.variables = variables
        self.result_model = model
        self.result_history = payload.get("history")
        return model

    def _run_local(self, pkg: str, out: str, timeout) -> None:
        env = dict(os.environ)
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env["PYTHONPATH"] = root + os.pathsep + env.get("PYTHONPATH", "")
        subprocess.run(
            [sys.executable, "-m", "distkeras_tpu.job_runner", pkg, out],
            check=True, timeout=timeout, env=env)

    def _run_ssh(self, pkg: str, out: str, timeout) -> None:
        pc = self.punchcard
        ssh_base = ["ssh"]
        scp_base = ["scp"]
        if pc.key_file:
            ssh_base += ["-i", pc.key_file]
            scp_base += ["-i", pc.key_file]
        rdir = pc.remote_dir.rstrip("/")
        rpkg = f"{rdir}/{os.path.basename(pkg)}"
        rout = f"{rdir}/{os.path.basename(out)}"
        subprocess.run([*scp_base, pkg, f"{pc.target}:{rpkg}"],
                       check=True, timeout=timeout)
        remote_cmd = " ".join([
            shlex.quote(pc.python), "-m", "distkeras_tpu.job_runner",
            shlex.quote(rpkg), shlex.quote(rout)])
        subprocess.run([*ssh_base, pc.target, remote_cmd],
                       check=True, timeout=timeout)
        subprocess.run([*scp_base, f"{pc.target}:{rout}", out],
                       check=True, timeout=timeout)

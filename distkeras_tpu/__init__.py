"""dist-keras-tpu: TPU-native distributed training framework.

A ground-up JAX/XLA re-design of the capabilities of dist-keras
(feihugis/dist-keras, fork of cerndb/dist-keras): the same trainer /
transformer / predictor / evaluator API surface, with Spark + socket
parameter servers replaced by SPMD collectives over a TPU mesh (sync path)
and a host-side asynchronous parameter server (async-parity path).
"""

__version__ = "0.1.0"

from . import continual, data, models, obs, ops, parallel, serve, utils
from .data import Dataset
from .models import Model, Sequential, generate_beam, generate_tokens
from .trainers import (
    ADAG,
    AEASGD,
    DOWNPOUR,
    AveragingTrainer,
    DistributedTrainer,
    DynSGD,
    EAMSGD,
    EnsembleTrainer,
    PipelineTrainer,
    SingleTrainer,
    SpmdTrainer,
    Trainer,
)
from .predictors import ModelPredictor, Predictor
from .serve import DecodeEngine, ServeClient, ServeConfig, ServeServer
from .continual import ContinualConfig, ContinualTrainer, DeployGate
from .evaluators import AccuracyEvaluator, Evaluator, F1Evaluator, LossEvaluator
from .job_deployment import Job, Punchcard
from .models import zoo
from .data import datasets
from .utils.checkpoint import CheckpointManager
from .utils.metrics import MetricsLogger, profile_trace

"""Evaluation — parity with reference ``distkeras/evaluators.py``.

The reference evaluates predicted DataFrames on the driver (and its
notebooks also use Spark's ``MulticlassClassificationEvaluator``).  Ours
are vectorized NumPy/JAX reductions over Dataset columns with the same
``.evaluate(ds) -> float`` surface.
"""

from __future__ import annotations

import numpy as np

from .data.dataset import Dataset


class Evaluator:
    """Base evaluator (reference ``distkeras/evaluators.py:Evaluator``).

    ``prediction_kind`` / ``label_kind`` disambiguate what the columns
    hold: ``"auto"`` (default — infer, see ``_to_class_index``),
    ``"ids"`` (class indices, any shape), ``"onehot"`` (one-hot or
    probability vectors, argmaxed on the last axis).  Pass an explicit
    kind when auto-inference is ambiguous — e.g. integer (B, T) per-token
    targets over a binary vocabulary, which value-based inference could
    misread as one-hot rows (ADVICE r3).  Integer one-hot labels with
    3+ columns and 8+ rows are read as one-hot silently (the
    per-token-ids reading would need every row of the eval set to hold
    exactly one 1-token — not a plausible coincidence at that size);
    only genuinely ambiguous shapes warn: 2-column arrays (``[0, 1]``
    rows are equally consistent with 2-class one-hot and 2-token binary
    ids) and tiny eval sets.  Pass ``label_kind='onehot'`` (or
    ``'ids'``) to state which reading applies and silence the
    warning."""

    def __init__(self, prediction_col: str = "prediction",
                 label_col: str = "label", prediction_kind: str = "auto",
                 label_kind: str = "auto"):
        self.prediction_col = prediction_col
        self.label_col = label_col
        for kind in (prediction_kind, label_kind):
            if kind not in ("auto", "ids", "onehot"):
                raise ValueError(
                    f"kind must be auto|ids|onehot, got {kind!r}")
        self.prediction_kind = prediction_kind
        self.label_kind = label_kind

    def evaluate(self, dataset: Dataset) -> float:
        raise NotImplementedError


def _to_class_index(a: np.ndarray, threshold: float = 0.5,
                    kind: str = "auto") -> np.ndarray:
    """Accept class indices (any shape — (B,) classifiers or (B, T)
    per-token LM targets), one-hot/probability vectors (argmaxed on the
    last axis), or (for the binary 1-column case) sigmoid probabilities
    thresholded at 0.5.  ``kind`` overrides the inference ("ids" /
    "onehot"); integer one-hot auto-detection is restricted to 2-D
    arrays, so (B, T, V) integer targets need the explicit kind."""
    a = np.asarray(a)
    if kind == "onehot":
        return np.argmax(a, axis=-1)
    if kind == "ids":
        if a.ndim >= 2 and a.shape[-1] == 1:
            a = a[..., 0]
        return a.astype(np.int64)
    if np.issubdtype(a.dtype, np.integer) or a.dtype == bool:
        if a.ndim >= 2 and a.shape[-1] == 1:
            a = a[..., 0]
        if a.ndim == 2 and a.shape[-1] > 1 and a.min() >= 0 \
                and a.max() <= 1 and np.all(a.sum(axis=-1) == 1):
            # every row holds exactly one 1: one-hot rows.  The competing
            # reading — (B, T) per-token ids over a binary vocabulary —
            # would require every row of the eval set to coincidentally
            # hold exactly one 1-token: at C >= 3 columns and B >= 8 rows
            # that chance is < (3/8)^8 ≈ 4e-4, so legitimate one-hot
            # evals read silently (ISSUE 4 satellite; ADVICE r4 warned on
            # all of them).  Genuinely ambiguous shapes still warn:
            # 2-column rows ([0, 1] reads both ways at ANY size) and
            # too-few-row arrays (the signature is weak evidence).
            if a.shape[-1] == 2 or a.shape[0] < 8:
                import warnings
                warnings.warn(
                    f"auto kind read a {a.shape} integer array whose rows "
                    "sum to 1 as one-hot rows and argmaxed it, but this "
                    "shape is also consistent with (B, T) per-token class "
                    "ids over a binary vocabulary; pass prediction_kind/"
                    "label_kind='ids' if the column holds per-token ids, "
                    "or 'onehot' to confirm one-hot rows and silence this "
                    "warning", stacklevel=3)
            return np.argmax(a, axis=-1)  # integer one-hot rows
        return a.astype(np.int64)         # class ids, (B,) or (B, T)
    if a.ndim >= 2 and a.shape[-1] > 1:
        return np.argmax(a, axis=-1)
    flat = a.reshape(a.shape[0])
    if np.issubdtype(flat.dtype, np.floating) and flat.size and \
            not np.all(flat == np.round(flat)):
        return (flat >= threshold).astype(np.int64)
    return flat.astype(np.int64)


class AccuracyEvaluator(Evaluator):
    """Classification accuracy.  Both columns may hold class indices,
    one-hot labels, or probability vectors (the reference pipeline first
    runs ``LabelIndexTransformer``; we accept raw vectors too)."""

    def evaluate(self, dataset: Dataset) -> float:
        pred = _to_class_index(dataset[self.prediction_col],
                               kind=self.prediction_kind)
        label = _to_class_index(dataset[self.label_col],
                                kind=self.label_kind)
        return float(np.mean(pred == label))


class F1Evaluator(Evaluator):
    """Macro-averaged F1 (the reference notebooks report Spark's F1 metric
    via ``MulticlassClassificationEvaluator``)."""

    def evaluate(self, dataset: Dataset) -> float:
        pred = _to_class_index(dataset[self.prediction_col],
                               kind=self.prediction_kind)
        label = _to_class_index(dataset[self.label_col],
                                kind=self.label_kind)
        classes = np.unique(np.concatenate([pred, label]))
        f1s = []
        for c in classes:
            tp = np.sum((pred == c) & (label == c))
            fp = np.sum((pred == c) & (label != c))
            fn = np.sum((pred != c) & (label == c))
            denom = 2 * tp + fp + fn
            f1s.append(2 * tp / denom if denom else 0.0)
        return float(np.mean(f1s))


class LossEvaluator(Evaluator):
    """Mean of a loss function over prediction/label columns.

    ``outputs`` says what the prediction column holds: ``"probs"`` (the
    default — ``ModelPredictor`` on the reference-style softmax-ending
    models yields probabilities) resolves crossentropy names to the on-probs
    variants; ``"logits"`` uses the logit forms.
    """

    def __init__(self, loss="categorical_crossentropy",
                 prediction_col: str = "prediction", label_col: str = "label",
                 outputs: str = "probs"):
        super().__init__(prediction_col, label_col)
        from .ops.losses import get_loss, probs_loss_variant
        self.loss_fn = None
        if outputs == "probs" and isinstance(loss, str):
            self.loss_fn = probs_loss_variant(loss)
        if self.loss_fn is None:
            self.loss_fn = get_loss(loss)

    def evaluate(self, dataset: Dataset) -> float:
        import jax.numpy as jnp
        pred = jnp.asarray(dataset[self.prediction_col])
        label = jnp.asarray(dataset[self.label_col])
        return float(self.loss_fn(pred, label))

"""Metrics / logging / profiling — SURVEY.md §5.1 + §5.5.

The reference's observability is wall-clock + per-worker loss history plus
Spark's web UI.  Ours: a structured JSONL metrics sink (stdout or file),
trainer-emitted per-epoch records (loss, samples/sec, epoch seconds), and
a ``jax.profiler`` trace context for TensorBoard/Perfetto captures.
"""

from __future__ import annotations

import collections
import contextlib
import json
import sys
import time
from typing import IO, Optional, Union


class MetricsLogger:
    """Append-only JSONL metrics sink.

    ``MetricsLogger("train.jsonl")`` or ``MetricsLogger(sys.stdout)``;
    ``log(event, **fields)`` writes one line with a wall-clock timestamp.
    The most recent ``keep_records`` records are also kept in ``.records``
    so callers (benchmarks, notebooks) can read trainer-emitted metrics
    back without parsing the sink; the cap keeps memory bounded even if a
    long-lived service logs per-step events (the sink, if any, still gets
    every record).
    """

    def __init__(self, sink: Union[str, IO, None] = None,
                 keep_records: int = 100_000):
        self._own = False
        self.records: collections.deque = collections.deque(
            maxlen=keep_records)
        if sink is None:
            self._fh = None
        elif isinstance(sink, str):
            self._fh = open(sink, "a", buffering=1)
            self._own = True
        else:
            self._fh = sink

    def log(self, event: str, **fields) -> dict:
        rec = {"ts": time.time(), "event": event, **fields}
        self.records.append(rec)
        if self._fh is not None:
            self._fh.write(json.dumps(rec, default=float) + "\n")
        return rec

    def close(self) -> None:
        if self._own and self._fh is not None:
            self._fh.close()
            self._fh = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


@contextlib.contextmanager
def profile_trace(log_dir: str):
    """Capture a ``jax.profiler`` trace (open with TensorBoard/Perfetto).

    TPU equivalent of the reference leaning on the Spark UI for task
    timing: wrap any training region::

        with profile_trace("/tmp/trace"):
            trainer.train(ds)
    """
    import jax
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class StepTimer:
    """Honest step timing: ``mark()`` between steps; ``rate(samples)``
    reports samples/sec.  Callers are responsible for a hard sync (e.g. a
    scalar readback) before ``mark`` — see bench.py's methodology note."""

    def __init__(self):
        self.t0 = time.perf_counter()
        self.laps: list = []

    def mark(self) -> float:
        t = time.perf_counter()
        lap = t - self.t0
        self.t0 = t
        self.laps.append(lap)
        return lap

    def rate(self, samples_per_lap: int) -> float:
        if not self.laps:
            return 0.0
        return samples_per_lap * len(self.laps) / sum(self.laps)

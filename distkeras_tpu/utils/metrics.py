"""Metrics / logging / profiling — SURVEY.md §5.1 + §5.5.

The reference's observability is wall-clock + per-worker loss history plus
Spark's web UI.  Ours: a structured JSONL metrics sink (stdout or file),
trainer-emitted per-epoch records (loss, samples/sec, epoch seconds), and
a ``jax.profiler`` trace context for TensorBoard/Perfetto captures.
"""

from __future__ import annotations

import collections
import contextlib
import json
import math
import sys
import threading
import time
from typing import IO, Optional, Union

import numpy as np

from ..obs.logging import get_logger

#: arrays at or below this many elements serialize as nested lists; larger
#: ones as a shape/dtype/stats summary (a logged metric should never drag
#: megabytes of weights into the JSONL stream)
_ARRAY_INLINE_MAX = 64


def json_safe(x):
    """Coerce a logged value into strictly-valid JSON data.

    ``json.dumps(default=float)`` raised on ``np.ndarray`` and emitted bare
    ``NaN``/``Infinity`` tokens (invalid JSON — downstream parsers choke).
    Rules: ndarrays become nested lists (small) or a summary dict (large);
    numpy scalars become Python scalars; non-finite floats become the
    strings ``"NaN"`` / ``"Infinity"`` / ``"-Infinity"``; anything exotic
    falls back through ``np.asarray`` and finally ``str``.
    """
    if x is None or isinstance(x, (bool, int, str)):
        return x
    if isinstance(x, float):
        if math.isfinite(x):
            return x
        if math.isnan(x):
            return "NaN"
        return "Infinity" if x > 0 else "-Infinity"
    if isinstance(x, dict):
        return {str(k): json_safe(v) for k, v in x.items()}
    if isinstance(x, (list, tuple)):
        return [json_safe(v) for v in x]
    if isinstance(x, np.integer):
        return int(x)
    if isinstance(x, np.floating):
        return json_safe(float(x))
    if isinstance(x, np.bool_):
        return bool(x)
    if isinstance(x, np.ndarray):
        if x.dtype == object:
            return str(x)
        if x.size <= _ARRAY_INLINE_MAX:
            return json_safe(x.tolist())
        out = {"shape": list(x.shape), "dtype": str(x.dtype)}
        if x.size and np.issubdtype(x.dtype, np.number):
            xf = np.asarray(x, dtype=np.float64)
            out.update(mean=json_safe(float(xf.mean())),
                       min=json_safe(float(xf.min())),
                       max=json_safe(float(xf.max())))
        return out
    try:  # jax.Array and friends expose __array__
        return json_safe(np.asarray(x))
    except (TypeError, ValueError, RuntimeError) as e:
        # the swallowed catch-all here turned serialization bugs into
        # silent "<object repr>" strings in the metrics stream (dklint
        # swallow-guard); narrow types + a warning keep the fallback
        # without hiding the cause
        get_logger("utils.metrics").warning(
            "json_safe: %s is not array-coercible (%s); logging str()",
            type(x).__name__, e)
        return str(x)


class MetricsLogger:
    """Append-only JSONL metrics sink.

    ``MetricsLogger("train.jsonl")`` or ``MetricsLogger(sys.stdout)``;
    ``log(event, **fields)`` writes one line with a wall-clock timestamp.
    The most recent ``keep_records`` records are also kept in ``.records``
    so callers (benchmarks, notebooks) can read trainer-emitted metrics
    back without parsing the sink; the cap keeps memory bounded even if a
    long-lived service logs per-step events (the sink, if any, still gets
    every record).
    """

    def __init__(self, sink: Union[str, IO, None] = None,
                 keep_records: int = 100_000):
        self._own = False
        self.records: collections.deque = collections.deque(
            maxlen=keep_records)
        #: async workers heartbeat from their own threads; one lock keeps
        #: JSONL lines whole (interleaved writes would corrupt the stream)
        self._lock = threading.Lock()
        if sink is None:
            self._fh = None
        elif isinstance(sink, str):
            self._fh = open(sink, "a", buffering=1)
            self._own = True
        else:
            self._fh = sink

    def log(self, event: str, **fields) -> dict:
        rec = {"ts": time.time(), "event": event, **fields}
        # raw values stay in .records (benchmarks read them back without a
        # parse round-trip); only the serialized line is coerced
        line = None
        if self._fh is not None:
            line = json.dumps(json_safe(rec), allow_nan=False) + "\n"
        with self._lock:
            self.records.append(rec)
            # re-check under the lock: a concurrent close() may have
            # retired the sink after the serialization check above
            if line is not None and self._fh is not None:
                self._fh.write(line)
        return rec

    def close(self) -> None:
        # under the write lock: a concurrent log() must never observe a
        # half-closed sink (close raced unsynchronized before — dklint)
        with self._lock:
            if self._own and self._fh is not None:
                self._fh.close()
                self._fh = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


@contextlib.contextmanager
def profile_trace(log_dir: str):
    """Capture a ``jax.profiler`` trace (open with TensorBoard/Perfetto).

    TPU equivalent of the reference leaning on the Spark UI for task
    timing: wrap any training region::

        with profile_trace("/tmp/trace"):
            trainer.train(ds)

    Thin alias for ``obs.profile.device_trace`` (ISSUE 6) — the one
    sanctioned start/stop seam: the output dir is announced once via
    ``obs.logging`` and the trace session can no longer leak open on
    exception paths (this helper used to own a bare start/stop pair that
    did exactly that when ``stop_trace`` failed during unwind)."""
    from ..obs.profile import device_trace
    with device_trace(log_dir):
        yield


class StepTimer:
    """Honest step timing: ``mark()`` between steps; ``rate(samples)``
    reports samples/sec.  Callers are responsible for a hard sync (e.g. a
    scalar readback) before ``mark`` — see bench.py's methodology note."""

    def __init__(self):
        self.t0 = time.perf_counter()
        self.laps: list = []

    def mark(self) -> float:
        t = time.perf_counter()
        lap = t - self.t0
        self.t0 = t
        self.laps.append(lap)
        return lap

    def rate(self, samples_per_lap: int) -> float:
        if not self.laps:
            return 0.0
        return samples_per_lap * len(self.laps) / sum(self.laps)

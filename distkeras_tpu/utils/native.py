"""ctypes bindings for the native host data plane (``native/dknative.cpp``).

Loads (building on first use, g++) ``libdknative.so`` and exposes:

* ``fused_add(a, b, scale)``   — ``a + scale·b`` in one multithreaded pass
  (the PS commit rule; ctypes releases the GIL for the duration).
* ``axpy_inplace(dst, src, scale)`` — in-place variant.
* ``parse_csv(path)``          — multithreaded CSV → float32 array.

Every entry point has a NumPy fallback, so the framework works without a
toolchain; ``available()`` reports which path is active.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libdknative.so")

_lib = None
_tried = False
_lock = threading.Lock()


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        try:
            if not os.path.exists(_LIB_PATH):
                subprocess.run(["make", "-C", _NATIVE_DIR], check=True,
                               capture_output=True, timeout=120)
            lib = ctypes.CDLL(_LIB_PATH)
            lib.dk_fused_add_f32.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_float, ctypes.c_size_t, ctypes.c_int]
            lib.dk_axpy_inplace_f32.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_float,
                ctypes.c_size_t, ctypes.c_int]
            lib.dk_fused_add_f64.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_double, ctypes.c_size_t, ctypes.c_int]
            lib.dk_parse_csv_f32.argtypes = [
                ctypes.c_char_p, ctypes.c_size_t, ctypes.c_void_p,
                ctypes.c_size_t, ctypes.c_int]
            lib.dk_parse_csv_f32.restype = ctypes.c_size_t
            assert lib.dk_version() == 1
            _lib = lib
        except (OSError, subprocess.SubprocessError, AssertionError):
            _lib = None
    return _lib


def available() -> bool:
    return _load() is not None


def fused_add(a: np.ndarray, b: np.ndarray, scale: float = 1.0,
              nthreads: int = 0) -> np.ndarray:
    """``a + scale·b`` — fused native pass when possible, NumPy otherwise.

    Always returns a NEW array (replace semantics: safe for the PS's
    lock-free pull snapshots)."""
    lib = _load()
    if (lib is None or a.dtype != b.dtype or a.shape != b.shape
            or a.dtype not in (np.float32, np.float64)):
        return (a + np.asarray(b, a.dtype) * scale).astype(a.dtype, copy=False)
    a = np.ascontiguousarray(a)
    b = np.ascontiguousarray(b)
    out = np.empty_like(a)
    fn = (lib.dk_fused_add_f32 if a.dtype == np.float32
          else lib.dk_fused_add_f64)
    fn(out.ctypes.data, a.ctypes.data, b.ctypes.data, scale, a.size, nthreads)
    return out


def axpy_inplace(dst: np.ndarray, src: np.ndarray, scale: float = 1.0,
                 nthreads: int = 0) -> None:
    """``dst += scale·src`` in place (dst must be writable f32)."""
    lib = _load()
    if (lib is None or dst.dtype != np.float32 or src.dtype != np.float32
            or not dst.flags.writeable or not dst.flags.c_contiguous):
        dst += np.asarray(src, dst.dtype) * scale
        return
    src = np.ascontiguousarray(src)
    lib.dk_axpy_inplace_f32(dst.ctypes.data, src.ctypes.data, scale,
                            dst.size, nthreads)


def parse_csv(path: str, nthreads: int = 0) -> np.ndarray:
    """All numeric values in a CSV file as one float32 vector (caller
    reshapes).  Native multithreaded parse, NumPy fallback."""
    with open(path, "rb") as f:
        buf = f.read()
    lib = _load()
    if lib is None:
        # fallback with the SAME token semantics as the native parser:
        # split on , \n \r space \t; keep numeric-start tokens only
        import re
        vals = []
        for tok in re.split(rb"[,\r\n \t]+", buf):
            if tok and (tok[0:1].isdigit() or tok[0:1] in (b"-", b"+", b".")):
                try:
                    vals.append(float(tok))
                except ValueError:
                    # strtof semantics: parse the leading numeric prefix
                    m = re.match(rb"[-+.]?[0-9]*\.?[0-9]*(?:[eE][-+]?[0-9]+)?",
                                 tok)
                    if m and m.group():
                        try:
                            vals.append(float(m.group()))
                        except ValueError:
                            pass
        return np.asarray(vals, dtype=np.float32)
    # upper bound on value count: one per separator byte + 1
    max_vals = sum(buf.count(s) for s in (b",", b"\n", b"\r", b" ", b"\t")) + 2
    out = np.empty(max_vals, np.float32)
    n = lib.dk_parse_csv_f32(buf, len(buf), out.ctypes.data, max_vals, nthreads)
    return out[:n].copy()

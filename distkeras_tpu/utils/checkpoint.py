"""Checkpoint / resume — the subsystem the reference lacks (SURVEY.md §5.4).

The reference's only persistence is the final in-memory model; its
serialize/deserialize pair is the de-facto format.  We provide real
mid-training checkpointing: pytree leaves in our msgpack ndarray encoding
(``utils.serde``), written atomically (tmp + rename), with a rolling-keep
manager.  Restore unflattens into the structure of a caller-supplied
reference tree (``like``) so arbitrary optax opt-states — NamedTuple
chains msgpack can't represent — round-trip losslessly.
"""

from __future__ import annotations

import os
import re
import tempfile
from typing import Any, Optional

import jax
import numpy as np

from . import serde

Tree = Any


def _leaf_to_host(x):
    """Device leaf → host numpy.  Leaves sharded over a multi-PROCESS
    mesh (jax.distributed) cannot be read directly; allgather them so
    every process checkpoints the complete tree (same bytes everywhere —
    the atomic rename makes concurrent writers to a shared dir benign,
    and per-host dirs on a real pod don't collide at all)."""
    if isinstance(x, jax.Array) and not x.is_fully_addressable:
        from jax.experimental import multihost_utils
        return np.asarray(multihost_utils.process_allgather(x, tiled=True))
    return np.asarray(x)


def save_tree(path: str, tree: Tree, meta: Optional[dict] = None) -> None:
    """Atomically write ``tree``'s leaves (+ JSON-able ``meta``);
    multi-host aware (see ``_leaf_to_host``)."""
    leaves = [_leaf_to_host(x) for x in jax.tree_util.tree_leaves(tree)]
    blob = serde.tree_to_bytes({"leaves": leaves, "meta": meta or {}})
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(blob)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def load_tree(path: str, like: Tree) -> tuple:
    """Returns ``(tree, meta)`` with ``tree`` shaped like ``like``."""
    with open(path, "rb") as f:
        payload = serde.tree_from_bytes(f.read())
    treedef = jax.tree_util.tree_structure(like)
    ref_leaves = jax.tree_util.tree_leaves(like)
    leaves = payload["leaves"]
    if len(leaves) != len(ref_leaves):
        raise ValueError(
            f"checkpoint has {len(leaves)} leaves, reference tree has "
            f"{len(ref_leaves)} — structure mismatch")
    return jax.tree_util.tree_unflatten(treedef, leaves), payload["meta"]


class CheckpointManager:
    """Rolling checkpoints ``step-N.ckpt`` under a directory, keep last K."""

    _PAT = re.compile(r"^step-(\d+)\.ckpt$")

    def __init__(self, directory: str, keep: int = 3):
        self.directory = directory
        self.keep = int(keep)
        os.makedirs(directory, exist_ok=True)

    def _path(self, step: int) -> str:
        return os.path.join(self.directory, f"step-{step}.ckpt")

    def steps(self) -> list:
        out = []
        for name in os.listdir(self.directory):
            m = self._PAT.match(name)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    def latest_step(self) -> Optional[int]:
        s = self.steps()
        return s[-1] if s else None

    def save(self, step: int, tree: Tree, meta: Optional[dict] = None) -> str:
        meta = dict(meta or {})
        meta["step"] = int(step)
        path = self._path(step)
        save_tree(path, tree, meta)
        for old in self.steps()[: -self.keep]:
            try:
                os.unlink(self._path(old))
            except OSError:
                pass
        return path

    def restore(self, like: Tree, step: Optional[int] = None) -> tuple:
        """Returns ``(tree, meta)`` from ``step`` (default: latest)."""
        if step is None:
            step = self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints in {self.directory}")
        return load_tree(self._path(step), like)

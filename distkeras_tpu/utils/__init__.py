"""Utility surface — parity with reference ``distkeras/utils.py``."""

from __future__ import annotations

import numpy as np
import jax

from .serde import (tree_to_bytes, tree_from_bytes, serialize_model,
                    deserialize_model)

# Reference-parity aliases (``distkeras/utils.py:serialize_keras_model``).
serialize_keras_model = serialize_model
deserialize_keras_model = deserialize_model


def shuffle(dataset, seed=None):
    """Parity: ``distkeras/utils.py:shuffle(df)``."""
    return dataset.shuffle(seed)


def to_dense_vector(label, output_dim: int) -> np.ndarray:
    """Parity: ``distkeras/utils.py:to_dense_vector`` (one-hot a label)."""
    label, output_dim = int(label), int(output_dim)
    if not 0 <= label < output_dim:
        raise ValueError(f"label {label} out of range [0, {output_dim})")
    v = np.zeros((output_dim,), dtype=np.float32)
    v[label] = 1.0
    return v


def new_dataset_row(row: dict, col: str, value) -> dict:
    """Parity: ``distkeras/utils.py:new_dataframe_row`` (append a column)."""
    out = dict(row)
    out[col] = value
    return out


new_dataframe_row = new_dataset_row


def uniform_weights(variables: dict, seed: int = 0, bound: float = 0.05) -> dict:
    """Re-initialize every param uniformly in [-bound, bound].

    Parity: ``distkeras/utils.py:uniform_weights`` (used to decorrelate
    ensemble members).
    """
    leaves, treedef = jax.tree_util.tree_flatten(variables["params"])
    rng = jax.random.PRNGKey(seed)
    keys = jax.random.split(rng, len(leaves))
    new = [jax.random.uniform(k, l.shape, l.dtype, -bound, bound)
           for k, l in zip(keys, leaves)]
    return {"params": jax.tree_util.tree_unflatten(treedef, new),
            "state": variables["state"]}


def history_average(history: list) -> float:
    """Average a loss history list (parity helper for the workflow plots)."""
    if not history:
        return float("nan")
    return float(np.mean([h["loss"] if isinstance(h, dict) else h for h in history]))

"""Pytree / model serialization.

TPU-native replacement for the reference's pickle-based serde
(``distkeras/utils.py:serialize_keras_model`` — architecture JSON + list of
weight ndarrays — and ``distkeras/networking.py:send_data/recv_data`` which
pickle arbitrary objects).  We use msgpack with an explicit, versioned
ndarray encoding instead of pickle: safe to use as a wire format for the
async parameter server and as the checkpoint format.

Two encodings share the ndarray leaf convention:

* **v1, inline** (``tree_to_bytes``/``tree_from_bytes``): one
  self-contained msgpack blob; every tensor's bytes are copied into it
  via ``tobytes()``.  The checkpoint/model-blob format, and the
  compatibility wire format.
* **v2, framed** (``tree_to_frames``/``tree_from_frames``): the msgpack
  header holds only dtype/shape/segment-index stubs and the tensor bytes
  travel as out-of-band **segments** — zero-copy ``memoryview``s of the
  arrays' own buffers, handed to ``socket.sendmsg`` scatter-gather by
  ``ps.networking``.  The PS hot-path wire format (ISSUE 4): a pull or
  commit never copies its tensors into an intermediate blob.
"""

from __future__ import annotations

import json
from typing import Any, List, Tuple

import jax.numpy as jnp
import msgpack
import numpy as np

_ND = "__nd__"      # v1: inline ndarray marker key
_NDSEG = "__ndseg__"  # v2: out-of-band segment stub marker key


def _default(obj):
    if isinstance(obj, (np.ndarray, jnp.ndarray)):
        arr = np.asarray(obj)
        if arr.dtype == np.dtype("bfloat16"):
            return {_ND: 1, "dtype": "bfloat16", "shape": list(arr.shape),
                    "data": arr.view(np.uint16).tobytes()}
        return {_ND: 1, "dtype": arr.dtype.str, "shape": list(arr.shape),
                "data": arr.tobytes()}
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    if isinstance(obj, tuple):
        return list(obj)
    raise TypeError(f"cannot serialize {type(obj)}")


def _object_hook(obj):
    if _ND in obj:
        if obj["dtype"] == "bfloat16":
            arr = np.frombuffer(obj["data"], dtype=np.uint16).view(
                jnp.bfloat16.dtype).reshape(obj["shape"])
        else:
            arr = np.frombuffer(obj["data"], dtype=np.dtype(obj["dtype"])) \
                    .reshape(obj["shape"])
        return arr
    return obj


def tree_to_bytes(tree: Any) -> bytes:
    """Serialize a pytree of ndarrays / scalars / dicts / lists to msgpack."""
    return msgpack.packb(tree, default=_default, use_bin_type=True)


def tree_from_bytes(data: bytes) -> Any:
    return msgpack.unpackb(data, object_hook=_object_hook, raw=False,
                           strict_map_key=False)


# ---------------------------------------------------------------------------
# v2 framed encoding — zero-copy tensor segments (ISSUE 4 fast path)
# ---------------------------------------------------------------------------

def _segment_view(arr: np.ndarray) -> Tuple[str, np.ndarray]:
    """(dtype tag, buffer-protocol view) for one ndarray.  bfloat16 has no
    buffer-protocol support, so it ships as its uint16 bit pattern (same
    rule as the v1 inline encoding)."""
    if arr.dtype == np.dtype("bfloat16"):
        return "bfloat16", arr.view(np.uint16)
    return arr.dtype.str, arr


def tree_to_frames(tree: Any) -> Tuple[bytes, List[Any]]:
    """Serialize a pytree to ``(header, segments)``.

    ``header`` is a msgpack blob in which every ndarray leaf is replaced
    by a ``{_NDSEG: i, dtype, shape}`` stub; ``segments[i]`` is a
    buffer-protocol view (ndarray / memoryview) over the i-th tensor's
    bytes — NOT a copy.  Non-contiguous arrays are the one exception
    (compacted first; wire deltas/centers are always contiguous).
    """
    segments: List[Any] = []

    def default(obj):
        if isinstance(obj, (np.ndarray, jnp.ndarray)):
            arr = np.asarray(obj)
            if not arr.flags.c_contiguous:  # ascontiguousarray would also
                arr = np.ascontiguousarray(arr)  # promote 0-d to 1-d
            dtype, view = _segment_view(arr)
            stub = {_NDSEG: len(segments), "dtype": dtype,
                    "shape": list(arr.shape)}
            segments.append(view)
            return stub
        return _default(obj)

    header = msgpack.packb(tree, default=default, use_bin_type=True)
    return header, segments


def tree_from_frames(header: bytes, segments: List[Any]) -> Any:
    """Inverse of :func:`tree_to_frames`.  ``segments`` may be any
    buffer-protocol objects (``bytearray`` straight off ``recv_into``):
    leaves are ``np.frombuffer`` views over them — zero additional
    copies after the socket read."""

    def hook(obj):
        if _NDSEG in obj:
            buf = segments[obj[_NDSEG]]
            if obj["dtype"] == "bfloat16":
                arr = np.frombuffer(buf, dtype=np.uint16).view(
                    jnp.bfloat16.dtype)
            else:
                arr = np.frombuffer(buf, dtype=np.dtype(obj["dtype"]))
            return arr.reshape(obj["shape"])
        return _object_hook(obj)

    return msgpack.unpackb(header, object_hook=hook, raw=False,
                           strict_map_key=False)


# ---------------------------------------------------------------------------
# model-level serde (parity: serialize_keras_model / deserialize_keras_model)
# ---------------------------------------------------------------------------

def serialize_model(model, variables: Any = None) -> bytes:
    """Architecture config + variables blob.

    Parity with reference ``distkeras/utils.py:serialize_keras_model(model)``
    which returned ``{'model': model.to_json(), 'weights': model.get_weights()}``.
    """
    payload = {"arch": json.dumps(model.config()),
               "variables": variables}
    return tree_to_bytes(payload)


def model_from_config(cfg: dict):
    """Rebuild a model from its config dict, dispatching on flavor: native
    configs go through ``models.Model``, ingested Keras-3 configs (marked
    by their ``keras_json`` key) through ``KerasAdapter``.  The single
    dispatch point for every consumer of serialized configs (serde, job
    runner)."""
    from ..models.model import Model
    if "keras_json" in cfg:
        from ..models.keras_adapter import KerasAdapter
        return KerasAdapter.from_config(cfg)
    return Model.from_config(cfg)


def deserialize_model(data: bytes):
    """Returns ``(model, variables)``; variables is None if not saved.

    Handles both native configs (``models.Model``) and ingested Keras-3
    models (``models.keras_adapter.KerasAdapter``).
    """
    payload = tree_from_bytes(data)
    model = model_from_config(json.loads(payload["arch"]))
    return model, payload.get("variables")

"""Pytree / model serialization.

TPU-native replacement for the reference's pickle-based serde
(``distkeras/utils.py:serialize_keras_model`` — architecture JSON + list of
weight ndarrays — and ``distkeras/networking.py:send_data/recv_data`` which
pickle arbitrary objects).  We use msgpack with an explicit, versioned
ndarray encoding instead of pickle: safe to use as a wire format for the
async parameter server and as the checkpoint format.
"""

from __future__ import annotations

import json
from typing import Any

import jax.numpy as jnp
import msgpack
import numpy as np

_ND = "__nd__"  # ndarray marker key


def _default(obj):
    if isinstance(obj, (np.ndarray, jnp.ndarray)):
        arr = np.asarray(obj)
        if arr.dtype == np.dtype("bfloat16"):
            return {_ND: 1, "dtype": "bfloat16", "shape": list(arr.shape),
                    "data": arr.view(np.uint16).tobytes()}
        return {_ND: 1, "dtype": arr.dtype.str, "shape": list(arr.shape),
                "data": arr.tobytes()}
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    if isinstance(obj, tuple):
        return list(obj)
    raise TypeError(f"cannot serialize {type(obj)}")


def _object_hook(obj):
    if _ND in obj:
        if obj["dtype"] == "bfloat16":
            arr = np.frombuffer(obj["data"], dtype=np.uint16).view(
                jnp.bfloat16.dtype).reshape(obj["shape"])
        else:
            arr = np.frombuffer(obj["data"], dtype=np.dtype(obj["dtype"])) \
                    .reshape(obj["shape"])
        return arr
    return obj


def tree_to_bytes(tree: Any) -> bytes:
    """Serialize a pytree of ndarrays / scalars / dicts / lists to msgpack."""
    return msgpack.packb(tree, default=_default, use_bin_type=True)


def tree_from_bytes(data: bytes) -> Any:
    return msgpack.unpackb(data, object_hook=_object_hook, raw=False,
                           strict_map_key=False)


# ---------------------------------------------------------------------------
# model-level serde (parity: serialize_keras_model / deserialize_keras_model)
# ---------------------------------------------------------------------------

def serialize_model(model, variables: Any = None) -> bytes:
    """Architecture config + variables blob.

    Parity with reference ``distkeras/utils.py:serialize_keras_model(model)``
    which returned ``{'model': model.to_json(), 'weights': model.get_weights()}``.
    """
    payload = {"arch": json.dumps(model.config()),
               "variables": variables}
    return tree_to_bytes(payload)


def model_from_config(cfg: dict):
    """Rebuild a model from its config dict, dispatching on flavor: native
    configs go through ``models.Model``, ingested Keras-3 configs (marked
    by their ``keras_json`` key) through ``KerasAdapter``.  The single
    dispatch point for every consumer of serialized configs (serde, job
    runner)."""
    from ..models.model import Model
    if "keras_json" in cfg:
        from ..models.keras_adapter import KerasAdapter
        return KerasAdapter.from_config(cfg)
    return Model.from_config(cfg)


def deserialize_model(data: bytes):
    """Returns ``(model, variables)``; variables is None if not saved.

    Handles both native configs (``models.Model``) and ingested Keras-3
    models (``models.keras_adapter.KerasAdapter``).
    """
    payload = tree_from_bytes(data)
    model = model_from_config(json.loads(payload["arch"]))
    return model, payload.get("variables")

"""Distributed inference — parity with reference ``distkeras/predictors.py``.

The reference maps a serialized Keras model over DataFrame partitions with
``rdd.mapPartitions``, calling ``model.predict`` per row and appending a
prediction column.  TPU-native: ONE jit-compiled batched apply sharded over
the device mesh — every row of the dataset streams through HBM in large
MXU-shaped batches instead of per-row Python calls.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .data.dataset import Dataset
from .models.model import Model
from .obs import profile as obs_profile


class Predictor:
    """Base predictor (reference ``distkeras/predictors.py:Predictor``)."""

    def __init__(self, keras_model: Model, variables: Optional[dict] = None):
        self.model = keras_model
        self.variables = variables if variables is not None \
            else keras_model.variables
        if self.variables is None:
            raise ValueError("model has no variables; train it first or pass "
                             "variables= explicitly")

    def predict(self, dataset: Dataset) -> Dataset:
        raise NotImplementedError


class ModelPredictor(Predictor):
    """Append a prediction column (reference ``ModelPredictor``):
    ``predict(ds)`` returns the dataset with ``output_col`` holding the raw
    model output per row."""

    def __init__(self, keras_model: Model, features_col: str = "features",
                 output_col: str = "prediction",
                 variables: Optional[dict] = None,
                 batch_size: int = 512, devices=None):
        super().__init__(keras_model, variables)
        self.features_col = features_col
        self.output_col = output_col
        self.batch_size = int(batch_size)
        self._devices = devices
        # retrace sentinel (ISSUE 6): predict batches are padded to a
        # fixed shape, so any retrace after the cold compile means the
        # padding contract broke — counted into ``jit.retraces``
        self._sentinel = obs_profile.RetraceSentinel(
            f"{type(self).__name__}.predict")
        self._fn = self._sentinel.wrap(jax.jit(self.model.predict_fn()))

    def predict(self, dataset: Dataset) -> Dataset:
        x = dataset[self.features_col]
        n = x.shape[0]
        if n == 0:
            out_shape = self.model.output_shape
            return dataset.with_column(
                self.output_col, np.zeros((0, *out_shape), np.float32))
        fn = self._fn

        bs = min(self.batch_size, n)
        pad = (-n) % bs
        if pad:
            x = np.concatenate([x, np.repeat(x[-1:], pad, axis=0)])
        xb = x.reshape(-1, bs, *x.shape[1:])

        variables = self.variables
        outs = []
        for i in range(xb.shape[0]):
            outs.append(np.asarray(fn(variables, jnp.asarray(xb[i]))))
        preds = np.concatenate(outs)[:n]
        return dataset.with_column(self.output_col, preds)


class StreamingPredictor(Predictor):
    """Online prediction over an unbounded stream — parity with the
    reference's Kafka + Spark-Streaming example (``examples/`` in the
    reference: a trained model mapped over a DStream of feature rows).

    Feed any iterator of feature arrays (single rows or batches); get
    predictions back with bounded latency.  Rows are micro-batched to
    ``batch_size`` and padded to a fixed shape so XLA compiles exactly one
    program (no recompilation per batch — the streaming analogue of the
    static-shape rule).

    ``predict_stream`` yields one prediction per input row, in order.
    """

    def __init__(self, keras_model: Model, variables: Optional[dict] = None,
                 batch_size: int = 64):
        super().__init__(keras_model, variables)
        self.batch_size = int(batch_size)
        # streaming contract: exactly ONE compiled shape (micro-batches
        # pad to batch_size) — the sentinel turns any violation into a
        # counted, logged retrace instead of a silent latency cliff
        self._sentinel = obs_profile.RetraceSentinel(
            f"{type(self).__name__}.predict")
        self._fn = self._sentinel.wrap(jax.jit(self.model.predict_fn()))

    def _predict_batch(self, rows: list) -> np.ndarray:
        x = np.stack(rows)
        k = x.shape[0]
        if k < self.batch_size:  # pad to the compiled shape
            x = np.concatenate(
                [x, np.repeat(x[-1:], self.batch_size - k, axis=0)])
        return np.asarray(self._fn(self.variables, jnp.asarray(x)))[:k]

    def predict_stream(self, feature_iter):
        buf: list = []
        for item in feature_iter:
            item = np.asarray(item)
            if item.ndim == len(self.model.input_shape):  # single row
                buf.append(item)
            else:  # already a batch
                buf.extend(item)
            while len(buf) >= self.batch_size:
                batch, buf = buf[: self.batch_size], buf[self.batch_size:]
                yield from self._predict_batch(batch)
        if buf:
            yield from self._predict_batch(buf)

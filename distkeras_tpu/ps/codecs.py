"""Delta-compression codecs for the PS commit wire (ISSUE 4).

Every communication window ships a full fp32 delta up to the parameter
server.  For SGD-family updates that payload is massively compressible:
per-tensor-scaled **int8 quantization** (4×), **bfloat16 truncation** (2×)
and **top-k sparsification** (1/frac ×) all preserve convergence when the
quantization error is carried forward — the worker keeps an
**error-feedback residual** (Seide et al. 2014; Karimireddy et al. 2019
EF-SGD) added to the next window's delta before encoding, so nothing is
lost, only delayed.

Shape of the scheme:

* A ``Codec`` instance lives on the WORKER (one per connection — the
  residual is per-worker state): ``encode(tree)`` maps floating ndarray
  leaves to ``{_MARK: name, ...}`` stub dicts and accumulates the
  residual.  Integer/bool leaves (RNG counters) pass through untouched —
  the server skips them anyway.
* Decoding is STATELESS and self-describing per leaf
  (:func:`decode_tree`) so one server handles workers running different
  codecs — and uncompressed workers — on the same port.
* The encoded leaves are plain dicts of scalars + small ndarrays, so they
  ride both wire formats; under the v2 framing the quantized bytes ship
  zero-copy.

``comm_codec`` on the distributed trainers selects per trainer:
``"none"`` (default — bit-identical to the uncompressed path), ``"int8"``,
``"bf16"``, or ``"topk<frac>"`` (e.g. ``"topk0.01"``; top-k implies
error feedback or it would diverge).

Obs instrumentation (ISSUE 4): encode counts
``ps.codec.bytes_raw`` / ``ps.codec.bytes_encoded`` / ``ps.codec.bytes_saved``
into the caller's registry (compression ratio = raw/encoded); encode and
decode latency land in ``ps.codec.encode_seconds`` /
``ps.codec.decode_seconds`` histograms at the call sites
(``ps.client`` / ``ps.servers``).
"""

from __future__ import annotations

from typing import Any, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

_MARK = "__dkcodec__"

Tree = Any


def _is_stub(x) -> bool:
    return isinstance(x, dict) and _MARK in x


def _floating(a: np.ndarray) -> bool:
    return np.issubdtype(a.dtype, np.floating) or \
        a.dtype == jnp.bfloat16.dtype


def _dtype_tag(a: np.ndarray) -> str:
    """Self-describing dtype tag (bfloat16 has no portable ``.str``)."""
    return "bfloat16" if a.dtype == jnp.bfloat16.dtype else a.dtype.str


def _stub_dtype(stub: dict):
    """Inverse of :func:`_dtype_tag` — the one place the tag convention
    is resolved back to a dtype for every decoder."""
    return jnp.bfloat16.dtype if stub["dtype"] == "bfloat16" \
        else np.dtype(stub["dtype"])


class Codec:
    """Base: identity codec (``comm_codec='none'``).  Stateful subclasses
    implement ``_enc_leaf``/``_dec_leaf``; :meth:`encode` threads the
    error-feedback residual through them."""

    name = "none"
    #: identity codecs skip the encode walk entirely so the default path
    #: stays bit-for-bit the pre-codec wire
    is_identity = True
    #: add the previous window's quantization error before encoding
    error_feedback = True

    def encode(self, tree: Tree) -> Tree:
        if self.is_identity:
            return tree
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        residual: List[Optional[np.ndarray]] = getattr(
            self, "_residual", None) or [None] * len(leaves)
        if len(residual) != len(leaves):  # tree changed: drop stale state
            residual = [None] * len(leaves)
        enc, res = [], []
        for a, r in zip(leaves, residual):
            a = np.asarray(a)
            if not _floating(a) or a.size == 0:
                enc.append(a)
                res.append(None)
                continue
            if self.error_feedback and r is not None:
                a = a + r
            stub = self._enc_leaf(a)
            enc.append(stub)
            # "raw" stubs ship the leaf verbatim — nothing is lost, so no
            # residual (and non-finite leaves would poison it: inf - inf)
            res.append((a - self._dec_leaf(stub)).astype(a.dtype)
                       if self.error_feedback and stub[_MARK] != "raw"
                       else None)
        self._residual = res
        return jax.tree_util.tree_unflatten(treedef, enc)

    def _enc_leaf(self, a: np.ndarray) -> dict:
        raise NotImplementedError

    def _dec_leaf(self, stub: dict) -> np.ndarray:
        raise NotImplementedError


class Int8Codec(Codec):
    """Per-tensor linear quantization to int8: ``q = round(a / scale)``
    with ``scale = max|a| / 127`` — 4× smaller than fp32 on the wire."""

    name = "int8"
    is_identity = False

    def _enc_leaf(self, a):
        scale = float(np.max(np.abs(a))) / 127.0 if a.size else 0.0
        if scale == 0.0 or not np.isfinite(scale):
            # all-zero (or non-finite peak: ship verbatim, don't destroy it)
            if scale == 0.0:
                return {_MARK: "int8", "dtype": _dtype_tag(a), "scale": 0.0,
                        "shape": list(a.shape),
                        "q": np.zeros(0, dtype=np.int8)}
            return {_MARK: "raw", "data": a}
        q = np.round(np.asarray(a, np.float32) / scale).astype(np.int8)
        return {_MARK: "int8", "dtype": _dtype_tag(a), "scale": scale,
                "shape": list(a.shape), "q": q}

    @staticmethod
    def _dec_leaf(stub):
        # "raw" stubs never reach here: encode skips their residual and
        # decode_tree dispatches them to the shared raw decoder
        if stub["scale"] == 0.0:
            return np.zeros(stub["shape"], dtype=_stub_dtype(stub))
        return (np.asarray(stub["q"], np.float32) * stub["scale"]) \
            .astype(_stub_dtype(stub))


class Bf16Codec(Codec):
    """Truncate fp32/fp64 deltas to bfloat16 (2× / 4×): same exponent
    range as fp32, 8-bit mantissa — the TPU-native low-precision
    format, no scale bookkeeping needed."""

    name = "bf16"
    is_identity = False

    def _enc_leaf(self, a):
        if a.dtype == jnp.bfloat16.dtype:  # already 2 bytes: ship verbatim
            return {_MARK: "raw", "data": a}
        return {_MARK: "bf16", "dtype": _dtype_tag(a),
                "data": a.astype(jnp.bfloat16.dtype)}

    @staticmethod
    def _dec_leaf(stub):
        return np.asarray(stub["data"]).astype(_stub_dtype(stub))


class TopKCodec(Codec):
    """Magnitude top-k sparsification: ship only the ``frac`` largest-
    magnitude entries (values + flat indices).  Error feedback is what
    makes this converge — dropped coordinates accumulate in the residual
    and ship once they grow."""

    name = "topk"
    is_identity = False

    def __init__(self, frac: float):
        if not 0.0 < frac <= 1.0:
            raise ValueError(f"topk fraction must be in (0, 1], got {frac}")
        self.frac = float(frac)
        self.name = f"topk{frac:g}"

    def _enc_leaf(self, a):
        flat = np.asarray(a, np.float32).reshape(-1)
        k = max(1, int(round(self.frac * flat.size)))
        if k >= flat.size:
            return {_MARK: "raw", "data": a}
        idx = np.argpartition(np.abs(flat), flat.size - k)[-k:]
        idx = np.sort(idx).astype(
            np.int32 if flat.size < 2**31 else np.int64)
        return {_MARK: "topk", "dtype": _dtype_tag(a),
                "shape": list(a.shape), "idx": idx, "vals": flat[idx]}

    @staticmethod
    def _dec_leaf(stub):
        flat = np.zeros(int(np.prod(stub["shape"])), dtype=np.float32)
        flat[np.asarray(stub["idx"])] = np.asarray(stub["vals"])
        return flat.reshape(stub["shape"]).astype(_stub_dtype(stub))


_DECODERS = {
    "int8": Int8Codec._dec_leaf,
    "bf16": Bf16Codec._dec_leaf,
    "topk": TopKCodec._dec_leaf,
    "raw": lambda stub: np.asarray(stub["data"]),
}


def get_codec(spec) -> Codec:
    """``comm_codec`` spec string (or Codec instance) -> fresh Codec.

    Accepted: ``"none"`` / ``None``, ``"int8"``, ``"bf16"``,
    ``"topk<frac>"`` (e.g. ``"topk0.01"``).
    """
    if isinstance(spec, Codec):
        return spec
    if spec is None or spec == "none":
        return Codec()
    if spec == "int8":
        return Int8Codec()
    if spec in ("bf16", "bfloat16"):
        return Bf16Codec()
    if isinstance(spec, str) and spec.startswith("topk"):
        try:
            return TopKCodec(float(spec[4:]))
        except ValueError as e:
            raise ValueError(
                f"bad comm_codec {spec!r}: topk needs a fraction suffix, "
                f"e.g. 'topk0.01' ({e})") from e
    raise ValueError(f"unknown comm_codec {spec!r} "
                     f"(known: none, int8, bf16, topk<frac>)")


def decode_tree(tree: Tree) -> Tree:
    """Stateless inverse of ``Codec.encode`` — dispatches per leaf stub,
    so mixed-codec (and uncompressed) trees all decode."""
    return jax.tree_util.tree_map(
        lambda x: _DECODERS[x[_MARK]](x) if _is_stub(x) else x,
        tree, is_leaf=_is_stub)


def tree_payload_bytes(tree: Tree) -> int:
    """Tensor-payload bytes of a (possibly encoded) tree: ndarray leaf
    bytes, plus the ndarray fields inside codec stubs — the number the
    ``ps.codec.bytes_*`` counters report (framing/msgpack keys excluded).
    Pure dtype/shape arithmetic (``.nbytes``): never materializes or
    transfers a leaf."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree, is_leaf=_is_stub):
        if _is_stub(leaf):
            for v in leaf.values():
                if isinstance(v, (np.ndarray, jnp.ndarray)):
                    total += v.nbytes
        elif isinstance(leaf, (np.ndarray, jnp.ndarray)):
            total += leaf.nbytes
    return total


def count_codec_bytes(registry, raw: int, encoded: int) -> None:
    """Fold one encode/decode's byte accounting into ``registry``."""
    registry.counter("ps.codec.bytes_raw").inc(raw)
    registry.counter("ps.codec.bytes_encoded").inc(encoded)
    registry.counter("ps.codec.bytes_saved").inc(max(0, raw - encoded))

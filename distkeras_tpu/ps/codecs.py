"""Delta-compression codecs for the PS commit wire (ISSUE 4).

Every communication window ships a full fp32 delta up to the parameter
server.  For SGD-family updates that payload is massively compressible:
per-tensor-scaled **int8 quantization** (4×), **bfloat16 truncation** (2×)
and **top-k sparsification** (1/frac ×) all preserve convergence when the
quantization error is carried forward — the worker keeps an
**error-feedback residual** (Seide et al. 2014; Karimireddy et al. 2019
EF-SGD) added to the next window's delta before encoding, so nothing is
lost, only delayed.

Shape of the scheme:

* A ``Codec`` instance lives on the WORKER (one per connection — the
  residual is per-worker state): ``encode(tree)`` maps floating ndarray
  leaves to ``{_MARK: name, ...}`` stub dicts and accumulates the
  residual.  Integer/bool leaves (RNG counters) pass through untouched —
  the server skips them anyway.
* Decoding is STATELESS and self-describing per leaf
  (:func:`decode_tree`) so one server handles workers running different
  codecs — and uncompressed workers — on the same port.
* The encoded leaves are plain dicts of scalars + small ndarrays, so they
  ride both wire formats; under the v2 framing the quantized bytes ship
  zero-copy.

``comm_codec`` on the distributed trainers selects per trainer:
``"none"`` (default — bit-identical to the uncompressed path), ``"int8"``,
``"bf16"``, or ``"topk<frac>"`` (e.g. ``"topk0.01"``; top-k implies
error feedback or it would diverge).

Obs instrumentation (ISSUE 4): encode counts
``ps.codec.bytes_raw`` / ``ps.codec.bytes_encoded`` / ``ps.codec.bytes_saved``
into the caller's registry (compression ratio = raw/encoded); encode and
decode latency land in ``ps.codec.encode_seconds`` /
``ps.codec.decode_seconds`` histograms at the call sites
(``ps.client`` / ``ps.servers``).

ISSUE 12 adds the **DOWN direction**: every pull used to ship the full
raw center.  :func:`encode_ref_delta` / :func:`apply_ref_delta` quantize
the center as a residual against a **reference center** both ends hold
(the server's shared per-K-counters snapshot — ``ps.state.DownRefState``)
using the same stateless per-leaf stubs, so any UP codec's decoder
already understands the DOWN wire.  No error feedback is needed DOWN:
each pull encodes ``center - reference`` fresh, so quantization error is
bounded per pull, never accumulated.  :class:`AdaptiveDownPolicy` picks
the DOWN codec per connection from the client-measured RTT-vs-bytes
ratios, with hysteresis and a recorded ``ps.codec.switches`` trail.
"""

from __future__ import annotations

import collections
from typing import Any, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..obs.logging import get_logger

_MARK = "__dkcodec__"

Tree = Any


def _is_stub(x) -> bool:
    return isinstance(x, dict) and _MARK in x


def _floating(a: np.ndarray) -> bool:
    return np.issubdtype(a.dtype, np.floating) or \
        a.dtype == jnp.bfloat16.dtype


def _dtype_tag(a: np.ndarray) -> str:
    """Self-describing dtype tag (bfloat16 has no portable ``.str``)."""
    return "bfloat16" if a.dtype == jnp.bfloat16.dtype else a.dtype.str


def _stub_dtype(stub: dict):
    """Inverse of :func:`_dtype_tag` — the one place the tag convention
    is resolved back to a dtype for every decoder."""
    return jnp.bfloat16.dtype if stub["dtype"] == "bfloat16" \
        else np.dtype(stub["dtype"])


class Codec:
    """Base: identity codec (``comm_codec='none'``).  Stateful subclasses
    implement ``_enc_leaf``/``_dec_leaf``; :meth:`encode` threads the
    error-feedback residual through them."""

    name = "none"
    #: identity codecs skip the encode walk entirely so the default path
    #: stays bit-for-bit the pre-codec wire
    is_identity = True
    #: add the previous window's quantization error before encoding
    error_feedback = True

    def encode(self, tree: Tree) -> Tree:
        if self.is_identity:
            return tree
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        residual: List[Optional[np.ndarray]] = getattr(
            self, "_residual", None) or [None] * len(leaves)
        if len(residual) != len(leaves):  # tree changed: drop stale state
            residual = [None] * len(leaves)
        enc, res = [], []
        for a, r in zip(leaves, residual):
            a = np.asarray(a)
            if not _floating(a) or a.size == 0:
                enc.append(a)
                res.append(None)
                continue
            if self.error_feedback and r is not None:
                a = a + r
            stub = self._enc_leaf(a)
            enc.append(stub)
            # "raw" stubs ship the leaf verbatim — nothing is lost, so no
            # residual (and non-finite leaves would poison it: inf - inf)
            res.append((a - self._dec_leaf(stub)).astype(a.dtype)
                       if self.error_feedback and stub[_MARK] != "raw"
                       else None)
        self._residual = res
        return jax.tree_util.tree_unflatten(treedef, enc)

    def _enc_leaf(self, a: np.ndarray) -> dict:
        raise NotImplementedError

    def _dec_leaf(self, stub: dict) -> np.ndarray:
        raise NotImplementedError


class Int8Codec(Codec):
    """Per-tensor linear quantization to int8: ``q = round(a / scale)``
    with ``scale = max|a| / 127`` — 4× smaller than fp32 on the wire."""

    name = "int8"
    is_identity = False

    def _enc_leaf(self, a):
        scale = float(np.max(np.abs(a))) / 127.0 if a.size else 0.0
        if scale == 0.0 or not np.isfinite(scale):
            # all-zero (or non-finite peak: ship verbatim, don't destroy it)
            if scale == 0.0:
                return {_MARK: "int8", "dtype": _dtype_tag(a), "scale": 0.0,
                        "shape": list(a.shape),
                        "q": np.zeros(0, dtype=np.int8)}
            return {_MARK: "raw", "data": a}
        q = np.round(np.asarray(a, np.float32) / scale).astype(np.int8)
        return {_MARK: "int8", "dtype": _dtype_tag(a), "scale": scale,
                "shape": list(a.shape), "q": q}

    @staticmethod
    def _dec_leaf(stub):
        # "raw" stubs never reach here: encode skips their residual and
        # decode_tree dispatches them to the shared raw decoder
        if stub["scale"] == 0.0:
            return np.zeros(stub["shape"], dtype=_stub_dtype(stub))
        return (np.asarray(stub["q"], np.float32) * stub["scale"]) \
            .astype(_stub_dtype(stub))


class Bf16Codec(Codec):
    """Truncate fp32/fp64 deltas to bfloat16 (2× / 4×): same exponent
    range as fp32, 8-bit mantissa — the TPU-native low-precision
    format, no scale bookkeeping needed."""

    name = "bf16"
    is_identity = False

    def _enc_leaf(self, a):
        if a.dtype == jnp.bfloat16.dtype:  # already 2 bytes: ship verbatim
            return {_MARK: "raw", "data": a}
        return {_MARK: "bf16", "dtype": _dtype_tag(a),
                "data": a.astype(jnp.bfloat16.dtype)}

    @staticmethod
    def _dec_leaf(stub):
        return np.asarray(stub["data"]).astype(_stub_dtype(stub))


class TopKCodec(Codec):
    """Magnitude top-k sparsification: ship only the ``frac`` largest-
    magnitude entries (values + flat indices).  Error feedback is what
    makes this converge — dropped coordinates accumulate in the residual
    and ship once they grow."""

    name = "topk"
    is_identity = False

    def __init__(self, frac: float):
        if not 0.0 < frac <= 1.0:
            raise ValueError(f"topk fraction must be in (0, 1], got {frac}")
        self.frac = float(frac)
        self.name = f"topk{frac:g}"

    def _enc_leaf(self, a):
        flat = np.asarray(a, np.float32).reshape(-1)
        k = max(1, int(round(self.frac * flat.size)))
        if k >= flat.size:
            return {_MARK: "raw", "data": a}
        idx = np.argpartition(np.abs(flat), flat.size - k)[-k:]
        idx = np.sort(idx).astype(
            np.int32 if flat.size < 2**31 else np.int64)
        return {_MARK: "topk", "dtype": _dtype_tag(a),
                "shape": list(a.shape), "idx": idx, "vals": flat[idx]}

    @staticmethod
    def _dec_leaf(stub):
        flat = np.zeros(int(np.prod(stub["shape"])), dtype=np.float32)
        flat[np.asarray(stub["idx"])] = np.asarray(stub["vals"])
        return flat.reshape(stub["shape"]).astype(_stub_dtype(stub))


_DECODERS = {
    "int8": Int8Codec._dec_leaf,
    "bf16": Bf16Codec._dec_leaf,
    "topk": TopKCodec._dec_leaf,
    "raw": lambda stub: np.asarray(stub["data"]),
}


def get_codec(spec) -> Codec:
    """``comm_codec`` spec string (or Codec instance) -> fresh Codec.

    Accepted: ``"none"`` / ``None``, ``"int8"``, ``"bf16"``,
    ``"topk<frac>"`` (e.g. ``"topk0.01"``).
    """
    if isinstance(spec, Codec):
        return spec
    if spec is None or spec == "none":
        return Codec()
    if spec == "int8":
        return Int8Codec()
    if spec in ("bf16", "bfloat16"):
        return Bf16Codec()
    if isinstance(spec, str) and spec.startswith("topk"):
        try:
            return TopKCodec(float(spec[4:]))
        except ValueError as e:
            raise ValueError(
                f"bad comm_codec {spec!r}: topk needs a fraction suffix, "
                f"e.g. 'topk0.01' ({e})") from e
    raise ValueError(f"unknown comm_codec {spec!r} "
                     f"(known: none, int8, bf16, topk<frac>)")


def decode_tree(tree: Tree) -> Tree:
    """Stateless inverse of ``Codec.encode`` — dispatches per leaf stub,
    so mixed-codec (and uncompressed) trees all decode."""
    return jax.tree_util.tree_map(
        lambda x: _DECODERS[x[_MARK]](x) if _is_stub(x) else x,
        tree, is_leaf=_is_stub)


def tree_payload_bytes(tree: Tree) -> int:
    """Tensor-payload bytes of a (possibly encoded) tree: ndarray leaf
    bytes, plus the ndarray fields inside codec stubs — the number the
    ``ps.codec.bytes_*`` counters report (framing/msgpack keys excluded).
    Pure dtype/shape arithmetic (``.nbytes``): never materializes or
    transfers a leaf."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree, is_leaf=_is_stub):
        if _is_stub(leaf):
            for v in leaf.values():
                if isinstance(v, (np.ndarray, jnp.ndarray)):
                    total += v.nbytes
        elif isinstance(leaf, (np.ndarray, jnp.ndarray)):
            total += leaf.nbytes
    return total


def count_codec_bytes(registry, raw: int, encoded: int,
                      prefix: str = "ps.codec") -> None:
    """Fold one encode/decode's byte accounting into ``registry``.
    ``prefix`` splits the ledgers: ``ps.codec`` is the UP (commit)
    direction, ``ps.down`` the DOWN (pull) direction (ISSUE 12)."""
    registry.counter(f"{prefix}.bytes_raw").inc(raw)
    registry.counter(f"{prefix}.bytes_encoded").inc(encoded)
    registry.counter(f"{prefix}.bytes_saved").inc(max(0, raw - encoded))


# ---------------------------------------------------------------------------
# DOWN direction: reference/residual center compression (ISSUE 12)
# ---------------------------------------------------------------------------

#: DOWN codec specs a current client can decode — advertised in the
#: hello so a newer server never ships a stub this build cannot open
DOWN_CODECS = ("int8", "bf16", "topk")


def validate_down_spec(spec) -> str:
    """Normalize/validate a ``comm_down`` spec: ``None``/"none" (raw
    pulls, the bit-identical default), "adaptive" (per-link policy), or
    any non-identity ``get_codec`` spec ("int8" / "bf16" / "topk<frac>")."""
    if spec is None or spec == "none":
        return "none"
    if spec == "adaptive":
        return "adaptive"
    codec = get_codec(spec)
    if codec.is_identity:
        raise ValueError(f"comm_down {spec!r} is an identity codec; use "
                         f"'none' to disable DOWN compression")
    return codec.name


def encode_ref_delta(center: Tree, ref: Tree, spec: str) -> Tree:
    """Encode ``center`` as a quantized residual against ``ref`` (the
    reference center the peer already holds): floating leaves become the
    same self-describing stubs the UP codecs ship (``center - ref``
    through ``spec``'s leaf encoder), non-floating/empty leaves pass
    through verbatim.  Stateless — no error feedback: the residual is
    recomputed against the reference every pull, so quantization error
    is bounded per pull, never accumulated."""
    codec = get_codec(spec)

    def enc(c, r):
        c = np.asarray(c)
        if not _floating(c) or c.size == 0:
            return c
        return codec._enc_leaf((c - np.asarray(r)).astype(c.dtype))

    return jax.tree_util.tree_map(enc, center, ref)


def apply_ref_delta(ref: Tree, residual: Tree) -> Tree:
    """Inverse of :func:`encode_ref_delta`: ``ref + decode(stub)`` per
    stub leaf (new arrays — pulled trees stay read-only), pass-through
    leaves adopted as-is."""

    def dec(r, s):
        if _is_stub(s):
            r = np.asarray(r)
            return (r + _DECODERS[s[_MARK]](s).astype(r.dtype, copy=False))
        return s

    return jax.tree_util.tree_map(dec, ref, residual,
                                  is_leaf=lambda x: _is_stub(x))


class AdaptiveDownPolicy:
    """Per-link DOWN codec selection from measured pull RTTs (ISSUE 12).

    Lives on the CLIENT — the end that actually measures the link: each
    pull's VISIBLE wait (which folds in the server's encode time, the
    un-overlapped transfer, and this end's decode — but never the
    caller's compute between ``pull_begin`` and ``pull_join``, so
    dispatch-ahead pulls compare codecs by what they still cost the
    critical path) is attributed to the codec that carried it.  The policy seeds an EWMA per candidate during a warmup
    sweep, then serves the argmin — with **hysteresis**: a challenger
    must beat the incumbent by ``margin`` on ``patience`` consecutive
    evaluations before a switch, so RTT noise never flaps the link.
    Every switch increments ``ps.codec.switches`` and appends to the
    bounded :attr:`trail` (the recorded decision log obsview and tests
    read); a periodic re-probe keeps the losers' EWMAs honest as link
    conditions drift.

    ISSUE 15 folds the reprobe schedule into the straggler detector's
    **link-quality signal**: given a :class:`~..obs.stragglers.LinkQuality`
    (the per-link pull/commit RTT EWMAs the client already measures), a
    degraded link (1) **downshifts** the codec one step toward more
    compression IMMEDIATELY — no hysteresis wait, because the remedy for
    a link that just got slower is fewer bytes *now*, before the
    worker's stretched window gap gets it flagged as a straggler — with
    every downshift a recorded ``ps.link.downshifts`` event on the
    trail, and (2) tightens the re-probe cadence (``reprobe_every // 4``)
    while degraded, so the EWMAs re-learn the shifted link quickly.  The
    normal hysteresis path still owns the recovery upshift once probes
    show the cheaper codec winning again.
    """

    #: candidate order is bytes-descending ("none" ships the most), so a
    #: downshift is one step to the right — strictly fewer bytes
    def __init__(self, registry, candidates=("none", "bf16", "int8"),
                 margin: float = 0.2, patience: int = 3,
                 reprobe_every: int = 25, alpha: float = 0.3,
                 warmup_samples: int = 2, link=None):
        for c in candidates:
            if c != "none":
                validate_down_spec(c)
        self.candidates = tuple(candidates)
        self.margin = float(margin)
        self.patience = int(patience)
        self.reprobe_every = int(reprobe_every)
        self.alpha = float(alpha)
        self.warmup_samples = int(warmup_samples)
        #: per-link RTT EWMAs with a degradation edge (ISSUE 15); None
        #: keeps the pre-link behavior exactly
        self.link = link
        #: cumulative link-degradation downshifts — shipped on the
        #: commit RPC next to the link EWMA
        self.downshifts = 0
        self.current = self.candidates[0]
        self._ewma: dict = {}
        self._samples: dict = {c: 0 for c in self.candidates}
        self._streak_for: Optional[str] = None
        self._streak = 0
        self._n = 0
        self._probe_cursor = 0
        #: bounded decision log: one entry per switch
        self.trail: collections.deque = collections.deque(maxlen=256)
        self._c_switches = registry.counter("ps.codec.switches")
        self._c_downshifts = registry.counter("ps.link.downshifts")
        self._log = get_logger("ps.down")

    def _downshift(self) -> Optional[str]:
        """One step toward more compression on a degraded link, or None
        when already at the smallest candidate."""
        i = self.candidates.index(self.current)
        if i + 1 >= len(self.candidates):
            return None
        nxt = self.candidates[i + 1]
        self.trail.append({"pull": self._n, "from": self.current,
                           "to": nxt, "kind": "downshift"})
        self._log.warning(
            "link degraded (RTT EWMA over %.1fx its best): downshifting "
            "DOWN codec %s -> %s", self.link.degrade_factor, self.current,
            nxt)
        self.current = nxt
        self.downshifts += 1
        self._c_downshifts.inc()
        self._streak_for, self._streak = None, 0
        # the link's byte profile just changed: rebase the degradation
        # baseline so the edge measures the NEW codec's link, and the
        # downshift self-cools instead of cascading every pull
        self.link.rebase()
        return nxt

    def next_codec(self) -> str:
        """The codec the NEXT pull should request."""
        for c in self.candidates:  # warmup: seed every candidate's EWMA
            if self._samples[c] < self.warmup_samples:
                return c
        self._n += 1
        degraded = self.link is not None and self.link.degraded()
        if degraded:
            shifted = self._downshift()
            if shifted is not None:
                return shifted
        reprobe = self.reprobe_every
        if degraded and reprobe:
            # a degraded link's EWMAs are stale by definition: re-probe
            # the alternatives 4x as often until the edge clears
            reprobe = max(2, reprobe // 4)
        if reprobe and self._n % reprobe == 0:
            others = [c for c in self.candidates if c != self.current]
            if others:
                self._probe_cursor = (self._probe_cursor + 1) % len(others)
                return others[self._probe_cursor]
        return self.current

    def observe(self, codec: str, rtt_s: float) -> None:
        """Fold one pull's measured RTT into ``codec``'s EWMA and
        re-evaluate the incumbent."""
        if codec not in self.candidates or not np.isfinite(rtt_s) \
                or rtt_s < 0:
            return
        self._samples[codec] += 1
        prev = self._ewma.get(codec)
        self._ewma[codec] = float(rtt_s) if prev is None \
            else (1 - self.alpha) * prev + self.alpha * float(rtt_s)
        if any(self._samples[c] < self.warmup_samples
               for c in self.candidates):
            return
        best = min(self.candidates, key=lambda c: self._ewma[c])
        if best == self.current or \
                self._ewma[best] >= self._ewma[self.current] * \
                (1.0 - self.margin):
            self._streak_for, self._streak = None, 0
            return
        if self._streak_for == best:
            self._streak += 1
        else:
            self._streak_for, self._streak = best, 1
        if self._streak >= self.patience:
            ratio = self._ewma[self.current] / max(self._ewma[best], 1e-12)
            self.trail.append({"pull": self._n, "from": self.current,
                               "to": best, "rtt_ratio": round(ratio, 3)})
            self._log.info(
                "adaptive DOWN codec switch: %s -> %s (EWMA RTT ratio "
                "%.2fx over %d consecutive evaluations)", self.current,
                best, ratio, self._streak)
            self.current = best
            self._c_switches.inc()
            self._streak_for, self._streak = None, 0

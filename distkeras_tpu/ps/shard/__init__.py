"""Sharded parameter server (ISSUE 10): partition the center pytree
across a fleet of single-shard servers — per-shard locks, accept loops,
pull caches, codec state, and obs registries — with **consistent-cut
pulls** so a worker never trains on a half-applied center.

The star topology's measured ceiling (one ``apply_commit`` lock, one
accept thread — the w4 contention sweep's 6× commit-RTT pileup) becomes
one ceiling per shard; this is the DistBelief/DOWNPOUR star→fleet step
(Dean et al., NIPS'12) in the Li et al. (OSDI'14) sharded-server shape.

* :class:`ShardPlan` — deterministic per-tensor placement, digest-checked
  between workers and shards in the ``hello`` negotiation.
* :class:`ShardedParameterServer` — hosts N shards; supervisor-facing
  facade (evict/respawn/join fan out; a dead shard is a named fatal
  error, failover deferred to the ROADMAP's self-healing round 3).
* :class:`ShardedPSClient` — the ``PSClient`` surface over parallel
  fan-out; pulls retry lagging shards until the per-worker commit-count
  version vectors agree across the fleet.
"""

from .plan import ShardPlan  # noqa: F401
from .server import (  # noqa: F401
    ShardedParameterServer,
    ShardFleetError,
    ShardFrontend,
)
from .client import (  # noqa: F401
    ConsistentCutError,
    ShardedPSClient,
    ShardPlanMismatch,
    merge_fleet_stats,
)

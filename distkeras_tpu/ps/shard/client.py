"""Worker-side sharded PS client: pipelined fan-out with consistent-cut
pulls (ISSUE 10).

``ShardedPSClient`` keeps one :class:`~..client.PSClient` per shard (so
each connection negotiates its own wire version, owns its own
error-feedback codec residual, and reuses the per-shard pull cache) and
presents the exact ``PSClient`` surface the workers already drive —
``ps_shards=1`` fleets keep using ``PSClient`` itself, untouched.

**Pipelined fan-out.**  A logical pull/commit uses the split-phase
protocol primitives (``pull_send``/``pull_finish``,
``commit_send``/``commit_finish``): every shard's request goes out
first, then the replies are collected — all on the worker's own thread.
Shard 0 is decoding and applying while the slices for shards 1..N-1 are
still being sent, and the shards' applies overlap each other under
their own locks; a thread-per-shard fan-out would instead pay GIL
contention and pool dispatch per RPC (measured 2× worse on the
contention bench).  One thread also means the worker's trace identity
and spans propagate exactly as in the single-server path.

**Consistent-cut pull.**  Each shard's pull reply carries its per-worker
commit counts — a version vector captured atomically with the center
slice.  A logical commit lands once on EVERY shard, so a cut is
consistent exactly when all shards report the SAME vector: no commit is
half-applied across the assembled center.  The pull fans out, compares
vectors, and re-pulls only the shards that disagree until the vectors
match (bounded rounds; every retry is a recorded
``ps.shard.torn_pulls``).  If the vectors stop moving while still
unequal — a committer died mid-fan-out, leaving a permanently torn
commit — the pull accepts the freshest cut and records
``ps.shard.cut_incomplete`` instead of spinning forever (shard-failure
recovery is the ROADMAP's round-3 item).

Plan agreement is verified at connect time: v2 connections check the
shard descriptor from the ``hello`` reply, v1-pinned connections (no
hello) fetch it via the ``plan`` RPC — either way a digest/index/epoch
mismatch raises :class:`ShardPlanMismatch` before any traffic flows.
"""

from __future__ import annotations

import contextlib
import time
from typing import Any, List, Optional, Sequence, Tuple

from ...obs import TIME_BUCKETS, Registry, default_registry
from ...obs.logging import get_logger
from ..client import PSClient, WorkerEvicted
from .plan import ShardPlan

Tree = Any


def merge_fleet_stats(replies: Sequence[dict]) -> dict:
    """The consistent merged view over a shard fleet's per-shard ``stats``
    replies — ONE definition shared by :meth:`ShardedPSClient.stats` and
    ``obsview --ps``: registry counters/histograms fold via
    ``Registry.merge_snapshots``, per-worker commits take the
    element-wise MIN (the fully-committed prefix — a commit counts once
    every shard applied it), ``num_updates`` the MAX (the in-flight
    edge)."""
    merged = Registry.merge_snapshots(*[r.get("stats", {})
                                        for r in replies])
    by_worker: dict = {}
    for r in replies:
        for w, c in (r.get("commits_by_worker") or {}).items():
            w = int(w)
            by_worker[w] = c if w not in by_worker \
                else min(by_worker[w], c)
    return {"stats": merged,
            "num_updates": max((int(r.get("num_updates") or 0)
                                for r in replies), default=0),
            "commits_by_worker": by_worker}


class ShardPlanMismatch(RuntimeError):
    """A shard's placement descriptor disagrees with this client's plan —
    assembling centers across it would silently interleave two different
    partitionings."""


class ConsistentCutError(RuntimeError):
    """The version vectors kept moving without ever agreeing within the
    round budget — the fleet is committing faster than this client can
    snapshot it."""


class ShardedPSClient:
    """Fan commits/pulls across a shard fleet over the existing v2 wire.

    ``template`` (any tree with the center's structure — the worker's own
    variables) derives the plan locally; every shard's descriptor is then
    verified against it.  All of ``worker_id`` / ``codec`` /
    ``wire_version`` / ``tracer`` / ``generation`` / ``down`` / ``shm``
    mean exactly what they mean on ``PSClient``; the codec SPEC is shared
    but each shard connection builds its own instance (per-shard
    error-feedback isolation — one shard's residual never leaks into
    another's), and likewise each connection owns its own DOWN reference
    epoch, adaptive policy, and shm rings (ISSUE 12) — a mixed fleet
    where only SOME shards can attach the rings simply runs those
    connections on TCP, per-link.  Streamed pulls (ISSUE 15) negotiate
    per-connection the same way: a shard that refused (or predates) the
    ``stream`` offer answers monolithically while its siblings stream,
    and the assembled center is identical either way."""

    def __init__(self, addrs: Sequence[Tuple[str, int]], template: Tree,
                 worker_id: int = 0, registry: Optional[Registry] = None,
                 codec=None, wire_version: Optional[int] = None,
                 tracer=None, generation: int = 0, plan_epoch: int = 0,
                 max_cut_rounds: int = 100, down=None,
                 shm: Optional[bool] = None,
                 shm_mb: Optional[float] = None,
                 stream: Optional[bool] = None,
                 stream_chunk_bytes: Optional[int] = None):
        addrs = [(h, int(p)) for h, p in addrs]
        if not addrs:
            raise ValueError("ShardedPSClient needs at least one shard")
        self.worker_id = int(worker_id)
        self.registry = registry if registry is not None \
            else default_registry()
        self.plan = ShardPlan.build(template, len(addrs), epoch=plan_epoch)
        self.max_cut_rounds = int(max_cut_rounds)
        self.tracer = tracer
        self._log = get_logger("ps.shard")
        self._c_rounds = self.registry.counter("ps.shard.pull_rounds")
        self._c_torn = self.registry.counter("ps.shard.torn_pulls")
        self._c_incomplete = self.registry.counter("ps.shard.cut_incomplete")
        self._c_repairs = self.registry.counter("ps.shard.commit_repairs")
        self._h_assemble = self.registry.histogram(
            "ps.shard.assemble_seconds", TIME_BUCKETS)
        self.clients: List[PSClient] = []
        try:
            for host, port in addrs:
                self.clients.append(PSClient(
                    host, port, worker_id, registry=self.registry,
                    codec=codec, wire_version=wire_version, tracer=tracer,
                    generation=generation, down=down, shm=shm,
                    shm_mb=shm_mb, stream=stream,
                    stream_chunk_bytes=stream_chunk_bytes))
            self._verify_plan()
        except BaseException:
            self.close()
            raise
        self.wire_version = min(c.wire_version for c in self.clients)
        #: per-shard update counters from the most recent pull — the
        #: split of the scalar ``last_update`` workers hand back to
        #: ``commit`` (staleness is a per-shard quantity)
        self._pull_counters = [0] * len(self.clients)
        self._warned_incomplete = False
        #: True while an overlapped pull's round-1 requests are in
        #: flight (ISSUE 15: ``pull_begin`` sent, ``pull_join`` pending)
        self._begun = False

    # -- plan agreement -----------------------------------------------------
    def _verify_plan(self) -> None:
        for i, c in enumerate(self.clients):
            info = c.shard_info
            if info is None:
                # v1 connection (no hello) or a pre-shard server: the
                # ``plan`` RPC is the wire-version-independent source
                resp = c._rpc({"action": "plan",
                               "worker_id": self.worker_id}, retry=True)
                if not isinstance(resp, dict) or not resp.get("ok"):
                    raise ShardPlanMismatch(
                        f"shard {i} at {c.host}:{c.port} does not speak "
                        f"the shard protocol (reply: {resp!r}) — is a "
                        "plain parameter server listening there?")
                info = resp.get("shard") or {}
            mine = self.plan.descriptor()
            theirs = {k: info.get(k) for k in
                      ("num_shards", "epoch", "digest")}
            if theirs != mine or int(info.get("index", -1)) != i:
                raise ShardPlanMismatch(
                    f"shard {i} at {c.host}:{c.port} disagrees on the "
                    f"placement plan (mine {mine} / index {i}, theirs "
                    f"{theirs} / index {info.get('index')}) — refusing "
                    "to interleave two partitionings")

    # -- the consistent-cut pull -------------------------------------------
    @staticmethod
    def _norm_vv(vv) -> dict:
        return {int(k): int(v) for k, v in vv.items()} \
            if isinstance(vv, dict) else {}

    def _span(self, name: str):
        if self.tracer is None:
            return contextlib.nullcontext()
        return self.tracer.span(name, worker=self.worker_id)

    def _pull_round(self, pending, min_updates=None,
                    presend: bool = True) -> dict:
        """One pipelined pull round over the ``pending`` shard indices:
        all requests out, then all replies in.  A dead connection gets
        one reconnect per phase (a pull is an idempotent read).  On
        retry rounds ``min_updates`` carries the cut target's total
        commit count: the lagging shard WAITS for its in-flight applies
        instead of shipping a slice the cut check would discard.
        ``presend=False`` skips the send phase — an overlapped pull
        (:meth:`pull_begin`) already fanned round 1's requests out."""
        if presend:
            for i in pending:
                c = self.clients[i]
                try:
                    c.pull_send(min_updates)
                except (ConnectionError, OSError):
                    c.reconnect()
                    c.pull_send(min_updates)
        out = {}
        for i in pending:
            c = self.clients[i]
            try:
                out[i] = c.pull_finish()
            except (ConnectionError, OSError):
                c.reconnect()
                c.pull_send(min_updates)
                out[i] = c.pull_finish()
        return out

    def pull(self) -> tuple:
        """Assembled ``(center, total_updates)`` from a consistent cut:
        no shard's slice reflects a commit any other shard's slice is
        missing."""
        with self._span("ps.shard.pull"):
            return self._pull_cut()

    # -- overlapped pulls (ISSUE 15) ----------------------------------------
    def pull_begin(self, min_updates=None) -> None:
        """Phase 1 of an overlapped consistent-cut pull: round 1's
        requests go to every shard (pipelined, reconnect-once like any
        idempotent read); the dispatch-ahead worker computes its window
        while every shard's slice rides the wire, then
        :meth:`pull_join` collects round 1 and runs the cut protocol."""
        for c in self.clients:
            try:
                c.pull_send(min_updates)
            except (ConnectionError, OSError):
                c.reconnect()
                c.pull_send(min_updates)
        self._begun = True

    def pull_join(self) -> tuple:
        """Phase 2 of an overlapped pull: ``(center, total_updates,
        None, None)`` — the same leading shape as
        ``PSClient.pull_finish`` so the worker loop drives either client
        identically."""
        with self._span("ps.shard.pull"):
            try:
                center, total = self._pull_cut(first_sent=self._begun)
            finally:
                self._begun = False
            return center, total, None, None

    def _pull_cut(self, first_sent: bool = False) -> tuple:
        n = len(self.clients)
        results: List[Optional[tuple]] = [None] * n
        pending = list(range(n))
        min_updates = None
        prev_vvs = None
        stable = 0
        for rnd in range(self.max_cut_rounds):
            self._c_rounds.inc()
            replies = self._pull_round(
                pending, min_updates,
                presend=not (first_sent and rnd == 0))
            for i, r in replies.items():
                results[i] = r
            for i, (_, _, _, epoch) in enumerate(results):
                if epoch is not None and epoch != self.plan.epoch:
                    raise ShardPlanMismatch(
                        f"shard {i} serves plan epoch {epoch}, this "
                        f"client holds epoch {self.plan.epoch} — the "
                        "fleet was re-sharded under us")
            vvs = [self._norm_vv(r[2]) for r in results]
            target = {}
            for vv in vvs:
                for w, c in vv.items():
                    target[w] = max(target.get(w, 0), c)
            pending = [i for i, vv in enumerate(vvs) if vv != target]
            if not pending:
                return self._assemble(results)
            # a lagging shard's counter must reach the target's total
            # before its vector can possibly match — let the server wait
            # for its in-flight applies instead of re-shipping stale
            # slices round after round
            min_updates = sum(target.values())
            self._c_torn.inc()
            if vvs == prev_vvs:
                stable += 1
                if stable >= 2:
                    # no movement across three rounds: a committer died
                    # mid-fan-out and left a permanently torn commit.
                    # Serve the freshest cut rather than spin forever —
                    # recorded, and warned once per client.
                    self._c_incomplete.inc()
                    if not self._warned_incomplete:
                        self._warned_incomplete = True
                        self._log.warning(
                            "consistent-cut pull gave up waiting on a "
                            "permanently torn commit (shards %s lag the "
                            "fleet maximum); serving the freshest cut — "
                            "recorded as ps.shard.cut_incomplete", pending)
                    return self._assemble(results)
            else:
                stable = 0
            prev_vvs = vvs
            time.sleep(0.001)  # yield: let in-flight applies land
        raise ConsistentCutError(
            f"no consistent cut within {self.max_cut_rounds} pull rounds "
            f"(shards still torn: {pending}) — the fleet is committing "
            "faster than this client can snapshot it")

    def _assemble(self, results) -> tuple:
        t0 = time.perf_counter()
        self._pull_counters = [int(r[1]) for r in results]
        center = self.plan.assemble(*[r[0] for r in results])
        self._h_assemble.observe(time.perf_counter() - t0)
        return center, sum(self._pull_counters)

    # -- commit -------------------------------------------------------------
    def commit(self, delta: Tree, last_update: Optional[int] = None,
               gap_s: Optional[float] = None) -> bool:
        """Split the delta along the plan and commit every slice — one
        logical commit, one counter bump per shard, pipelined: every
        slice is on the wire before the first reply is read, so the
        shards' applies overlap under their own locks.
        ``last_update`` (DynSGD) is resolved to the PER-SHARD counters of
        the most recent pull: staleness is measured against each shard's
        own clock, which matches the single-server math because shard
        counters move in lockstep.  Never auto-retries a dead connection —
        it surfaces to the worker's retry policy with the other shards'
        replies drained.

        A fault-injector drop is handled by SHAPE: every shard dropped is
        the single-server lost-update (return False, vectors still
        aligned); SOME shards dropped is a torn logical commit — left
        alone the version vectors never re-agree and every future pull
        degrades to the ``cut_incomplete`` fallback — so the dropped
        slices are re-sent (bounded, each a recorded
        ``ps.shard.commit_repairs``) until the commit landed everywhere.
        Only identity codecs can re-send: an error-feedback codec's
        residual already absorbed the first encode, so re-encoding would
        double-count the delta — there the torn commit stands (the
        documented degraded path) and the commit reports False."""
        with self._span("ps.shard.commit"):
            slices = self.plan.split(delta)

            def _send(i: int) -> None:
                self.clients[i].commit_send(
                    slices[i],
                    last_update=self._pull_counters[i]
                    if last_update is not None else None,
                    gap_s=gap_s)

            def _finish(idxs) -> list:
                errs = []
                for i in idxs:
                    try:
                        ok[i] = self.clients[i].commit_finish()
                    except BaseException as e:  # noqa: BLE001 — re-raised
                        errs.append(e)
                for e in errs:
                    if isinstance(e, WorkerEvicted):
                        raise e  # clean wind-down signal outranks faults
                if errs:
                    raise errs[0]
                return errs

            ok = [False] * len(self.clients)
            for i in range(len(self.clients)):
                _send(i)
            _finish(range(len(self.clients)))
            for _ in range(2):
                dropped = [i for i, o in enumerate(ok) if not o]
                if not dropped or not any(ok):
                    break  # landed everywhere, or a clean full drop
                if not all(self.clients[i].codec.is_identity
                           for i in dropped):
                    break  # EF residual already spent — can't re-send
                self._c_repairs.inc(len(dropped))
                for i in dropped:
                    _send(i)
                _finish(dropped)
            return all(ok)

    # -- the rest of the PSClient surface ------------------------------------
    def invalidate(self) -> None:
        """Drop every shard connection's center cache (see
        ``PSClient.invalidate``); DOWN references are kept per-link."""
        for c in self.clients:
            c.invalidate()

    def stats(self) -> dict:
        """One merged stats document + the per-shard replies (balance
        inspection): counters/histograms sum across shards, ground-truth
        counters take the consistent view (min for per-worker commits,
        max for the in-flight update edge)."""
        replies = [c.stats() for c in self.clients]
        return {**merge_fleet_stats(replies),
                "server": "ShardedParameterServer",
                "num_workers": replies[0].get("num_workers"),
                "plan": self.plan.descriptor(),
                "shards": replies}

    def close(self) -> None:
        for c in self.clients:
            c.close()  # PSClient.close already tolerates dead sockets

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

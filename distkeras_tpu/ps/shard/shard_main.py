"""One shard-server OS process: ``python -m distkeras_tpu.ps.shard.shard_main SPEC``.

The deployment shape of a sharded parameter server is a FLEET — one
single-shard server per process (per host, at scale), exactly like the
reference's parameter-server processes (Li et al., OSDI'14).  This module
is that process: it rebuilds the center from a spec file, derives the
shard plan deterministically (the same pure function every worker runs),
hosts ITS slice behind a :class:`~.server.ShardFrontend`, writes the
bound port to ``port_file`` for the spawner, and serves until killed.

The spec is a msgpack tree (``utils.serde``)::

    {"center_blob": tree_to_bytes(full center tree),
     "num_shards": int, "shard_index": int, "epoch": int,
     "ps_class": "delta" | "adag" | "dynsgd",
     "num_workers": int, "host": str (default 127.0.0.1),
     "port": int (0 = ephemeral), "port_file": path}

Used by :class:`~.server.ProcessShardFleet` (the bench's
``--ps-shard-placement processes`` mode); also runnable by hand for a
manual multi-host fleet — same spec on every host, ``shard_index``
varied.
"""

from __future__ import annotations

import os
import sys
import time


def run_spec(spec_path: str) -> None:
    # shard servers are pure host-side processes: never grab a device.
    # The env var alone is not enough on machines with an interpreter
    # startup hook that re-points JAX at the accelerator (same rule as
    # ps.worker_main): config.update before first backend use wins.
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    from ...utils import serde
    from ..servers import (ADAGParameterServer, DeltaParameterServer,
                           DynSGDParameterServer)
    from .plan import ShardPlan
    from .server import ShardFrontend

    classes = {"delta": DeltaParameterServer, "adag": ADAGParameterServer,
               "dynsgd": DynSGDParameterServer}
    with open(spec_path, "rb") as f:
        spec = serde.tree_from_bytes(f.read())
    center = serde.tree_from_bytes(spec["center_blob"])
    plan = ShardPlan.build(center, int(spec["num_shards"]),
                           epoch=int(spec.get("epoch", 0)))
    i = int(spec["shard_index"])
    ps = classes[spec.get("ps_class", "delta")](
        plan.split(center)[i], num_workers=int(spec.get("num_workers", 1)))
    server = ShardFrontend(ps, plan, i,
                           host=spec.get("host", "127.0.0.1"),
                           port=int(spec.get("port", 0))).start()
    if spec.get("port_file"):
        tmp = spec["port_file"] + ".tmp"
        with open(tmp, "w") as f:
            f.write(str(server.port))
        os.replace(tmp, spec["port_file"])  # atomic: spawner never
        #                                      reads a half-written port
    try:
        while True:  # serve until the spawner kills us
            time.sleep(1.0)
    except KeyboardInterrupt:
        pass
    finally:
        server.stop()


def main(argv=None) -> int:
    from ...obs import emit
    argv = argv if argv is not None else sys.argv[1:]
    if len(argv) != 1:
        emit("usage: python -m distkeras_tpu.ps.shard.shard_main SPEC",
             err=True)
        return 2
    run_spec(argv[0])
    return 0


if __name__ == "__main__":
    sys.exit(main())

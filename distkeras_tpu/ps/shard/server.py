"""Sharded center host: N single-shard parameter servers behind N
front-ends, one process (ISSUE 10).

``ShardedParameterServer`` partitions the center pytree with a
:class:`~.plan.ShardPlan` and hosts one ``ParameterServer`` (the caller's
update-rule class, unmodified) per shard behind one :class:`ShardFrontend`
each — so every shard owns its own commit mutex, accept loop, handler
threads, pre-serialized pull cache, codec accounting, and obs registry.
Commits and pulls from a ``ShardedPSClient`` hit the shards in parallel:
the single ``apply_commit`` lock and single accept thread the w4
contention sweep measured stop being THE ceiling and become one ceiling
per shard.

The facade also speaks the ``ParameterServer``-shaped surface the
``FleetSupervisor`` and async runner drive (``evict_worker`` /
``register_respawn`` / ``register_join`` / ``commits_by_worker`` /
``get_model`` / ``last_seen_age``), fanning lifecycle transitions out to
every shard.  Generation tombstoning is per-shard best-effort, not a
fleet-wide transaction: a zombie whose commit fan-out races the
sequential eviction sweep can land on a not-yet-bumped shard while the
already-bumped ones tombstone it.  The safety nets are the ones the
single-server path already relies on — the consistent-cut pull's
``cut_incomplete`` fallback absorbs the diverged version vector, and
respawn's MIN-window resume replays at-least-once rather than losing the
window (fleet-wide atomic eviction is 2PC territory: ROADMAP,
self-healing round 3).

Shard failure is **fatal and loud** (ISSUE 10 satellite):
:meth:`raise_if_unhealthy` — polled by the supervisor — names the dead
shard and its last commit counter instead of letting workers spin in
reconnect backoff against a vanished listener.  Automatic shard failover
is explicitly deferred (ROADMAP, self-healing round 3).
"""

from __future__ import annotations

import os
import subprocess
import sys
import tempfile
import threading
import time
from typing import Any, Callable, List, Optional, Tuple

from ...obs import Registry
from ..networking import WIRE_VERSION
from ..servers import SocketParameterServer
from .plan import ShardPlan

Tree = Any


class ShardFleetError(RuntimeError):
    """A PS shard died while the fleet depended on it — fatal for the
    run (failover is a ROADMAP item, not a silent hang)."""


class ShardFrontend(SocketParameterServer):
    """One shard's TCP front-end: a ``SocketParameterServer`` that
    (1) ships the shard placement descriptor in its ``hello`` reply so
    clients verify plan agreement at negotiation time, (2) answers the
    ``plan`` action with the full plan document (the v1-interop and
    obsview path — v1 clients never send a hello), and (3) serves
    **versioned pulls**: the reply carries this shard's per-worker commit
    counts (the version vector) and plan epoch, captured atomically with
    the center — the consistent-cut pull's raw material."""

    def __init__(self, ps, plan: ShardPlan, shard_index: int, **kw):
        super().__init__(ps, **kw)
        self.plan = plan
        self.shard_index = int(shard_index)
        self.registry.gauge("ps.shard.index").set(self.shard_index)

    def shard_descriptor(self) -> dict:
        return {"index": self.shard_index, **self.plan.descriptor()}

    def hello_reply(self, msg: dict, ver: int) -> dict:
        reply = super().hello_reply(msg, ver)
        reply["shard"] = self.shard_descriptor()
        return reply

    def _pull_state(self):
        center, updates, vv = self.ps.pull_versioned()
        return center, updates, {"vv": vv, "shard": self.shard_index,
                                 "plan_epoch": self.plan.epoch}

    def handle_request(self, action, msg, ver, conn):
        if action == "plan":
            return {"ok": True, "shard": self.shard_descriptor(),
                    "plan": self.plan.doc()}
        reply = super().handle_request(action, msg, ver, conn)
        if action == "stats" and isinstance(reply, dict):
            reply["shard"] = self.shard_descriptor()
        return reply


class _MergedRegistryView:
    """Read-only merged view over the shard registries — satisfies the
    ``.snapshot()`` surface the runner persists (counters/histograms sum
    across shards; per-shard views stay exact via each shard's own
    ``stats`` RPC)."""

    def __init__(self, servers: List[ShardFrontend]):
        self._servers = servers

    def snapshot(self) -> dict:
        return Registry.merge_snapshots(
            *[s.registry.snapshot() for s in self._servers])


class ShardedParameterServer:
    """N single-shard servers + the supervisor-facing facade.

    ``ps_factory(center_slice, num_workers=...)`` builds each shard's
    update-rule server (the trainer's ``_ps_factory`` unchanged — a
    shard's slice is a valid pytree).  Every shard gets its own registry,
    lock, accept loop, pull cache, and codec accounting.
    """

    def __init__(self, center: Tree, num_shards: int,
                 ps_factory: Callable[..., Any], num_workers: int = 1,
                 host: str = "127.0.0.1",
                 epoch: int = 0, fault_injector=None,
                 max_wire_version: int = WIRE_VERSION,
                 tracer_factory: Optional[Callable[[Registry], Any]] = None):
        self.plan = ShardPlan.build(center, num_shards, epoch=epoch)
        self.host = host
        slices = self.plan.split(center)
        self.shards = [ps_factory(slices[i], num_workers=num_workers)
                       for i in range(num_shards)]
        self.servers = [
            ShardFrontend(self.shards[i], self.plan, i, host=host,
                          fault_injector=fault_injector,
                          max_wire_version=max_wire_version,
                          tracer=tracer_factory(self.shards[i].registry)
                          if tracer_factory is not None else None)
            for i in range(num_shards)]
        self.num_workers = int(num_workers)
        self.registry = _MergedRegistryView(self.servers)
        #: facade generation mirror (the supervisor reads it under
        #: ``mutex`` exactly like a plain ParameterServer's)
        self.mutex = threading.Lock()
        self.generations: dict = {}
        self._stopping = False

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "ShardedParameterServer":
        for s in self.servers:
            s.start()
        return self

    def stop(self) -> None:
        self._stopping = True
        for s in self.servers:
            s.stop()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    @property
    def ports(self) -> List[int]:
        return [s.port for s in self.servers]

    def addrs(self) -> List[Tuple[str, int]]:
        return [(self.host, s.port) for s in self.servers]

    # -- health (ISSUE 10 satellite: dead shard == fatal, named) ------------
    def _dead_reason(self, srv: ShardFrontend) -> Optional[str]:
        if not srv._running.is_set():
            return "stopped"
        if srv._sock is None or srv._sock.fileno() < 0:
            return "listener closed"
        with srv._conn_lock:
            accept = srv._threads[0] if srv._threads else None
        if accept is not None and not accept.is_alive():
            return "accept loop died"
        return None

    def raise_if_unhealthy(self) -> None:
        """Raise :class:`ShardFleetError` naming any dead shard (id,
        address, last commit counter) — the supervisor polls this so a
        vanished shard fails the run in seconds with a diagnosis instead
        of every worker hanging in reconnect backoff."""
        if self._stopping:
            return
        for i, srv in enumerate(self.servers):
            reason = self._dead_reason(srv)
            if reason is not None:
                raise ShardFleetError(
                    f"ps shard {i}/{self.plan.num_shards} "
                    f"({self.host}:{srv.port}) is dead ({reason}); its "
                    f"last commit counter was {self.shards[i].num_updates} "
                    "— shard failover is not implemented (ROADMAP: "
                    "self-healing round 3), treating this as a fatal "
                    "fleet error")

    # -- supervisor-facing ParameterServer surface --------------------------
    @property
    def num_updates(self) -> int:
        """Logical update count: shards move in lockstep (every logical
        commit lands once per shard); the max is the in-flight edge."""
        return max((ps.num_updates for ps in self.shards), default=0)

    @property
    def commits_by_worker(self) -> dict:
        """Element-wise MIN across shards — the fully-committed prefix
        (a commit counts once every shard has applied it)."""
        out: dict = {}
        for ps in self.shards:
            with ps.mutex:
                counts = dict(ps.commits_by_worker)
            for w, c in counts.items():
                out[w] = c if w not in out else min(out[w], c)
        return out

    def evict_worker(self, worker_id) -> int:
        """Fan the eviction to every shard (each independently tombstones
        the zombie's late commits); returns the fully-committed window
        (element-wise MIN — conservative: a commit the sweep caught on
        only SOME shards is replayed by the respawn, at-least-once, not
        lost).  The sweep is sequential, so a zombie mid-fan-out can land
        a slice on a not-yet-bumped shard — see the module docstring for
        why that is absorbed rather than prevented."""
        w = int(worker_id)
        window = None
        for ps in self.shards:
            win = ps.evict_worker(w)
            window = win if window is None else min(window, win)
        with self.mutex:
            self.generations[w] = self.generations.get(w, 0) + 1
        return window or 0

    def register_respawn(self, worker_id) -> tuple:
        w = int(worker_id)
        window, gen = None, 0
        for ps in self.shards:
            win, g = ps.register_respawn(w)
            window = win if window is None else min(window, win)
            gen = max(gen, g)
        return (window or 0, gen)

    def register_join(self, worker_id) -> tuple:
        w = int(worker_id)
        window, gen = None, 0
        for ps in self.shards:
            win, g = ps.register_join(w)
            window = win if window is None else min(window, win)
            gen = max(gen, g)
        return (window or 0, gen)

    def get_model(self) -> Tree:
        """Assemble the full center from every shard's slice.  Reads each
        shard under its own mutex; at rest (workers joined) this is the
        exact center, mid-run it is a best-effort snapshot — workers use
        the consistent-cut client pull instead."""
        return self.plan.assemble(*[ps.get_model() for ps in self.shards])

    def last_seen_age(self, worker_id) -> Optional[float]:
        """Freshest traffic from this worker across ALL shards — a worker
        is live if anything from it reached any shard recently."""
        ages = [srv.last_seen_age(worker_id) for srv in self.servers]
        ages = [a for a in ages if a is not None]
        return min(ages) if ages else None

    # -- telemetry ----------------------------------------------------------
    def stats(self) -> dict:
        """Merged stats document + per-shard balance (the obsview fleet
        view's source when polled in-process)."""
        per_shard = []
        for i, (ps, srv) in enumerate(zip(self.shards, self.servers)):
            snap = ps.registry.snapshot()
            per_shard.append({
                "shard": i, "port": srv.port,
                "num_updates": ps.num_updates,
                "commits": snap.get("ps.commits", {}).get("value", 0),
                "bytes_sent": snap.get("net.bytes_sent", {}).get("value", 0),
                "bytes_recv": snap.get("net.bytes_recv", {}).get("value", 0),
            })
        return {"stats": self.registry.snapshot(),
                "num_updates": self.num_updates,
                "commits_by_worker": self.commits_by_worker,
                "server": type(self).__name__,
                "num_workers": self.num_workers,
                "plan": self.plan.descriptor(),
                "shards": per_shard}

    def write_plan(self, path: str) -> None:
        """Persist the plan file (addresses included) — the hand-off
        artifact ``obsview --ps <plan.json>`` and out-of-process clients
        consume."""
        import json
        with open(path, "w") as f:
            json.dump(self.plan.doc(addresses=self.addrs()), f, indent=1)


class ProcessShardFleet:
    """The deployment shape: one shard-server OS PROCESS per shard
    (``ps.shard.shard_main``), so shards stop sharing one interpreter's
    GIL — the bench's ``--ps-shard-placement processes`` mode and the
    manual multi-host recipe (same spec per host, ``shard_index``
    varied).  Exposes ``addrs()``/``plan``/``stop()`` like the
    in-process :class:`ShardedParameterServer`; workers connect with the
    same ``ShardedPSClient``.

    Process shards are stats-pollable over the wire (``obsview --ps``
    with the plan file) but are NOT supervisor-integrated here: the
    in-process fleet remains the trainer default, and shard failover is
    the ROADMAP's round-3 item either way.
    """

    def __init__(self, center: Any, num_shards: int,
                 ps_class: str = "delta", num_workers: int = 1,
                 host: str = "127.0.0.1", epoch: int = 0,
                 start_timeout_s: float = 60.0):
        from ...utils import serde
        self.plan = ShardPlan.build(center, num_shards, epoch=epoch)
        self.host = host
        self._td = tempfile.TemporaryDirectory(prefix="dktpu-shards-")
        blob = serde.tree_to_bytes(center)
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"  # shard hosts never grab a device
        self.procs: List[subprocess.Popen] = []
        port_files = []
        for i in range(num_shards):
            spec = {"center_blob": blob, "num_shards": int(num_shards),
                    "shard_index": i, "epoch": int(epoch),
                    "ps_class": ps_class, "num_workers": int(num_workers),
                    "host": host, "port": 0,
                    "port_file": os.path.join(self._td.name, f"port_{i}")}
            spec_path = os.path.join(self._td.name, f"shard_{i}.spec")
            with open(spec_path, "wb") as f:
                f.write(serde.tree_to_bytes(spec))
            port_files.append(spec["port_file"])
            self.procs.append(subprocess.Popen(
                [sys.executable, "-m", "distkeras_tpu.ps.shard.shard_main",
                 spec_path], env=env))
        self.ports: List[int] = []
        deadline = time.monotonic() + float(start_timeout_s)
        for i, pf in enumerate(port_files):
            while not os.path.exists(pf):
                if self.procs[i].poll() is not None:
                    self.stop()
                    raise RuntimeError(
                        f"shard process {i} exited rc="
                        f"{self.procs[i].returncode} before binding")
                if time.monotonic() > deadline:
                    self.stop()
                    raise RuntimeError(
                        f"shard process {i} did not bind within "
                        f"{start_timeout_s:.0f}s")
                time.sleep(0.02)
            with open(pf) as f:
                self.ports.append(int(f.read()))

    def addrs(self) -> List[Tuple[str, int]]:
        return [(self.host, p) for p in self.ports]

    def stop(self) -> None:
        for p in self.procs:
            if p.poll() is None:
                p.kill()
        for p in self.procs:
            if p.poll() is None:
                p.wait()
        self._td.cleanup()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()

"""Shard placement plan: deterministic per-tensor partitioning of the
center pytree across N parameter-server shards (ISSUE 10).

The plan is a pure function of ``(tree structure, num_shards)``: leaves
are enumerated in a canonical path order (dict keys sorted, sequence
indices in order) and placed with a greedy byte-balance rule — largest
tensors first, each onto the currently-lightest shard, ties broken by
shard index.  Workers and shards each build the plan independently from
their own copy of the tree and must agree; the :attr:`ShardPlan.digest`
(sha256 over the canonical assignment map) is exchanged in the ``hello``
negotiation so disagreement is caught at connect time, not as silently
mis-assembled centers.

A shard's slice of the tree is a **flat path-keyed dict**
(``{"params/0/w": ndarray, ...}``): ndarray-leaved, msgpack-safe, and a
valid pytree for every update rule, so each shard hosts an unmodified
``ParameterServer`` subclass over its slice.  :meth:`ShardPlan.assemble`
rebuilds the original structure from the union of slices.
"""

from __future__ import annotations

import hashlib
import json
from typing import Any, Dict, List, Tuple

import numpy as np

Tree = Any

SCHEMA = "dktpu-shard-plan/v1"


class _Slot:
    """Leaf placeholder in the structure skeleton (a plain string could
    collide with a genuine string leaf)."""

    __slots__ = ("path",)

    def __init__(self, path: str):
        self.path = path


def _flatten(tree: Tree, prefix: str = "") -> List[Tuple[str, Any]]:
    """Canonical-order ``(path, leaf)`` pairs: dicts by sorted key,
    sequences by index — the one leaf enumeration every plan builder
    (worker AND shard host) must share for digests to agree."""
    if isinstance(tree, dict):
        out: List[Tuple[str, Any]] = []
        for k in sorted(tree):
            if not isinstance(k, str):
                raise TypeError(
                    f"shard plans need string dict keys, got {k!r}")
            out.extend(_flatten(tree[k], f"{prefix}{k}/"))
        return out
    if isinstance(tree, (list, tuple)):
        out = []
        for i, v in enumerate(tree):
            out.extend(_flatten(v, f"{prefix}{i}/"))
        return out
    return [(prefix[:-1] if prefix else "", tree)]


def _skeleton(tree: Tree, prefix: str = "") -> Tree:
    """The tree with every leaf replaced by a :class:`_Slot` — assembly's
    structural template (empty containers survive verbatim)."""
    if isinstance(tree, dict):
        return {k: _skeleton(tree[k], f"{prefix}{k}/") for k in tree}
    if isinstance(tree, (list, tuple)):
        seq = [_skeleton(v, f"{prefix}{i}/") for i, v in enumerate(tree)]
        return seq if isinstance(tree, list) else tuple(seq)
    return _Slot(prefix[:-1] if prefix else "")


def _leaf_bytes(leaf: Any) -> int:
    """Placement weight of one leaf (ndarray nbytes; scalars count 8).
    Must be derivable identically on every participant — it only reads
    dtype/shape, never values."""
    try:
        return int(np.asarray(leaf).nbytes)
    except (TypeError, ValueError):
        return 8


class ShardPlan:
    """Deterministic per-tensor placement of a pytree across N shards."""

    def __init__(self, assignments: Dict[str, int], num_shards: int,
                 epoch: int, skeleton: Tree, leaf_bytes: Dict[str, int]):
        self.assignments = dict(assignments)
        self.num_shards = int(num_shards)
        #: plan generation: a re-sharded / restarted fleet bumps it, and
        #: the consistent-cut pull refuses to assemble slices from two
        #: different epochs
        self.epoch = int(epoch)
        self._skeleton = skeleton
        self.leaf_bytes = dict(leaf_bytes)
        self.digest = self._digest()

    # -- construction -------------------------------------------------------
    @classmethod
    def build(cls, tree: Tree, num_shards: int, epoch: int = 0) -> "ShardPlan":
        """Greedy byte-balanced placement: leaves sorted by (bytes desc,
        path), each assigned to the lightest shard so far (ties -> lowest
        index).  Deterministic for a given structure, so workers and the
        shard host derive the SAME plan independently."""
        num_shards = int(num_shards)
        if num_shards < 1:
            raise ValueError(f"num_shards must be >= 1, got {num_shards}")
        leaves = _flatten(tree)
        if len(set(p for p, _ in leaves)) != len(leaves):
            raise ValueError("duplicate leaf paths (a dict key contains "
                             "'/' ambiguously)")
        sizes = {p: _leaf_bytes(v) for p, v in leaves}
        load = [0] * num_shards
        assignments: Dict[str, int] = {}
        for path, _ in sorted(leaves, key=lambda kv: (-sizes[kv[0]], kv[0])):
            shard = min(range(num_shards), key=lambda i: (load[i], i))
            assignments[path] = shard
            load[shard] += sizes[path]
        return cls(assignments, num_shards, epoch, _skeleton(tree), sizes)

    def _digest(self) -> str:
        doc = {"schema": SCHEMA, "num_shards": self.num_shards,
               "epoch": self.epoch,
               "assignments": {k: self.assignments[k]
                               for k in sorted(self.assignments)}}
        return hashlib.sha256(
            json.dumps(doc, sort_keys=True).encode()).hexdigest()[:16]

    # -- negotiation --------------------------------------------------------
    def descriptor(self) -> dict:
        """The compact agreement token the ``hello`` reply carries."""
        return {"num_shards": self.num_shards, "epoch": self.epoch,
                "digest": self.digest}

    # -- split / assemble ---------------------------------------------------
    def split(self, tree: Tree) -> List[Dict[str, Any]]:
        """Tree -> one flat ``{path: leaf}`` slice per shard.  The tree
        must have exactly the plan's structure (same leaf paths)."""
        slices: List[Dict[str, Any]] = [{} for _ in range(self.num_shards)]
        paths = set()
        for path, leaf in _flatten(tree):
            shard = self.assignments.get(path)
            if shard is None:
                raise KeyError(f"leaf {path!r} is not in the shard plan")
            slices[shard][path] = leaf
            paths.add(path)
        missing = set(self.assignments) - paths
        if missing:
            raise KeyError(f"tree is missing planned leaves: "
                           f"{sorted(missing)[:4]}...")
        return slices

    def assemble(self, *slices: Dict[str, Any]) -> Tree:
        """Union of flat slices -> the original tree structure."""
        flat: Dict[str, Any] = {}
        for s in slices:
            flat.update(s)

        def fill(node):
            if isinstance(node, _Slot):
                if node.path not in flat:
                    raise KeyError(f"assembled center is missing leaf "
                                   f"{node.path!r}")
                return flat[node.path]
            if isinstance(node, dict):
                return {k: fill(v) for k, v in node.items()}
            if isinstance(node, list):
                return [fill(v) for v in node]
            if isinstance(node, tuple):
                return tuple(fill(v) for v in node)
            return node

        return fill(self._skeleton)

    # -- documents ----------------------------------------------------------
    def doc(self, addresses=None) -> dict:
        """Plain-data plan document (the ``plan`` RPC reply body; with
        ``addresses`` it is also the plan FILE ``obsview --ps`` reads:
        one entry per shard with host/port and its leaves)."""
        shards = []
        for i in range(self.num_shards):
            paths = sorted(p for p, s in self.assignments.items() if s == i)
            entry = {"index": i,
                     "paths": paths,
                     "bytes": int(sum(self.leaf_bytes.get(p, 0)
                                      for p in paths))}
            if addresses is not None:
                entry["host"], entry["port"] = addresses[i]
            shards.append(entry)
        return {"schema": SCHEMA, "num_shards": self.num_shards,
                "epoch": self.epoch, "digest": self.digest,
                "shards": shards}

"""TCP message layer — parity with reference ``distkeras/networking.py``.

Same surface (``determine_host_address``, ``connect``, send/recv of whole
messages), different wire format: the reference pickles arbitrary objects
(``send_data``/``recv_data``); we frame **msgpack** blobs with a uint64
length prefix via ``utils.serde`` — safe against arbitrary-code
deserialization and identical across hosts.

Instrumented (ISSUE 2): every framed send/recv counts messages and wire
bytes (frame header included) into an ``obs.Registry`` — the component's
own when the caller passes one (the PS server's ``STATS`` snapshot counts
its traffic), the process-wide default otherwise; ``connect`` counts
attempts that failed-and-retried.
"""

from __future__ import annotations

import socket
import struct
import time
from typing import Any, Optional

from ..obs import default_registry
from ..utils import serde

_LEN = struct.Struct(">Q")


def determine_host_address() -> str:
    """Routable local IP via the UDP-connect trick (parity: reference
    ``distkeras/networking.py:determine_host_address``)."""
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect(("8.8.8.8", 80))
        return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"
    finally:
        s.close()


def connect(host: str, port: int, timeout: Optional[float] = 30.0,
            retries: int = 20, retry_delay: float = 0.1) -> socket.socket:
    """Connect with retries (the PS thread may not be listening yet —
    the reference relied on Spark task startup latency to hide this)."""
    last = None
    reg = default_registry()
    for _ in range(max(1, retries)):
        try:
            s = socket.create_connection((host, port), timeout=timeout)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            reg.counter("net.connects").inc()
            return s
        except OSError as e:
            last = e
            reg.counter("net.connect_retries").inc()
            time.sleep(retry_delay)
    raise ConnectionError(f"cannot connect to {host}:{port}: {last}")


def send_msg(sock: socket.socket, obj: Any, registry=None) -> None:
    """Length-prefixed msgpack send (parity: reference ``send_data``)."""
    blob = serde.tree_to_bytes(obj)
    sock.sendall(_LEN.pack(len(blob)) + blob)
    reg = registry if registry is not None else default_registry()
    reg.counter("net.msgs_sent").inc()
    reg.counter("net.bytes_sent").inc(_LEN.size + len(blob))


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n:
        chunk = sock.recv(min(n, 1 << 20))
        if not chunk:
            raise ConnectionError("socket closed mid-message")
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


def recv_msg(sock: socket.socket, registry=None) -> Any:
    """Recv-all loop for one framed message (parity: reference
    ``recv_data``)."""
    (n,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    msg = serde.tree_from_bytes(_recv_exact(sock, n))
    reg = registry if registry is not None else default_registry()
    reg.counter("net.msgs_recv").inc()
    reg.counter("net.bytes_recv").inc(_LEN.size + n)
    return msg

"""TCP message layer — parity with reference ``distkeras/networking.py``.

Same surface (``determine_host_address``, ``connect``, send/recv of whole
messages), different wire format: the reference pickles arbitrary objects
(``send_data``/``recv_data``); we frame **msgpack** blobs — safe against
arbitrary-code deserialization and identical across hosts.

Two frame formats coexist on the same port (ISSUE 4):

* **v1**: ``>Q`` length prefix + one self-contained msgpack blob
  (``serde.tree_to_bytes`` — every tensor copied into the blob).  The
  compatibility format old workers speak.
* **v2**: ``b"DKW2"`` magic + segment count + length table, then the
  msgpack header and the raw tensor **segments** (``serde.tree_to_frames``)
  sent scatter-gather via ``socket.sendmsg`` — tensor bytes go straight
  from the arrays' buffers to the kernel, never through an intermediate
  blob; the receiver reads each segment into its own buffer
  (``recv_into``) and wraps it zero-copy.

``recv_msg`` auto-detects the format per message (the v2 magic's first
byte can never open a v1 length prefix below 4.9 EB), so a server accepts
both; which format a peer may *send to you* is negotiated once per
connection by the PS hello handshake (``ps.client`` / ``ps.servers``).

The framing is payload-agnostic; protocol extensions ride as extra keys
in the msgpack map, never as frame changes — unknown keys are ignored by
every parser of this wire, so extensions degrade cleanly against old
peers.  ISSUE 5 adds two: a ``trace`` header (``trace_id``/``parent_span``
— cross-process span linkage) that clients send only on v2 connections
(adoption needs both ends current, so v1 peers never see it), and
``gap_s`` (the worker's heartbeat gap, feeding the server's straggler
detector) which rides EVERY commit regardless of wire version — straggler
visibility matters most for the legacy-pinned fleets most likely to
contain one; old servers ignore it.  ISSUE 9 adds ``gen`` (the worker
incarnation's commit generation — the server tombstones commits from
generations it has evicted; old servers ignore it and old workers imply
generation 0) and a process-wide fault-injection seam
(:func:`set_fault_hook`) the chaos harness uses to inject socket resets
and timeouts into the negotiation and commit paths.

ISSUE 12 adds two transport-level pieces:

* **Direction-tagged wire counters** — send/recv calls that name a
  ``count_as`` counter additionally fold the message's bytes into it
  (``ps.wire.bytes_up`` for worker->server traffic, ``ps.wire.bytes_down``
  for server->worker), so DOWN-compression savings are directly
  observable; the aggregate ``net.bytes_sent``/``net.bytes_recv`` totals
  keep their historical meaning for baseline continuity.
* **Same-host shared-memory transport** — negotiated in the existing
  ``hello`` seam like the v2 frame: the client creates two
  ``multiprocessing.shared_memory`` rings and ships their names in the
  hello; a server that can actually attach them (the capability probe —
  no host heuristics) acks, and from then on v2 messages travel as a
  ``DKW3`` control frame over TCP (header + length table + ring offset)
  with the tensor segments exchanged through the ring: one memcpy,
  no kernel socket path, for co-located peers (the cluster runner
  co-locates PS shards and workers on process 0's host; thread-placed
  shard fleets are all-local by construction).  Messages too big for the
  ring transparently fall back to the TCP frame per message — the
  receiver auto-detects ``DKW2`` vs ``DKW3`` like it auto-detects v1/v2.
  The ring owner (client) unlinks on close; attachments just close.

ISSUE 15 adds **streamed pull replies** (``DKW4``): a pull reply used to
be one monolithic message, so the client could touch byte 0 only after
the last byte left the server.  A streamed reply is a ``DKW4`` announce
frame (magic + chunk count) followed by ordinary framed messages — one
tiny **prologue** (the reply document with every tensor leaf replaced by
an index stub) and N self-describing **chunk** frames, each carrying a
bounded leaf group in tree order.  The receiver decodes chunk k while
chunk k+1 is still on the wire: the prologue also announces each
chunk's exact frame size, so every chunk lands via one big
``recv_into`` into a slice of a pooled per-pull receive arena — no
intermediate assembly blob, zero-copy leaf views, zero large
allocations in steady state — and a worker that issued the pull before
blocking on its device step hides the whole transfer behind compute
(``ps.client`` / ``ps.workers``).
Streaming is negotiated in the hello (``stream`` extra; ``DKTPU_STREAM=0``
pins either end to monolithic replies) and requested per pull, so v1
peers, stream-disabled peers, and non-pull traffic stay bit-identical on
the wire.  Over a negotiated shm channel the chunks ride the ring only
when the WHOLE stream fits at once (:meth:`ShmRing.stream_begin` — the
wrap rule assumes one unread message, which a multi-frame stream is
not); otherwise the reply's frames stay on TCP.

Instrumented (ISSUE 2): every framed send/recv counts messages and wire
bytes (frame header included) into an ``obs.Registry`` — the component's
own when the caller passes one (the PS server's ``STATS`` snapshot counts
its traffic), the process-wide default otherwise; ``connect`` counts
attempts that failed-and-retried.  Shared-memory segment bytes count in
the same totals (they are message bytes, whatever plane carried them)
plus ``net.bytes_shm`` for the share that bypassed TCP.
"""

from __future__ import annotations

import os
import socket
import struct
import sys
import threading
import time
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

from ..obs import default_registry
from ..obs.logging import get_logger
from ..utils import serde

_LEN = struct.Struct(">Q")
_MAGIC2 = b"DKW2"
_MAGIC3 = b"DKW3"  # shm data plane: control frame on TCP, segments in the ring
_MAGIC4 = b"DKW4"  # streamed pull reply: announce + prologue + chunk frames
_V2HEAD = struct.Struct(">4sI")  # magic + segment count

#: newest frame format this build speaks; the hello handshake negotiates
#: min(client, server) per connection
WIRE_VERSION = 2

#: max buffers per sendmsg call (stay well under any platform IOV_MAX)
_IOV_CHUNK = 256


# ---------------------------------------------------------------------------
# fault-injection seam (ISSUE 9: the chaos harness's socket-level hook)
# ---------------------------------------------------------------------------

#: process-wide chaos hook (``distkeras_tpu.chaos.SocketFaults`` installs
#: one): called at the wire's choke points — ``("connect", None)`` before
#: each dial, ``("handshake", None)`` entering the v1/v2 negotiation,
#: ``("send", action)`` / ``("recv", None)`` around each framed message —
#: and *raises* (ConnectionResetError, socket.timeout, ...) to inject the
#: fault.  None (the default) costs one global read per message.
_fault_hook = None


def set_fault_hook(hook):
    """Install (or clear, with None) the socket fault-injection hook;
    returns the previous hook so chaos harnesses can nest/restore."""
    global _fault_hook
    prev = _fault_hook
    _fault_hook = hook
    return prev


def _inject_fault(stage: str, action=None) -> None:
    hook = _fault_hook
    if hook is not None:
        hook(stage, action)


def backoff_delays(attempts: int, base: float = 0.1, cap: float = 2.0,
                   jitter: float = 0.25):
    """Capped exponential backoff with ±``jitter`` randomization — the
    retry pacing both reconnect paths share (ISSUE 9 satellite: a fleet
    of workers re-dialing a restarted PS in lockstep is a thundering
    herd; jitter de-synchronizes them).  Yields ``attempts - 1`` sleep
    durations (one per gap between attempts)."""
    import random
    d = float(base)
    for _ in range(max(0, int(attempts) - 1)):
        yield d * (1.0 + random.uniform(-jitter, jitter))
        d = min(d * 2.0, float(cap))


def retry_with_backoff(attempt, attempts: int, base: float, cap: float,
                       on_failure, what: str, log_channel: str):
    """Run ``attempt()`` up to ``attempts`` times with
    :func:`backoff_delays` pacing — the one reconnect loop ``PSClient``
    and ``ServeClient`` share.  ``on_failure()`` is called on EVERY
    failed attempt (the reconnect-failure counters); the final failure
    re-raises.  Returns ``attempt()``'s result."""
    delays = backoff_delays(attempts, base=base, cap=cap)
    for delay in [*delays, None]:
        try:
            return attempt()
        except (ConnectionError, OSError) as e:
            on_failure()
            if delay is None:
                raise
            get_logger(log_channel).warning(
                "%s failed (%s); retrying in %.2fs", what, e, delay)
            time.sleep(delay)


# ---------------------------------------------------------------------------
# streamed pull replies (ISSUE 15: the DKW4 frame)
# ---------------------------------------------------------------------------

#: default per-chunk tensor-payload bound for streamed pulls; a client
#: may request another bound in its hello/pull (one oversized leaf is
#: its own chunk — the bound caps chunk memory, not leaf size)
STREAM_CHUNK_BYTES = int(
    float(os.environ.get("DKTPU_STREAM_CHUNK_MB", 1)) * (1 << 20))

#: floor on a peer-requested chunk bound: a hostile 1-byte request must
#: not turn a pull into thousands of per-leaf frames
MIN_STREAM_CHUNK_BYTES = 64 * 1024


def stream_enabled_env() -> bool:
    """``DKTPU_STREAM=0`` pins this process to monolithic pull replies
    (both directions: a client stops offering, a server stops acking)."""
    return os.environ.get("DKTPU_STREAM") != "0"


_STREAM_LEAF = "__dkstream__"


def stream_split(doc: Any, chunk_bytes: int) -> Tuple[Any, List[tuple]]:
    """``(skeleton, groups)`` for one reply document: every non-empty
    ndarray leaf is replaced by an ``{_STREAM_LEAF: i}`` index stub, and
    ``groups`` is a list of ``(first_leaf_index, [arrays])`` with each
    group's payload bounded by ``chunk_bytes``.  Leaves stay in tree
    (= plan) order, so the receiver can place group k's arrays by index
    without waiting for the rest.  Empty arrays and non-tensor values
    stay inline in the skeleton — they cost nothing to ship there."""
    leaves: List[Any] = []

    def strip(obj):
        if isinstance(obj, np.ndarray) and obj.nbytes:
            leaves.append(obj)
            return {_STREAM_LEAF: len(leaves) - 1}
        if isinstance(obj, dict):
            return {k: strip(v) for k, v in obj.items()}
        if isinstance(obj, (list, tuple)):
            return [strip(v) for v in obj]
        return obj

    skeleton = strip(doc)
    bound = max(1, int(chunk_bytes))
    groups: List[tuple] = []
    cur: List[Any] = []
    cur_bytes, start = 0, 0
    for i, a in enumerate(leaves):
        if cur and cur_bytes + a.nbytes > bound:
            groups.append((start, cur))
            cur, cur_bytes, start = [], 0, i
        cur.append(a)
        cur_bytes += a.nbytes
    if cur:
        groups.append((start, cur))
    return skeleton, groups


def pack_stream(doc: Any, chunk_bytes: int,
                version: int = 2) -> List[Tuple[List[Any], int]]:
    """Pre-serialize one streamed pull reply: ``[prologue, chunk_0,
    ...]`` as :func:`pack_msg` payloads (the pull cache's unit).  The
    prologue is self-describing — skeleton, leaf count, and each chunk's
    exact FRAME size (``frame_bytes``) so the receiver can read a whole
    chunk frame with one big ``recv_into`` into one preallocated buffer
    and decode the leaves as zero-copy views over it; each chunk carries
    its first leaf index, so any placement mistake is detected at
    assembly, never decoded wrong."""
    skeleton, groups = stream_split(doc, chunk_bytes)
    nleaves = sum(len(arrs) for _, arrs in groups)
    chunks = [pack_msg({"chunk": k, "i0": start, "leaves": arrs},
                       version=version)
              for k, (start, arrs) in enumerate(groups)]
    prologue = {"stream": 1, "nchunks": len(groups), "nleaves": nleaves,
                "frame_bytes": [total for _, total in chunks],
                "skeleton": skeleton}
    return [pack_msg(prologue, version=version)] + chunks


def stream_join(skeleton: Any, leaves: List[Any]) -> Any:
    """Inverse of :func:`stream_split`: the skeleton with every index
    stub replaced by its received leaf."""

    def fill(obj):
        if isinstance(obj, dict):
            if _STREAM_LEAF in obj:
                return leaves[obj[_STREAM_LEAF]]
            return {k: fill(v) for k, v in obj.items()}
        if isinstance(obj, list):
            return [fill(v) for v in obj]
        return obj

    return fill(skeleton)


def determine_host_address() -> str:
    """Routable local IP via the UDP-connect trick (parity: reference
    ``distkeras/networking.py:determine_host_address``)."""
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect(("8.8.8.8", 80))
        return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"
    finally:
        s.close()


def connect(host: str, port: int, timeout: Optional[float] = 30.0,
            retries: int = 20, retry_delay: float = 0.1) -> socket.socket:
    """Connect with retries (the PS thread may not be listening yet —
    the reference relied on Spark task startup latency to hide this)."""
    last = None
    reg = default_registry()
    for _ in range(max(1, retries)):
        try:
            _inject_fault("connect")
            s = socket.create_connection((host, port), timeout=timeout)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            reg.counter("net.connects").inc()
            return s
        except OSError as e:
            last = e
            reg.counter("net.connect_retries").inc()
            time.sleep(retry_delay)
    raise ConnectionError(f"cannot connect to {host}:{port}: {last}")


# ---------------------------------------------------------------------------
# hello negotiation (ISSUE 7: the seam the PS stack and the serve stack
# share — one definition of "which frame format may this connection use")
# ---------------------------------------------------------------------------

def pinned_wire_version(want: Optional[int]) -> Optional[int]:
    """Resolve a caller's wire pin: an explicit ``want`` wins; otherwise
    ``DKTPU_WIRE=1`` pins the whole process to the legacy frame."""
    if want is None and os.environ.get("DKTPU_WIRE") == "1":
        return 1
    return want


def choose_wire_version(offered: Optional[Sequence[int]],
                        max_wire_version: int = WIRE_VERSION) -> int:
    """Server side of the hello handshake: the newest offered format this
    end also speaks (1 when nothing admissible was offered — v1 is the
    frozen floor every peer parses)."""
    versions = [int(v) for v in (offered or [1])]
    return max(v for v in versions + [1] if v <= int(max_wire_version))


def client_handshake(sock: socket.socket, registry=None,
                     worker_id: Optional[int] = None,
                     want: Optional[int] = None,
                     info: Optional[dict] = None,
                     extras: Optional[dict] = None) -> int:
    """Client side of the hello handshake; returns the negotiated wire
    version for this connection.  The hello itself is always v1-framed
    (any server parses it); current servers answer with the agreed
    version, old ones with an unknown-action error — that failure IS the
    negotiation result: v1.

    ``info``, when given, is updated in place with the server's full
    hello reply — the channel for negotiation-time extras like a shard
    front-end's placement descriptor (ISSUE 10); old servers' replies
    simply carry no extra keys.  ``extras`` rides in the hello REQUEST
    the same way (ISSUE 12: the DOWN-codec advertisement and the shm
    ring names) — included only when the caller opted in, so the default
    hello stays byte-identical to previous builds."""
    want = pinned_wire_version(want)
    want = WIRE_VERSION if want is None else int(want)
    if want < 2:
        return 1
    _inject_fault("handshake")
    msg: dict = {"action": "hello", "versions": list(range(1, want + 1))}
    if worker_id is not None:
        msg["worker_id"] = int(worker_id)
    if extras:
        msg.update(extras)
    send_msg(sock, msg, registry=registry)
    resp = recv_msg(sock, registry=registry)
    if info is not None and isinstance(resp, dict):
        info.update(resp)
    if resp.get("ok"):
        return int(resp.get("version", 1))
    return 1


# ---------------------------------------------------------------------------
# same-host shared-memory data plane (ISSUE 12)
# ---------------------------------------------------------------------------

#: default ring capacity; a message whose segments exceed the ring falls
#: back to the TCP frame for that message, so this bounds memory, not
#: message size
SHM_RING_MB = float(os.environ.get("DKTPU_SHM_MB", 64))


class ShmRing:
    """One-direction tensor-segment ring over a
    ``multiprocessing.shared_memory`` segment.

    The TCP connection stays the control plane and strictly orders use:
    the writer copies a message's segments into the ring BEFORE sending
    the ``DKW3`` control frame, the reader copies them out after
    receiving it, and the request/reply protocol allows one outstanding
    message per connection — so a write can never overtake an unread
    message.  Lifecycle: the CREATING end owns the segment and must
    ``unlink()`` it on its shutdown path; attaching ends just
    ``close()`` (the dklint ``shm-lifecycle`` rule guards exactly this
    pairing)."""

    def __init__(self, shm, owner: bool):
        self._shm = shm
        self.owner = owner
        self.name = shm.name
        self.size = shm.size
        self._pos = 0

    @classmethod
    def create(cls, size: int) -> "ShmRing":
        from multiprocessing import shared_memory
        return cls(shared_memory.SharedMemory(create=True, size=int(size)),
                   owner=True)

    @classmethod
    def attach(cls, name: str) -> "ShmRing":
        from multiprocessing import shared_memory
        shm = shared_memory.SharedMemory(name=str(name))
        try:
            # the attaching end must NOT own cleanup: unregister it from
            # this process's resource tracker or interpreter shutdown
            # "reclaims" (unlinks) a segment the creator still owns
            from multiprocessing import resource_tracker
            resource_tracker.unregister(shm._name, "shared_memory")
        except (ImportError, AttributeError, KeyError):
            pass
        return cls(shm, owner=False)

    def write(self, views: list) -> Optional[int]:
        """Copy ``views`` contiguously into the ring; returns the start
        offset, or None when they cannot fit (caller falls back to the
        TCP frame for this message)."""
        total = sum(v.nbytes for v in views)
        if total > self.size:
            return None
        if self._pos + total > self.size:
            self._pos = 0  # wrap: the previous message was already read
        off = self._pos
        buf = self._shm.buf
        pos = off
        for v in views:
            buf[pos:pos + v.nbytes] = v
            pos += v.nbytes
        self._pos = pos
        return off

    def stream_begin(self, total: int) -> bool:
        """Start a multi-frame streamed reply (ISSUE 15): reset the write
        cursor to 0 — safe because the strict request/reply ordering
        means every prior message was already read — so the stream's
        sequential chunk writes never wrap mid-stream and a later chunk
        can never overwrite an unread earlier one (per-chunk
        :meth:`write` wrapping assumes ONE unread message, which a
        multi-frame stream is not).  Returns False when ``total`` exceeds
        the ring: the caller must keep the whole stream on TCP."""
        if total > self.size:
            return False
        self._pos = 0
        return True

    def read(self, offset: int, lens: List[int]) -> List[bytearray]:
        """Copy ``lens``-sized segments out of the ring starting at
        ``offset`` — copies, so the writer's next message can never
        mutate a tensor this one decoded."""
        end = offset + sum(lens)
        if offset < 0 or end > self.size:
            raise ConnectionError(
                f"shm frame outside the ring ({offset}..{end} of "
                f"{self.size} bytes)")
        out, pos = [], int(offset)
        view = self._shm.buf
        for n in lens:
            out.append(bytearray(view[pos:pos + n]))
            pos += n
        return out

    def close(self) -> None:
        try:
            self._shm.close()
        except (OSError, BufferError):
            pass

    def unlink(self) -> None:
        try:
            # thread-placed peers attach in the CREATOR's process, and
            # the attach-side unregister removed this process's tracker
            # entry; re-register (idempotent set add) so the unregister
            # inside SharedMemory.unlink balances instead of raising
            # KeyError noise in the tracker at interpreter exit
            from multiprocessing import resource_tracker
            resource_tracker.register(self._shm._name, "shared_memory")
        except (ImportError, AttributeError):
            pass
        try:
            self._shm.unlink()
        except (OSError, FileNotFoundError):
            pass


class ShmChannel:
    """A negotiated connection: TCP control socket + one ring per
    direction.  Passed anywhere a socket goes (``send_msg`` /
    ``send_packed`` / ``recv_msg`` unwrap it); v2 payloads whose
    segments fit ride the ring, everything else (v1 frames, oversized
    messages) uses the socket unchanged."""

    def __init__(self, sock: socket.socket, tx: ShmRing, rx: ShmRing):
        self.sock = sock
        self.tx = tx
        self.rx = rx

    @classmethod
    def serve_attach(cls, sock: socket.socket, spec: dict) -> "ShmChannel":
        """Server side: attach the client-created rings named in the
        hello's ``shm`` spec.  Failure to attach (different host, dead
        segment) raises — the capability probe that IS the same-host
        check."""
        rx = ShmRing.attach(spec["c2s"])
        try:
            tx = ShmRing.attach(spec["s2c"])
        except BaseException:
            rx.close()
            raise
        return cls(sock, tx=tx, rx=rx)

    def close_rings(self, unlink: bool = False) -> None:
        """Release both ring attachments; ``unlink=True`` additionally
        destroys owned segments (the creating end's shutdown path)."""
        for ring in (self.tx, self.rx):
            if unlink and ring.owner:
                ring.unlink()
            ring.close()


def _chan_parts(chan) -> Tuple[socket.socket, Optional[ShmChannel]]:
    if isinstance(chan, ShmChannel):
        return chan.sock, chan
    return chan, None


def _count_wire(reg, sent: bool, nbytes: int,
                count_as: Optional[str], msgs: int = 1) -> None:
    """One message's byte accounting: the aggregate ``net.*`` totals plus
    the direction-tagged counter when the caller named one (ISSUE 12).
    ``msgs=0`` counts bytes only — a streamed reply's frames are ONE
    logical message however many chunks carried it (ISSUE 15), so the
    historical request/reply message-count invariants keep holding."""
    if sent:
        reg.counter("net.msgs_sent").inc(msgs)
        reg.counter("net.bytes_sent").inc(nbytes)
    else:
        reg.counter("net.msgs_recv").inc(msgs)
        reg.counter("net.bytes_recv").inc(nbytes)
    if count_as is not None:
        reg.counter(count_as).inc(nbytes)


# ---------------------------------------------------------------------------
# send path
# ---------------------------------------------------------------------------

def _flat_view(buf: Any) -> memoryview:
    """Any buffer-protocol object -> flat byte view (0-d ndarrays cannot
    cast directly; go through their 1-element reshape.  Empty multi-dim
    views cannot cast either — memoryview refuses zeros in shape — and
    carry no bytes anyway)."""
    v = memoryview(buf)
    if v.nbytes == 0:
        return memoryview(b"")
    if v.ndim == 0:
        v = memoryview(buf.reshape(1))
    return v.cast("B")


def _sendmsg_all(sock: socket.socket, bufs: List[Any]) -> None:
    """Scatter-gather send of every buffer, partial sends handled.  Falls
    back to per-buffer ``sendall`` where ``sendmsg`` is unavailable."""
    views = [v for v in (_flat_view(b) for b in bufs) if v.nbytes]
    if not hasattr(sock, "sendmsg"):
        for v in views:
            sock.sendall(v)
        return
    while views:
        chunk = views[:_IOV_CHUNK]
        sent = sock.sendmsg(chunk)
        # drop fully-sent buffers, slice the partially-sent one
        while sent:
            if sent >= len(views[0]):
                sent -= len(views[0])
                views.pop(0)
            else:
                views[0] = views[0][sent:]
                sent = 0


def pack_msg(obj: Any, version: int = 1) -> Tuple[List[Any], int]:
    """Pre-serialize ``obj`` into ``(buffers, total_bytes)`` for repeated
    :func:`send_packed` calls — the PS pull-reply cache (ISSUE 4): the
    center is encoded ONCE per update, not once per pull.  v2 buffers hold
    zero-copy views of the tree's tensors, safe to cache because PS
    commits replace (never mutate) center arrays."""
    if version >= 2:
        header, segs = serde.tree_to_frames(obj)
        lens = [len(header)] + [memoryview(s).nbytes for s in segs]
        pre = _V2HEAD.pack(_MAGIC2, len(segs)) \
            + b"".join(_LEN.pack(n) for n in lens)
        bufs: List[Any] = [pre, header, *segs]
        return bufs, len(pre) + sum(lens)
    blob = serde.tree_to_bytes(obj)
    framed = _LEN.pack(len(blob)) + blob
    return [framed], len(framed)


def send_packed(sock: socket.socket, payload: Tuple[List[Any], int],
                registry=None, count_as: Optional[str] = None,
                count_msgs: int = 1) -> None:
    """Send a :func:`pack_msg` payload (counted like any message; the
    optional ``count_as`` counter gets the direction-tagged total).  On a
    negotiated :class:`ShmChannel`, v2 payloads whose segments fit the
    ring travel as a ``DKW3`` control frame + ring segments; anything
    else uses the TCP socket unchanged."""
    sock, shm = _chan_parts(sock)
    bufs, total = payload
    reg = registry if registry is not None else default_registry()
    if shm is not None and len(bufs) >= 2 and \
            bytes(bufs[0][:4]) == _MAGIC2:
        views = [_flat_view(b) for b in bufs[2:]]
        off = shm.tx.write(views)
        if off is not None:
            # control frame: v2 head with the shm magic + ring offset +
            # the original length table; segments already in the ring
            pre = memoryview(bufs[0])
            ctrl = _V2HEAD.pack(_MAGIC3, len(bufs) - 2) + _LEN.pack(off) \
                + bytes(pre[_V2HEAD.size:])
            _sendmsg_all(sock, [ctrl, bufs[1]])
            _count_wire(reg, True, total + _LEN.size, count_as,
                        msgs=count_msgs)
            reg.counter("net.bytes_shm").inc(sum(v.nbytes for v in views))
            return
    _sendmsg_all(sock, bufs)
    _count_wire(reg, True, total, count_as, msgs=count_msgs)


def send_msg(sock: socket.socket, obj: Any, registry=None,
             version: int = 1, count_as: Optional[str] = None) -> None:
    """One framed message (parity: reference ``send_data``).  ``version=2``
    uses the zero-copy scatter-gather frame; the peer must have negotiated
    v2 (its ``recv_msg`` auto-detects either way)."""
    _inject_fault("send", obj.get("action") if isinstance(obj, dict)
                  else None)
    send_packed(sock, pack_msg(obj, version=version), registry=registry,
                count_as=count_as)


def send_stream(chan, parts: List[Tuple[List[Any], int]], registry=None,
                count_as: Optional[str] = None,
                action: str = "pull_stream") -> None:
    """One ``DKW4`` streamed pull reply (ISSUE 15): an announce frame
    (magic + chunk count), then the prologue and each chunk as ordinary
    :func:`send_packed` frames — the receiver decodes chunk k while
    chunk k+1 is still in flight.  ``parts`` is the pre-packed
    ``[prologue, chunk_0, ...]`` list (the pull cache's unit).

    ``action`` names the stream for the chaos fault hook (ISSUE 16: the
    serve KV fabric streams ``kv_fetch`` replies over this same seam,
    and its faults must be addressable separately from PS pulls).

    On a negotiated :class:`ShmChannel` the chunks ride the ring only
    when the WHOLE stream fits at once (:meth:`ShmRing.stream_begin`);
    otherwise every frame of this reply stays on TCP — a per-chunk ring
    fallback could wrap onto an unread earlier chunk."""
    _inject_fault("send", action)
    sock, shm = _chan_parts(chan)
    reg = registry if registry is not None else default_registry()
    # however many frames carry it, a streamed reply is ONE message in
    # the net.* ledgers — the request/reply count invariants hold
    if shm is not None:
        total = sum(sum(_flat_view(b).nbytes for b in bufs[2:])
                    for bufs, _ in parts[1:]
                    if len(bufs) >= 2 and bytes(bufs[0][:4]) == _MAGIC2)
        if shm.tx.stream_begin(total):
            _sendmsg_all(sock, [_V2HEAD.pack(_MAGIC4, len(parts) - 1)])
            _count_wire(reg, True, _V2HEAD.size, count_as, msgs=1)
            for p in parts:
                send_packed(chan, p, registry=reg, count_as=count_as,
                            count_msgs=0)
            return
    # TCP: ONE scatter-gather send for announce + every frame — a
    # per-frame send would pay a sender/receiver scheduler round-trip
    # per chunk (measured ~1.5ms extra on a 4 MB loopback pull),
    # erasing the win streaming exists for
    bufs: List[Any] = [_V2HEAD.pack(_MAGIC4, len(parts) - 1)]
    total = _V2HEAD.size
    for p_bufs, p_total in parts:
        bufs.extend(p_bufs)
        total += p_total
    _sendmsg_all(sock, bufs)
    _count_wire(reg, True, total, count_as, msgs=1)


# ---------------------------------------------------------------------------
# recv path
# ---------------------------------------------------------------------------

def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n:
        chunk = sock.recv(min(n, 1 << 20))
        if not chunk:
            raise ConnectionError("socket closed mid-message")
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


def _recv_exact_into(sock: socket.socket, view: memoryview) -> None:
    """Fill ``view`` from the socket — the segment read lands directly in
    the buffer the decoded ndarray will wrap (no join, no second copy)."""
    while view.nbytes:
        got = sock.recv_into(view)
        if not got:
            raise ConnectionError("socket closed mid-message")
        view = view[got:]


def recv_msg(sock: socket.socket, registry=None,
             count_as: Optional[str] = None) -> Any:
    """Recv-all loop for one framed message, v1/v2/shm auto-detected
    (parity: reference ``recv_data``)."""
    _inject_fault("recv")
    sock, shm = _chan_parts(sock)
    head = _recv_exact(sock, _LEN.size)
    reg = registry if registry is not None else default_registry()
    return _recv_framed(sock, shm, head, reg, count_as)


def _recv_framed(sock: socket.socket, shm, head: bytes, reg,
                 count_as: Optional[str], msgs: int = 1) -> Any:
    """Decode one framed message whose 8-byte head was already read.
    ``msgs=0``: count bytes only (a frame inside a streamed reply)."""
    if head[:4] == _MAGIC4:
        raise ConnectionError(
            "peer sent a streamed (DKW4) reply where a single message "
            "was expected — protocol desync")
    if head[:4] in (_MAGIC2, _MAGIC3):
        _, nseg = _V2HEAD.unpack(head)
        extra = 0
        if head[:4] == _MAGIC3:
            if shm is None:
                raise ConnectionError(
                    "peer sent a shm frame on a connection with no "
                    "negotiated shared-memory ring")
            (off,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
            extra = _LEN.size
        table = _recv_exact(sock, _LEN.size * (nseg + 1))
        lens = [_LEN.unpack_from(table, i * _LEN.size)[0]
                for i in range(nseg + 1)]
        header = _recv_exact(sock, lens[0])
        if head[:4] == _MAGIC3:
            segments = shm.rx.read(off, lens[1:])
            reg.counter("net.bytes_shm").inc(sum(lens[1:]))
        else:
            segments = []
            for n in lens[1:]:
                buf = bytearray(n)
                _recv_exact_into(sock, memoryview(buf))
                segments.append(buf)
        msg = serde.tree_from_frames(header, segments)
        _count_wire(reg, False, len(head) + extra + len(table) + sum(lens),
                    count_as, msgs=msgs)
        return msg
    (n,) = _LEN.unpack(head)
    msg = serde.tree_from_bytes(_recv_exact(sock, n))
    _count_wire(reg, False, _LEN.size + n, count_as, msgs=msgs)
    return msg


def _take_arena(scratch: Optional[list], nbytes: int):
    """A receive arena of ≥ ``nbytes``: reused from the caller's bounded
    ``scratch`` pool when a pooled arena is provably unreferenced
    (refcount == pool + loop binding + getrefcount's own argument — the
    previous pull's leaves all died), else freshly allocated and pooled.
    Fresh multi-MB allocations every pull ping-pong the allocator
    against the still-referenced previous center (measured ~2x a whole
    4 MB pull on this class of host); the pool turns the steady state
    into zero large allocations."""
    if scratch is not None:
        for i, a in enumerate(scratch):
            if a.nbytes >= nbytes and sys.getrefcount(a) <= 3:
                del scratch[i]
                scratch.append(a)
                return a
    arena = np.empty(nbytes, np.uint8)
    if scratch is not None:
        scratch.append(arena)
        del scratch[:-2]  # bound: current + previous (still referenced)
    return arena


def recv_pull(chan, registry=None, count_as: Optional[str] = None,
              scratch: Optional[list] = None) -> Tuple[Any, Optional[list]]:
    """One pull reply, monolithic or streamed, auto-detected per message
    like v1/v2 (ISSUE 15).  Returns ``(doc, chunk_payload_bytes)`` —
    ``chunk_payload_bytes`` is None for a monolithic reply, else one
    tensor-byte total per received chunk (the client's chunk-size
    telemetry).  Each chunk decodes as it lands (the same zero-copy
    ``recv_into`` path as any v2 frame — no intermediate assembly blob);
    the skeleton is filled only once every leaf arrived, and any gap or
    overlap in the leaf indices fails loudly rather than assembling a
    wrong center."""
    _inject_fault("recv")
    sock, shm = _chan_parts(chan)
    head = _recv_exact(sock, _LEN.size)
    reg = registry if registry is not None else default_registry()
    if head[:4] != _MAGIC4:
        return _recv_framed(sock, shm, head, reg, count_as), None
    _, nchunks = _V2HEAD.unpack(head)
    _count_wire(reg, False, _V2HEAD.size, count_as, msgs=1)
    _inject_fault("recv")
    prologue = _recv_framed(sock, shm, _recv_exact(sock, _LEN.size), reg,
                            count_as, msgs=0)
    nleaves = int(prologue["nleaves"])
    frame_bytes = [int(x) for x in (prologue.get("frame_bytes") or [])]
    # ONE receive arena per pull (pooled via ``scratch``, np.empty — no
    # zero-fill), sliced per chunk frame: the decoded leaves are views
    # into it, and one pooled allocation per pull beats one fresh buffer
    # per chunk (see _take_arena)
    arena = _take_arena(scratch,
                        max(0, sum(frame_bytes)
                            - _LEN.size * len(frame_bytes))) \
        if frame_bytes else None
    arena_off = 0
    slots: dict = {}
    sizes: List[int] = []
    for kidx in range(int(nchunks)):
        c, used = _recv_stream_chunk(chan, sock, shm, kidx, frame_bytes,
                                     arena, arena_off, reg, count_as)
        arena_off += used
        arrs = c["leaves"]
        i0 = int(c["i0"])
        nbytes = 0
        for j, a in enumerate(arrs):
            if i0 + j in slots or not 0 <= i0 + j < nleaves:
                raise ConnectionError(
                    f"streamed pull chunk {c.get('chunk')} places leaf "
                    f"{i0 + j} outside/over the announced {nleaves} "
                    "leaves — torn stream")
            slots[i0 + j] = a
            nbytes += int(getattr(a, "nbytes", 0))
        sizes.append(nbytes)
    if len(slots) != nleaves:
        raise ConnectionError(
            f"streamed pull delivered {len(slots)} of {nleaves} leaves "
            "— torn stream")
    doc = stream_join(prologue["skeleton"],
                      [slots[i] for i in range(nleaves)])
    return doc, sizes


def _recv_stream_chunk(chan, sock, shm, kidx: int, frame_bytes: list,
                       arena, arena_off: int, reg,
                       count_as: Optional[str]) -> tuple:
    """One streamed chunk frame; returns ``(chunk_doc, arena_bytes
    _used)``.  On TCP, the prologue's announced frame size lets the
    whole remaining frame land in ONE slice of the pull's receive arena
    via one big ``recv_into`` — the reader stays blocked in a large
    kernel read for the whole chunk, and the decoded leaves are
    zero-copy views over the arena.  Ring-borne (``DKW3``) frames and
    peers predating ``frame_bytes`` fall back to the generic per-frame
    reader (their slice of the arena simply goes unused)."""
    _inject_fault("recv")
    head = _recv_exact(sock, _LEN.size)
    if head[:4] != _MAGIC2 or kidx >= len(frame_bytes) or arena is None:
        return _recv_framed(sock, shm, head, reg, count_as, msgs=0), 0
    total = int(frame_bytes[kidx])
    _, nseg = _V2HEAD.unpack(head)
    tbl = _LEN.size * (nseg + 1)
    if total < _V2HEAD.size + tbl or \
            arena_off + total - _V2HEAD.size > arena.nbytes:
        raise ConnectionError(
            f"streamed chunk {kidx} announces {total} frame bytes "
            f"({nseg} segments) outside the prologue's layout — torn "
            "stream")
    mv = memoryview(arena)[arena_off:arena_off + total - _V2HEAD.size]
    _recv_exact_into(sock, mv)
    lens = [_LEN.unpack_from(mv, i * _LEN.size)[0]
            for i in range(nseg + 1)]
    if tbl + sum(lens) != mv.nbytes:
        raise ConnectionError(
            f"streamed chunk {kidx}: length table does not add up to "
            "the announced frame size — torn stream")
    off = tbl
    header = bytes(mv[off:off + lens[0]])
    off += lens[0]
    segments: List[Any] = []
    for n in lens[1:]:
        segments.append(mv[off:off + n])
        off += n
    msg = serde.tree_from_frames(header, segments)
    _count_wire(reg, False, total, count_as, msgs=0)
    return msg, total - _V2HEAD.size


# ---------------------------------------------------------------------------
# shared TCP front-end frame (ISSUE 8: ps.servers and serve.server carried
# mirror copies of this accept/handler/stop machinery — one definition,
# so a protocol or lifecycle fix lands once)
# ---------------------------------------------------------------------------

#: sentinel a ``handle_request`` implementation returns when it already
#: sent its own reply on the connection (the PS pull path's
#: pre-serialized ``send_packed`` payload)
REPLY_SENT = object()


class FrameServer:
    """The TCP front-end both socket services share: listener + accept
    loop, one daemon handler thread per connection (finished handlers
    pruned per accept so a long-lived server polled once per obsview
    tick never accumulates dead Thread objects), per-connection ``hello``
    wire negotiation, a uniform error policy — a malformed FIELD answers
    ``{"ok": False, "error": ...}`` on the same connection instead of
    killing the handler replyless — and the stop sequencing: listener
    first (no NEW connections), then the subclass's
    ``_before_close_connections`` hook (the serve front-end drains its
    engine here), then live sockets, then handler joins.

    Subclasses implement ``handle_request(action, msg, ver, conn)``
    returning a reply dict (sent on the negotiated wire version),
    :data:`REPLY_SENT` when the reply already went out on ``conn``, or
    ``None`` for an unknown action.  ``hello`` and ``stop`` are handled
    here.  ``metric_prefix`` names the connections/in-flight gauges
    (``<prefix>.connections`` / ``<prefix>.inflight``) and the log
    channel (``<prefix>.server``); wire byte counts land in
    ``registry`` so one ``stats`` snapshot covers protocol AND traffic.
    """

    #: obs/gauge/log prefix — "ps" and "serve" for the two front-ends
    metric_prefix = "srv"

    def __init__(self, registry, host: str = "127.0.0.1", port: int = 0,
                 max_wire_version: int = WIRE_VERSION):
        self.registry = registry
        self.host = host
        self.port = int(port)
        #: newest frame format this server will negotiate; pin to 1 to
        #: emulate (and interop-test against) a legacy v1-only server
        self.max_wire_version = int(max_wire_version)
        self._sock: Optional[socket.socket] = None
        self._threads: list = []
        self._conns: list = []
        self._conn_lock = threading.Lock()
        self._running = threading.Event()
        #: telemetry plane (ISSUE 20): every front-end accepts pushed
        #: ``telemetry`` frames into a lazily-created aggregator and
        #: answers ``alerts`` polls; ``enable_alerts`` attaches a live
        #: rule engine.  Lazy so a server nobody ships to carries no
        #: store at all.
        self.telemetry = None
        self.alerts = None
        self._plane_lock = threading.Lock()
        self._g_conns = registry.gauge(f"{self.metric_prefix}.connections")
        self._g_inflight = registry.gauge(f"{self.metric_prefix}.inflight")
        #: transient accept-loop errors survived (ISSUE 9 satellite:
        #: EMFILE under fd pressure / ECONNABORTED used to silently end
        #: the server's ability to take connections)
        self._c_accept_errors = registry.counter(
            f"{self.metric_prefix}.accept_errors")

    # -- subclass hooks -----------------------------------------------------
    def handle_request(self, action, msg: dict, ver: int,
                       conn: socket.socket):
        """One request -> a reply dict, :data:`REPLY_SENT`, or ``None``
        (unknown action).  Runs on the connection's handler thread."""
        raise NotImplementedError

    def _on_start(self) -> None:
        """After the listener is bound, before the accept thread spawns."""

    def hello_reply(self, msg: dict, ver: int) -> dict:
        """The ``hello`` reply document.  Subclasses append
        negotiation-time extras (a shard front-end ships its placement
        descriptor here — ISSUE 10); unknown keys are ignored by every
        parser of this wire, so extras degrade cleanly against old
        clients."""
        return {"ok": True, "version": ver}

    def _before_close_connections(self) -> None:
        """Between closing the listener and closing live connections —
        where in-flight work drains so replies still flush."""

    # -- telemetry plane (ISSUE 20) -----------------------------------------
    def enable_telemetry(self, store=None):
        """Attach (or lazily create) the push-telemetry aggregator.
        Idempotent; also called implicitly by the first ``telemetry``
        frame, so shippers need no out-of-band setup handshake."""
        with self._plane_lock:
            if self.telemetry is None:
                if store is None:
                    from ..obs.timeseries import TimeSeriesStore
                    store = TimeSeriesStore(registry=self.registry)
                self.telemetry = store
            return self.telemetry

    def enable_alerts(self, rules, *, events=None, self_ingest=True,
                      eval_interval_s=0.25):
        """Attach a live :class:`~distkeras_tpu.obs.alerts.AlertEngine`
        over this server's aggregator.  ``self_ingest`` folds the
        server's OWN registry into the store each evaluation, so a
        standalone server (no pushing workers yet) is still alertable
        on its local metrics.  ``rules`` takes parsed
        :class:`~distkeras_tpu.obs.alerts.AlertRule` objects or the raw
        OBS_BASELINE ``alerts`` document form (list of dicts / dict
        with an ``alerts`` key)."""
        from ..obs.alerts import AlertEngine, AlertRule, parse_rules
        if not (isinstance(rules, (list, tuple))
                and all(isinstance(r, AlertRule) for r in rules)):
            rules = parse_rules(rules)
        store = self.enable_telemetry()
        with self._plane_lock:
            if self.alerts is None:
                self.alerts = AlertEngine(
                    store, rules, registry=self.registry, events=events,
                    source_registry=self.registry if self_ingest else None,
                    eval_interval_s=eval_interval_s)
            return self.alerts

    def _handle_plane(self, action, msg: dict):
        """Generic ``telemetry``/``alerts`` actions every front-end
        answers (PS, shard, engine, router) — tried before the
        subclass's unknown-action fallback.  Returns ``None`` for other
        actions."""
        if action == "telemetry":
            store = self.telemetry or self.enable_telemetry()
            n = store.ingest_delta(str(msg.get("source") or "unknown"),
                                   msg.get("delta"))
            if self.alerts is not None:
                # evaluation rides the ingest path, rate-limited inside
                # the engine — no dedicated alert thread anywhere
                self.alerts.evaluate()
            return {"ok": True, "accepted": n}
        if action == "alerts":
            alerts_doc = None
            if self.alerts is not None:
                self.alerts.evaluate()
                alerts_doc = self.alerts.state_doc()
            return {"ok": True, "alerts": alerts_doc,
                    "telemetry": self.telemetry.summary()
                    if self.telemetry is not None else None}
        return None

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "FrameServer":
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((self.host, self.port))
        self.port = self._sock.getsockname()[1]
        self._sock.listen(128)
        self._running.set()
        self._on_start()
        t = threading.Thread(target=self._accept_loop, daemon=True,
                             name=f"{self.metric_prefix}-accept")
        # _threads is appended by this (caller) thread AND the accept
        # thread, and iterated by stop(): every touch goes through
        # _conn_lock (dklint lock-discipline).  Append BEFORE start so
        # index 0 is always the accept thread — an instant connection
        # could otherwise slot a handler thread in first and stop()'s
        # [1:] join would skip it.
        with self._conn_lock:
            self._threads.append(t)
        t.start()
        return self

    def stop(self) -> None:
        self._running.clear()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        self._before_close_connections()
        # close live connections so handlers blocked in recv unblock
        with self._conn_lock:
            conns = list(self._conns)
            threads = list(self._threads)
        for c in conns:
            try:
                c.close()
            except OSError:
                pass
        for t in threads[1:]:
            t.join(timeout=5)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # -- loops --------------------------------------------------------------
    def _accept(self):
        """One listener accept — a seam so tests can inject EMFILE-style
        transient errors without monkeypatching the socket object."""
        return self._sock.accept()

    def _accept_loop(self):
        log = get_logger(f"{self.metric_prefix}.server")
        while self._running.is_set():
            try:
                conn, _ = self._accept()
            except OSError as e:
                # stop() clears _running BEFORE closing the listener, so
                # a running server that sees accept fail is hitting a
                # TRANSIENT error (EMFILE under fd pressure, ECONNABORTED
                # on a peer that hung up mid-handshake): log, breathe,
                # keep accepting — one bad accept must not end the
                # server's ability to take connections (ISSUE 9).  A
                # listener torn down under us (fd gone) is fatal.
                if not self._running.is_set() or self._sock.fileno() < 0:
                    return  # listener closed by stop()
                self._c_accept_errors.inc()
                log.warning("accept failed (transient, continuing): %s", e)
                time.sleep(0.05)
                continue
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._conn_lock:
                self._conns.append(conn)
            self._g_conns.inc()
            t = threading.Thread(target=self._handle_connection,
                                 args=(conn,), daemon=True,
                                 name=f"{self.metric_prefix}-conn")
            t.start()
            with self._conn_lock:
                # prune finished handlers; index 0 stays the accept thread
                self._threads[1:] = [h for h in self._threads[1:]
                                     if h.is_alive()]
                self._threads.append(t)

    def _negotiate_shm(self, conn: socket.socket, msg: dict, ver: int,
                       reply: dict, log):
        """Try to attach the client-created rings named in the hello's
        ``shm`` spec (ISSUE 12).  Attach success IS the same-host check —
        no hostname heuristics; a cross-host peer's open() simply fails
        and the connection stays on TCP, ack-less."""
        spec = msg.get("shm")
        if not isinstance(spec, dict) or ver < 2:
            return None
        try:
            chan = ShmChannel.serve_attach(conn, spec)
        except (OSError, ValueError, KeyError, TypeError) as e:
            log.info("shm negotiation refused (cross-host peer, or dead "
                     "segment): %s", e)
            return None
        reply["shm"] = {"ok": True}
        return chan

    def _handle_connection(self, conn: socket.socket):
        reg = self.registry
        log = get_logger(f"{self.metric_prefix}.server")
        ver = 1  # per-connection wire version; hello upgrades it
        up = f"{self.metric_prefix}.wire.bytes_up"
        down = f"{self.metric_prefix}.wire.bytes_down"
        chan = conn  # hello may upgrade to a ShmChannel (ISSUE 12)
        try:
            while self._running.is_set():
                try:
                    msg = recv_msg(chan, registry=reg, count_as=up)
                except (ConnectionError, OSError):
                    return
                action = msg.get("action")
                self._g_inflight.inc()
                try:
                    if action == "hello":
                        ver = choose_wire_version(msg.get("versions"),
                                                  self.max_wire_version)
                        reply = self.hello_reply(msg, ver)
                        new_chan = self._negotiate_shm(conn, msg, ver,
                                                       reply, log)
                        # the reply itself stays v1-framed AND on TCP:
                        # the client switches only after reading it
                        send_msg(conn, reply, registry=reg, count_as=down)
                        if new_chan is not None:
                            chan = new_chan
                    elif action == "stop":
                        send_msg(chan, {"ok": True}, registry=reg,
                                 version=ver, count_as=down)
                        return
                    else:
                        reply = self._handle_plane(action, msg)
                        if reply is None:
                            reply = self.handle_request(action, msg, ver,
                                                        chan)
                        if reply is None:
                            reply = {"ok": False,
                                     "error": f"unknown action {action!r}"}
                        if reply is not REPLY_SENT:
                            send_msg(chan, reply, registry=reg, version=ver,
                                     count_as=down)
                except (ConnectionError, OSError) as e:
                    log.warning("reply to %r failed (peer gone?): %s",
                                action, e)
                    return
                except Exception as e:
                    # a malformed FIELD (bad versions list, undecodable
                    # codec stub, mismatched promote tree) answers like
                    # any bad request instead of killing the handler and
                    # dropping the peer's connection replyless
                    log.warning("action %r failed: %s", action, e)
                    try:
                        send_msg(chan, {"ok": False, "error": str(e)},
                                 registry=reg, version=ver, count_as=down)
                    except (ConnectionError, OSError):
                        return
                finally:
                    self._g_inflight.dec()
        finally:
            if isinstance(chan, ShmChannel):
                # attachments only: the creating client owns the unlink
                chan.close_rings()
            try:
                conn.close()
            except OSError:
                pass
            with self._conn_lock:
                if conn in self._conns:
                    self._conns.remove(conn)
            self._g_conns.dec()

"""Worker-side PS client: one persistent connection, pull/commit calls.

Parity with the reference's worker-side socket usage (reference
``distkeras/workers.py:NetworkWorker.pull``/``commit``): full center down,
delta up, at communication-window boundaries.

Instrumented (ISSUE 2): every RPC observes its round-trip latency into a
``ps.client.rtt_seconds`` histogram and reconnect events count under
``ps.client.reconnects`` (process-wide default registry unless one is
passed — worker threads share a process, so the default aggregates the
whole worker pool).  Idempotent reads (``pull``/``stats``) transparently
reconnect-and-retry once on a broken connection; ``commit`` does NOT
auto-retry (the server may have applied the delta before the connection
died — resending would double-apply; the worker-level retry-once policy
owns that failure, as in the reference's Spark task retry).
"""

from __future__ import annotations

import time
from typing import Any, Optional

from ..obs import TIME_BUCKETS, Registry, default_registry
from .networking import connect, recv_msg, send_msg


class PSClient:
    def __init__(self, host: str, port: int, worker_id: int = 0,
                 registry: Optional[Registry] = None):
        self.worker_id = int(worker_id)
        self.host = host
        self.port = port
        self.registry = registry if registry is not None \
            else default_registry()
        self._h_rtt = self.registry.histogram("ps.client.rtt_seconds",
                                              TIME_BUCKETS)
        self._c_reconnects = self.registry.counter("ps.client.reconnects")
        self.sock = connect(host, port)

    def reconnect(self) -> None:
        """Drop the (possibly broken) connection and dial again."""
        try:
            self.sock.close()
        except OSError:
            pass
        self.sock = connect(self.host, self.port)
        self._c_reconnects.inc()

    def _rpc(self, msg: dict, retry: bool = False) -> Any:
        """One framed request/response, rtt observed.  ``retry=True``
        reconnects and resends once on a dead connection — only safe for
        idempotent reads."""
        t0 = time.perf_counter()
        try:
            send_msg(self.sock, msg, registry=self.registry)
            resp = recv_msg(self.sock, registry=self.registry)
        except (ConnectionError, OSError):
            if not retry:
                raise
            self.reconnect()
            send_msg(self.sock, msg, registry=self.registry)
            resp = recv_msg(self.sock, registry=self.registry)
        self._h_rtt.observe(time.perf_counter() - t0)
        return resp

    def pull(self) -> tuple:
        """Returns ``(center_tree, server_update_counter)``."""
        resp = self._rpc({"action": "pull", "worker_id": self.worker_id},
                         retry=True)
        return resp["center"], int(resp["updates"])

    def commit(self, delta: Any, last_update: Optional[int] = None) -> bool:
        """Commit a delta; returns False if a fault injector dropped it."""
        msg = {"action": "commit", "worker_id": self.worker_id,
               "delta": delta}
        if last_update is not None:
            msg["last_update"] = int(last_update)
        resp = self._rpc(msg)
        return not resp.get("dropped", False)

    def stats(self) -> dict:
        """Poll the server's live telemetry: ``{"stats": <registry
        snapshot>, "num_updates": int, "commits_by_worker": dict, ...}`` —
        no center transfer, safe to call while training runs."""
        return self._rpc({"action": "stats", "worker_id": self.worker_id},
                         retry=True)

    def close(self) -> None:
        try:
            send_msg(self.sock, {"action": "stop"}, registry=self.registry)
            recv_msg(self.sock, registry=self.registry)
        except (ConnectionError, OSError):
            pass
        finally:
            try:
                self.sock.close()
            except OSError:
                pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

"""Worker-side PS client: one persistent connection, pull/commit calls.

Parity with the reference's worker-side socket usage (reference
``distkeras/workers.py:NetworkWorker.pull``/``commit``): full center down,
delta up, at communication-window boundaries — with the ISSUE 4 fast path
layered on:

* **wire negotiation** — a ``hello`` handshake on connect picks the
  newest frame format both ends speak (v2 zero-copy scatter-gather when
  the server is current, v1 msgpack blobs against old servers, which
  answer ``hello`` with an unknown-action error we treat as "v1 only");
* **pull caching** — ``pull`` reports the update counter of the center
  this client already holds; the server answers ``unchanged`` without
  re-shipping the center when no commits landed, and the cached copy is
  returned (the caller must treat pulled trees as read-only, which the
  workers' replace-style updates already do);
* **delta codecs** — an optional ``ps.codecs`` codec compresses commit
  payloads (int8/bf16/top-k with worker-side error feedback); encode
  latency and bytes saved land in this client's registry;
* **trace propagation** (ISSUE 5) — with a ``tracer``, pull/commit run
  inside ``ps.pull``/``ps.commit`` spans and, on v2 connections, ship the
  open span's ``(trace_id, parent_span)`` as a ``trace`` header so the
  server's apply span links back to the worker window that caused it;
  ``commit(gap_s=...)`` additionally carries the worker's heartbeat gap
  for the server-side straggler detector.

Instrumented (ISSUE 2): every RPC observes its round-trip latency into a
``ps.client.rtt_seconds`` histogram and reconnect events count under
``ps.client.reconnects`` (process-wide default registry unless one is
passed — worker threads share a process, so the default aggregates the
whole worker pool).  Idempotent reads (``pull``/``stats``) transparently
reconnect-and-retry once on a broken connection; ``commit`` does NOT
auto-retry (the server may have applied the delta before the connection
died — resending would double-apply; the worker-level retry-once policy
owns that failure, as in the reference's Spark task retry).
"""

from __future__ import annotations

import contextlib
import time
from typing import Any, Optional

from ..obs import TIME_BUCKETS, Registry, default_registry
from ..obs.spans import SpanTracer
from . import codecs
from .networking import (client_handshake, connect, pinned_wire_version,
                         recv_msg, retry_with_backoff, send_msg)


class WorkerEvicted(RuntimeError):
    """The PS tombstoned this incarnation's commit (its generation was
    superseded by an eviction — ISSUE 9): a supervisor-spawned replacement
    owns the worker id now.  The worker loop exits cleanly on this; it is
    an eviction notice, not a failure."""


class PSClient:
    def __init__(self, host: str, port: int, worker_id: int = 0,
                 registry: Optional[Registry] = None,
                 codec=None, wire_version: Optional[int] = None,
                 tracer: Optional[SpanTracer] = None,
                 generation: int = 0):
        self.worker_id = int(worker_id)
        #: commit generation this incarnation runs under (ISSUE 9):
        #: stamped on every commit so a post-eviction zombie's deltas
        #: tombstone server-side instead of double-applying
        self.generation = int(generation)
        self.host = host
        self.port = port
        self.registry = registry if registry is not None \
            else default_registry()
        self._h_rtt = self.registry.histogram("ps.client.rtt_seconds",
                                              TIME_BUCKETS)
        self._h_encode = self.registry.histogram("ps.codec.encode_seconds",
                                                 TIME_BUCKETS)
        self._c_reconnects = self.registry.counter("ps.client.reconnects")
        self._c_reconnect_failures = self.registry.counter(
            "ps.client.reconnect_failures")
        self._c_unchanged = self.registry.counter(
            "ps.client.pulls_unchanged")
        #: delta codec (``ps.codecs``) — owned here because its
        #: error-feedback residual is per-worker state
        self.codec = codecs.get_codec(codec)
        #: span tracer for cross-process trace propagation (ISSUE 5): when
        #: set, pull/commit RPCs run inside ``ps.pull``/``ps.commit`` spans
        #: and — on a v2 connection — ship ``(trace_id, parent_span)`` in a
        #: ``trace`` header so the server's apply span links back here.
        #: v1 peers simply never see the header (protocol untouched).
        self.tracer = tracer
        #: ``None`` negotiates (the default); ``1`` pins the legacy wire —
        #: also reachable via ``DKTPU_WIRE=1`` for whole-process opt-out
        self._want_version = pinned_wire_version(wire_version)
        self.wire_version = 1
        #: client-side center cache: (center_tree, server_update_counter,
        #: version_vector_or_None, plan_epoch_or_None)
        self._last_pull: Optional[tuple] = None
        #: shard placement descriptor from the server's hello reply
        #: (ISSUE 10) — None against a plain (un-sharded) server or on a
        #: v1 connection (no hello is sent)
        self.shard_info: Optional[dict] = None
        self.sock = connect(host, port)
        self._handshake()

    def _handshake(self) -> None:
        """Negotiate the wire format for this connection (the shared
        ``networking.client_handshake`` seam — serve clients run the same
        exchange).  A shard front-end's hello reply additionally carries
        its placement descriptor (``shard``: index / num_shards / plan
        epoch / plan digest — ISSUE 10), captured here so the sharded
        client can verify agreement at negotiation time; plain servers
        leave it None."""
        info: dict = {}
        self.wire_version = client_handshake(
            self.sock, registry=self.registry, worker_id=self.worker_id,
            want=self._want_version, info=info)
        self.shard_info = info.get("shard")

    def reconnect(self, attempts: int = 6, base_delay: float = 0.1,
                  max_delay: float = 2.0) -> None:
        """Drop the (possibly broken) connection and dial again (the
        replacement server may be older/newer: re-negotiate).  The pull
        cache is dropped too — a RESTARTED server's update counter can
        coincide with the cached one while its center differs, and an
        ``unchanged`` answer would then silently serve the old server's
        center.

        Retries the whole dial + handshake up to ``attempts`` times with
        capped exponential backoff + jitter (ISSUE 9 satellite — a PS
        restart takes seconds, and a fleet re-dialing in lockstep is a
        thundering herd); each failed attempt counts under
        ``ps.client.reconnect_failures``, the final one re-raises."""
        try:
            self.sock.close()
        except OSError:
            pass
        self._last_pull = None

        def dial():
            # one dial per attempt: the backoff (not connect's own
            # fixed-cadence retry loop) paces the re-dials
            self.sock = connect(self.host, self.port, retries=1)
            self._handshake()

        retry_with_backoff(dial, attempts, base_delay, max_delay,
                           self._c_reconnect_failures.inc,
                           f"reconnect to {self.host}:{self.port}",
                           "ps.client")
        self._c_reconnects.inc()

    def _rpc(self, msg: dict, retry: bool = False) -> Any:
        """One framed request/response, rtt observed.  ``retry=True``
        reconnects and resends once on a dead connection — only safe for
        idempotent reads."""
        t0 = time.perf_counter()
        try:
            send_msg(self.sock, msg, registry=self.registry,
                     version=self.wire_version)
            resp = recv_msg(self.sock, registry=self.registry)
        except (ConnectionError, OSError):
            if not retry:
                raise
            self.reconnect()
            send_msg(self.sock, msg, registry=self.registry,
                     version=self.wire_version)
            resp = recv_msg(self.sock, registry=self.registry)
        self._h_rtt.observe(time.perf_counter() - t0)
        return resp

    @staticmethod
    def _raise_on_error(what: str, resp: dict) -> None:
        """Server error replies ({"ok": False, "error": ...} from a
        failed dispatch) raise instead of being misread as data."""
        if isinstance(resp, dict) and resp.get("error") is not None:
            raise RuntimeError(f"ps {what} failed on the server: "
                               f"{resp['error']}")

    def _span(self, name: str):
        """``ps.pull``/``ps.commit`` client span, or a no-op scope when no
        tracer is attached (spans must never be a hard dependency)."""
        if self.tracer is None:
            return contextlib.nullcontext()
        return self.tracer.span(name, worker=self.worker_id)

    def _trace_header(self) -> Optional[dict]:
        """``(trace_id, parent_span)`` of the currently-open client span —
        the cross-process link the server's apply span adopts.  Only on v2
        connections: the header is this build's protocol extension, and v1
        is the frozen legacy surface old servers parse."""
        if self.tracer is None or self.wire_version < 2:
            return None
        trace_id, span_id = self.tracer.context()
        hdr = {"trace_id": trace_id}
        if span_id is not None:
            hdr["parent_span"] = span_id
        return hdr

    def pull(self) -> tuple:
        """Returns ``(center_tree, server_update_counter)``.  Carries the
        counter of the center already held so an idle server answers
        ``unchanged`` instead of re-shipping megabytes (ISSUE 4)."""
        center, updates, _, _ = self.pull_versioned()
        return center, updates

    # -- split-phase protocol (ISSUE 10) ------------------------------------
    # The request/reply halves of pull and commit as separate calls, so a
    # sharded client PIPELINES a fan-out on one thread: send every
    # shard's request first (each shard starts decoding/applying while
    # the later sends are still in flight), then collect the replies.  A
    # thread-per-shard fan-out pays GIL contention and pool dispatch per
    # RPC; the pipeline pays one pass of sends and one of receives.

    def _pull_msg(self, have=None, min_updates=None) -> dict:
        # one assembly point so protocol keys (like the trace header)
        # can never be added to one request shape and missed on another
        msg = {"action": "pull", "worker_id": self.worker_id}
        trace = self._trace_header()
        if trace is not None:
            msg["trace"] = trace
        if have is not None:
            msg["have"] = have
        if min_updates is not None:
            msg["min_updates"] = int(min_updates)
        return msg

    def pull_send(self, min_updates: Optional[int] = None) -> None:
        """Phase 1 of a pull: the request goes out (with the cached
        counter as ``have``); :meth:`pull_finish` must be the next call
        on this connection.  ``min_updates`` asks the server to briefly
        wait until its counter reaches that value before serving — the
        consistent-cut retry hint (old servers ignore it)."""
        self._t_pull = time.perf_counter()
        have = self._last_pull[1] if self._last_pull is not None else None
        send_msg(self.sock, self._pull_msg(have, min_updates),
                 registry=self.registry, version=self.wire_version)

    def pull_finish(self) -> tuple:
        """Phase 2 of a pull: ``(center, updates, version_vector,
        plan_epoch)``.  Against a shard front-end the reply carries the
        shard's per-worker commit counts (the version vector a
        consistent-cut pull compares across shards) and its plan epoch;
        plain servers leave both None.  An ``unchanged`` answer reuses
        the cached center/vv/epoch — they can only change when the
        counter does."""
        resp = recv_msg(self.sock, registry=self.registry)
        self._h_rtt.observe(time.perf_counter() - self._t_pull)
        self._raise_on_error("pull", resp)
        updates = int(resp["updates"])
        if resp.get("unchanged"):
            if self._last_pull is not None:
                self._c_unchanged.inc()
                return (self._last_pull[0], updates,
                        self._last_pull[2], self._last_pull[3])
            # the cache was invalidated mid-exchange (a reconnect dropped
            # it, but a stale ``have`` was resent): ask again
            # unconditionally for the full center
            resp = self._rpc(self._pull_msg())
            self._raise_on_error("pull", resp)
            updates = int(resp["updates"])
        vv = resp.get("vv")
        if isinstance(vv, dict):
            vv = {int(k): int(v) for k, v in vv.items()}
        epoch = resp.get("plan_epoch")
        self._last_pull = (resp["center"], updates, vv, epoch)
        return resp["center"], updates, vv, epoch

    def pull_versioned(self) -> tuple:
        """The full pull protocol in one call (transparently reconnects
        and retries once on a dead connection — an idempotent read)."""
        with self._span("ps.pull"):
            try:
                self.pull_send()
                return self.pull_finish()
            except (ConnectionError, OSError):
                self.reconnect()
                self.pull_send()
                return self.pull_finish()

    def commit_send(self, delta: Any, last_update: Optional[int] = None,
                    gap_s: Optional[float] = None) -> None:
        """Phase 1 of a commit: codec-encode and ship the delta;
        :meth:`commit_finish` must be the next call on this
        connection."""
        if not self.codec.is_identity:
            t0 = time.perf_counter()
            raw = codecs.tree_payload_bytes(delta)
            delta = self.codec.encode(delta)
            codecs.count_codec_bytes(self.registry, raw,
                                     codecs.tree_payload_bytes(delta))
            self._h_encode.observe(time.perf_counter() - t0)
        msg = {"action": "commit", "worker_id": self.worker_id,
               "gen": self.generation,
               "delta": delta, "codec": self.codec.name}
        trace = self._trace_header()
        if trace is not None:
            msg["trace"] = trace
        if gap_s is not None:
            msg["gap_s"] = float(gap_s)
        if last_update is not None:
            msg["last_update"] = int(last_update)
        self._t_commit = time.perf_counter()
        send_msg(self.sock, msg, registry=self.registry,
                 version=self.wire_version)

    def commit_finish(self) -> bool:
        """Phase 2 of a commit: True when applied, False when a fault
        injector dropped it; an eviction notice raises
        :class:`WorkerEvicted`."""
        resp = recv_msg(self.sock, registry=self.registry)
        self._h_rtt.observe(time.perf_counter() - self._t_commit)
        # a server-side apply failure answers {"ok": False, "error"}
        # (it did NOT apply the delta) — that must surface as a
        # failure to the worker's retry policy, never as success
        self._raise_on_error("commit", resp)
        if resp.get("evicted"):
            # the PS tombstoned this commit: a newer incarnation owns
            # the worker id — this one's loop must wind down (ISSUE 9)
            raise WorkerEvicted(
                f"worker {self.worker_id} generation "
                f"{self.generation} evicted by the PS")
        return not resp.get("dropped", False)

    def commit(self, delta: Any, last_update: Optional[int] = None,
               gap_s: Optional[float] = None) -> bool:
        """Commit a delta; returns False if a fault injector dropped it.
        A non-identity codec compresses the payload here (error-feedback
        residual updated as a side effect) — the server decodes
        statelessly from the per-leaf stubs.  Never auto-retries (the
        server may have applied the delta before a connection died).

        ``gap_s`` is the worker's monotonic gap since its previous window
        commit — the heartbeat signal the server-side straggler detector
        folds in (ISSUE 5); harmless extra key to old servers."""
        with self._span("ps.commit"):
            self.commit_send(delta, last_update=last_update, gap_s=gap_s)
            return self.commit_finish()

    def stats(self) -> dict:
        """Poll the server's live telemetry: ``{"stats": <registry
        snapshot>, "num_updates": int, "commits_by_worker": dict, ...}`` —
        no center transfer, safe to call while training runs."""
        return self._rpc({"action": "stats", "worker_id": self.worker_id},
                         retry=True)

    def close(self) -> None:
        try:
            send_msg(self.sock, {"action": "stop"}, registry=self.registry,
                     version=self.wire_version)
            recv_msg(self.sock, registry=self.registry)
        except (ConnectionError, OSError):
            pass
        finally:
            try:
                self.sock.close()
            except OSError:
                pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

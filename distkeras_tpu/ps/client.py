"""Worker-side PS client: one persistent connection, pull/commit calls.

Parity with the reference's worker-side socket usage (reference
``distkeras/workers.py:NetworkWorker.pull``/``commit``): full center down,
delta up, at communication-window boundaries — with the ISSUE 4 fast path
layered on:

* **wire negotiation** — a ``hello`` handshake on connect picks the
  newest frame format both ends speak (v2 zero-copy scatter-gather when
  the server is current, v1 msgpack blobs against old servers, which
  answer ``hello`` with an unknown-action error we treat as "v1 only");
* **pull caching** — ``pull`` reports the update counter of the center
  this client already holds; the server answers ``unchanged`` without
  re-shipping the center when no commits landed, and the cached copy is
  returned (the caller must treat pulled trees as read-only, which the
  workers' replace-style updates already do);
* **delta codecs** — an optional ``ps.codecs`` codec compresses commit
  payloads (int8/bf16/top-k with worker-side error feedback); encode
  latency and bytes saved land in this client's registry;
* **DOWN compression** (ISSUE 12) — ``down=`` requests quantized pulls:
  the server encodes each center as a residual against a shared
  reference this connection acknowledges by epoch (full resync on the
  first pull, after an epoch roll, and for every fresh incarnation —
  a respawned worker's new client starts reference-less, so a stale
  reference can never decode garbage).  ``down="adaptive"`` runs a
  per-link :class:`~.codecs.AdaptiveDownPolicy` choosing the codec from
  this client's measured pull RTTs, with hysteresis and a recorded
  ``ps.codec.switches`` trail;
* **shared-memory transport** (ISSUE 12) — ``shm=True`` (or
  ``DKTPU_SHM=1``) offers a same-host data plane in the hello: this
  client creates one ring per direction and the server acks only if it
  can actually attach them; v2 tensor segments then skip TCP entirely.
  Refused negotiations (cross-host peers, old servers) silently stay on
  TCP; this end owns the rings and unlinks them on close/reconnect;
* **streamed pulls** (ISSUE 15) — on by default when the server acks the
  hello offer (``stream=False`` or ``DKTPU_STREAM=0`` opts out): a fresh
  pull's reply arrives as self-describing chunk frames decoded as they
  land, and the split-phase ``pull_begin``/``pull_join`` surface lets a
  dispatch-ahead worker hide the whole transfer behind its device step —
  measured per pull into ``ps.pull.hidden_seconds`` and the running
  ``ps.pull.overlap_fraction`` gauge;
* **link quality** (ISSUE 15) — every fresh pull/commit RTT feeds a
  per-link :class:`~..obs.stragglers.LinkQuality` EWMA pair whose
  degradation edge drives the adaptive policy's codec downshifts
  (recorded ``ps.link.downshifts``) and rides each commit as
  ``link_rtt_s`` for the server-side straggler detector's link table;
* **trace propagation** (ISSUE 5) — with a ``tracer``, pull/commit run
  inside ``ps.pull``/``ps.commit`` spans and, on v2 connections, ship the
  open span's ``(trace_id, parent_span)`` as a ``trace`` header so the
  server's apply span links back to the worker window that caused it;
  ``commit(gap_s=...)`` additionally carries the worker's heartbeat gap
  for the server-side straggler detector.

Instrumented (ISSUE 2): every RPC observes its round-trip latency into a
``ps.client.rtt_seconds`` histogram and reconnect events count under
``ps.client.reconnects`` (process-wide default registry unless one is
passed — worker threads share a process, so the default aggregates the
whole worker pool).  Idempotent reads (``pull``/``stats``) transparently
reconnect-and-retry once on a broken connection; ``commit`` does NOT
auto-retry (the server may have applied the delta before the connection
died — resending would double-apply; the worker-level retry-once policy
owns that failure, as in the reference's Spark task retry).
"""

from __future__ import annotations

import contextlib
import os
import time
from typing import Any, Optional

from ..obs import TIME_BUCKETS, LinkQuality, Registry, default_registry
from ..obs.logging import get_logger
from ..obs.spans import SpanTracer
from . import codecs
from .networking import (SHM_RING_MB, STREAM_CHUNK_BYTES, ShmChannel,
                         ShmRing, client_handshake, connect,
                         pinned_wire_version, recv_msg, recv_pull,
                         retry_with_backoff, send_msg, stream_enabled_env)

#: direction-tagged wire counters (ISSUE 12): on the worker side, sends
#: are UP (commits/requests) and receives are DOWN (pulled centers)
_UP = "ps.wire.bytes_up"
_DOWN = "ps.wire.bytes_down"

#: streamed-pull chunk-size histogram buckets (bytes)
_CHUNK_BUCKETS = (1 << 12, 1 << 14, 1 << 16, 1 << 18, 1 << 20, 1 << 21,
                  1 << 22, 1 << 23, 1 << 24)


class WorkerEvicted(RuntimeError):
    """The PS tombstoned this incarnation's commit (its generation was
    superseded by an eviction — ISSUE 9): a supervisor-spawned replacement
    owns the worker id now.  The worker loop exits cleanly on this; it is
    an eviction notice, not a failure."""


class PSClient:
    def __init__(self, host: str, port: int, worker_id: int = 0,
                 registry: Optional[Registry] = None,
                 codec=None, wire_version: Optional[int] = None,
                 tracer: Optional[SpanTracer] = None,
                 generation: int = 0, down=None,
                 shm: Optional[bool] = None,
                 shm_mb: Optional[float] = None,
                 stream: Optional[bool] = None,
                 stream_chunk_bytes: Optional[int] = None):
        self.worker_id = int(worker_id)
        #: commit generation this incarnation runs under (ISSUE 9):
        #: stamped on every commit so a post-eviction zombie's deltas
        #: tombstone server-side instead of double-applying
        self.generation = int(generation)
        self.host = host
        self.port = port
        self.registry = registry if registry is not None \
            else default_registry()
        self._h_rtt = self.registry.histogram("ps.client.rtt_seconds",
                                              TIME_BUCKETS)
        self._h_encode = self.registry.histogram("ps.codec.encode_seconds",
                                                 TIME_BUCKETS)
        self._c_reconnects = self.registry.counter("ps.client.reconnects")
        self._c_reconnect_failures = self.registry.counter(
            "ps.client.reconnect_failures")
        self._c_unchanged = self.registry.counter(
            "ps.client.pulls_unchanged")
        #: delta codec (``ps.codecs``) — owned here because its
        #: error-feedback residual is per-worker state
        self.codec = codecs.get_codec(codec)
        #: span tracer for cross-process trace propagation (ISSUE 5): when
        #: set, pull/commit RPCs run inside ``ps.pull``/``ps.commit`` spans
        #: and — on a v2 connection — ship ``(trace_id, parent_span)`` in a
        #: ``trace`` header so the server's apply span links back here.
        #: v1 peers simply never see the header (protocol untouched).
        self.tracer = tracer
        #: ``None`` negotiates (the default); ``1`` pins the legacy wire —
        #: also reachable via ``DKTPU_WIRE=1`` for whole-process opt-out
        self._want_version = pinned_wire_version(wire_version)
        self.wire_version = 1
        #: client-side center cache: (center_tree, server_update_counter,
        #: version_vector_or_None, plan_epoch_or_None)
        self._last_pull: Optional[tuple] = None
        #: shard placement descriptor from the server's hello reply
        #: (ISSUE 10) — None against a plain (un-sharded) server or on a
        #: v1 connection (no hello is sent)
        self.shard_info: Optional[dict] = None
        #: DOWN pull compression (ISSUE 12): the requested spec, whether
        #: the server acked it, the per-link adaptive policy (when
        #: ``down="adaptive"``), and the (epoch, tree) reference this
        #: connection last acknowledged — reset on every (re)connect so
        #: a fresh incarnation always resyncs
        self.down_spec = codecs.validate_down_spec(down)
        self.down_enabled = False
        self._down_policy: Optional[codecs.AdaptiveDownPolicy] = None
        self._down_ref: Optional[tuple] = None
        self._down_req: Optional[str] = None
        self._c_resyncs = self.registry.counter("ps.down.resyncs")
        self._h_down_decode = self.registry.histogram(
            "ps.down.decode_seconds", TIME_BUCKETS)
        #: same-host shared-memory transport (ISSUE 12): requested via
        #: the ``shm`` arg or ``DKTPU_SHM=1``; active only after the
        #: server proves it can attach this client's rings
        self.shm_requested = bool(shm) if shm is not None \
            else os.environ.get("DKTPU_SHM") == "1"
        self.shm_mb = float(shm_mb) if shm_mb is not None else SHM_RING_MB
        self.shm_active = False
        #: streamed pulls (ISSUE 15): on by default (``DKTPU_STREAM=0``
        #: or ``stream=False`` opts out), active only after the server
        #: acks the hello offer — old/pinned/disabled peers keep the
        #: monolithic reply, bit-identical on the wire
        self.stream_requested = stream_enabled_env() if stream is None \
            else bool(stream)
        self.stream_chunk_bytes = int(stream_chunk_bytes) \
            if stream_chunk_bytes is not None else STREAM_CHUNK_BYTES
        self.stream_enabled = False
        self._c_streams = self.registry.counter("ps.pull.streams")
        self._c_stream_chunks = self.registry.counter(
            "ps.pull.stream_chunks")
        self._h_chunk_bytes = self.registry.histogram(
            "ps.pull.chunk_bytes", _CHUNK_BUCKETS)
        #: overlap accounting (ISSUE 15): how much of each fresh pull's
        #: wall time passed BEFORE this end started waiting on the reply
        #: (= transfer hidden behind whatever the caller did between
        #: ``pull_send`` and ``pull_finish`` — the worker's device step)
        self._h_hidden = self.registry.histogram("ps.pull.hidden_seconds",
                                                 TIME_BUCKETS)
        self._g_overlap = self.registry.gauge("ps.pull.overlap_fraction")
        self._hidden_total = 0.0
        self._pull_wall_total = 0.0
        #: per-link RTT EWMAs with a degradation edge (ISSUE 15) — feeds
        #: the adaptive DOWN policy's downshift/reprobe schedule and
        #: rides every commit as ``link_rtt_s`` for the server-side
        #: straggler detector's link table
        self.link = LinkQuality(registry=self.registry)
        #: bounded receive-arena pool for streamed pulls (ISSUE 15):
        #: steady state reuses the previous-but-one pull's arena once
        #: its leaves died, so a streaming client performs zero large
        #: allocations per pull
        self._pull_scratch: list = []
        self._chan = None
        self.sock = connect(host, port)
        self._handshake()

    def _make_rings(self) -> Optional[tuple]:
        """(c2s, s2c) rings for the shm offer, or None when creation
        fails (no /dev/shm, quota) — the connection then stays TCP."""
        try:
            size = max(1 << 20, int(self.shm_mb * (1 << 20)))
            c2s = ShmRing.create(size)
            try:
                s2c = ShmRing.create(size)
            except OSError:
                c2s.unlink()
                c2s.close()
                raise
            return c2s, s2c
        except OSError as e:
            get_logger("ps.client").warning(
                "cannot create shared-memory rings (%s); staying on TCP", e)
            return None

    def _handshake(self) -> None:
        """Negotiate the wire format for this connection (the shared
        ``networking.client_handshake`` seam — serve clients run the same
        exchange).  A shard front-end's hello reply additionally carries
        its placement descriptor (``shard``: index / num_shards / plan
        epoch / plan digest — ISSUE 10), captured here so the sharded
        client can verify agreement at negotiation time; plain servers
        leave it None.  ISSUE 12 extras — the DOWN-codec advertisement
        and the shm ring offer — ride the same hello, included only when
        requested so the default handshake stays byte-identical."""
        extras: dict = {}
        if self.down_spec != "none":
            extras["down"] = {"codecs": list(codecs.DOWN_CODECS)}
        rings = None
        pinned = pinned_wire_version(self._want_version)
        if self.stream_requested and (pinned is None or pinned >= 2):
            extras["stream"] = {"chunk_bytes": self.stream_chunk_bytes}
        if self.shm_requested and (pinned is None or pinned >= 2):
            # a v1-pinned connection sends no hello: creating (and
            # immediately unlinking) 2 x shm_mb of /dev/shm per dial
            # would be pure waste
            rings = self._make_rings()
            if rings is not None:
                extras["shm"] = {"c2s": rings[0].name, "s2c": rings[1].name,
                                 "size": rings[0].size}
        info: dict = {}
        try:
            self.wire_version = client_handshake(
                self.sock, registry=self.registry, worker_id=self.worker_id,
                want=self._want_version, info=info,
                extras=extras or None)
        except BaseException:
            if rings is not None:
                for r in rings:
                    r.unlink()
                    r.close()
            raise
        self.shard_info = info.get("shard")
        self._down_ref = None
        self.down_enabled = (self.down_spec != "none"
                             and self.wire_version >= 2
                             and bool((info.get("down") or {}).get("ok")))
        self.stream_enabled = (self.stream_requested
                               and self.wire_version >= 2
                               and bool((info.get("stream") or {}).get("ok")))
        if self.down_enabled and self.down_spec == "adaptive" \
                and self._down_policy is None:
            # the policy survives reconnects: its EWMAs describe the
            # LINK, which is the same network path either way (the
            # LinkQuality edge rides along for the same reason)
            self._down_policy = codecs.AdaptiveDownPolicy(self.registry,
                                                          link=self.link)
        self.shm_active = False
        self._chan = self.sock
        if rings is not None:
            if (info.get("shm") or {}).get("ok"):
                self._chan = ShmChannel(self.sock, tx=rings[0], rx=rings[1])
                self.shm_active = True
            else:
                # refused (cross-host server, old server): this end owns
                # the segments — destroy them now, not at GC
                for r in rings:
                    r.unlink()
                    r.close()

    def _teardown_shm(self) -> None:
        if isinstance(self._chan, ShmChannel):
            self._chan.close_rings(unlink=True)
        self._chan = self.sock
        self.shm_active = False

    def reconnect(self, attempts: int = 6, base_delay: float = 0.1,
                  max_delay: float = 2.0) -> None:
        """Drop the (possibly broken) connection and dial again (the
        replacement server may be older/newer: re-negotiate).  The pull
        cache is dropped too — a RESTARTED server's update counter can
        coincide with the cached one while its center differs, and an
        ``unchanged`` answer would then silently serve the old server's
        center.

        Retries the whole dial + handshake up to ``attempts`` times with
        capped exponential backoff + jitter (ISSUE 9 satellite — a PS
        restart takes seconds, and a fleet re-dialing in lockstep is a
        thundering herd); each failed attempt counts under
        ``ps.client.reconnect_failures``, the final one re-raises."""
        self._teardown_shm()  # dead connection's rings: unlink now
        try:
            self.sock.close()
        except OSError:
            pass
        self._last_pull = None

        def dial():
            # one dial per attempt: the backoff (not connect's own
            # fixed-cadence retry loop) paces the re-dials
            self.sock = connect(self.host, self.port, retries=1)
            self._chan = self.sock
            self._handshake()

        retry_with_backoff(dial, attempts, base_delay, max_delay,
                           self._c_reconnect_failures.inc,
                           f"reconnect to {self.host}:{self.port}",
                           "ps.client")
        self._c_reconnects.inc()

    def _rpc(self, msg: dict, retry: bool = False) -> Any:
        """One framed request/response, rtt observed.  ``retry=True``
        reconnects and resends once on a dead connection — only safe for
        idempotent reads."""
        t0 = time.perf_counter()
        try:
            send_msg(self._chan, msg, registry=self.registry,
                     version=self.wire_version, count_as=_UP)
            resp = recv_msg(self._chan, registry=self.registry,
                            count_as=_DOWN)
        except (ConnectionError, OSError):
            if not retry:
                raise
            self.reconnect()
            send_msg(self._chan, msg, registry=self.registry,
                     version=self.wire_version, count_as=_UP)
            resp = recv_msg(self._chan, registry=self.registry,
                            count_as=_DOWN)
        self._h_rtt.observe(time.perf_counter() - t0)
        return resp

    @staticmethod
    def _raise_on_error(what: str, resp: dict) -> None:
        """Server error replies ({"ok": False, "error": ...} from a
        failed dispatch) raise instead of being misread as data."""
        if isinstance(resp, dict) and resp.get("error") is not None:
            raise RuntimeError(f"ps {what} failed on the server: "
                               f"{resp['error']}")

    def _span(self, name: str):
        """``ps.pull``/``ps.commit`` client span, or a no-op scope when no
        tracer is attached (spans must never be a hard dependency)."""
        if self.tracer is None:
            return contextlib.nullcontext()
        return self.tracer.span(name, worker=self.worker_id)

    def _trace_header(self) -> Optional[dict]:
        """``(trace_id, parent_span)`` of the currently-open client span —
        the cross-process link the server's apply span adopts.  Only on v2
        connections: the header is this build's protocol extension, and v1
        is the frozen legacy surface old servers parse."""
        if self.tracer is None or self.wire_version < 2:
            return None
        trace_id, span_id = self.tracer.context()
        hdr = {"trace_id": trace_id}
        if span_id is not None:
            hdr["parent_span"] = span_id
        return hdr

    def pull(self) -> tuple:
        """Returns ``(center_tree, server_update_counter)``.  Carries the
        counter of the center already held so an idle server answers
        ``unchanged`` instead of re-shipping megabytes (ISSUE 4)."""
        center, updates, _, _ = self.pull_versioned()
        return center, updates

    # -- split-phase protocol (ISSUE 10) ------------------------------------
    # The request/reply halves of pull and commit as separate calls, so a
    # sharded client PIPELINES a fan-out on one thread: send every
    # shard's request first (each shard starts decoding/applying while
    # the later sends are still in flight), then collect the replies.  A
    # thread-per-shard fan-out pays GIL contention and pool dispatch per
    # RPC; the pipeline pays one pass of sends and one of receives.

    def _pull_msg(self, have=None, min_updates=None) -> dict:
        # one assembly point so protocol keys (like the trace header)
        # can never be added to one request shape and missed on another
        msg = {"action": "pull", "worker_id": self.worker_id}
        trace = self._trace_header()
        if trace is not None:
            msg["trace"] = trace
        if have is not None:
            msg["have"] = have
        if min_updates is not None:
            msg["min_updates"] = int(min_updates)
        if self.down_enabled:
            codec = self._down_policy.next_codec() \
                if self._down_policy is not None else self.down_spec
            self._down_req = codec
            d: dict = {"codec": codec}
            if self._down_ref is not None:
                d["ref_epoch"] = int(self._down_ref[0])
            msg["down"] = d
        if self.stream_enabled:
            msg["stream"] = {"chunk_bytes": self.stream_chunk_bytes}
        return msg

    def pull_send(self, min_updates: Optional[int] = None) -> None:
        """Phase 1 of a pull: the request goes out (with the cached
        counter as ``have``); :meth:`pull_finish` must be the next call
        on this connection.  ``min_updates`` asks the server to briefly
        wait until its counter reaches that value before serving — the
        consistent-cut retry hint (old servers ignore it)."""
        self._t_pull = time.perf_counter()
        have = self._last_pull[1] if self._last_pull is not None else None
        send_msg(self._chan, self._pull_msg(have, min_updates),
                 registry=self.registry, version=self.wire_version,
                 count_as=_UP)

    def pull_finish(self) -> tuple:
        """Phase 2 of a pull: ``(center, updates, version_vector,
        plan_epoch)``.  Against a shard front-end the reply carries the
        shard's per-worker commit counts (the version vector a
        consistent-cut pull compares across shards) and its plan epoch;
        plain servers leave both None.  An ``unchanged`` answer reuses
        the cached center/vv/epoch — they can only change when the
        counter does.

        A streamed reply (ISSUE 15) is auto-detected per message: the
        chunks decode as they land (into the same zero-copy ``recv_into``
        buffers a monolithic v2 frame uses) and the per-chunk sizes feed
        ``ps.pull.stream_chunks`` / ``ps.pull.chunk_bytes``.  Every
        fresh pull also records how much of its wall time passed before
        this call started waiting (``ps.pull.hidden_seconds`` — the
        transfer a dispatch-ahead worker hid behind its device step) and
        the running ``ps.pull.overlap_fraction`` gauge."""
        t_wait = time.perf_counter()
        resp, chunks = recv_pull(self._chan, registry=self.registry,
                                 count_as=_DOWN,
                                 scratch=self._pull_scratch)
        # rtt_seconds keeps its "what this RPC cost the caller" meaning
        # under overlap: measured from the WAIT start, not the send — an
        # overlapped pull's device step must not read as wire latency
        # (identical to the old span for sequential pulls, where the
        # wait starts right after the send)
        self._h_rtt.observe(time.perf_counter() - t_wait)
        self._raise_on_error("pull", resp)
        updates = int(resp["updates"])
        if resp.get("unchanged"):
            # unchanged replies are codec-free and near-instant: never
            # fold their RTT into the adaptive policy's per-codec EWMAs
            # (nor the link EWMA — a no-payload RTT would bias the
            # degradation baseline toward zero)
            if self._last_pull is not None:
                self._c_unchanged.inc()
                return (self._last_pull[0], updates,
                        self._last_pull[2], self._last_pull[3])
            # the cache was invalidated mid-exchange (a reconnect dropped
            # it, but a stale ``have`` was resent): ask again
            # unconditionally for the full center
            send_msg(self._chan, self._pull_msg(), registry=self.registry,
                     version=self.wire_version, count_as=_UP)
            resp, chunks = recv_pull(self._chan, registry=self.registry,
                                     count_as=_DOWN,
                                     scratch=self._pull_scratch)
            self._raise_on_error("pull", resp)
            updates = int(resp["updates"])
        center = self._decode_down(resp)
        t_done = time.perf_counter()
        if chunks is not None:
            self._c_streams.inc()
            self._c_stream_chunks.inc(len(chunks))
            for n in chunks:
                self._h_chunk_bytes.observe(n)
        # overlap accounting over fresh pulls only: hidden = in-flight
        # time before this end blocked on the reply
        hidden = max(0.0, t_wait - self._t_pull)
        total = max(t_done - self._t_pull, 1e-9)
        self._h_hidden.observe(hidden)
        self._hidden_total += hidden
        self._pull_wall_total += total
        self._g_overlap.set(self._hidden_total / self._pull_wall_total)
        # the link/codec EWMAs are fed the VISIBLE wait (blocked ->
        # decoded), never send->decoded: for a sequential pull the two
        # coincide, but an overlapped pull's span includes the caller's
        # whole device step — folding that in would read healthy links
        # as degraded, downshift codecs for no wire reason, and report
        # compute time as link RTT.  The visible wait is exactly the
        # pull's critical-path cost in either mode, so the EWMAs stay
        # comparable and a degraded link still shows (more bytes left
        # to drain after compute).
        wait_s = max(t_done - t_wait, 1e-9)
        self.link.observe_pull(wait_s)
        if self._down_policy is not None and self._down_req is not None:
            # measured to AFTER decode: the per-codec EWMAs must fold in
            # this end's decode cost, or a heavy-decode codec looks
            # cheaper than it is end to end
            self._down_policy.observe(
                (resp.get("down") or {}).get("codec", "none")
                if isinstance(resp.get("down"), dict) else "none",
                wait_s)
        vv = resp.get("vv")
        if isinstance(vv, dict):
            vv = {int(k): int(v) for k, v in vv.items()}
        epoch = resp.get("plan_epoch")
        self._last_pull = (center, updates, vv, epoch)
        return center, updates, vv, epoch

    def _decode_down(self, resp: dict):
        """The pulled center: raw (``center`` key — v1 peers, down
        disabled, or the adaptive policy picked "none") or decoded from
        the DOWN residual against this connection's acknowledged
        reference (ISSUE 12).  A ``reference``-carrying reply is a full
        resync: adopt it AND the epoch; a residual-only reply for an
        epoch this connection does not hold is a protocol desync and
        fails loudly rather than decode against the wrong reference."""
        down = resp.get("down")
        if not isinstance(down, dict):
            return resp["center"]
        t0 = time.perf_counter()
        epoch = int(down["ref_epoch"])
        ref = down.get("reference")
        if ref is not None:
            self._down_ref = (epoch, ref)
            self._c_resyncs.inc()
        elif self._down_ref is None or self._down_ref[0] != epoch:
            raise RuntimeError(
                f"ps pull: server encoded against reference epoch "
                f"{epoch} but this connection holds "
                f"{None if self._down_ref is None else self._down_ref[0]}")
        center = codecs.apply_ref_delta(self._down_ref[1], down["residual"])
        codecs.count_codec_bytes(
            self.registry, codecs.tree_payload_bytes(center),
            codecs.tree_payload_bytes(down["residual"])
            + (codecs.tree_payload_bytes(ref) if ref is not None else 0),
            prefix="ps.down")
        self._h_down_decode.observe(time.perf_counter() - t0)
        return center

    def pull_versioned(self) -> tuple:
        """The full pull protocol in one call (transparently reconnects
        and retries once on a dead connection — an idempotent read)."""
        with self._span("ps.pull"):
            try:
                self.pull_send()
                return self.pull_finish()
            except (ConnectionError, OSError):
                self.reconnect()
                self.pull_send()
                return self.pull_finish()

    # -- overlapped pulls (ISSUE 15) ----------------------------------------
    def pull_begin(self, min_updates: Optional[int] = None) -> None:
        """Phase 1 of an OVERLAPPED pull, with the idempotent-read
        reconnect: the dispatch-ahead worker issues this right after its
        device step is dispatched, so the center transfer rides the wire
        while the device computes; :meth:`pull_join` collects it."""
        try:
            self.pull_send(min_updates)
        except (ConnectionError, OSError):
            self.reconnect()
            self.pull_send(min_updates)

    def pull_join(self) -> tuple:
        """Phase 2 of an overlapped pull (same return shape as
        :meth:`pull_finish`); a connection that died mid-flight — a
        mid-stream reset included — reconnects via the standard backoff
        and re-pulls: a pull is an idempotent read, so the retry can
        never double-apply anything."""
        try:
            return self.pull_finish()
        except (ConnectionError, OSError):
            self.reconnect()
            self.pull_send()
            return self.pull_finish()

    def commit_send(self, delta: Any, last_update: Optional[int] = None,
                    gap_s: Optional[float] = None) -> None:
        """Phase 1 of a commit: codec-encode and ship the delta;
        :meth:`commit_finish` must be the next call on this
        connection."""
        if not self.codec.is_identity:
            t0 = time.perf_counter()
            raw = codecs.tree_payload_bytes(delta)
            delta = self.codec.encode(delta)
            codecs.count_codec_bytes(self.registry, raw,
                                     codecs.tree_payload_bytes(delta))
            self._h_encode.observe(time.perf_counter() - t0)
        msg = {"action": "commit", "worker_id": self.worker_id,
               "gen": self.generation,
               "delta": delta, "codec": self.codec.name}
        trace = self._trace_header()
        if trace is not None:
            msg["trace"] = trace
        if gap_s is not None:
            msg["gap_s"] = float(gap_s)
        link_rtt = self.link.ewma
        if link_rtt is not None:
            # the link half of the straggler picture (ISSUE 15):
            # harmless extra keys to old servers, like gap_s
            msg["link_rtt_s"] = float(link_rtt)
            if self._down_policy is not None and \
                    self._down_policy.downshifts:
                msg["link_downshifts"] = int(self._down_policy.downshifts)
        if last_update is not None:
            msg["last_update"] = int(last_update)
        self._t_commit = time.perf_counter()
        send_msg(self._chan, msg, registry=self.registry,
                 version=self.wire_version, count_as=_UP)

    def commit_finish(self) -> bool:
        """Phase 2 of a commit: True when applied, False when a fault
        injector dropped it; an eviction notice raises
        :class:`WorkerEvicted`."""
        resp = recv_msg(self._chan, registry=self.registry, count_as=_DOWN)
        dt = time.perf_counter() - self._t_commit
        self._h_rtt.observe(dt)
        self.link.observe_commit(dt)
        # a server-side apply failure answers {"ok": False, "error"}
        # (it did NOT apply the delta) — that must surface as a
        # failure to the worker's retry policy, never as success
        self._raise_on_error("commit", resp)
        if resp.get("evicted"):
            # the PS tombstoned this commit: a newer incarnation owns
            # the worker id — this one's loop must wind down (ISSUE 9)
            raise WorkerEvicted(
                f"worker {self.worker_id} generation "
                f"{self.generation} evicted by the PS")
        return not resp.get("dropped", False)

    def commit(self, delta: Any, last_update: Optional[int] = None,
               gap_s: Optional[float] = None) -> bool:
        """Commit a delta; returns False if a fault injector dropped it.
        A non-identity codec compresses the payload here (error-feedback
        residual updated as a side effect) — the server decodes
        statelessly from the per-leaf stubs.  Never auto-retries (the
        server may have applied the delta before a connection died).

        ``gap_s`` is the worker's monotonic gap since its previous window
        commit — the heartbeat signal the server-side straggler detector
        folds in (ISSUE 5); harmless extra key to old servers."""
        with self._span("ps.commit"):
            self.commit_send(delta, last_update=last_update, gap_s=gap_s)
            return self.commit_finish()

    def invalidate(self) -> None:
        """Drop the client-side center cache: the next pull ships a full
        center even at an unchanged counter (reconnect does this
        implicitly; callers use it after out-of-band center changes —  a
        restored checkpoint — and the pull-heavy bench phase uses it to
        measure fresh-pull RTTs).  The DOWN reference is kept: it is
        per-connection wire state, still valid for residual decode."""
        self._last_pull = None

    def stats(self) -> dict:
        """Poll the server's live telemetry: ``{"stats": <registry
        snapshot>, "num_updates": int, "commits_by_worker": dict, ...}`` —
        no center transfer, safe to call while training runs."""
        return self._rpc({"action": "stats", "worker_id": self.worker_id},
                         retry=True)

    def ship_telemetry(self, delta: dict, *, source: str) -> dict:
        """Push one ``snapshot_delta`` increment frame to the server's
        telemetry aggregator (ISSUE 20).  Never auto-retries: a frame
        the server may already have folded would double-count on replay
        — the shipper keeps unacked increments in its next frame
        instead."""
        return self._rpc({"action": "telemetry",
                          "worker_id": self.worker_id,
                          "source": str(source), "delta": delta},
                         retry=False)

    def close(self) -> None:
        try:
            # over the negotiated channel: a shm server answers even the
            # stop ack on the ring
            send_msg(self._chan, {"action": "stop"},
                     registry=self.registry, version=self.wire_version)
            recv_msg(self._chan, registry=self.registry)
        except (ConnectionError, OSError):
            pass
        finally:
            # this end created the shm segments: destroy them on the
            # shutdown path (dklint shm-lifecycle), after the stop
            # exchange so the server's handler is already done with them
            self._teardown_shm()
            try:
                self.sock.close()
            except OSError:
                pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

"""Worker-side PS client: one persistent connection, pull/commit calls.

Parity with the reference's worker-side socket usage (reference
``distkeras/workers.py:NetworkWorker.pull``/``commit``): full center down,
delta up, at communication-window boundaries.
"""

from __future__ import annotations

from typing import Any, Optional

from .networking import connect, recv_msg, send_msg


class PSClient:
    def __init__(self, host: str, port: int, worker_id: int = 0):
        self.worker_id = int(worker_id)
        self.sock = connect(host, port)

    def pull(self) -> tuple:
        """Returns ``(center_tree, server_update_counter)``."""
        send_msg(self.sock, {"action": "pull", "worker_id": self.worker_id})
        resp = recv_msg(self.sock)
        return resp["center"], int(resp["updates"])

    def commit(self, delta: Any, last_update: Optional[int] = None) -> bool:
        """Commit a delta; returns False if a fault injector dropped it."""
        msg = {"action": "commit", "worker_id": self.worker_id,
               "delta": delta}
        if last_update is not None:
            msg["last_update"] = int(last_update)
        send_msg(self.sock, msg)
        resp = recv_msg(self.sock)
        return not resp.get("dropped", False)

    def close(self) -> None:
        try:
            send_msg(self.sock, {"action": "stop"})
            recv_msg(self.sock)
        except (ConnectionError, OSError):
            pass
        finally:
            try:
                self.sock.close()
            except OSError:
                pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

"""Asynchronous parameter server — the reference's behavioral twin.

The sync engine (``distkeras_tpu.parallel.sync``) is the idiomatic TPU
formulation, but it is the *synchronous limit* of each algorithm: staleness
is identically zero.  The reference's defining behaviors — true asynchrony,
per-commit update rules, DynSGD's staleness scaling — need a real shared
center variable that workers hit at their own pace.  This package provides
it: a host-side TCP parameter server (star topology, mutex-guarded commits,
per-connection threads — structurally the reference's
``distkeras/parameter_servers.py`` + ``distkeras/networking.py``) speaking
length-prefixed **msgpack** (never pickle) over localhost or DCN, with
workers running jit-compiled window scans on their device between pulls and
commits.
"""

from .networking import (  # noqa: F401
    WIRE_VERSION,
    connect,
    determine_host_address,
    pack_msg,
    recv_msg,
    send_msg,
    send_packed,
)
from .codecs import Codec, decode_tree, get_codec  # noqa: F401
from .servers import (  # noqa: F401
    ADAGParameterServer,
    DeltaParameterServer,
    DynSGDParameterServer,
    ParameterServer,
    SocketParameterServer,
)
from .client import PSClient, WorkerEvicted  # noqa: F401
from .shard import (  # noqa: F401
    ConsistentCutError,
    ShardedParameterServer,
    ShardedPSClient,
    ShardFleetError,
    ShardPlan,
    ShardPlanMismatch,
)

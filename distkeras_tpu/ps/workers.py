"""Async worker loops — parity with reference ``distkeras/workers.py``.

Each worker owns a device, runs the jit-compiled window scan
(``parallel.sync.make_window_fn``) on its partition, and talks to the
parameter server at window boundaries:

* ``PullCommitWorker``  — DOWNPOUR / ADAG (reference ``DOWNPOURWorker`` /
  ``ADAGWorker``): pull center, train a window from it, commit the delta.
* ``StalenessWorker``   — DynSGD (reference ``DynSGDWorker``): same, but the
  commit carries the update counter seen at pull time so the server can
  compute staleness.
* ``ElasticWorker``     — AEASGD / EAMSGD (reference ``AEASGDWorker`` /
  ``EAMSGDWorker``): the local model persists across windows; the elastic
  force E = α(local − center) moves local toward center and is committed.

Workers run as threads in this process (the reference's ran as Spark
executor tasks): JAX compute releases the GIL, so windows genuinely overlap
and commits interleave nondeterministically — real asynchrony, real
staleness.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable, Optional

import jax
import numpy as np

from ..obs import profile as obs_profile
from ..obs.logging import get_logger
from ..obs.spans import SpanTracer
from ..parallel.sync import _inexact, adopt_float_leaves, tmap as _tmap
from .client import PSClient, WorkerEvicted

Tree = Any


def _host(tree):
    return _tmap(np.asarray, tree)


def _merge_pull(local, center):
    """Adopt the pulled center's floating leaves; keep worker-local
    integer/bool state (RNG counters stay decorrelated across workers —
    same rule as the sync engine's window edge)."""
    return adopt_float_leaves(center, local)


class AsyncWorker(threading.Thread):
    """Base: epochs × windows loop over this worker's partition slice."""

    def __init__(self, worker_id: int, window_fn: Callable,
                 variables: Tree, opt_state: Tree, rng,
                 host: str, port: int, num_epoch: int,
                 device=None, start_window: int = 0, metrics=None,
                 comm_codec: str = "none", profile_memory: bool = True,
                 generation: int = 0, comm_down: str = "none",
                 shm: bool = False, pull_overlap: bool = False,
                 telemetry_s: Optional[float] = None):
        super().__init__(name=f"worker-{worker_id}", daemon=True)
        self.worker_id = worker_id
        #: commit generation this incarnation runs under (ISSUE 9): the
        #: supervisor bumps it on eviction, so a zombie predecessor's
        #: late commits tombstone instead of double-applying
        self.generation = int(generation)
        #: True when the PS evicted this incarnation (a replacement owns
        #: the id): a CLEAN exit, distinct from ``error``
        self.evicted = False
        self.window_fn = window_fn
        self.variables = variables
        self.opt_state = opt_state
        self.rng = rng
        self.ps_host = host
        self.ps_port = port
        self.num_epoch = num_epoch
        self.device = device
        #: delta-compression codec spec (``ps.codecs``): the client built
        #: in ``run()`` owns the stateful error-feedback instance
        self.comm_codec = comm_codec
        #: DOWN pull-compression spec and same-host shm-transport opt-in
        #: (ISSUE 12) — like the codec, the client owns the per-link
        #: state (reference epoch, adaptive policy, rings); a respawned
        #: incarnation's fresh client starts reference-less, so its
        #: first pull is a full resync by construction
        self.comm_down = comm_down
        self.shm = bool(shm)
        #: dispatch-ahead pulls (ISSUE 15): issue window k+1's pull right
        #: after window k's device step is DISPATCHED, so the center
        #: transfer rides the wire while the device computes — the pull
        #: all but leaves the window critical path (recorded per pull as
        #: ``ps.pull.hidden_seconds`` / ``ps.pull.overlap_fraction``).
        #: The worker then trains window k+1 from a center pulled before
        #: its own commit k landed: one extra window of self-staleness,
        #: exactly the regime the async update rules already absorb
        #: (DynSGD's staleness math sees it as staleness 1).  Pull-first
        #: workers only; the elastic family computes before it pulls, so
        #: there is nothing to hide the transfer behind.
        self.pull_overlap = bool(pull_overlap)
        #: (center, seen_updates) collected by the previous window's
        #: overlapped pull — the next window dispatches from it the
        #: moment the final chunk lands
        self._next_center = None
        #: set per window by ``_train`` so the LAST window skips issuing
        #: a dispatch-ahead pull nothing will consume
        self._is_last_window = False
        #: optional shared JSONL sink (``MetricsLogger`` — thread-safe):
        #: one ``heartbeat`` record per committed window, so a stalled or
        #: straggling worker is visible IN-RUN, not post-mortem (ISSUE 2)
        self.metrics = metrics
        #: exact resume: global window index to continue from (= this
        #: worker's commit count in the restored PS snapshot; one commit
        #: per window).  0 on a fresh run.
        self.start_window = int(start_window)
        self.losses: list = []          # one (n_windows, w) array per epoch
        self.epoch_losses: dict = {}    # absolute epoch -> (n_windows, w)
        #: flat (global_window_index, (w,) losses) pairs — the exact record
        self.window_losses: list = []
        self.error: Optional[BaseException] = None
        self.xs = self.ys = None        # (n_windows, w, batch, ...) numpy
        #: per-worker span tracer (built on the worker's own thread in
        #: ``run()``): trace id ``w<worker_id>``, sink shared with the
        #: heartbeats — commit/pull spans and the server's linked apply
        #: spans interleave in one stream (ISSUE 5)
        self.tracer: Optional[SpanTracer] = None
        #: monotonic clock of the previous commit — the heartbeat-gap
        #: source (``gap_s``); wall-clock diffs would absorb NTP steps
        self._last_commit_mono: Optional[float] = None
        self._gap_s: Optional[float] = None
        #: memory-watermark sampling at the heartbeat points (ISSUE 6):
        #: ``mem.*`` gauges in the process-wide registry + ``live_bytes``
        #: on every heartbeat record (the per-window HBM trail)
        self.profile_memory = bool(profile_memory)
        #: push-telemetry cadence (ISSUE 20): when set, the worker ships
        #: ``snapshot_delta`` frames of its process-wide registry to the
        #: PS every ``telemetry_s`` seconds.  Meant for PROCESS placement
        #: (one registry per worker process); thread-placement fleets
        #: share one registry, so the supervisor ingests it in-process
        #: instead of N workers each shipping the same deltas.
        self.telemetry_s = telemetry_s
        self._shipper = None

    def set_data(self, xs, ys):
        self.xs, self.ys = xs, ys

    def set_stream(self, factory: Callable, n_windows: int):
        """Disk-streaming data source: ``factory(epoch) -> iterator`` of
        ``(wx, wy)`` window tuples, each ``(window, batch, ...)``.  The
        worker streams its OWN shard partition instead of holding the
        epoch in RAM (SURVEY.md §7 hard part 6)."""
        self._stream_factory = factory
        self._stream_windows = int(n_windows)

    def _put(self, tree):
        if self.device is not None:
            return _tmap(lambda x: jax.device_put(x, self.device), tree)
        return tree

    def _make_client(self):
        """One PS connection — or, when ``port`` is a LIST of shard
        ports (ISSUE 10), a ``ShardedPSClient`` fanning this worker's
        traffic across the fleet with consistent-cut pulls.  Either way
        the worker loop drives the same pull/commit surface."""
        if isinstance(self.ps_port, (list, tuple)):
            from .shard import ShardedPSClient
            return ShardedPSClient(
                [(self.ps_host, p) for p in self.ps_port],
                template=_host(self.variables), worker_id=self.worker_id,
                codec=self.comm_codec, tracer=self.tracer,
                generation=self.generation, down=self.comm_down,
                shm=self.shm or None)
        return PSClient(self.ps_host, self.ps_port, self.worker_id,
                        codec=self.comm_codec, tracer=self.tracer,
                        generation=self.generation, down=self.comm_down,
                        shm=self.shm or None)

    def run(self):
        try:
            # built HERE so the thread-local trace id binds to the worker's
            # own thread (__init__ runs on the spawning thread)
            self.tracer = SpanTracer(self.metrics)
            self.tracer.set_trace_id(f"w{self.worker_id}")
            self._last_commit_mono = time.monotonic()
            client = self._make_client()
            if self.telemetry_s:
                from ..obs.registry import default_registry
                from ..obs.timeseries import TelemetryShipper
                # frames ride the existing PS connection; retry-less, so
                # a frame the server may have folded never replays
                self._shipper = TelemetryShipper(
                    default_registry(),
                    lambda p: client.ship_telemetry(
                        p["delta"], source=p["source"]),
                    source=f"worker{self.worker_id}",
                    period_s=float(self.telemetry_s))
            try:
                self._train(client)
            finally:
                if self._shipper is not None:
                    # flush the tail increments before the socket closes
                    # (ship() itself swallows and counts SEND failures;
                    # this guard keeps teardown alive on anything else)
                    try:
                        self._shipper.ship()
                    except Exception as e:
                        get_logger("ps.worker").warning(
                            "final telemetry flush failed: %s", e)
                client.close()
        except WorkerEvicted:
            # eviction notice, not a failure: the supervisor's replacement
            # owns this worker id — wind down without burning the slice
            self.evicted = True
        except BaseException as e:  # surfaced by the runner after join()
            self.error = e

    def _commit_gap(self) -> float:
        """Monotonic seconds since this worker's previous commit — the
        per-window heartbeat gap shipped on the commit RPC (and echoed on
        the heartbeat record) so the straggler detector and obsview never
        reconstruct gaps from wall-clock diffs (ISSUE 5).  The first
        window measures from loop start: a worker that stalls before its
        first commit still shows a stretched gap."""
        now = time.monotonic()
        self._gap_s = now - self._last_commit_mono
        self._last_commit_mono = now
        return self._gap_s

    @staticmethod
    def _link_ewma(client) -> Optional[float]:
        """The client's link RTT EWMA (ISSUE 15) — representative across
        a sharded client's connections (the slowest link gates the
        fan-out, so take the max)."""
        link = getattr(client, "link", None)
        if link is not None:
            return link.ewma
        subs = getattr(client, "clients", None)
        if subs:
            ewmas = [c.link.ewma for c in subs if c.link.ewma is not None]
            return max(ewmas) if ewmas else None
        return None

    def _train(self, client: PSClient):
        self._client = client
        stream = getattr(self, "_stream_factory", None)
        n_windows = self._stream_windows if stream is not None \
            else int(self.xs.shape[0])
        total = self.num_epoch * n_windows
        try:
            if stream is not None:
                self._stream_epochs(client, stream, n_windows, total)
            else:
                for gw in range(self.start_window, total):
                    wi = gw % n_windows  # window within the epoch
                    self._is_last_window = gw == total - 1
                    wx = self._put(self.xs[wi])
                    wy = self._put(self.ys[wi])
                    losses = self._window(client, wx, wy)
                    self.window_losses.append((gw, np.asarray(losses)))
                    self._heartbeat(gw, n_windows)
        finally:
            # per-epoch view for the COMPLETE epochs this run covered —
            # built even on a crash so a retried worker's merge keeps the
            # epochs this attempt finished (a resumed worker may start
            # mid-epoch; that partial epoch is only in window_losses)
            by_epoch: dict = {}
            for gw, l in self.window_losses:
                by_epoch.setdefault(gw // n_windows, []).append(l)
            self.epoch_losses = {e: np.stack(ls)
                                 for e, ls in by_epoch.items()
                                 if len(ls) == n_windows}
            self.losses = [self.epoch_losses[e]
                           for e in sorted(self.epoch_losses)]

    def _stream_epochs(self, client: PSClient, factory: Callable,
                       n_windows: int, total: int):
        """Epoch loop over streamed windows; a resumed worker fast-forwards
        its first epoch's iterator to the window its commits reached (the
        skipped windows are read and dropped — disk IO, no compute)."""
        gw = self.start_window
        while gw < total:
            epoch = gw // n_windows
            it = factory(epoch)
            try:
                skip = gw % n_windows
                for _ in range(skip):
                    next(it)
                for _ in range(skip, n_windows):
                    wx, wy = next(it)
                    self._is_last_window = gw == total - 1
                    losses = self._window(client, self._put(wx),
                                          self._put(wy))
                    self.window_losses.append((gw, np.asarray(losses)))
                    self._heartbeat(gw, n_windows)
                    gw += 1
            finally:
                if hasattr(it, "close"):
                    it.close()

    def _heartbeat(self, gw: int, n_windows: int) -> None:
        """One liveness record per committed window into the shared sink.
        The latest window's mean loss rides along so a live tail of the
        JSONL shows progress AND health per worker; ``worker_id`` +
        monotonic ``gap_s`` make each record self-contained for the
        straggler detector and obsview (ISSUE 5 — no wall-clock-diff
        reconstruction downstream; readers fall back to the pre-PR-5
        ``worker`` key on old streams)."""
        if self._shipper is not None:
            # window-boundary hook, BEFORE the metrics-sink guard: push
            # telemetry is independent of the JSONL heartbeat stream
            self._shipper.maybe_ship()
        if self.metrics is None:
            return
        _, losses = self.window_losses[-1]
        extra = {}
        if self.profile_memory:
            extra["live_bytes"] = obs_profile.observe_memory()["live_bytes"]
        link = self._link_ewma(getattr(self, "_client", None))
        if link is not None:
            # the link half of the health record (ISSUE 15): obsview's
            # offline replay renders gap and link side by side
            extra["link_rtt_s"] = float(link)
        self.metrics.log("heartbeat", worker_id=self.worker_id, window=gw,
                         epoch=gw // n_windows, gap_s=self._gap_s,
                         mean_loss=float(np.mean(losses)), **extra)

    def _run_window(self, wx, wy):
        # slow-motion throttle for the chaos harness / contention benches
        # (ISSUE 9): toy windows finish in ms, far too fast to inject a
        # mid-run fault deterministically — a per-window sleep stretches
        # the run without changing any numerics.  Off (0) in production.
        delay = float(os.environ.get("DKTPU_WINDOW_DELAY_S", 0) or 0)
        if delay > 0:
            time.sleep(delay)
        self.variables, self.opt_state, self.rng, losses = self.window_fn(
            self.variables, self.opt_state, self.rng, wx, wy)
        return losses

    def _window(self, client: PSClient, wx, wy):
        raise NotImplementedError


class _PullFirstWorker(AsyncWorker):
    """Shared loop shape of the pull-first family (DOWNPOUR / ADAG /
    DynSGD): pull center -> train a window from it -> commit the delta.

    With ``pull_overlap`` (ISSUE 15) the loop becomes dispatch-ahead:

    1. dispatch window k's device step (JAX async dispatch — returns
       before the device finishes);
    2. ``pull_begin()`` — window k+1's center transfer starts NOW;
    3. block on window k's outputs (the device time is what hides the
       transfer) and build the delta;
    4. ``pull_join()`` — by now the final chunk has usually landed, so
       window k+1 can dispatch the moment this returns;
    5. commit window k.

    The wire order per connection stays the strict split-phase contract
    (pull request, pull reply, commit request, commit reply), so there
    is no head-of-line deadlock and no reply mismatch; the cost is one
    window of self-staleness — window k+1's center predates commit k —
    which is exactly the regime the async update rules absorb."""

    def _commit_kw(self, seen_updates) -> dict:
        """Extra commit kwargs derived from the pull (DynSGD's
        ``last_update``)."""
        return {}

    def _window(self, client, wx, wy):
        if self._next_center is not None:
            center, seen = self._next_center
            self._next_center = None
        else:
            pulled = client.pull()
            center, seen = pulled[0], pulled[1]
        self.variables = self._put(_merge_pull(_host(self.variables), center))
        losses = self._run_window(wx, wy)
        overlap = self.pull_overlap and not self._is_last_window
        if overlap:
            # window k+1's pull rides the wire while the device runs
            client.pull_begin()
        after = _host(self.variables)
        delta = _tmap(lambda a, c: a - np.asarray(c), after, center)
        if overlap:
            nxt = client.pull_join()
            self._next_center = (nxt[0], nxt[1])
        client.commit(delta, **self._commit_kw(seen),
                      gap_s=self._commit_gap())
        return losses


class PullCommitWorker(_PullFirstWorker):
    """DOWNPOUR / ADAG: local model is replaced by the pulled center each
    window; the commit is the accumulated local update Δ = θ_after −
    θ_pulled (the server's rule decides scaling)."""


class StalenessWorker(_PullFirstWorker):
    """DynSGD: like PullCommitWorker but the commit reports the server
    update counter observed at pull time (staleness bookkeeping)."""

    def _commit_kw(self, seen_updates):
        return {"last_update": seen_updates}


class ElasticWorker(AsyncWorker):
    """AEASGD / EAMSGD: local model persists (exploration); every window the
    elastic force E = α(local − center) is applied locally and committed."""

    def __init__(self, *args, alpha: float = 0.05, **kw):
        super().__init__(*args, **kw)
        self.alpha = float(alpha)

    def _window(self, client, wx, wy):
        losses = self._run_window(wx, wy)
        center, _ = client.pull()
        local = _host(self.variables)
        # elastic force on floating leaves only; integer/bool state (RNG
        # counters) commits a zero delta (the server skips it anyway) and
        # stays worker-local, dtype intact
        elastic = _tmap(
            lambda l, c: self.alpha * (l - np.asarray(c)) if _inexact(l)
            else np.zeros_like(l), local, center)
        self.variables = self._put(
            _tmap(lambda l, e: l - e, local, elastic))
        client.commit(elastic, gap_s=self._commit_gap())
        return losses

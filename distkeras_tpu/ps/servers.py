"""Parameter servers — parity with reference ``distkeras/parameter_servers.py``.

``SocketParameterServer`` owns the listen/accept loop (one handler thread
per connected worker, like the reference) and the mutex around commits; the
subclasses implement the per-commit update rules:

* ``DeltaParameterServer``   — center += delta (DOWNPOUR / AEASGD / EAMSGD)
* ``ADAGParameterServer``    — center += delta / num_workers
* ``DynSGDParameterServer``  — center += delta / (staleness + 1)

The center variable is a NumPy pytree (the reference's was a Keras weight
list).  A ``fault_injector`` hook can drop or delay commits — the test
harness the reference never had (SURVEY.md §5.3).

Instrumented end to end (ISSUE 2): every server owns an ``obs.Registry``
(commit/pull counters, apply-latency histogram, per-worker staleness
histograms, connection/in-flight gauges, wire byte counts), and
``SocketParameterServer`` answers a ``stats`` action with a full registry
snapshot plus ground-truth counters — a running PS is pollable live
(``PSClient.stats()`` / ``scripts/obsview.py --ps host:port``).
"""

from __future__ import annotations

import collections
import contextlib
import socket
import threading
import time
from typing import Any, Callable, Optional

import numpy as np

from ..obs import COUNT_BUCKETS, TIME_BUCKETS, Registry, StragglerDetector
from ..obs.spans import SpanTracer
from ..parallel.sync import _inexact, tmap as _tree_map
from ..utils import native
from . import codecs
from .networking import (MIN_STREAM_CHUNK_BYTES, REPLY_SENT,
                         STREAM_CHUNK_BYTES, WIRE_VERSION, FrameServer,
                         pack_stream, send_packed, send_stream,
                         stream_enabled_env)
from .state import DeltaDecoder, DownRefState, LivenessTable, PullCache

Tree = Any


def _tree_fused_add(center: Tree, delta: Tree, scale: float) -> Tree:
    """center + scale·delta leaf-wise via the native data plane
    (``native/dknative.cpp``) — one fused multithreaded pass per leaf, GIL
    released; NumPy fallback.  Returns NEW arrays (replace semantics keep
    the lock-free pull/checkpoint snapshots race-free).

    Floating leaves only: integer/bool variable state (e.g. Keras
    SeedGenerator counters) has no meaningful delta arithmetic — the
    center keeps its value (mirrors the sync engine's window-edge rule)."""
    return _tree_map(
        lambda c, d: native.fused_add(np.asarray(c), np.asarray(d), scale)
        if _inexact(c) else np.asarray(c),
        center, delta)


class ParameterServer:
    """Base (reference ``ParameterServer``): holds the center variable and
    the update counter.  Optionally checkpoints the center every
    ``checkpoint_every`` commits (SURVEY.md §5.4 — persistence the
    reference lacked).

    Fleet lifecycle (ISSUE 9): every worker id carries a **generation** —
    bumped by :meth:`evict_worker` when the supervisor declares the
    incarnation dead.  A commit stamped with a stale generation is
    **tombstoned**: counted (``ps.commits_tombstoned``), never applied —
    so a SIGCONT'd zombie or a delayed socket can never double-apply a
    window its replacement already re-trained.  Respawns and elastic
    joins register through :meth:`register_respawn` /
    :meth:`register_join`, which hand back the exact window (= the
    per-worker commit count) the new incarnation resumes from."""

    def __init__(self, center: Tree, num_workers: int = 1,
                 checkpoint_manager=None, checkpoint_every: int = 0,
                 registry: Optional[Registry] = None):
        self.center = _tree_map(np.asarray, center)
        self.num_workers = int(num_workers)
        self.num_updates = 0
        #: per-worker commit counts — exact resume bookkeeping: commit k of
        #: worker w IS window k of worker w (one commit per communication
        #: window), so a restored snapshot tells each worker exactly which
        #: window to continue from (SURVEY.md §5.4).
        self.commits_by_worker: dict = {}
        #: fleet lifecycle state (ISSUE 9), every touch under ``mutex``:
        #: worker -> current commit generation (evictions bump it) and the
        #: per-worker eviction/respawn/join/tombstone tallies the live
        #: ``stats`` RPC surfaces
        self.generations: dict = {}
        self.tombstoned_by_worker: dict = {}
        self.evictions_by_worker: dict = {}
        self.respawns_by_worker: dict = {}
        self.joins_by_worker: dict = {}
        self.mutex = threading.Lock()
        self.checkpoint_manager = checkpoint_manager
        self.checkpoint_every = int(checkpoint_every)
        #: component-scoped instruments: a ``stats`` snapshot describes
        #: exactly THIS server (a shared/default registry would fold every
        #: in-process component into the reply)
        self.registry = registry if registry is not None else Registry()
        self._c_commits = self.registry.counter("ps.commits")
        self._c_pulls = self.registry.counter("ps.pulls")
        self._c_tombstoned = self.registry.counter("ps.commits_tombstoned")
        self._c_evictions = self.registry.counter("ps.evictions")
        self._c_respawns = self.registry.counter("ps.respawns")
        self._c_joins = self.registry.counter("ps.joins")
        self._h_apply = self.registry.histogram("ps.apply_seconds",
                                                TIME_BUCKETS)
        #: time commits spend WAITING for the mutex (ISSUE 10): the
        #: single-lock convoy the contention sweep measures, directly —
        #: ``ps.apply_seconds`` is the hold time, this is the queue
        self._h_lock_wait = self.registry.histogram(
            "ps.lock_wait_seconds", TIME_BUCKETS)

    # -- update rule (subclass responsibility) ------------------------------
    def apply_commit(self, delta: Tree, meta: dict) -> None:  # dklint: holds=mutex
        """Apply one commit to the center.  Contract: ``handle_commit``
        calls this with ``self.mutex`` held — implementations read and
        replace shared state without re-locking.  Implementations fold
        :meth:`_commit_scale` into their update so a down-weighted
        straggler's delta lands scaled (ISSUE 9)."""
        raise NotImplementedError

    @staticmethod
    def _commit_scale(meta: dict) -> float:  # dklint: holds=mutex
        """Flag-aware down-weighting multiplier the front-end attached
        (``commit_weight`` — 1.0 for healthy workers); every update rule
        multiplies its own scale by this."""
        return float(meta.get("commit_weight", 1.0))

    def handle_commit(self, delta: Tree, meta: dict) -> bool:
        """Apply one commit; returns True when applied, False when the
        commit's generation is stale (a tombstoned zombie commit)."""
        snapshot = None
        t0 = time.perf_counter()
        with self.mutex:
            self._h_lock_wait.observe(time.perf_counter() - t0)
            w = meta.get("worker_id")
            if w is not None:
                w = int(w)
                if int(meta.get("gen", 0)) < self.generations.get(w, 0):
                    # stale incarnation: its replacement already owns this
                    # window range — record, never apply (ISSUE 9)
                    self.tombstoned_by_worker[w] = \
                        self.tombstoned_by_worker.get(w, 0) + 1
                    self._c_tombstoned.inc()
                    return False
            self.apply_commit(delta, meta)
            self.num_updates += 1
            if w is not None:
                self.commits_by_worker[w] = self.commits_by_worker.get(w, 0) + 1
            if (self.checkpoint_manager is not None and self.checkpoint_every
                    and self.num_updates % self.checkpoint_every == 0):
                # capture the reference only; commits replace (never mutate)
                # the center tree, so serializing outside the lock is safe
                # and pulls/commits don't stall on the disk write
                snapshot = (self.center, self.num_updates,
                            dict(self.commits_by_worker))
        # lock-held time IS the apply latency workers contend on
        self._h_apply.observe(time.perf_counter() - t0)
        self._c_commits.inc()
        if snapshot is not None:
            center, n, by_worker = snapshot
            self.checkpoint_manager.save(
                n, center, {"num_updates": n,
                            "commits_by_worker": by_worker})
        return True

    # -- fleet lifecycle (ISSUE 9) ------------------------------------------
    def evict_worker(self, worker_id) -> int:
        """Declare worker ``worker_id``'s current incarnation dead: bump
        its generation so any late commit from it tombstones.  Returns the
        window its commits reached — the replacement's exact resume
        point."""
        w = int(worker_id)
        with self.mutex:
            self.generations[w] = self.generations.get(w, 0) + 1
            self.evictions_by_worker[w] = \
                self.evictions_by_worker.get(w, 0) + 1
            window = self.commits_by_worker.get(w, 0)
        self._c_evictions.inc()
        return window

    def register_respawn(self, worker_id) -> tuple:
        """A replacement incarnation for an evicted worker: returns
        ``(start_window, generation)`` it must run under."""
        w = int(worker_id)
        with self.mutex:
            self.respawns_by_worker[w] = self.respawns_by_worker.get(w, 0) + 1
            out = (self.commits_by_worker.get(w, 0),
                   self.generations.get(w, 0))
        self._c_respawns.inc()
        return out

    def register_join(self, worker_id) -> tuple:
        """Elastic join: a worker id joining the live run (never seen, or
        returning after a completed run).  Returns ``(start_window,
        generation)`` — the same resume contract as a respawn."""
        w = int(worker_id)
        with self.mutex:
            self.joins_by_worker[w] = self.joins_by_worker.get(w, 0) + 1
            out = (self.commits_by_worker.get(w, 0),
                   self.generations.get(w, 0))
        self._c_joins.inc()
        return out

    def fleet_snapshot(self) -> dict:  # dklint: holds=mutex
        """Plain-data fleet lifecycle state; caller holds ``mutex``."""
        return {"generations": dict(self.generations),
                "tombstoned_by_worker": dict(self.tombstoned_by_worker),
                "evictions_by_worker": dict(self.evictions_by_worker),
                "respawns_by_worker": dict(self.respawns_by_worker),
                "joins_by_worker": dict(self.joins_by_worker)}

    def restore(self, checkpoint_manager) -> bool:
        """Load the latest center checkpoint; returns True if restored."""
        if checkpoint_manager.latest_step() is None:
            return False
        with self.mutex:
            self.center, meta = checkpoint_manager.restore(self.center)
            self.num_updates = int(meta.get("num_updates", 0))
            self.commits_by_worker = {
                int(k): int(v)
                for k, v in (meta.get("commits_by_worker") or {}).items()}
        return True

    def pull(self) -> tuple:
        self._c_pulls.inc()
        with self.mutex:
            return self.center, self.num_updates

    def pull_versioned(self) -> tuple:
        """``(center, num_updates, commits_by_worker)`` captured under ONE
        mutex hold — the shard front-end's pull source (ISSUE 10): the
        per-worker commit counts are the **version vector** a sharded
        client compares across shards to detect a torn cut, so they must
        be atomic with the center they describe."""
        self._c_pulls.inc()
        with self.mutex:
            return (self.center, self.num_updates,
                    {int(k): int(v) for k, v in self.commits_by_worker.items()})

    def stats(self) -> dict:
        """Registry snapshot + ground-truth counters — the payload the
        socket front-end returns for a ``stats`` request."""
        with self.mutex:
            num_updates = self.num_updates
            by_worker = dict(self.commits_by_worker)
            fleet = self.fleet_snapshot()
        return {"stats": self.registry.snapshot(),
                "num_updates": num_updates,
                "commits_by_worker": by_worker,
                "fleet": fleet,
                "server": type(self).__name__,
                "num_workers": self.num_workers}

    def get_model(self) -> Tree:
        """Parity: reference ``ParameterServer.get_model``."""
        with self.mutex:
            return self.center


class DeltaParameterServer(ParameterServer):
    """center += delta.  Serves DOWNPOUR (delta = accumulated local update,
    i.e. θ_after − θ_pulled) and the EASGD family (delta = elastic force E).
    Parity: reference ``DeltaParameterServer``."""

    def apply_commit(self, delta, meta):  # dklint: holds=mutex
        self.center = _tree_fused_add(self.center, delta,
                                      self._commit_scale(meta))


class ADAGParameterServer(ParameterServer):
    """center += delta / num_workers — the accumulated-gradient commit
    normalized by worker count (parity: reference ``ADAGParameterServer``;
    upstream README's recommended algorithm)."""

    def apply_commit(self, delta, meta):  # dklint: holds=mutex
        self.center = _tree_fused_add(self.center, delta,
                                      self._commit_scale(meta)
                                      / self.num_workers)


class DynSGDParameterServer(ParameterServer):
    """Staleness-aware commits (parity: reference ``DynSGDParameterServer``):
    the worker reports the update counter it last pulled at; staleness =
    current counter − reported; center += delta / (staleness + 1).

    ``staleness_seen`` keeps the most recent commits' staleness (bounded —
    the unbounded list leaked on long-lived servers); the full-run
    distribution lives in the registry's merged ``ps.staleness`` histogram
    plus per-worker ``ps.staleness.worker<k>`` histograms (surfaced as
    ``trainer.ps_stats`` after training and via the ``stats`` RPC live)."""

    #: recent-commit window kept verbatim (tail inspection / tests); the
    #: histograms carry the complete, bounded-memory distribution
    staleness_keep = 4096

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        self.staleness_seen: collections.deque = collections.deque(
            maxlen=self.staleness_keep)
        self._h_staleness = self.registry.histogram("ps.staleness",
                                                    COUNT_BUCKETS)
        #: worker id -> Histogram, cached so the mutex-held apply path
        #: skips the registry's name-format + lock on every commit
        self._h_by_worker: dict = {}

    def _worker_hist(self, w: int):  # dklint: holds=mutex
        h = self._h_by_worker.get(w)
        if h is None:
            # labeled per-worker series (ISSUE 20); flattens to the
            # legacy ps.staleness.worker<k> name
            h = self._h_by_worker[w] = self.registry.histogram(
                "ps.staleness", COUNT_BUCKETS, labels={"worker": w})
        return h

    def apply_commit(self, delta, meta):  # dklint: holds=mutex
        staleness = max(0, self.num_updates - int(meta.get("last_update", 0)))
        self.staleness_seen.append(staleness)
        self._h_staleness.observe(staleness)
        w = meta.get("worker_id")
        if w is not None:
            self._worker_hist(int(w)).observe(staleness)
        # staleness- AND flag-aware (ISSUE 9): a flagged straggler's
        # commit is scaled by both rules at once
        self.center = _tree_fused_add(self.center, delta,
                                      self._commit_scale(meta)
                                      / (staleness + 1))


class SocketParameterServer(FrameServer):
    """TCP front-end: accept loop + one handler thread per worker connection
    (parity: reference ``SocketParameterServer.run``/``handle_connection``),
    on the shared ``networking.FrameServer`` frame (ISSUE 8 — the accept/
    handler/stop machinery previously mirrored by ``serve.server``).

    Protocol: each request is one framed msgpack map with an ``action`` key
    (``hello`` / ``pull`` / ``commit`` / ``stats`` / ``stop``); every
    request gets a response.  ``stats`` returns the PS registry snapshot +
    ground-truth counters without touching the center — the live-poll path
    (``PSClient.stats()``, ``scripts/obsview.py --ps``).

    ISSUE 4 fast path: ``hello`` negotiates the frame format per
    connection (v2 zero-copy scatter-gather; clients that never say hello
    stay on v1, so old workers keep working); ``pull`` answers
    ``unchanged`` — no center payload — when the client already holds the
    current center, and otherwise serves a **pre-serialized center
    payload** cached per (update counter, wire version): the center is
    encoded once per commit, not once per pull (safe because commits
    replace, never mutate, the center arrays the cached v2 frames
    reference); ``commit`` decodes ``ps.codecs`` deltas statelessly.

    ISSUE 5 observability: commits carrying a ``trace`` header get their
    ``ps.apply`` span parented on the committing worker's span (the
    cross-process timeline); commits carrying ``gap_s`` feed the
    heartbeat-gap straggler detector, whose ``ps.stragglers`` gauge and
    snapshot ride the ``stats`` reply.

    ISSUE 12 DOWN compression: a pull request carrying a ``down`` map
    (``{"codec": spec, "ref_epoch": held}``) gets the center as a
    quantized residual against the shared :class:`~.state.DownRefState`
    reference — ONE snapshot per ``down_ref_every`` counters, so the
    reference state stays O(1) per front-end however many connections
    pull.  An epoch mismatch (first pull, respawned incarnation,
    reference rolled) serves a full **resync** payload carrying the
    reference verbatim.  Encoded payloads cache under composite
    ``(ver, codec, epoch, resync)`` keys — anything that changes the
    bytes without bumping the counter is in the key, so an adaptive
    link switching codecs can never be served a stale pre-serialized
    payload.  Requests without ``down`` (v1 peers, ``comm_down="none"``)
    take the exact pre-ISSUE-12 raw path, bit-identical on the wire.

    ISSUE 15 streamed pulls: a pull request carrying a ``stream`` map on
    a stream-negotiated connection gets its reply as a ``DKW4`` chunk
    stream — the same reply document (raw or DOWN-compressed), split
    into plan-ordered leaf groups and cached as pre-serialized chunk
    payloads under a composite ``(ver, "stream", chunk_bytes, ...)`` key
    (single-flight per chunk shape), so a cold fleet pays one
    serialization per chunk.  The client decodes chunk k while chunk
    k+1 is on the wire and dispatches its window the moment the final
    chunk lands.  Requests without ``stream`` (v1 peers,
    stream-disabled clients, ``DKTPU_STREAM=0`` on either end) take the
    exact monolithic path, bit-identical on the wire.
    """

    metric_prefix = "ps"

    def __init__(self, ps: ParameterServer, host: str = "127.0.0.1",
                 port: int = 0,
                 fault_injector: Optional[Callable[[str, dict], bool]] = None,
                 max_wire_version: int = WIRE_VERSION,
                 tracer: Optional[SpanTracer] = None,
                 straggler_detector: Optional[StragglerDetector] = None,
                 down_ref_every: int = 64,
                 stream: Optional[bool] = None):
        #: front-end instruments live in the PS's registry so one snapshot
        #: covers update rules AND wire traffic
        super().__init__(ps.registry, host=host, port=port,
                         max_wire_version=max_wire_version)
        self.ps = ps
        self.fault_injector = fault_injector
        #: server-side span tracer (ISSUE 5): when set, every commit apply
        #: runs inside a ``ps.apply`` span that ADOPTS the trace context a
        #: v2 client shipped in the request (``trace_id``/``parent_span``)
        #: — the cross-process link obsview's timeline renders.  None keeps
        #: the handler span-free (no sink, no overhead).
        self.tracer = tracer
        #: heartbeat-gap straggler detector fed from the commit RPC's
        #: ``gap_s`` field; publishes the ``ps.stragglers`` gauge into the
        #: PS registry so the live ``stats`` RPC carries it
        self.stragglers = straggler_detector if straggler_detector \
            is not None else StragglerDetector(registry=ps.registry)
        #: composable center-state layer (ISSUE 10 — the state half of the
        #: PR 8 FrameServer extraction): pre-serialized pull cache,
        #: per-worker liveness stamps, codec decode — each a standalone
        #: component so a shard fleet hosts one SET per shard instead of
        #: N copies of this class's internals
        self._pull_cache = PullCache(ps.registry)
        self._liveness = LivenessTable()
        self._decode_delta = DeltaDecoder(ps.registry)
        #: DOWN-compression reference center (ISSUE 12): one shared
        #: epoch-stamped snapshot per ``down_ref_every`` counters
        self._down_ref = DownRefState(ps.registry,
                                      refresh_every=down_ref_every)
        self._h_down_encode = ps.registry.histogram(
            "ps.down.encode_seconds", TIME_BUCKETS)
        self._c_down_resyncs = ps.registry.counter("ps.down.resyncs_served")
        self._c_requests = ps.registry.counter("ps.commit_requests")
        self._c_dropped = ps.registry.counter("ps.commits_dropped")
        self._c_unchanged = ps.registry.counter("ps.pulls_unchanged")
        #: streamed-pull serving (ISSUE 15): opt-out per server or via
        #: ``DKTPU_STREAM=0``; counters pre-created so 0 is present in
        #: every snapshot, streamed or not
        self.stream = stream_enabled_env() if stream is None \
            else bool(stream)
        self._c_streams = ps.registry.counter("ps.pull.streams")
        self._c_stream_chunks = ps.registry.counter("ps.pull.stream_chunks")

    def _remote_span(self, name: str, msg: dict):
        """Server-side span adopting the requester's trace context (the
        ``trace`` header a v2 client ships on commit/pull).  No tracer —
        or an untraced request on ``serve_pull`` — means no span at all:
        v1 peers and span-free servers pay nothing."""
        if self.tracer is None:
            return contextlib.nullcontext()
        trace = msg.get("trace")
        if not isinstance(trace, dict):
            if name != "ps.apply":
                return contextlib.nullcontext()
            trace = {}
        fields = {"worker": msg.get("worker_id")}
        if trace.get("trace_id") is not None:
            fields["trace_id"] = trace["trace_id"]
        if trace.get("parent_span") is not None:
            fields["parent_span"] = trace["parent_span"]
        return self.tracer.span(name, **fields)

    def last_seen_age(self, worker_id) -> Optional[float]:
        """Seconds since this worker's last commit/pull; None if it never
        reached the server — the supervisor's liveness source."""
        return self._liveness.age(worker_id)

    def _commit_weight(self, worker_id) -> float:
        """Down-weighting multiplier for this commit (ISSUE 9 rung 1),
        every CHANGE recorded as a ``ps.commit_weight.worker<k>`` gauge —
        the restore to 1.0 when the flag clears included."""
        if worker_id is None:
            return 1.0
        w = int(worker_id)
        weight = self.stragglers.commit_weight(w)
        if self._liveness.weight_changed(w, weight):
            self.ps.registry.gauge("ps.commit_weight",
                                   labels={"worker": w}).set(weight)
        return weight

    # -- pull state seam (ISSUE 10) -----------------------------------------
    def _pull_state(self) -> tuple:
        """``(center, updates, extra_reply_fields)`` for one pull.  The
        shard front-end overrides this to add its version vector and plan
        epoch — the consistent-cut pull's raw material — without
        re-implementing the cache/unchanged protocol."""
        center, updates = self.ps.pull()
        return center, updates, {}

    def hello_reply(self, msg: dict, ver: int) -> dict:
        """A DOWN-advertising hello (ISSUE 12) is acked with the codec
        families this server can encode; v1 connections and plain hellos
        get the unchanged reply — the advertisement is the client's
        opt-in, so the default handshake stays byte-identical."""
        reply = super().hello_reply(msg, ver)
        if ver >= 2 and isinstance(msg.get("down"), dict):
            reply["down"] = {"ok": True, "codecs": list(codecs.DOWN_CODECS)}
        if ver >= 2 and self.stream and isinstance(msg.get("stream"), dict):
            reply["stream"] = {"ok": True}
        return reply

    def _pull_doc(self, msg: dict, ver: int, center, updates: int,
                  extra: dict) -> tuple:
        """``(shape_key, build)`` for one pull's reply document — the
        payload-shape suffix of the cache key plus the builder the cache
        calls on miss.  ``()`` + a raw center doc for the plain path; a
        DOWN-compressed pull (ISSUE 12) gets the ``(spec, epoch,
        resync)`` shape and the residual/resync builder.  ONE definition
        so the monolithic and streamed reply paths (ISSUE 15) can never
        disagree on the document they serialize."""
        req = msg.get("down") if ver >= 2 else None
        spec = req.get("codec") if isinstance(req, dict) else None
        if not spec or spec == "none":
            return (), lambda: {"center": center, "updates": updates,
                                **extra}
        spec = str(spec)
        epoch, ref = self._down_ref.for_pull(center, updates)
        resync = req.get("ref_epoch") is None \
            or int(req["ref_epoch"]) != epoch
        if resync:
            # counted per REQUEST (a cached resync payload still resyncs
            # the connection it is served to), not per cache build
            self._c_down_resyncs.inc()

        def build() -> dict:
            t0 = time.perf_counter()
            residual = codecs.encode_ref_delta(center, ref, spec)
            enc = codecs.tree_payload_bytes(residual)
            down = {"codec": spec, "ref_epoch": epoch, "residual": residual}
            if resync:
                # the peer holds no (or a stale) reference: ship it
                # verbatim next to the residual so this pull decodes
                # exactly and the connection is synced for the next one
                down["reference"] = ref
                enc += codecs.tree_payload_bytes(ref)
            codecs.count_codec_bytes(self.ps.registry,
                                     codecs.tree_payload_bytes(center), enc,
                                     prefix="ps.down")
            self._h_down_encode.observe(time.perf_counter() - t0)
            return {"down": down, "updates": updates, **extra}

        # composite key (ISSUE 12): every input to the serialized bytes
        # besides the counter — codec, reference epoch, resync shape —
        # so a codec-state change without a counter bump can never be
        # served a stale pre-serialized payload
        return (spec, epoch, resync), build

    def _pull_payloads(self, msg: dict, ver: int, center, updates: int,
                       extra: dict) -> tuple:
        """``(parts_or_payload, streamed)`` for one fresh pull — the
        streamed chunk list when this request negotiated + asked for
        streaming (ISSUE 15), else the monolithic pre-serialized payload
        (bit-identical to the pre-streaming wire)."""
        shape, build = self._pull_doc(msg, ver, center, updates, extra)
        req = msg.get("stream") if ver >= 2 and self.stream else None
        if isinstance(req, dict):
            cb = max(MIN_STREAM_CHUNK_BYTES,
                     int(req.get("chunk_bytes") or STREAM_CHUNK_BYTES))

            def build_parts() -> tuple:
                doc = build()
                down = doc.get("down") or {}
                return (pack_stream(doc, cb, version=ver),
                        doc.get("center", down.get("reference")))

            parts = self._pull_cache.payload_parts(
                (ver, "stream", cb, *shape), updates, build_parts,
                owner=self.ps)
            self._c_streams.inc()
            self._c_stream_chunks.inc(len(parts) - 1)
            return parts, True
        key = (ver, *shape) if shape else ver
        return self._pull_cache.payload(key, updates, build,
                                        owner=self.ps), False

    def handle_request(self, action, msg: dict, ver: int,
                       conn: socket.socket):
        """PS protocol body on the shared frame (``hello``/``stop``/
        errors live in ``FrameServer``)."""
        if action == "pull":
            with self._remote_span("ps.serve_pull", msg):
                self._liveness.touch(msg.get("worker_id"))
                have = msg.get("have")
                want = msg.get("min_updates")
                if want is not None:
                    # consistent-cut retry hint (ISSUE 10): the puller
                    # already knows the fleet has reached ``want``
                    # updates, so briefly wait for the in-flight applies
                    # to land HERE rather than shipping a slice the
                    # client will discard as torn and re-request
                    deadline = time.perf_counter() + 0.05
                    while (self.ps.num_updates < int(want)
                           and self._running.is_set()
                           and time.perf_counter() < deadline):
                        time.sleep(0.0005)
                center, updates, extra = self._pull_state()
                if have is not None and int(have) == updates:
                    self._c_unchanged.inc()
                    return {"unchanged": True, "updates": updates, **extra}
                payload, streamed = self._pull_payloads(msg, ver, center,
                                                        updates, extra)
                down_counter = f"{self.metric_prefix}.wire.bytes_down"
                if streamed:
                    send_stream(conn, payload, registry=self.ps.registry,
                                count_as=down_counter)
                else:
                    send_packed(conn, payload, registry=self.ps.registry,
                                count_as=down_counter)
                return REPLY_SENT
        if action == "commit":
            # every commit REQUEST counts before any outcome branches, so
            # requests == applied + dropped + tombstoned always holds
            self._c_requests.inc()
            self._liveness.touch(msg.get("worker_id"))
            # liveness first: a dropped commit is still a heartbeat — the
            # fault injector models a lost UPDATE, not a dead worker
            if msg.get("gap_s") is not None:
                self.stragglers.record(msg.get("worker_id"),
                                       msg.get("gap_s"))
            if msg.get("link_rtt_s") is not None:
                # per-link RTT EWMA shipped next to the heartbeat gap
                # (ISSUE 15): the link-quality half of the straggler
                # picture — a stretched gap whose link stretched equally
                # is wire-degraded, not compute-stuck
                self.stragglers.record_link(msg.get("worker_id"),
                                            msg.get("link_rtt_s"),
                                            msg.get("link_downshifts"))
            dropped = bool(self.fault_injector and
                           self.fault_injector("commit", msg))
            applied = True
            if not dropped:
                weight = self._commit_weight(msg.get("worker_id"))
                if weight != 1.0:
                    msg["commit_weight"] = weight
                delta = self._decode_delta(msg)
                with self._remote_span("ps.apply", msg):
                    applied = self.ps.handle_commit(delta, msg)
            else:
                self._c_dropped.inc()
            reply = {"ok": True, "dropped": dropped}
            if not applied:
                # stale generation: tell the zombie it was evicted so it
                # can wind down instead of burning its slice forever
                reply["tombstoned"] = True
                reply["evicted"] = True
            return reply
        if action == "stats":
            reply = self.ps.stats()
            reply["stragglers"] = self.stragglers.snapshot()
            reply.setdefault("fleet", {})["last_seen_age_s"] = \
                self._liveness.ages()
            return reply
        return None

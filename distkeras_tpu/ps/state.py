"""Composable center-state components for PS front-ends (ISSUE 10).

PR 8's ``FrameServer`` extraction gave both TCP services one front-end
frame; this module is the matching **state half**: the pieces
``SocketParameterServer`` used to carry inline — the pre-serialized pull
cache, the per-worker liveness table, and the codec decode path — as
standalone classes, so a fleet of shard front-ends composes N of each
(one per shard, each with its own lock and registry) instead of
N copies of a 500-line server multiplying every concern.

* :class:`PullCache` — pre-serialized pull replies keyed by **payload
  shape** — ``(wire version, DOWN codec, ref-epoch, resync)`` — built
  once per commit and served to every puller, with the never-regress
  rule (a racing handler must not replace a newer center with an older
  snapshot).  The composite key closes the ISSUE 12 staleness hole: a
  codec-state change WITHOUT a counter bump (an adaptive link switching
  codec, a reference epoch rolling) lands on a different key and can
  never be served a stale pre-serialized payload.  The cache is the
  **publish point** of the lock-free pull-snapshot contract: once a
  center tree's buffers are handed to a cached v2 frame, commits must
  replace — never mutate — those arrays.  :func:`set_publish_hook` lets
  dklint's runtime racecheck observe every publish and flag
  write-after-publish violations (ISSUE 10 satellite).
* :class:`DownRefState` — the DOWN-compression **reference center**
  (ISSUE 12): ONE shared snapshot per K counters (not one per
  connection — a sharded fleet's reference state stays O(shards), and
  holding a center tree is free because commits replace, never mutate,
  its arrays), epoch-stamped so a peer holding a stale or absent
  reference is detected by epoch comparison and resynced with a full
  reference payload.
* :class:`LivenessTable` — monotonic last-seen stamps per worker (commit
  AND pull traffic both count) plus the last commit-weight gauge value,
  the supervisor's liveness source.
* :class:`DeltaDecoder` — stateless ``ps.codecs`` decode with the
  latency/byte accounting, per front-end so a shard's codec traffic is
  its own.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Optional

from ..obs import TIME_BUCKETS
from . import codecs
from .networking import pack_msg

# ---------------------------------------------------------------------------
# publish-hook seam (dklint racecheck's write-after-publish detector)
# ---------------------------------------------------------------------------

#: called as ``hook(owner, center_tree)`` every time a center tree's
#: buffers are handed to the pull cache (``owner`` identifies the
#: ParameterServer whose state was published).  None (the default) costs
#: one global read per cache build.
_publish_hook: Optional[Callable[[Any, Any], None]] = None


def set_publish_hook(hook):
    """Install (or clear, with None) the pull-cache publish observer;
    returns the previous hook so racecheck can nest/restore."""
    global _publish_hook
    prev = _publish_hook
    _publish_hook = hook
    return prev


class PullCache:
    """Pre-serialized pull replies: payload-shape key -> ``(updates,
    payload)``.

    ``key`` is any hashable describing every input to the serialized
    bytes BESIDES the update counter — the wire version alone for raw
    pulls, ``(ver, codec, ref_epoch, resync)`` for DOWN-compressed ones
    (ISSUE 12: anything that changes the payload without bumping the
    counter MUST be in the key, or a stale pre-serialized payload gets
    served).  The payload is encoded OUTSIDE the cache lock so a slow
    big-model serialization never serializes concurrent pulls of an
    already-cached center; the never-regress rule keeps a racing handler
    from replacing a NEWER cached center with an older snapshot (which
    would hand a committed worker a pre-commit center on its next pull).
    ISSUE 15: a STREAMED pull's chunk payloads cache the same way —
    :meth:`payload_parts` stores the whole prologue+chunks list under
    one composite key (chunk bound included), single-flight across the
    shape's chunks, so a cold fleet pays one serialization per chunk.
    """

    def __init__(self, registry, prefix: str = "ps"):
        self._cache: dict = {}
        self._lock = threading.Lock()
        self._c_hits = registry.counter(f"{prefix}.pull_cache_hits")

    def payload(self, key, updates: int, doc_builder: Callable[[], dict],
                owner: Any = None):
        """The cached ``pack_msg`` payload for this (counter, payload
        shape), building (and publishing) it on miss.  ``doc_builder``
        returns the reply document — called only when the cache misses,
        so versioned extras (a shard's version vector) are captured
        exactly once per counter.

        Builds are **single-flight per key**: the first miss claims the
        key (an Event placeholder) and encodes outside the lock; racing
        pullers of the same (key, counter) wait on the claim and serve
        the finished payload as a hit — a cold fleet pays ONE multi-MB
        serialization per payload shape, not one per puller.  Builds for
        DIFFERENT keys still overlap."""
        ver = key[0] if isinstance(key, tuple) else key

        def build():
            doc = doc_builder()
            down = doc.get("down") or {}
            return (pack_msg(doc, version=ver),
                    doc.get("center", down.get("reference")))

        return self._cached(key, updates, build, owner)

    def payload_parts(self, key, updates: int,
                      parts_builder: Callable[[], tuple],
                      owner: Any = None):
        """Like :meth:`payload` but for a STREAMED pull reply (ISSUE 15):
        the cached value is the ordered LIST of packed payloads —
        prologue + one per chunk (``networking.pack_stream``'s output) —
        under ONE composite key, so the single-flight claim covers every
        chunk of the shape at once: a cold fleet pays one serialization
        per chunk, never one per puller per chunk.  ``parts_builder``
        returns ``(packed_parts, publish_tree)`` — the chunk payloads
        alias the center's buffers, so the publish contract is the same
        as :meth:`payload`'s."""
        return self._cached(key, updates, parts_builder, owner)

    def _cached(self, key, updates: int, build: Callable[[], tuple],
                owner: Any):
        """The single-flight / never-regress cache body both payload
        shapes share; ``build()`` returns ``(value, publish_tree)``."""
        my_evt = None
        while True:
            with self._lock:
                ent = self._cache.get(key)
                if ent is not None and ent[0] == updates and \
                        not isinstance(ent[1], threading.Event):
                    self._c_hits.inc()
                    return ent[1]
                if ent is not None and ent[0] == updates:
                    waiter = ent[1]  # same counter mid-build: wait
                else:
                    if ent is None or updates >= ent[0]:
                        # claim the build (never-regress holds: the
                        # placeholder carries OUR counter)
                        my_evt = threading.Event()
                        self._cache[key] = (updates, my_evt)
                    # else: an entry NEWER than this capture exists (a
                    # commit raced the pull) — build this handler's own
                    # snapshot uncached, claiming would regress
                    break
            # the timeout is a liveness backstop only (a builder thread
            # killed uncleanly); the loop re-reads either way
            waiter.wait(timeout=30.0)
        try:
            payload, publish_tree = build()
        except BaseException:
            if my_evt is not None:
                with self._lock:
                    cur = self._cache.get(key)
                    if cur is not None and cur[1] is my_evt:
                        del self._cache[key]  # waiters re-claim, rebuild
                    my_evt.set()
            raise
        hook = _publish_hook
        if hook is not None:
            # the doc's center arrays are now referenced by wire buffers:
            # this is the publish instant the racecheck contract guards.
            # DOWN docs publish their reference tree instead — the one
            # center-owned buffer set a resync payload shares.
            hook(owner, publish_tree)
        with self._lock:
            cur = self._cache.get(key)
            if cur is None or updates >= cur[0] or cur[1] is my_evt:
                self._cache[key] = (updates, payload)
                # prune entries serialized at OLDER counters (stale
                # wire versions, rolled ref-epochs, retired codecs):
                # they would miss and rebuild on their next pull anyway,
                # and each holds a full center payload — without this
                # the ISSUE 12 composite keys grow the cache per epoch
                # roll instead of per live payload shape.  In-flight
                # claims (Events) are left to finish their own insert.
                stale = [k for k, ent in self._cache.items()
                         if ent[0] < updates
                         and not isinstance(ent[1], threading.Event)]
                for k in stale:
                    del self._cache[k]
            if my_evt is not None:
                # wake OUR waiters under the same hold that made the
                # payload (or this claim's removal) visible — a woken
                # racer can never re-read the still-pending placeholder
                my_evt.set()
        return payload


class DownRefState:
    """The DOWN-compression reference center (ISSUE 12).

    One shared snapshot per ``refresh_every`` counters: rolling the
    reference is O(1) — commits replace (never mutate) center arrays, so
    "snapshot" means holding the tree — and every peer decodes against
    the SAME reference, identified by a monotonically increasing
    **epoch**.  A pull request declares the epoch its connection holds;
    a mismatch (first pull, respawned incarnation, epoch rolled, server
    restarted) serves a **resync** payload carrying the reference
    verbatim next to the residual, so a stale reference can never decode
    garbage — the epoch comparison catches it first.
    """

    def __init__(self, registry, refresh_every: int = 64):
        if int(refresh_every) < 1:
            raise ValueError(f"down_ref_every must be >= 1, "
                             f"got {refresh_every}")
        self.refresh_every = int(refresh_every)
        self._epoch = 0
        self._counter = -1
        self._tree = None
        self._lock = threading.Lock()
        self._g_epoch = registry.gauge("ps.down.ref_epoch")

    def for_pull(self, center, updates: int) -> tuple:
        """``(epoch, reference_tree)`` for a pull serving ``center`` at
        counter ``updates`` — rolling the reference to THIS (center,
        counter) capture when none exists yet or the current one is
        ``refresh_every`` counters old (residual magnitude, and with it
        quantization error, grows with reference age)."""
        with self._lock:
            if self._tree is None or \
                    updates - self._counter >= self.refresh_every:
                self._epoch += 1
                self._counter = int(updates)
                self._tree = center
                self._g_epoch.set(self._epoch)
            return self._epoch, self._tree


class LivenessTable:
    """Per-worker liveness stamps + commit-weight memo, every touch under
    one lock (written by handler threads, read by the supervisor)."""

    def __init__(self):
        self._last_seen: dict = {}
        self._weights: dict = {}
        self._lock = threading.Lock()

    def touch(self, worker_id) -> None:
        """Refresh this worker's liveness stamp (commit AND pull traffic
        both count: a worker blocked in compute still pulled recently;
        one truly wedged — SIGSTOP, dead socket — goes silent on both)."""
        if worker_id is None:
            return
        now = time.monotonic()
        with self._lock:
            self._last_seen[int(worker_id)] = now

    def age(self, worker_id) -> Optional[float]:
        """Seconds since this worker's last commit/pull; None if it never
        reached the server — the supervisor's liveness source."""
        with self._lock:
            t = self._last_seen.get(int(worker_id))
        return None if t is None else time.monotonic() - t

    def ages(self) -> dict:
        """{worker: seconds since last seen} — the ``stats`` reply's
        fleet-liveness section."""
        now = time.monotonic()
        with self._lock:
            seen = dict(self._last_seen)
        return {w: now - t for w, t in seen.items()}

    def weight_changed(self, worker_id: int, weight: float) -> bool:
        """Record the latest commit weight; True when it differs from the
        last one seen (the gauge-update edge)."""
        with self._lock:
            changed = self._weights.get(worker_id) != weight
            self._weights[worker_id] = weight
        return changed


class DeltaDecoder:
    """Stateless commit-delta decode (``ps.codecs`` stubs) with the
    latency + byte accounting in the owning front-end's registry."""

    def __init__(self, registry):
        self.registry = registry
        self._h_decode = registry.histogram("ps.codec.decode_seconds",
                                            TIME_BUCKETS)

    def __call__(self, msg: dict):
        delta = msg.get("delta")
        if msg.get("codec") in (None, "none"):
            return delta
        t0 = time.perf_counter()
        enc_bytes = codecs.tree_payload_bytes(delta)
        delta = codecs.decode_tree(delta)
        codecs.count_codec_bytes(self.registry,
                                 codecs.tree_payload_bytes(delta), enc_bytes)
        self._h_decode.observe(time.perf_counter() - t0)
        return delta

"""Composable center-state components for PS front-ends (ISSUE 10).

PR 8's ``FrameServer`` extraction gave both TCP services one front-end
frame; this module is the matching **state half**: the pieces
``SocketParameterServer`` used to carry inline — the pre-serialized pull
cache, the per-worker liveness table, and the codec decode path — as
standalone classes, so a fleet of shard front-ends composes N of each
(one per shard, each with its own lock and registry) instead of
N copies of a 500-line server multiplying every concern.

* :class:`PullCache` — pre-serialized pull replies keyed by wire version,
  built once per commit and served to every puller, with the
  never-regress rule (a racing handler must not replace a newer center
  with an older snapshot).  The cache is the **publish point** of the
  lock-free pull-snapshot contract: once a center tree's buffers are
  handed to a cached v2 frame, commits must replace — never mutate —
  those arrays.  :func:`set_publish_hook` lets dklint's runtime
  racecheck observe every publish and flag write-after-publish
  violations (ISSUE 10 satellite).
* :class:`LivenessTable` — monotonic last-seen stamps per worker (commit
  AND pull traffic both count) plus the last commit-weight gauge value,
  the supervisor's liveness source.
* :class:`DeltaDecoder` — stateless ``ps.codecs`` decode with the
  latency/byte accounting, per front-end so a shard's codec traffic is
  its own.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Optional

from ..obs import TIME_BUCKETS
from . import codecs
from .networking import pack_msg

# ---------------------------------------------------------------------------
# publish-hook seam (dklint racecheck's write-after-publish detector)
# ---------------------------------------------------------------------------

#: called as ``hook(owner, center_tree)`` every time a center tree's
#: buffers are handed to the pull cache (``owner`` identifies the
#: ParameterServer whose state was published).  None (the default) costs
#: one global read per cache build.
_publish_hook: Optional[Callable[[Any, Any], None]] = None


def set_publish_hook(hook):
    """Install (or clear, with None) the pull-cache publish observer;
    returns the previous hook so racecheck can nest/restore."""
    global _publish_hook
    prev = _publish_hook
    _publish_hook = hook
    return prev


class PullCache:
    """Pre-serialized pull replies: wire version -> ``(updates, payload)``.

    The payload is encoded OUTSIDE the cache lock so a slow big-model
    serialization never serializes concurrent pulls of an already-cached
    center; the never-regress rule keeps a racing handler from replacing
    a NEWER cached center with an older snapshot (which would hand a
    committed worker a pre-commit center on its next pull).
    """

    def __init__(self, registry, prefix: str = "ps"):
        self._cache: dict = {}
        self._lock = threading.Lock()
        self._c_hits = registry.counter(f"{prefix}.pull_cache_hits")

    def payload(self, ver: int, updates: int, doc_builder: Callable[[], dict],
                owner: Any = None):
        """The cached ``pack_msg`` payload for this (counter, wire
        version), building (and publishing) it on miss.  ``doc_builder``
        returns the reply document — called only when the cache misses,
        so versioned extras (a shard's version vector) are captured
        exactly once per counter."""
        with self._lock:
            ent = self._cache.get(ver)
            if ent is not None and ent[0] == updates:
                self._c_hits.inc()
                return ent[1]
        doc = doc_builder()
        payload = pack_msg(doc, version=ver)
        hook = _publish_hook
        if hook is not None:
            # the doc's center arrays are now referenced by wire buffers:
            # this is the publish instant the racecheck contract guards
            hook(owner, doc.get("center"))
        with self._lock:
            cur = self._cache.get(ver)
            if cur is None or updates >= cur[0]:
                self._cache[ver] = (updates, payload)
        return payload


class LivenessTable:
    """Per-worker liveness stamps + commit-weight memo, every touch under
    one lock (written by handler threads, read by the supervisor)."""

    def __init__(self):
        self._last_seen: dict = {}
        self._weights: dict = {}
        self._lock = threading.Lock()

    def touch(self, worker_id) -> None:
        """Refresh this worker's liveness stamp (commit AND pull traffic
        both count: a worker blocked in compute still pulled recently;
        one truly wedged — SIGSTOP, dead socket — goes silent on both)."""
        if worker_id is None:
            return
        now = time.monotonic()
        with self._lock:
            self._last_seen[int(worker_id)] = now

    def age(self, worker_id) -> Optional[float]:
        """Seconds since this worker's last commit/pull; None if it never
        reached the server — the supervisor's liveness source."""
        with self._lock:
            t = self._last_seen.get(int(worker_id))
        return None if t is None else time.monotonic() - t

    def ages(self) -> dict:
        """{worker: seconds since last seen} — the ``stats`` reply's
        fleet-liveness section."""
        now = time.monotonic()
        with self._lock:
            seen = dict(self._last_seen)
        return {w: now - t for w, t in seen.items()}

    def weight_changed(self, worker_id: int, weight: float) -> bool:
        """Record the latest commit weight; True when it differs from the
        last one seen (the gauge-update edge)."""
        with self._lock:
            changed = self._weights.get(worker_id) != weight
            self._weights[worker_id] = weight
        return changed


class DeltaDecoder:
    """Stateless commit-delta decode (``ps.codecs`` stubs) with the
    latency + byte accounting in the owning front-end's registry."""

    def __init__(self, registry):
        self.registry = registry
        self._h_decode = registry.histogram("ps.codec.decode_seconds",
                                            TIME_BUCKETS)

    def __call__(self, msg: dict):
        delta = msg.get("delta")
        if msg.get("codec") in (None, "none"):
            return delta
        t0 = time.perf_counter()
        enc_bytes = codecs.tree_payload_bytes(delta)
        delta = codecs.decode_tree(delta)
        codecs.count_codec_bytes(self.registry,
                                 codecs.tree_payload_bytes(delta), enc_bytes)
        self._h_decode.observe(time.perf_counter() - t0)
        return delta

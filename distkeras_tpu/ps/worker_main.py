"""OS-process async worker: ``python -m distkeras_tpu.ps.worker_main SPEC``.

The reference's workers are separate OS processes on separate machines
(Spark executor tasks shipped via ``rdd.mapPartitionsWithIndex`` — SURVEY.md
§3.1 boundary #1).  This module is that process: it rebuilds the model from
a spec file, loads its partition, connects to the parameter server over TCP
(boundary #2) and runs the epochs × windows pull/commit loop, then writes
its loss history to the output file.

The spec is a msgpack tree (``utils.serde``):

    {"model_blob": <serialize_model bytes>,
     "worker_optimizer": str, "loss": str, "learning_rate": float,
     "compute_dtype": str|None, "mode": "pull_commit"|"staleness"|"elastic",
     "comm_codec": str (``ps.codecs`` spec, default "none"),
     "comm_down": str (DOWN pull-compression spec — "none"/"int8"/"bf16"/
     "topk<frac>"/"adaptive", default "none"; ISSUE 12),
     "ps_shm": bool (offer the same-host shared-memory transport in the
     hello — co-located workers skip TCP; default False),
     "pull_overlap": bool (dispatch-ahead pulls — issue window k+1's
     pull while window k's device step runs, hiding the center transfer
     behind compute; default False, ISSUE 15),
     "alpha": float, "worker_id": int, "host": str, "port": int,
     "num_epoch": int, "seed": int, "data_npz": path, "out_npz": path,
     "metrics_jsonl": path (optional — this process's own telemetry
     stream: heartbeats + ``ps.commit``/``ps.pull`` spans under trace id
     ``w<worker_id>``; the runner folds it back into the trainer's sink
     so ``obsview --export-trace`` links BOTH halves of every wire span,
     ISSUE 6),
     "telemetry_s": float|None (push-telemetry cadence — ship registry
     ``snapshot_delta`` frames to the PS aggregator every that many
     seconds over the existing connection; default None = off,
     ISSUE 20)}

Used by ``ps.runner.run_async_training`` when the trainer asks for
``async_workers="processes"``; also runnable by hand for manual clusters
(one spec per host, all pointing at the same PS address).
"""

from __future__ import annotations

import os
import sys
import traceback

import numpy as np

# Honor the platform the spawning runner chose for worker processes.  The
# env var alone is not enough on machines with an interpreter startup hook
# that re-points JAX_PLATFORMS at the accelerator (e.g. the axon tunnel):
# jax.config.update before first backend use is the reliable override.
_plat = os.environ.get("DKTPU_WORKER_PLATFORM") or os.environ.get(
    "JAX_PLATFORMS")
if _plat:
    import jax
    jax.config.update("jax_platforms", _plat)


def run_spec(spec_path: str) -> None:
    from ..parallel.sync import make_window_fn
    from ..trainers import Trainer
    from ..utils import serde
    from .runner import _WORKER_CLASSES

    with open(spec_path, "rb") as f:
        spec = serde.tree_from_bytes(f.read())

    model, center = serde.deserialize_model(spec["model_blob"])
    # borrow the Trainer's loss/optimizer resolution (probs-variant
    # detection included) so process workers train the same math as threads
    shim = Trainer(model, spec["worker_optimizer"], spec["loss"],
                   learning_rate=spec["learning_rate"],
                   compute_dtype=spec.get("compute_dtype"),
                   remat=bool(spec.get("remat", False)),
                   aux_weight=float(spec.get("aux_weight", 0.0)))
    loss_fn, optimizer = shim._resolve()
    window_fn = make_window_fn(model, loss_fn, optimizer,
                               compute_dtype=shim.compute_dtype,
                               remat=shim.remat,
                               aux_weight=shim.aux_weight)

    import jax
    worker_cls = _WORKER_CLASSES[spec["mode"]]
    kw = {"alpha": spec["alpha"]} if spec["mode"] == "elastic" else {}
    # this process's own telemetry stream (ISSUE 6): the worker's tracer
    # pins trace id ``w<worker_id>`` on its thread, so the commit/pull
    # spans recorded HERE carry the same identity the server's adopted
    # apply spans reference in the parent's stream — the runner merges
    # the two halves after join
    metrics = None
    if spec.get("metrics_jsonl"):
        from ..utils.metrics import MetricsLogger
        metrics = MetricsLogger(spec["metrics_jsonl"])
    # a LIST of ports is a shard fleet (ISSUE 10): the worker builds a
    # ShardedPSClient and fans its windows across every shard
    port = spec["port"]
    port = [int(p) for p in port] if isinstance(port, (list, tuple)) \
        else int(port)
    worker = worker_cls(
        int(spec["worker_id"]), window_fn, center,
        optimizer.init(center["params"]),
        jax.random.PRNGKey(int(spec["seed"])),
        spec["host"], port, int(spec["num_epoch"]),
        start_window=int(spec.get("start_window", 0)),
        comm_codec=spec.get("comm_codec", "none"), metrics=metrics,
        comm_down=spec.get("comm_down", "none"),
        shm=bool(spec.get("ps_shm", False)),
        pull_overlap=bool(spec.get("pull_overlap", False)),
        profile_memory=bool(spec.get("profile_memory", True)),
        generation=int(spec.get("gen", 0)),
        telemetry_s=spec.get("telemetry_s"), **kw)
    if "stream" in spec:
        # disk-streaming partition: this process reads ITS shards straight
        # from the (shared) dataset directory — nothing was staged for it.
        # ``data_worker`` decouples the partition index from the PS
        # identity (an elastic-joined id beyond the configured fleet
        # shares the partition ring — ISSUE 9)
        from ..data.streaming import ShardedFileDataset, worker_window_factory
        s = spec["stream"]
        factory = worker_window_factory(
            ShardedFileDataset(s["dir"]), list(s["cols"]),
            int(s["batch_size"]),
            int(spec.get("data_worker", spec["worker_id"])),
            int(s["num_workers"]), int(s["window"]), int(s["base_seed"]),
            bool(s["shuffle"]))
        worker.set_stream(factory, int(s["n_windows"]))
    else:
        with np.load(spec["data_npz"]) as d:
            worker.set_data(d["xs"], d["ys"])
    worker.run()  # synchronously in THIS process (it is the worker process)
    # write the complete epochs this attempt produced BEFORE surfacing any
    # failure: the runner merges them with the retry's epochs, so a crash
    # mid-epoch-1 doesn't lose epoch 0 (thread-placement parity)
    np.savez(spec["out_npz"],
             **{f"epoch_{e}": l for e, l in worker.epoch_losses.items()})
    if metrics is not None:
        metrics.close()
    if worker.error is not None:
        raise worker.error


def main(argv=None) -> int:
    from ..obs import emit
    argv = argv if argv is not None else sys.argv[1:]
    if len(argv) != 1:
        emit("usage: python -m distkeras_tpu.ps.worker_main SPEC", err=True)
        return 2
    try:
        run_spec(argv[0])
    except Exception:
        traceback.print_exc()
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())

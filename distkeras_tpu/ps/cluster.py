"""Async parameter-server training across a ``jax.distributed`` cluster —
the multi-HOST deployment shape (SURVEY.md §2 comm backend: host PS over
DCN; VERDICT r3 missing #3).

The reference runs its ``SocketParameterServer`` on the Spark driver and
workers on executors spread over machines.  The equivalent here: after
``parallel.multihost.initialize()`` forms the process group, process 0
hosts the TCP parameter server and EVERY process (0 included) runs one
async worker on its own devices, pulling/committing over TCP — localhost
within a host, DCN across hosts.  Same ``ps.servers`` / ``ps.workers``
machinery the single-process ``mode="async"`` path uses; this module only
adds the cross-process choreography:

    multihost.initialize(...)                 # or env-driven on a pod
    trainer = DOWNPOUR(model, num_workers=jax.process_count(), ...)
    model = run_cluster_async_training(trainer, dataset,
                                       ps_address=("host0", 7077))

Every process returns the same final model (the trained center is
broadcast from process 0); ``trainer.ps_stats`` is populated on process 0.
"""

from __future__ import annotations

from typing import Tuple

import jax
import numpy as np

from ..parallel.sync import make_window_fn
from ..utils import serde
from .runner import _WORKER_CLASSES
from .servers import SocketParameterServer


def run_cluster_async_training(trainer, dataset,
                               ps_address: Tuple[str, int],
                               fault_injector=None):
    """Drive async-PS training with one worker per ``jax.distributed``
    process and the PS on process 0.

    ``trainer``: an async-capable DistributedTrainer subclass with
    ``num_workers == jax.process_count()``.  ``dataset``: the FULL
    dataset, identical on every process (each process trains partition
    ``jax.process_index()`` — the reference's executor-gets-its-partition
    contract).  ``ps_address``: (host, port) of process 0's server,
    reachable from every process.
    """
    from jax.experimental import multihost_utils

    pid = jax.process_index()
    nproc = jax.process_count()
    if trainer.num_workers != nproc:
        raise ValueError(
            f"trainer.num_workers ({trainer.num_workers}) must equal the "
            f"cluster's process count ({nproc}): one async worker per "
            f"process")
    mode = getattr(trainer, "_async_mode", "pull_commit")
    worker_cls = _WORKER_CLASSES[mode]
    loss_fn, optimizer = trainer._resolve()
    window_fn = make_window_fn(trainer.model, loss_fn, optimizer,
                               compute_dtype=trainer.compute_dtype,
                               remat=trainer.remat,
                               aux_weight=trainer.aux_weight)

    # deterministic staging on every process; this one trains slice pid
    xs, ys, _ = trainer._stage_data(dataset, trainer.communication_window)
    center = jax.tree_util.tree_map(np.asarray,
                                    trainer.model.init(trainer.seed))

    host, port = ps_address
    server = None
    ps = None
    if pid == 0:
        ps = trainer._ps_factory()(center, num_workers=nproc)
        server = SocketParameterServer(ps, host="0.0.0.0", port=int(port),
                                       fault_injector=fault_injector)
        server.start()
    # workers must not race the server's bind
    multihost_utils.sync_global_devices("dkps_server_up")

    err = None
    try:
        kw = {}
        if worker_cls is _WORKER_CLASSES["elastic"]:
            kw["alpha"] = trainer.alpha
        worker = worker_cls(
            pid, window_fn, center,
            optimizer.init(center["params"]),
            jax.random.PRNGKey(trainer.seed + 1 + pid),
            host if pid != 0 else "127.0.0.1", int(port),
            trainer.num_epoch, metrics=trainer.metrics,
            comm_codec=getattr(trainer, "comm_codec", "none"),
            profile_memory=trainer.profile.memory, **kw)
        worker.set_data(xs[pid], ys[pid])
        worker.run()  # synchronously IN this process (it owns the devices)
        if worker.error is not None:
            err = worker.error
        else:
            trainer.history = [l for l in worker.losses]
    except Exception as e:  # noqa: BLE001 — re-raised after the barriers
        err = e
    # every process passes this barrier whether its worker succeeded or
    # not: raising before it would leave healthy processes waiting here
    # while the failed one ran ahead — mismatched barrier participation
    # deadlocks the cluster instead of surfacing the error (ADVICE r4)
    multihost_utils.sync_global_devices("dkps_workers_done")
    if server is not None:
        # barrier above guarantees every worker finished its protocol
        server.stop()
    # per-process status allgather so EVERY process raises a clear error
    # when any worker failed, not just the failed one
    fail_flags = multihost_utils.process_allgather(
        np.asarray([err is not None]))
    if err is not None:
        raise err
    if fail_flags.any():
        raise RuntimeError(
            f"async PS worker failed on process(es) "
            f"{sorted(np.nonzero(fail_flags.reshape(-1))[0].tolist())}; "
            f"see their logs for the underlying error")

    if pid == 0:
        trainer.ps_stats = {
            "num_updates": ps.num_updates,
            "commits_by_worker": dict(ps.commits_by_worker),
            "staleness_seen": list(getattr(ps, "staleness_seen", [])),
            "registry": ps.registry.snapshot()}
        # same stream contract as the single-host runner: the final
        # registry snapshot lands in process 0's JSONL for obsview
        trainer.metrics.log("ps_stats", num_updates=ps.num_updates,
                            commits_by_worker=dict(ps.commits_by_worker),
                            stats=ps.registry.snapshot())
        final = ps.get_model()
        blob = np.frombuffer(serde.tree_to_bytes(final), np.uint8)
        size = np.asarray([blob.size], np.int64)
    else:
        final = None
        size = np.asarray([0], np.int64)

    # broadcast the trained center to every process (variable-size blob:
    # size first, then the padded payload)
    size = int(multihost_utils.broadcast_one_to_all(size)[0])
    if pid == 0:
        payload = blob
    else:
        payload = np.zeros((size,), np.uint8)
    payload = multihost_utils.broadcast_one_to_all(payload)
    final = serde.tree_from_bytes(payload.tobytes())
    return trainer._finish(final)

"""Async-mode training driver — the reference's ``DistributedTrainer.train``
orchestration (start PS → ship workers → join → collect center), minus
Spark: the PS lives on localhost TCP (the same star topology), data slices
come from the partitioned ``Dataset``, and workers run as either

* **threads** (default): in-process, one device each — JAX compute releases
  the GIL so windows genuinely overlap; fast and hermetic, or
* **processes** (``async_workers="processes"``): one OS process per worker
  (``ps.worker_main``), the reference's actual deployment shape (Spark
  executor tasks, SURVEY.md §3.1 boundary #1) — full process isolation,
  commits arrive over real TCP from real processes.  On a multi-host pod
  the same spec files point workers at the coordinator's address
  (``parallel.multihost``); on this machine they run on CPU by default so
  they never fight the parent for the single TPU chip
  (``DKTPU_WORKER_PLATFORM`` overrides).
"""

from __future__ import annotations

import glob
import json
import os
import subprocess
import sys
import tempfile
import time

import jax
import numpy as np

from ..obs.spans import SpanTracer
from ..parallel.sync import make_window_fn
from ..utils import serde
from .servers import SocketParameterServer
from .workers import ElasticWorker, PullCommitWorker, StalenessWorker

_WORKER_CLASSES = {
    "pull_commit": PullCommitWorker,
    "staleness": StalenessWorker,
    "elastic": ElasticWorker,
}


class _StreamPlan:
    """Per-worker disk-streaming data plan (async counterpart of
    ``DistributedTrainer._train_sync_stream``): each worker iterates ITS
    shard partition of a ``ShardedFileDataset``; nothing is staged in RAM."""

    def __init__(self, trainer, source, shuffle: bool):
        from ..data.streaming import worker_windows_per_epoch
        self.source = source
        self.shuffle = bool(shuffle)
        self.P = trainer.num_workers
        self.bs = trainer.batch_size
        self.w = trainer.communication_window
        self.cols = [trainer.features_col, trainer.label_col]
        self.base_seed = trainer.seed
        self.n_windows = worker_windows_per_epoch(source, self.bs, self.P,
                                                  self.w)

    def factory(self, k: int):
        from ..data.streaming import worker_window_factory
        return worker_window_factory(self.source, self.cols, self.bs, k,
                                     self.P, self.w, self.base_seed,
                                     self.shuffle)


def run_async_training(trainer, dataset, fault_injector=None,
                       stream_shuffle=None):
    """Drive async-PS training for a DistributedTrainer subclass.

    The trainer supplies: model/loss/optimizer, ``num_workers``,
    ``communication_window``, epochs, the PS class (``_ps_factory``), the
    worker flavor (``_async_mode``) and the worker placement
    (``async_workers``: threads or processes).  ``dataset`` may be a
    disk-backed ``ShardedFileDataset`` — workers then stream their shard
    partitions instead of receiving staged arrays.
    """
    from ..data.streaming import ShardedFileDataset
    mode = getattr(trainer, "_async_mode", "pull_commit")
    placement = getattr(trainer, "async_workers", "threads")

    if isinstance(dataset, ShardedFileDataset):
        stream, xs, ys = _StreamPlan(trainer, dataset,
                                     bool(stream_shuffle)), None, None
    else:
        stream = None
        xs, ys, _ = trainer._stage_data(dataset,
                                        trainer.communication_window)

    center = jax.tree_util.tree_map(np.asarray,
                                    trainer.model.init(trainer.seed))
    ps_kwargs = {}
    ckpt = trainer._ckpt_manager()
    if ckpt is not None:
        # checkpoint the center roughly once per worker round of commits
        ps_kwargs = {"checkpoint_manager": ckpt,
                     "checkpoint_every": trainer.num_workers}
    ps = trainer._ps_factory()(center, num_workers=trainer.num_workers,
                               **ps_kwargs)
    num_epoch = trainer.num_epoch
    start_windows = [0] * trainer.num_workers
    if ckpt is not None and getattr(trainer, "_resume", False):
        if ps.restore(ckpt):
            # EXACT resume: one commit per communication window, so the
            # snapshot's per-worker commit count IS the global window index
            # each worker continues from — mid-epoch included (SURVEY.md
            # §5.4).  No epoch approximation from the global counter.
            start_windows = [ps.commits_by_worker.get(k, 0)
                             for k in range(trainer.num_workers)]
            center = ps.get_model()  # workers start from the restored center
    # server-side tracer shares the trainer's JSONL sink: every commit's
    # ``ps.apply`` span adopts the committing worker's trace context, so
    # the stream links server applies to the worker windows that caused
    # them (obsview's cross-process timeline, ISSUE 5); span durations
    # also land in the PS registry (``span.ps.apply.seconds``)
    server = SocketParameterServer(
        ps, fault_injector=fault_injector,
        tracer=SpanTracer(trainer.metrics, registry=ps.registry)).start()
    t_run0 = time.time()  # heartbeats at/after this instant belong to THIS run

    try:
        if placement == "processes":
            losses = _run_process_workers(trainer, ps, server, mode, center,
                                          xs, ys, num_epoch, start_windows,
                                          stream=stream)
        else:
            losses = _run_thread_workers(trainer, ps, server, mode, center,
                                         xs, ys, num_epoch, start_windows,
                                         stream=stream)
    finally:
        server.stop()

    # history: one row per epoch this run touched — (workers, steps) when
    # every worker trained that full epoch (the aligned fresh-run case),
    # else the available per-worker arrays (resumed runs may start
    # mid-epoch at per-worker offsets)
    # only THIS run's heartbeats: the records deque spans the trainer's
    # lifetime (repeated train() calls reuse epoch indices — timestamps,
    # not indices, scope a run; eviction by the deque cap just degrades
    # the affected epochs' dt to 0)
    heartbeats = [r for r in trainer.metrics.records
                  if r.get("event") == "heartbeat" and r["ts"] >= t_run0]
    for e in sorted(set().union(*[set(l) for l in losses])):
        rows = [l[e].reshape(-1) for l in losses if e in l]
        trainer.history.append(
            np.stack(rows) if len(rows) == trainer.num_workers else rows)
        # per-epoch record for the shared stream (sync paths emit these
        # from _EpochPipeline): loss from the merged rows; wall seconds
        # bounded by the epoch's heartbeat span (async epochs overlap
        # across workers — first-to-last commit is the honest window)
        ts = [r["ts"] for r in heartbeats if r.get("epoch") == e]
        dt = (max(ts) - min(ts)) if len(ts) > 1 else 0.0
        samples = sum(r.size for r in rows) * trainer.batch_size
        trainer.metrics.log(
            "epoch", trainer=type(trainer).__name__, epoch=int(e),
            mean_loss=float(np.mean(np.concatenate(rows))),
            epoch_seconds=dt,
            samples_per_sec=samples / dt if dt > 0 else 0.0)
    trainer.ps_stats = {"num_updates": ps.num_updates,
                        "commits_by_worker": dict(ps.commits_by_worker),
                        "staleness_seen": list(getattr(ps, "staleness_seen",
                                                       [])),
                        "registry": ps.registry.snapshot()}
    # final telemetry record into the run's JSONL stream: the registry
    # snapshot (staleness/apply-latency histograms, wire bytes, commit/pull
    # counters) — obsview's staleness-distribution source
    trainer.metrics.log("ps_stats", num_updates=ps.num_updates,
                        commits_by_worker=dict(ps.commits_by_worker),
                        stats=ps.registry.snapshot())
    return trainer._finish(ps.get_model())


# ---------------------------------------------------------------------------
# thread placement (in-process, one device per worker)
# ---------------------------------------------------------------------------

def _run_thread_workers(trainer, ps, server, mode, center, xs, ys, num_epoch,
                        start_windows, stream=None):
    loss_fn, optimizer = trainer._resolve()
    window_fn = make_window_fn(trainer.model, loss_fn, optimizer,
                               compute_dtype=trainer.compute_dtype,
                               remat=trainer.remat,
                               aux_weight=trainer.aux_weight)
    # cold-compile span (first worker to call pays the trace+compile; the
    # span lands in the shared JSONL stream from that worker's thread)
    window_fn = trainer._instrumented(window_fn, "async_window")
    worker_cls = _WORKER_CLASSES[mode]
    devices = jax.devices()
    workers = []
    for k in range(trainer.num_workers):
        dev = devices[k % len(devices)]
        kw = {}
        if worker_cls is ElasticWorker:
            kw["alpha"] = trainer.alpha
        variables = jax.device_put(center, dev)
        opt_state = jax.device_put(optimizer.init(center["params"]), dev)
        rng = jax.device_put(
            jax.random.PRNGKey(trainer.seed + 1 + k), dev)
        w = worker_cls(k, window_fn, variables, opt_state, rng,
                       "127.0.0.1", server.port, num_epoch,
                       device=dev, start_window=start_windows[k],
                       metrics=trainer.metrics,
                       comm_codec=getattr(trainer, "comm_codec", "none"),
                       profile_memory=trainer.profile.memory,
                       **kw)
        if stream is not None:
            w.set_stream(stream.factory(k), stream.n_windows)
        else:
            w.set_data(xs[k], ys[k])
        workers.append(w)
    for w in workers:
        w.start()
    for w in workers:
        w.join()
    # failed-task retry, the reference's implicit Spark behavior
    # (SURVEY.md §3.1: a failed executor task is rescheduled): re-run each
    # failed worker ONCE from the current center, continuing from the exact
    # window its commits reached (the PS's per-worker counter); a second
    # failure is fatal.
    merged = [w.epoch_losses for w in workers]
    for i, w in enumerate(workers):
        if w.error is None:
            continue
        fresh_center = ps.get_model()
        kw = {"alpha": trainer.alpha} if worker_cls is ElasticWorker else {}
        dev = w.device
        retry = worker_cls(
            w.worker_id, window_fn,
            jax.device_put(fresh_center, dev),
            jax.device_put(optimizer.init(fresh_center["params"]), dev),
            jax.device_put(jax.random.PRNGKey(
                trainer.seed + 101 + w.worker_id), dev),
            "127.0.0.1", server.port, num_epoch, device=dev,
            start_window=ps.commits_by_worker.get(w.worker_id, 0),
            metrics=trainer.metrics,
            comm_codec=getattr(trainer, "comm_codec", "none"),
            profile_memory=trainer.profile.memory, **kw)
        if stream is not None:
            retry.set_stream(stream.factory(w.worker_id), stream.n_windows)
        else:
            retry.set_data(xs[w.worker_id], ys[w.worker_id])
        retry.start()
        retry.join()
        if retry.error is not None:
            raise RuntimeError(
                f"async worker {w.worker_id} failed twice"
            ) from retry.error
        merged[i] = {**w.epoch_losses, **retry.epoch_losses}
    return merged


# ---------------------------------------------------------------------------
# process placement (one OS process per worker — ps.worker_main)
# ---------------------------------------------------------------------------

def _worker_env() -> dict:
    env = dict(os.environ)
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    env["PYTHONPATH"] = root + os.pathsep + env.get("PYTHONPATH", "")
    # single-accelerator machines: worker processes must not fight the
    # parent for the chip; real pods set DKTPU_WORKER_PLATFORM=tpu (one
    # worker process per host, each owning its local chips)
    env["JAX_PLATFORMS"] = os.environ.get("DKTPU_WORKER_PLATFORM", "cpu")
    env.pop("XLA_FLAGS", None)  # don't inherit the test mesh's fake devices
    return env


def _spawn(spec: dict, td: str, k: int) -> subprocess.Popen:
    spec_path = os.path.join(td, f"worker_{k}_{spec['attempt']}.spec")
    with open(spec_path, "wb") as f:
        f.write(serde.tree_to_bytes(spec))
    return subprocess.Popen(
        [sys.executable, "-m", "distkeras_tpu.ps.worker_main", spec_path],
        env=_worker_env())


def _run_process_workers(trainer, ps, server, mode, center, xs, ys,
                         num_epoch, start_windows, stream=None,
                         timeout: float = 1800.0):
    model_blob = serde.serialize_model(trainer.model, center)
    if not isinstance(trainer.worker_optimizer, str):
        # thread placement accepts optimizer OBJECTS (they stay in-process);
        # a process worker rebuilds its optimizer from the spec, so only
        # names ship — substituting a default would silently train
        # different math than the threads placement
        raise ValueError(
            "async_workers='processes' requires a string worker_optimizer "
            f"(got {type(trainer.worker_optimizer).__name__}); optimizer "
            "objects cannot be shipped to worker processes")
    if not isinstance(trainer.loss, str):
        raise ValueError(
            "async_workers='processes' requires a string loss (got "
            f"{type(trainer.loss).__name__}); loss callables cannot be "
            "shipped to worker processes")

    def make_spec(k: int, blob: bytes, seed: int, td: str, attempt: int,
                  start_window: int):
        if stream is not None:
            # streaming workers read their shard partition straight from
            # the dataset directory (shared filesystem — the reference's
            # executors read their partition from HDFS the same way)
            data_spec = {"stream": {
                "dir": stream.source.directory,
                "num_workers": stream.P, "batch_size": stream.bs,
                "window": stream.w, "n_windows": stream.n_windows,
                "cols": stream.cols, "shuffle": stream.shuffle,
                "base_seed": stream.base_seed}}
        else:
            data = os.path.join(td, f"data_{k}.npz")
            if not os.path.exists(data):
                np.savez(data, xs=xs[k], ys=ys[k])
            data_spec = {"data_npz": data}
        return {
            **data_spec,
            "model_blob": blob,
            "worker_optimizer": trainer.worker_optimizer,
            "loss": trainer.loss,
            "learning_rate": trainer.learning_rate,
            "compute_dtype": str(trainer.compute_dtype)
            if trainer.compute_dtype is not None else None,
            "remat": bool(trainer.remat),
            "aux_weight": float(trainer.aux_weight),
            "mode": mode,
            "comm_codec": getattr(trainer, "comm_codec", "none"),
            "profile_memory": bool(trainer.profile.memory),
            "alpha": float(getattr(trainer, "alpha", 0.0)),
            "worker_id": k, "host": "127.0.0.1", "port": server.port,
            "num_epoch": num_epoch, "seed": seed,
            "start_window": int(start_window),
            "out_npz": os.path.join(td, f"out_{k}_{attempt}.npz"),
            # the worker process's OWN telemetry stream (ISSUE 6):
            # heartbeats + client-side wire spans under trace id w<k>,
            # folded into the trainer's sink after join so obsview and
            # --export-trace see both halves of every cross-process span
            "metrics_jsonl": os.path.join(td,
                                          f"metrics_{k}_{attempt}.jsonl"),
            "attempt": attempt,
        }

    def read_epochs(out_npz: str) -> dict:
        with np.load(out_npz) as d:
            return {int(name.split("_", 1)[1]): d[name] for name in d.files}

    with tempfile.TemporaryDirectory() as td:
        specs = [make_spec(k, model_blob, trainer.seed + 1 + k, td, 0,
                           start_windows[k])
                 for k in range(trainer.num_workers)]
        procs = [_spawn(s, td, k) for k, s in enumerate(specs)]
        try:
            for p in procs:
                p.wait(timeout=timeout)
            losses = []
            # Spark-style single retry from the current center, continuing
            # at the exact window the dead process's commits reached
            # (thread path has the same rule)
            for k, p in enumerate(procs):
                if p.returncode == 0:
                    losses.append(read_epochs(specs[k]["out_npz"]))
                    continue
                # epochs attempt 0 completed before dying (worker_main
                # writes them even on failure) merge with the retry's —
                # same rule as the thread placement
                prior = read_epochs(specs[k]["out_npz"]) \
                    if os.path.exists(specs[k]["out_npz"]) else {}
                fresh = serde.serialize_model(trainer.model, ps.get_model())
                specs[k] = make_spec(k, fresh, trainer.seed + 101 + k, td, 1,
                                     ps.commits_by_worker.get(k, 0))
                retry = _spawn(specs[k], td, k)
                procs[k] = retry
                retry.wait(timeout=timeout)
                if retry.returncode != 0:
                    raise RuntimeError(f"async worker process {k} failed "
                                       f"twice (rc={retry.returncode})")
                losses.append({**prior,
                               **read_epochs(specs[k]["out_npz"])})
        finally:
            # a hung/failed worker must not orphan its siblings
            for p in procs:
                if p.poll() is None:
                    p.kill()
                    p.wait()
            # fold every worker process's telemetry into the trainer's
            # sink (failure paths included — the heartbeats are exactly
            # what the postmortem wants) BEFORE the tempdir vanishes
            _fold_worker_metrics(trainer, td)
    return losses


def _fold_worker_metrics(trainer, td: str) -> None:
    """Merge the worker processes' own JSONL streams (``metrics_jsonl``
    in the spec — heartbeats + client wire spans under trace id ``w<k>``)
    into the trainer's sink, original ``ts``/trace identity preserved.
    Before this fold only the SERVER half of a process worker's spans was
    recorded; with it, ``obsview`` and ``--export-trace`` link both
    halves exactly as in the threads placement (ISSUE 6)."""
    for path in sorted(glob.glob(os.path.join(td, "metrics_*.jsonl"))):
        try:
            with open(path) as f:
                lines = f.readlines()
        except OSError:
            continue  # worker died before its sink opened
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # a killed worker's torn final line
            # re-log under the original event name; the record's own
            # ``ts`` overrides the fresh stamp, so timelines stay honest
            trainer.metrics.log(rec.pop("event", "record"), **rec)

"""Async-mode training driver — the reference's ``DistributedTrainer.train``
orchestration (start PS → ship workers → join → collect center), minus
Spark: the PS lives on localhost TCP (the same star topology), data slices
come from the partitioned ``Dataset``, and workers run as either

* **threads** (default): in-process, one device each — JAX compute releases
  the GIL so windows genuinely overlap; fast and hermetic, or
* **processes** (``async_workers="processes"``): one OS process per worker
  (``ps.worker_main``), the reference's actual deployment shape (Spark
  executor tasks, SURVEY.md §3.1 boundary #1) — full process isolation,
  commits arrive over real TCP from real processes.  On a multi-host pod
  the same spec files point workers at the coordinator's address
  (``parallel.multihost``); on this machine they run on CPU by default so
  they never fight the parent for the single TPU chip
  (``DKTPU_WORKER_PLATFORM`` overrides).
"""

from __future__ import annotations

import glob
import json
import os
import subprocess
import sys
import tempfile
import threading
import time
from typing import Optional

import jax
import numpy as np

from ..obs.logging import get_logger
from ..obs.spans import SpanTracer
from ..parallel.sync import make_window_fn
from ..utils import serde
from .servers import SocketParameterServer
from .shard import ShardedParameterServer
from .workers import ElasticWorker, PullCommitWorker, StalenessWorker

_WORKER_CLASSES = {
    "pull_commit": PullCommitWorker,
    "staleness": StalenessWorker,
    "elastic": ElasticWorker,
}


# ---------------------------------------------------------------------------
# fleet supervision (ISSUE 9): detect -> evict -> respawn, DURING the run
# ---------------------------------------------------------------------------

class _ThreadHandle:
    """One thread-placement worker incarnation under supervision."""

    def __init__(self, worker, attempt: int):
        self.worker = worker
        self.worker_id = worker.worker_id
        self.generation = worker.generation
        self.start_window = worker.start_window
        self.attempt = int(attempt)
        self.started_mono = time.monotonic()

    def alive(self) -> bool:
        return self.worker.is_alive()

    def failure(self):
        return self.worker.error

    def evicted(self) -> bool:
        return self.worker.evicted

    def epoch_losses(self) -> dict:
        return self.worker.epoch_losses

    def reap(self, grace_s: float) -> None:
        self.worker.join(grace_s)

    def terminate(self) -> None:
        """Threads cannot be killed; they are daemons and die with the
        process (a tombstoned zombie exits at its next commit anyway)."""


class _ProcHandle:
    """One process-placement worker incarnation under supervision."""

    def __init__(self, worker_id: int, generation: int, start_window: int,
                 attempt: int, proc: subprocess.Popen, out_npz: str):
        self.worker_id = int(worker_id)
        self.generation = int(generation)
        self.start_window = int(start_window)
        self.attempt = int(attempt)
        self.proc = proc
        self.out_npz = out_npz
        self.started_mono = time.monotonic()

    def alive(self) -> bool:
        return self.proc.poll() is None

    def failure(self):
        rc = self.proc.poll()
        return rc if rc not in (None, 0) else None

    def evicted(self) -> bool:
        # a tombstoned worker process winds down cleanly (rc 0); the
        # supervisor already moved it out of the live set at eviction
        return False

    def epoch_losses(self) -> dict:
        if not os.path.exists(self.out_npz):
            return {}
        with np.load(self.out_npz) as d:
            return {int(name.split("_", 1)[1]): d[name] for name in d.files}

    def reap(self, grace_s: float) -> None:
        try:
            self.proc.wait(timeout=grace_s)
        except subprocess.TimeoutExpired:
            pass

    def terminate(self) -> None:
        if self.proc.poll() is None:
            self.proc.kill()
            self.proc.wait()


class FleetSupervisor:
    """Live fleet watchdog: closes the PR 5 detect-only loop (ISSUE 9).

    Watches every worker incarnation DURING the run — not after join —
    and acts on three liveness signals: incarnation death with an error
    (thread exception / nonzero process exit, which is also where
    repeated commit-RPC failures surface, since ``commit`` never
    auto-retries), and a heartbeat gap beyond the hard threshold (no
    commit OR pull reaching the PS — the SIGSTOP shape).  A bad worker is
    **evicted** (the PS bumps its commit generation, so the zombie's late
    commits tombstone) and **respawned** through the same retry
    machinery as before: from the current center, at the exact window
    its commits reached (the PS per-worker counter).  ``max_attempts``
    incarnations per worker keep the reference's Spark semantics —
    retry once, a second failure is fatal.

    :meth:`add_worker` is the same path invoked for a worker id the PS
    has never seen: **elastic join** — a mid-run worker pulls the center
    and starts committing, fully accounted (``ps.joins``).

    The supervisor runs on the caller's thread (``run()`` blocks until
    the fleet finishes); ``add_worker`` may be called concurrently from
    any thread.
    """

    def __init__(self, ps, server, spawn, *, heartbeat_hard_s: float = 30.0,
                 startup_grace_s: float = 300.0, poll_s: float = 0.05,
                 max_attempts: int = 2, timeout: Optional[float] = None,
                 metrics=None, placement: str = "threads",
                 shard_watch=None, telemetry_ingest_s: Optional[float] = 1.0):
        self.ps = ps
        self.server = server
        #: sharded-center health probe (ISSUE 10): called once per poll;
        #: raises ``ShardFleetError`` naming the dead shard (id, address,
        #: last commit counter) so the run fails loudly and immediately
        #: instead of workers spinning in reconnect backoff against a
        #: vanished listener.  None for the single-server star.
        self.shard_watch = shard_watch
        #: spawn(worker_id, start_window, generation, attempt) -> handle;
        #: the placement-specific closure (thread worker / worker process)
        self.spawn = spawn
        self.heartbeat_hard_s = float(heartbeat_hard_s)
        self.startup_grace_s = float(startup_grace_s)
        self.poll_s = float(poll_s)
        self.max_attempts = int(max_attempts)
        self.timeout = timeout
        self.metrics = metrics
        self.placement = placement
        self._lock = threading.Lock()
        self.live: dict = {}        # worker_id -> current incarnation
        self.attempts: dict = {}    # worker_id -> incarnations used
        self.finished: dict = {}    # worker_id -> [retired handles]
        self.zombies: list = []     # evicted-but-alive old incarnations
        self._handles: list = []    # every handle ever spawned (cleanup)
        self._log = get_logger("ps.fleet")
        #: self-healing latency (ISSUE 20 satellite): eviction -> the
        #: replacement's FIRST commit landing, per recovery.  The drift
        #: gate tracks it across runs; a regression here means respawn
        #: (re-serialize + interpreter start + recompile) got slower.
        reg = getattr(ps, "registry", None) or getattr(server, "registry",
                                                       None)
        # the sharded facade's registry is a read-only merged VIEW with
        # no instrument constructors — recovery timing needs a real
        # registry to write into, so sharded fleets skip the histogram
        self._h_recovery = reg.histogram("ps.recovery_seconds") \
            if reg is not None and hasattr(reg, "histogram") else None
        self._evicted_at: dict = {}   # worker_id -> eviction monotonic
        self._recovering: dict = {}   # worker_id -> (t_evict, start_window)
        #: in-process telemetry ingest cadence (ISSUE 20): thread
        #: placement shares ONE process registry across all workers, so
        #: per-worker shippers would multiply deltas — the supervisor
        #: folds the process registry into the server's aggregator
        #: instead, as one "workers" source.  None disables.
        self.telemetry_ingest_s = telemetry_ingest_s
        self._last_ingest: Optional[float] = None

    # -- spawning -----------------------------------------------------------
    def _spawn_into_live(self, k: int, start_window: int, generation: int,
                         attempt: int):
        h = self.spawn(k, start_window, generation, attempt)
        with self._lock:
            self.live[k] = h
            self.attempts[k] = self.attempts.get(k, 0) + 1
            self._handles.append(h)
        return h

    def add_initial(self, worker_id: int, start_window: int) -> None:
        """Start one of the run's configured workers (generation 0, or
        whatever the PS restored for it)."""
        with self.ps.mutex:
            gen = self.ps.generations.get(int(worker_id), 0)
        self._spawn_into_live(worker_id, start_window, gen, 0)

    def add_worker(self, worker_id: Optional[int] = None) -> int:
        """Elastic join (ISSUE 9): add a worker to the LIVE run.  With no
        id, picks the next unused one.  Returns the worker id."""
        with self._lock:
            known = set(self.live) | set(self.finished) | set(self.attempts)
            if worker_id is None:
                worker_id = max(known) + 1 if known else 0
            k = int(worker_id)
            if k in self.live:
                raise ValueError(f"worker {k} is already live")
            attempt = self.attempts.get(k, 0)
        window, gen = self.ps.register_join(k)
        self._log.info("elastic join: worker %d enters at window %d "
                       "(generation %d)", k, window, gen)
        self._event("join", k, window=window)
        self._spawn_into_live(k, window, gen, attempt)
        return k

    # -- liveness signals ---------------------------------------------------
    def _stall_reason(self, k: int, h) -> Optional[str]:
        """Non-None when incarnation ``h`` of worker ``k`` looks wedged:
        nothing from it (commit or pull) has reached the PS for longer
        than the hard threshold.  Before its first commit the startup
        grace applies instead — interpreter start + jit compile must not
        read as a stall (a respawn would just recompile and stall
        again)."""
        now = time.monotonic()
        seen = self.server.last_seen_age(k)
        since_start = now - h.started_mono
        # stamps older than this incarnation belong to its predecessor
        age = since_start if seen is None else min(seen, since_start)
        committed = self.ps.commits_by_worker.get(k, 0) > h.start_window
        limit = self.heartbeat_hard_s if committed \
            else max(self.heartbeat_hard_s, self.startup_grace_s)
        if age > limit:
            return (f"no PS traffic for {age:.1f}s "
                    f"(hard threshold {limit:.1f}s)")
        return None

    # -- evict / respawn ----------------------------------------------------
    def _event(self, kind: str, worker_id: int, **fields) -> None:
        if self.metrics is not None:
            self.metrics.log("fleet_event", kind=kind,
                             worker_id=int(worker_id), **fields)

    def _retire(self, k: int, h, reason: str) -> int:
        """Evict incarnation ``h``: bump the PS generation (its late
        commits now tombstone) and move it out of the live set.  Returns
        the window its commits reached."""
        window = self.ps.evict_worker(k)
        self._log.warning("evicting worker %d attempt %d (%s); commits "
                          "reached window %d", k, h.attempt, reason, window)
        self._event("evict", k, reason=reason, window=window)
        with self._lock:
            self._evicted_at[k] = time.monotonic()
            if self.live.get(k) is h:
                del self.live[k]
            if h.alive():
                self.zombies.append(h)   # losses collected when it dies
            else:
                self.finished.setdefault(k, []).append(h)
        return window

    def _respawn_or_raise(self, k: int, failed) -> None:
        with self._lock:
            used = self.attempts.get(k, 0)
        if used >= self.max_attempts:
            # "twice" is the historical retry-once contract wording; a
            # non-default budget or a stall-exhaustion says what really
            # happened instead of misstating count or cause
            times = "twice" if used == 2 else f"{used} times"
            err = failed.failure() if failed is not None else None
            if isinstance(err, BaseException):
                raise RuntimeError(
                    f"async worker {k} failed {times}") from err
            if err is not None:  # a worker process's exit code
                raise RuntimeError(
                    f"async worker process {k} failed {times} (rc={err})")
            raise RuntimeError(
                f"async worker {k} failed {times} (last incarnation "
                f"evicted: stalled past the heartbeat hard threshold)")
        start, gen = self.ps.register_respawn(k)
        self._log.warning("respawning worker %d (attempt %d) from the "
                          "current center at window %d, generation %d",
                          k, used, start, gen)
        self._event("respawn", k, window=start, attempt=used)
        self._spawn_into_live(k, start, gen, used)
        with self._lock:
            t0 = self._evicted_at.pop(k, None)
            if t0 is not None:
                # recovery window open: closes at the replacement's first
                # commit past its start window (the _stall_reason signal)
                self._recovering[k] = (t0, start)

    # -- the watch loop -----------------------------------------------------
    def run(self) -> dict:
        """Supervise until every live worker finishes; returns
        ``{worker_id: merged epoch_losses}`` across incarnations."""
        deadline = None if self.timeout is None \
            else time.monotonic() + float(self.timeout)
        while True:
            if self.shard_watch is not None:
                # a dead center shard is fatal for every worker at once:
                # surface it HERE, with its name, not as N workers timing
                # out in reconnect backoff (ISSUE 10 satellite; failover
                # is the ROADMAP's self-healing round-3 item)
                self.shard_watch()
            with self._lock:
                live = dict(self.live)
            if not live:
                break
            for k, h in live.items():
                with self._lock:
                    if self.live.get(k) is not h:
                        continue  # replaced by a concurrent join
                if h.alive():
                    reason = self._stall_reason(k, h)
                    if reason is not None:
                        self._retire(k, h, reason)
                        self._respawn_or_raise(k, None)
                elif h.failure() is not None:
                    self._retire(k, h, f"failed: {h.failure()!r}")
                    self._respawn_or_raise(k, h)
                else:
                    # clean exit (evicted zombies never sit in live —
                    # _retire moved them out before the replacement spawn)
                    with self._lock:
                        del self.live[k]
                        self.finished.setdefault(k, []).append(h)
            self._poll_recovery()
            self._maybe_ingest_telemetry()
            if deadline is not None and time.monotonic() > deadline:
                raise RuntimeError(
                    f"async fleet timed out after {self.timeout:.0f}s")
            time.sleep(self.poll_s)
        self._poll_recovery()   # a replacement may finish within one poll
        self._reap_zombies()
        return self._merged_losses()

    def _poll_recovery(self) -> None:
        """Close any open eviction->first-commit recovery windows."""
        if not self._recovering or self._h_recovery is None:
            return
        now = time.monotonic()
        with self._lock:
            open_windows = list(self._recovering.items())
        for k, (t0, start) in open_windows:
            if self.ps.commits_by_worker.get(k, 0) > start:
                with self._lock:
                    self._recovering.pop(k, None)
                self._h_recovery.observe(now - t0)
                self._event("recovered", k, seconds=now - t0)

    def _maybe_ingest_telemetry(self) -> None:
        """Thread placement's push substitute (ISSUE 20): fold the shared
        process registry into the server's aggregator as one source, at
        the shipper cadence, so the live fleet series exists without N
        same-registry shippers double-counting."""
        if self.telemetry_ingest_s is None or self.placement != "threads" \
                or not hasattr(self.server, "enable_telemetry"):
            return
        now = time.monotonic()
        if self._last_ingest is not None and \
                now - self._last_ingest < float(self.telemetry_ingest_s):
            return
        self._last_ingest = now
        from ..obs.registry import default_registry
        store = self.server.enable_telemetry()
        store.ingest_total("workers", default_registry().snapshot())
        if self.server.alerts is not None:
            self.server.alerts.evaluate()

    def _reap_zombies(self) -> None:
        """Give evicted-but-alive incarnations a short grace to wind down
        (a tombstoned commit exits them) and fold in whatever complete
        epochs they produced; one still wedged (SIGSTOP never lifted)
        forfeits its losses — its replacement re-trained the windows that
        mattered."""
        with self._lock:
            zombies = list(self.zombies)
        for h in zombies:
            h.reap(2.0)
            if h.alive():
                self._log.warning(
                    "evicted worker %d attempt %d still wedged at fleet "
                    "shutdown; its local losses are forfeit", h.worker_id,
                    h.attempt)
                continue
            with self._lock:
                self.finished.setdefault(h.worker_id, []).append(h)

    def _merged_losses(self) -> dict:
        out = {}
        with self._lock:
            finished = {k: list(v) for k, v in self.finished.items()}
        for k, handles in finished.items():
            d: dict = {}
            for h in sorted(handles, key=lambda h: h.attempt):
                d.update(h.epoch_losses())
            out[k] = d
        return out

    def terminate_all(self) -> None:
        """Kill every process incarnation still running (the runner's
        finally — a hung worker must not orphan the run)."""
        with self._lock:
            handles = list(self._handles)
        for h in handles:
            h.terminate()


class _StreamPlan:
    """Per-worker disk-streaming data plan (async counterpart of
    ``DistributedTrainer._train_sync_stream``): each worker iterates ITS
    shard partition of a ``ShardedFileDataset``; nothing is staged in RAM."""

    def __init__(self, trainer, source, shuffle: bool):
        from ..data.streaming import worker_windows_per_epoch
        self.source = source
        self.shuffle = bool(shuffle)
        self.P = trainer.num_workers
        self.bs = trainer.batch_size
        self.w = trainer.communication_window
        self.cols = [trainer.features_col, trainer.label_col]
        self.base_seed = trainer.seed
        self.n_windows = worker_windows_per_epoch(source, self.bs, self.P,
                                                  self.w)

    def factory(self, k: int):
        from ..data.streaming import worker_window_factory
        return worker_window_factory(self.source, self.cols, self.bs, k,
                                     self.P, self.w, self.base_seed,
                                     self.shuffle)


def run_async_training(trainer, dataset, fault_injector=None,
                       stream_shuffle=None):
    """Drive async-PS training for a DistributedTrainer subclass.

    The trainer supplies: model/loss/optimizer, ``num_workers``,
    ``communication_window``, epochs, the PS class (``_ps_factory``), the
    worker flavor (``_async_mode``) and the worker placement
    (``async_workers``: threads or processes).  ``dataset`` may be a
    disk-backed ``ShardedFileDataset`` — workers then stream their shard
    partitions instead of receiving staged arrays.
    """
    from ..data.streaming import ShardedFileDataset
    mode = getattr(trainer, "_async_mode", "pull_commit")
    placement = getattr(trainer, "async_workers", "threads")

    if isinstance(dataset, ShardedFileDataset):
        stream, xs, ys = _StreamPlan(trainer, dataset,
                                     bool(stream_shuffle)), None, None
    else:
        stream = None
        xs, ys, _ = trainer._stage_data(dataset,
                                        trainer.communication_window)

    center = jax.tree_util.tree_map(np.asarray,
                                    trainer.model.init(trainer.seed))
    ps_shards = int(getattr(trainer, "ps_shards", 1))
    ps_kwargs = {}
    ckpt = trainer._ckpt_manager()
    if ckpt is not None and ps_shards == 1:
        # checkpoint the center roughly once per worker round of commits
        ps_kwargs = {"checkpoint_manager": ckpt,
                     "checkpoint_every": trainer.num_workers}
    num_epoch = trainer.num_epoch
    start_windows = [0] * trainer.num_workers
    if ps_shards > 1:
        if ckpt is not None:
            get_logger("ps.shard").warning(
                "sharded PS (%d shards) does not checkpoint/restore the "
                "center yet (deferred with shard failover to the "
                "ROADMAP's self-healing round 3); this run is "
                "checkpoint-free", ps_shards)
        # one update-rule server + front-end PER SHARD, each with its own
        # lock, accept loop, pull cache, codec accounting and registry;
        # every shard's tracer shares the trainer's JSONL sink so apply
        # spans still link to the worker windows that caused them
        ps = ShardedParameterServer(
            center, ps_shards, trainer._ps_factory(),
            num_workers=trainer.num_workers, fault_injector=fault_injector,
            tracer_factory=lambda reg: SpanTracer(trainer.metrics,
                                                  registry=reg))
        server = ps.start()
    else:
        ps = trainer._ps_factory()(center, num_workers=trainer.num_workers,
                                   **ps_kwargs)
        if ckpt is not None and getattr(trainer, "_resume", False):
            if ps.restore(ckpt):
                # EXACT resume: one commit per communication window, so
                # the snapshot's per-worker commit count IS the global
                # window index each worker continues from — mid-epoch
                # included (SURVEY.md §5.4).  No epoch approximation from
                # the global counter.
                start_windows = [ps.commits_by_worker.get(k, 0)
                                 for k in range(trainer.num_workers)]
                center = ps.get_model()  # workers start from the restored
        # server-side tracer shares the trainer's JSONL sink: every
        # commit's ``ps.apply`` span adopts the committing worker's trace
        # context, so the stream links server applies to the worker
        # windows that caused them (obsview's cross-process timeline,
        # ISSUE 5); span durations also land in the PS registry
        server = SocketParameterServer(
            ps, fault_injector=fault_injector,
            tracer=SpanTracer(trainer.metrics,
                              registry=ps.registry)).start()
    t_run0 = time.time()  # heartbeats at/after this instant belong to THIS run

    try:
        if placement == "processes":
            losses = _run_process_workers(trainer, ps, server, mode, center,
                                          xs, ys, num_epoch, start_windows,
                                          stream=stream)
        else:
            losses = _run_thread_workers(trainer, ps, server, mode, center,
                                         xs, ys, num_epoch, start_windows,
                                         stream=stream)
    finally:
        server.stop()

    # history: one row per epoch this run touched — (workers, steps) when
    # every worker trained that full epoch (the aligned fresh-run case),
    # else the available per-worker arrays (resumed runs may start
    # mid-epoch at per-worker offsets)
    # only THIS run's heartbeats: the records deque spans the trainer's
    # lifetime (repeated train() calls reuse epoch indices — timestamps,
    # not indices, scope a run; eviction by the deque cap just degrades
    # the affected epochs' dt to 0)
    heartbeats = [r for r in trainer.metrics.records
                  if r.get("event") == "heartbeat" and r["ts"] >= t_run0]
    for e in sorted(set().union(*[set(l) for l in losses])):
        rows = [l[e].reshape(-1) for l in losses if e in l]
        trainer.history.append(
            np.stack(rows) if len(rows) == trainer.num_workers else rows)
        # per-epoch record for the shared stream (sync paths emit these
        # from _EpochPipeline): loss from the merged rows; wall seconds
        # bounded by the epoch's heartbeat span (async epochs overlap
        # across workers — first-to-last commit is the honest window)
        ts = [r["ts"] for r in heartbeats if r.get("epoch") == e]
        dt = (max(ts) - min(ts)) if len(ts) > 1 else 0.0
        samples = sum(r.size for r in rows) * trainer.batch_size
        trainer.metrics.log(
            "epoch", trainer=type(trainer).__name__, epoch=int(e),
            mean_loss=float(np.mean(np.concatenate(rows))),
            epoch_seconds=dt,
            samples_per_sec=samples / dt if dt > 0 else 0.0)
    trainer.ps_stats = {"num_updates": ps.num_updates,
                        "commits_by_worker": dict(ps.commits_by_worker),
                        "staleness_seen": list(getattr(ps, "staleness_seen",
                                                       [])),
                        "registry": ps.registry.snapshot()}
    # final telemetry record into the run's JSONL stream: the registry
    # snapshot (staleness/apply-latency histograms, wire bytes, commit/pull
    # counters) — obsview's staleness-distribution source
    trainer.metrics.log("ps_stats", num_updates=ps.num_updates,
                        commits_by_worker=dict(ps.commits_by_worker),
                        stats=ps.registry.snapshot())
    return trainer._finish(ps.get_model())


# ---------------------------------------------------------------------------
# thread placement (in-process, one device per worker)
# ---------------------------------------------------------------------------

def _endpoint(server):
    """Worker-facing PS endpoint: the single server's port, or the shard
    fleet's port LIST (workers then build a ``ShardedPSClient``)."""
    ports = getattr(server, "ports", None)
    return list(ports) if ports is not None else server.port


def _supervisor_for(trainer, ps, server, spawn, placement: str,
                    timeout: Optional[float] = None) -> FleetSupervisor:
    """Build the fleet supervisor from the trainer's knobs (ISSUE 9).
    A sharded center additionally wires its health probe in: a dead
    shard fails the run loudly (ISSUE 10)."""
    return FleetSupervisor(
        ps, server, spawn, placement=placement, timeout=timeout,
        heartbeat_hard_s=getattr(trainer, "heartbeat_hard_s", 30.0),
        startup_grace_s=getattr(trainer, "startup_grace_s", 300.0),
        metrics=trainer.metrics,
        shard_watch=getattr(server, "raise_if_unhealthy", None))


def _supervise(trainer, sup: FleetSupervisor, start_windows) -> list:
    """Start the configured fleet, watch it to completion, return the
    per-worker merged epoch losses (sorted by worker id — elastic joins
    append after the configured ids)."""
    trainer._supervisor = sup
    try:
        for k in range(trainer.num_workers):
            sup.add_initial(k, start_windows[k])
        merged = sup.run()
    finally:
        trainer._supervisor = None
    return [merged[k] for k in sorted(merged)]


def _run_thread_workers(trainer, ps, server, mode, center, xs, ys, num_epoch,
                        start_windows, stream=None):
    loss_fn, optimizer = trainer._resolve()
    window_fn = make_window_fn(trainer.model, loss_fn, optimizer,
                               compute_dtype=trainer.compute_dtype,
                               remat=trainer.remat,
                               aux_weight=trainer.aux_weight)
    # cold-compile span (first worker to call pays the trace+compile; the
    # span lands in the shared JSONL stream from that worker's thread)
    window_fn = trainer._instrumented(window_fn, "async_window")
    worker_cls = _WORKER_CLASSES[mode]
    devices = jax.devices()
    P = trainer.num_workers
    endpoint = _endpoint(server)

    def spawn(k: int, start_window: int, generation: int, attempt: int):
        """One worker incarnation: initial fleet, supervisor respawn, and
        elastic join all come through here — every incarnation starts
        from the CURRENT center (identical to the configured start for
        attempt 0: no commits have landed yet).  The retry seed rule is
        the historical one (seed+1+k, retries at +100 per attempt)."""
        dev = devices[k % len(devices)]
        kw = {"alpha": trainer.alpha} if worker_cls is ElasticWorker else {}
        fresh = ps.get_model()
        w = worker_cls(
            k, window_fn,
            jax.device_put(fresh, dev),
            jax.device_put(optimizer.init(fresh["params"]), dev),
            jax.device_put(jax.random.PRNGKey(
                trainer.seed + 1 + k + 100 * attempt), dev),
            "127.0.0.1", endpoint, num_epoch, device=dev,
            start_window=start_window, metrics=trainer.metrics,
            comm_codec=getattr(trainer, "comm_codec", "none"),
            comm_down=getattr(trainer, "comm_down", "none"),
            shm=getattr(trainer, "ps_shm", False),
            pull_overlap=getattr(trainer, "pull_overlap", False),
            profile_memory=trainer.profile.memory,
            generation=generation, **kw)
        if stream is not None:
            # elastic ids beyond the configured fleet share the partition
            # ring (worker P trains partition 0's slice alongside it)
            w.set_stream(stream.factory(k % stream.P), stream.n_windows)
        else:
            w.set_data(xs[k % P], ys[k % P])
        w.start()
        return _ThreadHandle(w, attempt)

    sup = _supervisor_for(trainer, ps, server, spawn, "threads")
    return _supervise(trainer, sup, start_windows)


# ---------------------------------------------------------------------------
# process placement (one OS process per worker — ps.worker_main)
# ---------------------------------------------------------------------------

def _worker_env() -> dict:
    env = dict(os.environ)
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    env["PYTHONPATH"] = root + os.pathsep + env.get("PYTHONPATH", "")
    # single-accelerator machines: worker processes must not fight the
    # parent for the chip; real pods set DKTPU_WORKER_PLATFORM=tpu (one
    # worker process per host, each owning its local chips)
    env["JAX_PLATFORMS"] = os.environ.get("DKTPU_WORKER_PLATFORM", "cpu")
    env.pop("XLA_FLAGS", None)  # don't inherit the test mesh's fake devices
    return env


def _spawn(spec: dict, td: str, k: int) -> subprocess.Popen:
    spec_path = os.path.join(td, f"worker_{k}_{spec['attempt']}.spec")
    with open(spec_path, "wb") as f:
        f.write(serde.tree_to_bytes(spec))
    return subprocess.Popen(
        [sys.executable, "-m", "distkeras_tpu.ps.worker_main", spec_path],
        env=_worker_env())


def _run_process_workers(trainer, ps, server, mode, center, xs, ys,
                         num_epoch, start_windows, stream=None,
                         timeout: float = 1800.0):
    model_blob = serde.serialize_model(trainer.model, center)
    if not isinstance(trainer.worker_optimizer, str):
        # thread placement accepts optimizer OBJECTS (they stay in-process);
        # a process worker rebuilds its optimizer from the spec, so only
        # names ship — substituting a default would silently train
        # different math than the threads placement
        raise ValueError(
            "async_workers='processes' requires a string worker_optimizer "
            f"(got {type(trainer.worker_optimizer).__name__}); optimizer "
            "objects cannot be shipped to worker processes")
    if not isinstance(trainer.loss, str):
        raise ValueError(
            "async_workers='processes' requires a string loss (got "
            f"{type(trainer.loss).__name__}); loss callables cannot be "
            "shipped to worker processes")

    P = trainer.num_workers

    def make_spec(k: int, blob: bytes, seed: int, td: str, attempt: int,
                  start_window: int, generation: int):
        if stream is not None:
            # streaming workers read their shard partition straight from
            # the dataset directory (shared filesystem — the reference's
            # executors read their partition from HDFS the same way);
            # elastic ids beyond the configured fleet share the ring
            data_spec = {"stream": {
                "dir": stream.source.directory,
                "num_workers": stream.P, "batch_size": stream.bs,
                "window": stream.w, "n_windows": stream.n_windows,
                "cols": stream.cols, "shuffle": stream.shuffle,
                "base_seed": stream.base_seed},
                "data_worker": k % stream.P}
        else:
            data = os.path.join(td, f"data_{k % P}.npz")
            if not os.path.exists(data):
                np.savez(data, xs=xs[k % P], ys=ys[k % P])
            data_spec = {"data_npz": data}
        return {
            **data_spec,
            "model_blob": blob,
            "worker_optimizer": trainer.worker_optimizer,
            "loss": trainer.loss,
            "learning_rate": trainer.learning_rate,
            "compute_dtype": str(trainer.compute_dtype)
            if trainer.compute_dtype is not None else None,
            "remat": bool(trainer.remat),
            "aux_weight": float(trainer.aux_weight),
            "mode": mode,
            "comm_codec": getattr(trainer, "comm_codec", "none"),
            "comm_down": getattr(trainer, "comm_down", "none"),
            "ps_shm": bool(getattr(trainer, "ps_shm", False)),
            "pull_overlap": bool(getattr(trainer, "pull_overlap", False)),
            "profile_memory": bool(trainer.profile.memory),
            "alpha": float(getattr(trainer, "alpha", 0.0)),
            "worker_id": k, "host": "127.0.0.1", "port": _endpoint(server),
            "num_epoch": num_epoch, "seed": seed,
            "start_window": int(start_window),
            "gen": int(generation),
            "out_npz": os.path.join(td, f"out_{k}_{attempt}.npz"),
            # the worker process's OWN telemetry stream (ISSUE 6):
            # heartbeats + client-side wire spans under trace id w<k>,
            # folded into the trainer's sink after join so obsview and
            # --export-trace see both halves of every cross-process span
            "metrics_jsonl": os.path.join(td,
                                          f"metrics_{k}_{attempt}.jsonl"),
            # push telemetry (ISSUE 20): each worker PROCESS ships its own
            # registry deltas to the PS aggregator — the live counterpart
            # of the post-join JSONL fold above
            "telemetry_s": getattr(trainer, "telemetry_s", 1.0),
            "attempt": attempt,
        }

    with tempfile.TemporaryDirectory() as td:
        def spawn(k: int, start_window: int, generation: int, attempt: int):
            """One worker-process incarnation (initial / respawn /
            elastic join): respawns and joins ship the CURRENT center;
            the configured fleet shares the one pre-serialized blob."""
            blob = model_blob if (attempt == 0 and ps.num_updates == 0) \
                else serde.serialize_model(trainer.model, ps.get_model())
            spec = make_spec(k, blob, trainer.seed + 1 + k + 100 * attempt,
                             td, attempt, start_window, generation)
            proc = _spawn(spec, td, k)
            return _ProcHandle(k, generation, start_window, attempt, proc,
                               spec["out_npz"])

        sup = _supervisor_for(trainer, ps, server, spawn, "processes",
                              timeout=timeout)
        try:
            losses = _supervise(trainer, sup, start_windows)
        finally:
            # a hung/failed/wedged worker must not orphan its siblings
            sup.terminate_all()
            # fold every worker process's telemetry into the trainer's
            # sink (failure paths included — the heartbeats are exactly
            # what the postmortem wants) BEFORE the tempdir vanishes.
            # Optional since ISSUE 20: a fleet on push telemetry already
            # has the live series — set fold_worker_jsonl=False to skip
            # the post-join re-read on large fleets
            if getattr(trainer, "fold_worker_jsonl", True):
                _fold_worker_metrics(trainer, td)
    return losses


def _fold_worker_metrics(trainer, td: str) -> None:
    """Merge the worker processes' own JSONL streams (``metrics_jsonl``
    in the spec — heartbeats + client wire spans under trace id ``w<k>``)
    into the trainer's sink, original ``ts``/trace identity preserved.
    Before this fold only the SERVER half of a process worker's spans was
    recorded; with it, ``obsview`` and ``--export-trace`` link both
    halves exactly as in the threads placement (ISSUE 6)."""
    for path in sorted(glob.glob(os.path.join(td, "metrics_*.jsonl"))):
        try:
            with open(path) as f:
                lines = f.readlines()
        except OSError:
            continue  # worker died before its sink opened
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # a killed worker's torn final line
            # re-log under the original event name; the record's own
            # ``ts`` overrides the fresh stamp, so timelines stay honest
            trainer.metrics.log(rec.pop("event", "record"), **rec)

"""Async-mode training driver — the reference's ``DistributedTrainer.train``
orchestration (start PS → ship workers → join → collect center), minus
Spark: workers are threads with their own devices, data slices come from
the partitioned ``Dataset``, and the PS lives on localhost TCP (the same
star topology; multi-host placement via ``jax.distributed`` puts the PS on
process 0 and workers elsewhere with identical code).
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np

from ..parallel.sync import make_window_fn
from .servers import SocketParameterServer
from .workers import ElasticWorker, PullCommitWorker, StalenessWorker

_WORKER_CLASSES = {
    "pull_commit": PullCommitWorker,
    "staleness": StalenessWorker,
    "elastic": ElasticWorker,
}


def run_async_training(trainer, dataset, fault_injector=None):
    """Drive async-PS training for a DistributedTrainer subclass.

    The trainer supplies: model/loss/optimizer, ``num_workers``,
    ``communication_window``, epochs, the PS class (``_ps_factory``) and
    the worker flavor (``_async_mode`` attribute).
    """
    loss_fn, optimizer = trainer._resolve()
    window_fn = make_window_fn(trainer.model, loss_fn, optimizer,
                               compute_dtype=trainer.compute_dtype)
    mode = getattr(trainer, "_async_mode", "pull_commit")
    worker_cls = _WORKER_CLASSES[mode]

    xs, ys, _ = trainer._stage_data(dataset, trainer.communication_window)

    center = jax.tree_util.tree_map(np.asarray,
                                    trainer.model.init(trainer.seed))
    ps_kwargs = {}
    ckpt = trainer._ckpt_manager()
    if ckpt is not None:
        # checkpoint the center roughly once per worker round of commits
        ps_kwargs = {"checkpoint_manager": ckpt,
                     "checkpoint_every": trainer.num_workers}
    ps = trainer._ps_factory()(center, num_workers=trainer.num_workers,
                               **ps_kwargs)
    num_epoch = trainer.num_epoch
    if ckpt is not None and getattr(trainer, "_resume", False):
        if ps.restore(ckpt):
            # true async training has no global epoch barrier; approximate
            # completed epochs from the commit counter (workers × windows
            # commits per epoch) and train only the remainder
            commits_per_epoch = trainer.num_workers * xs.shape[1]
            done = ps.num_updates // max(1, commits_per_epoch)
            num_epoch = max(0, trainer.num_epoch - done)
            center = ps.get_model()  # workers start from the restored center
    server = SocketParameterServer(ps, fault_injector=fault_injector).start()

    devices = jax.devices()
    workers = []
    try:
        for k in range(trainer.num_workers):
            dev = devices[k % len(devices)]
            kw = {}
            if worker_cls is ElasticWorker:
                kw["alpha"] = trainer.alpha
            variables = jax.device_put(center, dev)
            opt_state = jax.device_put(optimizer.init(center["params"]), dev)
            rng = jax.device_put(
                jax.random.PRNGKey(trainer.seed + 1 + k), dev)
            w = worker_cls(k, window_fn, variables, opt_state, rng,
                           "127.0.0.1", server.port, num_epoch,
                           device=dev, **kw)
            w.set_data(xs[k], ys[k])
            workers.append(w)
        for w in workers:
            w.start()
        for w in workers:
            w.join()
        # failed-task retry, the reference's implicit Spark behavior
        # (SURVEY.md §3.1: a failed executor task is rescheduled and its
        # partition silently re-trained): re-run each failed worker ONCE
        # from the current center; a second failure is fatal.
        for i, w in enumerate(workers):
            if w.error is None:
                continue
            fresh_center = ps.get_model()
            kw = {"alpha": trainer.alpha} if worker_cls is ElasticWorker else {}
            dev = w.device
            retry = worker_cls(
                w.worker_id, window_fn,
                jax.device_put(fresh_center, dev),
                jax.device_put(optimizer.init(fresh_center["params"]), dev),
                jax.device_put(jax.random.PRNGKey(
                    trainer.seed + 101 + w.worker_id), dev),
                "127.0.0.1", server.port, num_epoch, device=dev, **kw)
            retry.set_data(xs[w.worker_id], ys[w.worker_id])
            retry.start()
            retry.join()
            if retry.error is not None:
                raise RuntimeError(
                    f"async worker {w.worker_id} failed twice"
                ) from retry.error
            workers[i] = retry
    finally:
        server.stop()

    # history: list per epoch of (workers, steps)
    for e in range(num_epoch):
        trainer.history.append(np.stack(
            [w.losses[e].reshape(-1) for w in workers]))
    return trainer._finish(ps.get_model())

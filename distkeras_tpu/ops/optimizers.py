"""Worker optimizers.

The reference hands Keras optimizer names/objects to trainers as the
``worker_optimizer`` argument (``distkeras/trainers.py``).  We keep the
string surface and resolve to optax gradient transformations — pure pytree
update rules that live inside the jit-compiled train step.
"""

from __future__ import annotations

from typing import Union

import optax


def get_optimizer(spec: Union[str, optax.GradientTransformation],
                  learning_rate: float = 0.01) -> optax.GradientTransformation:
    """Resolve an optimizer spec.

    ``spec`` may be an optax ``GradientTransformation`` (used as-is), or one
    of the Keras-style names the reference accepts: ``sgd``, ``momentum``,
    ``nesterov``, ``adagrad``, ``adadelta``, ``rmsprop``, ``adam``.
    """
    if isinstance(spec, optax.GradientTransformation):
        return spec
    name = spec.lower()
    if name == "sgd":
        return optax.sgd(learning_rate)
    if name == "momentum":
        return optax.sgd(learning_rate, momentum=0.9)
    if name == "nesterov":
        return optax.sgd(learning_rate, momentum=0.9, nesterov=True)
    if name == "adagrad":
        return optax.adagrad(learning_rate)
    if name == "adadelta":
        return optax.adadelta(learning_rate)
    if name == "rmsprop":
        return optax.rmsprop(learning_rate)
    if name == "adam":
        return optax.adam(learning_rate)
    raise ValueError(f"unknown optimizer {spec!r}")

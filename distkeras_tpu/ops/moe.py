"""Mixture-of-Experts with expert parallelism over the ``ep`` mesh axis.

Absent from the reference (SURVEY.md §2: expert parallelism ABSENT) but
first-class here: E feed-forward experts are sharded one-group-per-device
along ``ep``; tokens are routed (switch/top-1, Fedus et al. 2021) to
their expert via ``lax.all_to_all`` — the canonical MoE collective, one
fused ICI exchange each way instead of a host-side shuffle.

Dataflow per device (inside ``shard_map``; P = ep size, E = P·E_loc):

    tokens (n_loc, d) ──router──▶ dispatch one-hot (n_loc, E, C)
      ──einsum──▶ (E, C, d) ──all_to_all──▶ (E_loc, P·C, d)
      ──expert FFN──▶ ──all_to_all back──▶ combine ▶ (n_loc, d)

Capacity: each source device sends at most C = ceil(n_loc/E ·
capacity_factor) tokens to any one expert; overflow tokens are dropped
(zero output — callers add a residual, the standard switch contract).
The whole block is differentiable (einsum dispatch + all_to_all), so it
trains under ``jax.grad`` with no custom backward.

Load-balance auxiliary loss: ``aux = E · Σ_e f_e · p_e`` (fraction of
tokens routed to e × mean router probability of e), pmean'd over the
mesh — add ``aux_weight * aux`` to the task loss to keep experts busy.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..parallel.mesh import shard_map
from ..parallel.sync import _shard_map_kw

Tree = Any


def init_moe_params(seed: int, num_experts: int, d_model: int,
                    d_hidden: int) -> Tree:
    """Router + E expert FFNs (relu MLPs).  Expert leaves carry a leading
    (E,) axis — the dim ``switch_moe_sharded`` shards over ``ep``."""
    k = jax.random.PRNGKey(seed)
    kg, k1, k2 = jax.random.split(k, 3)
    s1 = 1.0 / math.sqrt(d_model)
    s2 = 1.0 / math.sqrt(d_hidden)
    return {
        "router": {"wg": jax.random.normal(kg, (d_model, num_experts)) * s1},
        "experts": {
            "w1": jax.random.normal(k1, (num_experts, d_model, d_hidden)) * s1,
            "b1": jnp.zeros((num_experts, d_hidden)),
            "w2": jax.random.normal(k2, (num_experts, d_hidden, d_model)) * s2,
            "b2": jnp.zeros((num_experts, d_model)),
        },
    }


def _capacity(n_loc: int, num_experts: int, capacity_factor: float) -> int:
    return max(1, math.ceil(n_loc / num_experts * capacity_factor))


def switch_moe(params: Tree, x, *, axis_name: str = "ep",
               capacity_factor: float = 1.25):
    """Switch-MoE block; call INSIDE ``shard_map``.

    ``x``: (n_loc, d) local token shard.  ``params["experts"]`` leaves:
    local (E_loc, ...) expert shard; ``params["router"]["wg"]``:
    replicated (d, E).  Returns ``(out (n_loc, d), aux_loss scalar)``.
    """
    p_size = lax.axis_size(axis_name)
    wg = params["router"]["wg"]
    ex = params["experts"]
    n_loc, d = x.shape
    num_experts = wg.shape[1]
    e_loc = ex["w1"].shape[0]
    if e_loc * p_size != num_experts:
        raise ValueError(f"router knows {num_experts} experts but shards "
                         f"hold {e_loc}×{p_size}")
    cap = _capacity(n_loc, num_experts, capacity_factor)

    # -- route: top-1 expert per token, position within its send buffer --
    gates = jax.nn.softmax(x @ wg, axis=-1)            # (n_loc, E)
    expert_idx = jnp.argmax(gates, axis=-1)            # (n_loc,)
    gate = jnp.take_along_axis(gates, expert_idx[:, None], 1)[:, 0]
    # slot bookkeeping in int32: a low-precision token dtype (bf16) cannot
    # represent consecutive integers past 256, which would collide
    # capacity slots silently
    onehot_i = jax.nn.one_hot(expert_idx, num_experts, dtype=jnp.int32)
    pos = (jnp.cumsum(onehot_i, axis=0) - 1) * onehot_i  # arrival order
    keep = pos < cap
    onehot = onehot_i.astype(x.dtype)
    dispatch = onehot[..., None] * keep.astype(x.dtype)[..., None] * \
        jax.nn.one_hot(pos, cap, dtype=x.dtype)
    # (n_loc, E, C): exactly one 1 per kept token

    # -- dispatch to expert owners: one all_to_all each way -------------
    send = jnp.einsum("nec,nd->ecd", dispatch, x)      # (E, C, d)
    recv = lax.all_to_all(send, axis_name, split_axis=0, concat_axis=0,
                          tiled=True)                  # block s = src dev s
    recv = recv.reshape(p_size, e_loc, cap, d) \
        .transpose(1, 0, 2, 3).reshape(e_loc, p_size * cap, d)

    h = jax.nn.relu(jnp.einsum("egd,edh->egh", recv, ex["w1"])
                    + ex["b1"][:, None])
    y = jnp.einsum("egh,ehd->egd", h, ex["w2"]) + ex["b2"][:, None]

    back = y.reshape(e_loc, p_size, cap, d).transpose(1, 0, 2, 3) \
        .reshape(p_size * e_loc, cap, d)
    combined = lax.all_to_all(back, axis_name, split_axis=0, concat_axis=0,
                              tiled=True)              # (E, C, d) at source

    out = jnp.einsum("nec,ecd->nd", dispatch * gate[:, None, None],
                     combined)

    # -- switch load-balance loss (global: pmean over the mesh) ---------
    frac = jnp.mean(onehot, axis=0)                    # tokens per expert
    prob = jnp.mean(gates, axis=0)                     # router mass
    aux = num_experts * jnp.sum(lax.pmean(frac, axis_name)
                                * lax.pmean(prob, axis_name))
    return out, aux


def dense_moe(params: Tree, x):
    """Single-device reference formula: every token through its top-1
    expert, no capacity limit (nothing to overflow without a dispatch
    buffer).  Same math the sharded path computes for kept tokens."""
    wg = params["router"]["wg"]
    ex = params["experts"]
    gates = jax.nn.softmax(x @ wg, axis=-1)
    idx = jnp.argmax(gates, axis=-1)
    gate = jnp.take_along_axis(gates, idx[:, None], 1)[:, 0]
    h = jax.nn.relu(jnp.einsum("nd,edh->neh", x, ex["w1"]) + ex["b1"])
    y = jnp.einsum("neh,ehd->ned", h, ex["w2"]) + ex["b2"]
    picked = jnp.take_along_axis(y, idx[:, None, None], 1)[:, 0]
    onehot = jax.nn.one_hot(idx, wg.shape[1], dtype=x.dtype)
    aux = wg.shape[1] * jnp.sum(jnp.mean(onehot, 0) * jnp.mean(gates, 0))
    return gate[:, None] * picked, aux


def switch_moe_sharded(mesh: Mesh, params: Tree, x, *, axis: str = "ep",
                       capacity_factor: float = 1.25):
    """Whole-array entry point: tokens (N, d) sharded over ``mesh[axis]``,
    expert leaves sharded on their leading (E,) dim, router replicated.
    Returns ``(out (N, d), aux_loss scalar)``."""
    p_size = mesh.shape[axis]
    n_tokens = x.shape[0]
    num_experts = params["router"]["wg"].shape[1]
    if n_tokens % p_size:
        raise ValueError(f"token count {n_tokens} not divisible by the "
                         f"{axis!r} axis size {p_size}")
    if num_experts % p_size:
        raise ValueError(f"{num_experts} experts not divisible by the "
                         f"{axis!r} axis size {p_size}")
    specs = {"router": jax.tree_util.tree_map(lambda _: P(),
                                              params["router"]),
             "experts": jax.tree_util.tree_map(lambda _: P(axis),
                                               params["experts"])}
    fn = shard_map(
        partial(switch_moe, axis_name=axis,
                capacity_factor=capacity_factor),
        mesh=mesh,
        in_specs=(specs, P(axis)),
        out_specs=(P(axis), P()),
        **_shard_map_kw())
    return fn(params, x)


# ---------------------------------------------------------------------------
# layer API integration (models.layers contract)
# ---------------------------------------------------------------------------

from ..models.layers import Layer, register  # noqa: E402


@register
class MoEDense(Layer):
    """Switch-MoE feed-forward as a model layer: a drop-in for the
    transformer FF block (wrap in ``Residual`` like any FF).

    Runs the dense per-token formula (:func:`dense_moe`) — identical math
    to the ``ep``-sharded path, single-program — unless a mesh is
    attached (``layer.mesh = mesh``; find instances via
    ``model.iter_layers()``), which switches execution to
    :func:`switch_moe_sharded` over its ``ep`` axis.  The mesh is
    runtime placement, not architecture, so it is deliberately NOT part
    of the serialized config (a deserialized model runs dense until a
    mesh is re-attached).

    The mesh branch is TRACE-time state: attach it BEFORE any function
    over the model is jitted.  An already-compiled executable (e.g.
    ``ModelPredictor`` jits at construction) keeps its captured path —
    re-jit (rebuild the predictor / trainer) after switching.

    The router load-balance aux loss is written to ``state["aux_loss"]``
    each step.  By default the stock trainers optimize the task loss only
    (reference parity: its trainers have no auxiliary-loss concept); pass
    ``aux_weight=...`` to any trainer to fold the load-balance losses
    into the objective (``parallel.sync.make_local_step``) — the standard
    mitigation for router/expert collapse in long MoE runs.
    """

    def __init__(self, num_experts: int, d_hidden: Optional[int] = None,
                 capacity_factor: float = 1.25):
        self.num_experts = int(num_experts)
        self.d_hidden = d_hidden if d_hidden is None else int(d_hidden)
        self.capacity_factor = float(capacity_factor)
        self.mesh: Optional[Mesh] = None  # runtime attachment, not config

    def init(self, rng, in_shape):
        d = in_shape[-1]
        hidden = self.d_hidden if self.d_hidden is not None else 4 * d
        seed = int(jax.random.randint(rng, (), 0,
                                      jnp.iinfo(jnp.int32).max))
        params = init_moe_params(seed, self.num_experts, d, hidden)
        return params, {"aux_loss": jnp.zeros(())}, in_shape

    def apply(self, params, state, x, *, train=False, rng=None):
        tokens = x.reshape(-1, x.shape[-1])
        if self.mesh is not None:
            out, aux = switch_moe_sharded(
                self.mesh, params, tokens,
                capacity_factor=self.capacity_factor)
        else:
            out, aux = dense_moe(params, tokens)
        return out.reshape(x.shape), {"aux_loss": aux.astype(jnp.float32)}

    def get_config(self):
        return {"num_experts": self.num_experts, "d_hidden": self.d_hidden,
                "capacity_factor": self.capacity_factor}

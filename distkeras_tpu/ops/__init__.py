from .losses import get_loss, LOSSES
from .optimizers import get_optimizer

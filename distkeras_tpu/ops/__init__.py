from .losses import get_loss, LOSSES
from .optimizers import get_optimizer

# imported for their layer-registry side effect: serde's layer_from_config
# must find MultiHeadAttention/LayerNorm/MoEDense in a FRESH process that
# deserializes a model without having touched these modules first
from . import attention as _attention  # noqa: F401
from . import moe as _moe  # noqa: F401

"""Flash attention as a Pallas TPU kernel.

The one hot op where hand-scheduling beats XLA's fusion: dense attention
materializes the (T×T) score matrix in HBM; this kernel streams K/V blocks
through VMEM on a (batch·head, q-block, k-block) grid and keeps the
online-softmax running max/denominator/accumulator in VMEM scratch that
persists across the k dimension of the grid — HBM traffic is O(T·D)
instead of O(T²) and VMEM stays bounded by the block sizes, so sequence
length is limited by HBM, not by the score matrix (verified: T=16k+ on one
v5e chip where the dense path's scores alone would need tens of GB).

Math follows the same blockwise recurrence as
``parallel.ring.ring_attention`` (intra-chip instead of inter-chip); both
are tested equal to ``ops.attention.dot_product_attention``.  On non-TPU
backends the kernel runs in Pallas interpret mode (slow but exact) so
tests stay hermetic.

Backward: ``jax.custom_vjp`` re-computing through the dense formulation —
correct everywhere, O(T²) memory on the backward only.  A fused backward
kernel is future work.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

try:  # pallas TPU backend may be absent on pure-CPU builds
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

from .attention import dot_product_attention

_NEG = -1e30


def _attn_kernel(q_ref, k_ref, v_ref, o_ref, o_acc, m_acc, l_acc, *,
                 causal: bool, scale: float, block_q: int, block_k: int):
    """Grid (bh, qi, kb): one K/V block per step; accumulators persist
    across kb (TPU executes the grid sequentially, minor-most last)."""
    qi = pl.program_id(1)
    kb = pl.program_id(2)
    n_kb = pl.num_programs(2)

    @pl.when(kb == 0)
    def _init():
        o_acc[:] = jnp.zeros_like(o_acc)
        m_acc[:] = jnp.full_like(m_acc, _NEG)
        l_acc[:] = jnp.zeros_like(l_acc)

    def _compute():
        q = q_ref[0].astype(jnp.float32) * scale       # (BQ, D)
        k = k_ref[0].astype(jnp.float32)               # (BK, D)
        v = v_ref[0].astype(jnp.float32)
        # HIGHEST precision: keep f32 inputs un-truncated on the MXU
        s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            precision=lax.Precision.HIGHEST)
        if causal:
            q_pos = qi * block_q + lax.broadcasted_iota(jnp.int32, s.shape, 0)
            k_pos = kb * block_k + lax.broadcasted_iota(jnp.int32, s.shape, 1)
            mask = k_pos <= q_pos
            s = jnp.where(mask, s, _NEG)
        m_prev = m_acc[:, 0]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        if causal:
            p = jnp.where(mask, p, 0.0)
        corr = jnp.exp(m_prev - m_new)
        l_acc[:, 0] = l_acc[:, 0] * corr + jnp.sum(p, axis=-1)
        pv = lax.dot_general(p, v, (((1,), (0,)), ((), ())),
                             precision=lax.Precision.HIGHEST)
        o_acc[:] = o_acc[:] * corr[:, None] + pv
        m_acc[:, 0] = m_new

    if causal:
        # skip K/V blocks entirely in the future of this q block
        pl.when(kb * block_k <= qi * block_q + block_q - 1)(_compute)
    else:
        _compute()

    @pl.when(kb == n_kb - 1)
    def _finalize():
        o_ref[0] = (o_acc[:] / l_acc[:, 0][:, None]).astype(o_ref.dtype)


def _flash_fwd(q, k, v, *, causal: bool, block_q: int, block_k: int,
               interpret: bool):
    b, t, h, dh = q.shape
    scale = 1.0 / math.sqrt(dh)
    # (B*H, T, Dh) layout: grid walks (batch*head, q-block, k-block)
    qr = q.transpose(0, 2, 1, 3).reshape(b * h, t, dh)
    kr = k.transpose(0, 2, 1, 3).reshape(b * h, t, dh)
    vr = v.transpose(0, 2, 1, 3).reshape(b * h, t, dh)

    bq = min(block_q, t)
    bk = min(block_k, t)
    if t % bq or t % bk:
        raise ValueError(f"sequence length {t} must divide block sizes "
                         f"({bq}, {bk})")

    if not _HAS_PLTPU:  # pragma: no cover
        raise RuntimeError("pallas TPU module unavailable; use "
                           "dot_product_attention")
    kernel = functools.partial(_attn_kernel, causal=causal, scale=scale,
                               block_q=bq, block_k=bk)
    scratch = [pltpu.VMEM((bq, dh), jnp.float32),
               pltpu.VMEM((bq, 128), jnp.float32),
               pltpu.VMEM((bq, 128), jnp.float32)]
    out = pl.pallas_call(
        kernel,
        grid=(b * h, t // bq, t // bk),
        in_specs=[
            pl.BlockSpec((1, bq, dh), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, bk, dh), lambda bh, i, j: (bh, j, 0)),
            pl.BlockSpec((1, bk, dh), lambda bh, i, j: (bh, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, dh), lambda bh, i, j: (bh, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, t, dh), q.dtype),
        scratch_shapes=scratch,
        interpret=interpret,
    )(qr, kr, vr)
    return out.reshape(b, h, t, dh).transpose(0, 2, 1, 3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention(q, k, v, causal: bool = False, block_q: int = 128,
                    block_k: int = 128):
    """Pallas flash attention; q/k/v (B, T, H, Dh) → (B, T, H, Dh).

    Numerically equal to ``dot_product_attention`` (tested); O(T·D) HBM
    traffic, VMEM bounded by block sizes.  Interpret mode is selected
    automatically off TPU.
    """
    interpret = jax.default_backend() != "tpu" or not _HAS_PLTPU
    return _flash_fwd(q, k, v, causal=causal, block_q=block_q,
                      block_k=block_k, interpret=interpret)


def _vjp_fwd(q, k, v, causal, block_q, block_k):
    out = flash_attention(q, k, v, causal, block_q, block_k)
    return out, (q, k, v)


def _vjp_bwd(causal, block_q, block_k, res, g):
    q, k, v = res
    _, vjp = jax.vjp(
        lambda q, k, v: dot_product_attention(q, k, v, causal=causal),
        q, k, v)
    return vjp(g)


flash_attention.defvjp(_vjp_fwd, _vjp_bwd)

"""Flash attention as Pallas TPU kernels — fused forward AND backward.

The one hot op where hand-scheduling beats XLA's fusion: dense attention
materializes the (T×T) score matrix in HBM; these kernels stream K/V
blocks through VMEM on a (batch·head, block, block) grid with the
online-softmax running statistics in VMEM scratch that persists across the
minor grid dimension — HBM traffic is O(T·D) instead of O(T²), so
sequence length is limited by HBM, not by the score matrix (verified:
T=16k+ on one v5e chip where the dense path's scores alone would need
tens of GB).

Backward is the standard flash recurrence (Dao 2022): the forward saves
only O and the per-row logsumexp L; dQ and dK/dV are each one fused kernel
re-computing P = exp(S − L) blockwise, so training memory is O(T·D) too.

Math follows the same blockwise recurrence as
``parallel.ring.ring_attention`` (intra-chip instead of inter-chip); both
are tested equal to ``ops.attention.dot_product_attention``, gradients
included.  On non-TPU backends the kernels run in Pallas interpret mode
(slow but exact) so tests stay hermetic.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

try:  # pallas TPU backend may be absent on pure-CPU builds
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

_NEG = -1e30


def _interpret() -> bool:
    return jax.default_backend() != "tpu" or not _HAS_PLTPU


def _dot(a, b):
    """MXU matmul, f32 result.  Precision policy: f32 inputs use HIGHEST
    (multi-pass, exact — the 2.4e-6-vs-f64 configuration BASELINE.md
    records); sub-f32 inputs (bf16 training) run the MXU at full native
    rate with f32 ACCUMULATION — the standard flash-attention trade, and
    the same input precision XLA's dense path uses in bf16 training."""
    if a.dtype == jnp.float32 and b.dtype == jnp.float32:
        return lax.dot_general(a, b, (((1,), (0,)), ((), ())),
                               precision=lax.Precision.HIGHEST)
    # explicit DEFAULT: a global jax_default_matmul_precision=highest
    # override would otherwise request fp32 contract precision on bf16
    # operands, which Mosaic rejects ("Bad lhs type")
    return lax.dot_general(a, b, (((1,), (0,)), ((), ())),
                           precision=lax.Precision.DEFAULT,
                           preferred_element_type=jnp.float32)


def _dot_t(a, b):  # a @ b.T, same precision policy as _dot
    if a.dtype == jnp.float32 and b.dtype == jnp.float32:
        return lax.dot_general(a, b, (((1,), (1,)), ((), ())),
                               precision=lax.Precision.HIGHEST)
    return lax.dot_general(a, b, (((1,), (1,)), ((), ())),
                           precision=lax.Precision.DEFAULT,
                           preferred_element_type=jnp.float32)


def _causal_mask(qi, kb, block_q, block_k, shape):
    q_pos = qi * block_q + lax.broadcasted_iota(jnp.int32, shape, 0)
    k_pos = kb * block_k + lax.broadcasted_iota(jnp.int32, shape, 1)
    return k_pos <= q_pos


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, o_acc, m_acc, l_acc, *,
                causal: bool, scale: float, block_q: int, block_k: int):
    """Grid (bh, qi, kb): one K/V block per step; accumulators persist
    across kb (TPU executes the grid sequentially, minor-most last)."""
    qi = pl.program_id(1)
    kb = pl.program_id(2)
    n_kb = pl.num_programs(2)

    @pl.when(kb == 0)
    def _init():
        o_acc[:] = jnp.zeros_like(o_acc)
        m_acc[:] = jnp.full_like(m_acc, _NEG)
        l_acc[:] = jnp.zeros_like(l_acc)

    def _compute():
        # matmuls in the input dtype (f32 → HIGHEST, bf16 → full MXU
        # rate with f32 accumulation); softmax statistics always f32
        s = _dot_t(q_ref[0], k_ref[0]) * scale
        if causal:
            mask = _causal_mask(qi, kb, block_q, block_k, s.shape)
            s = jnp.where(mask, s, _NEG)
        m_prev = m_acc[:, 0]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        if causal:
            p = jnp.where(mask, p, 0.0)
        corr = jnp.exp(m_prev - m_new)
        l_acc[:, 0] = l_acc[:, 0] * corr + jnp.sum(p, axis=-1)
        o_acc[:] = o_acc[:] * corr[:, None] + _dot(
            p.astype(v_ref.dtype), v_ref[0])
        m_acc[:, 0] = m_new

    if causal:
        # skip K/V blocks entirely in the future of this q block
        pl.when(kb * block_k <= qi * block_q + block_q - 1)(_compute)
    else:
        _compute()

    @pl.when(kb == n_kb - 1)
    def _finalize():
        l = l_acc[:, 0]
        o_ref[0] = (o_acc[:] / l[:, None]).astype(o_ref.dtype)
        lse_ref[0, 0] = m_acc[:, 0] + jnp.log(l)


def _flash_fwd_raw(qr, kr, vr, *, causal, bq, bk, scale):
    """(BH, Tq, D) + (BH, Tk, D) in → (out (BH,Tq,D), lse (BH,Tq)) via the
    fused kernel.  Rectangular Tq ≠ Tk is the ring's half-block hop shape
    (zigzag schedule); causal requires Tq == Tk (diagonal alignment)."""
    bh, tq, dh = qr.shape
    tk = kr.shape[1]
    if causal and tq != tk:
        raise ValueError(f"causal flash needs equal q/k lengths, got "
                         f"{tq} vs {tk}")
    kernel = functools.partial(_fwd_kernel, causal=causal, scale=scale,
                               block_q=bq, block_k=bk)
    out, lse = pl.pallas_call(
        kernel,
        grid=(bh, tq // bq, tk // bk),
        in_specs=[
            pl.BlockSpec((1, bq, dh), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, dh), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bk, dh), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, dh), lambda b, i, j: (b, i, 0)),
            # (bh, 1, t) layout so the block's last-two dims satisfy the
            # TPU (8, 128) tiling rule (second-to-last == array dim == 1)
            pl.BlockSpec((1, 1, bq), lambda b, i, j: (b, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, tq, dh), qr.dtype),
            jax.ShapeDtypeStruct((bh, 1, tq), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((bq, dh), jnp.float32),
                        pltpu.VMEM((bq, 128), jnp.float32),
                        pltpu.VMEM((bq, 128), jnp.float32)],
        interpret=_interpret(),
    )(qr, kr, vr)
    return out, lse


# ---------------------------------------------------------------------------
# backward (Dao 2022 recurrence; P recomputed blockwise from L)
# ---------------------------------------------------------------------------

def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dvec_ref, dq_ref,
                   dq_acc, *, causal: bool, scale: float, block_q: int,
                   block_k: int):
    qi = pl.program_id(1)
    kb = pl.program_id(2)
    n_kb = pl.num_programs(2)

    @pl.when(kb == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    def _compute():
        s = _dot_t(q_ref[0], k_ref[0]) * scale
        p = jnp.exp(s - lse_ref[0, 0][:, None])
        if causal:
            mask = _causal_mask(qi, kb, block_q, block_k, s.shape)
            p = jnp.where(mask, p, 0.0)
        dp = _dot_t(do_ref[0], v_ref[0])
        ds = p * (dp - dvec_ref[0, 0][:, None]) * scale
        dq_acc[:] = dq_acc[:] + _dot(ds.astype(k_ref.dtype), k_ref[0])

    if causal:
        pl.when(kb * block_k <= qi * block_q + block_q - 1)(_compute)
    else:
        _compute()

    @pl.when(kb == n_kb - 1)
    def _finalize():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(k_ref, v_ref, q_ref, do_ref, lse_ref, dvec_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc, *, causal: bool,
                    scale: float, block_q: int, block_k: int):
    kb = pl.program_id(1)
    qj = pl.program_id(2)
    n_qb = pl.num_programs(2)

    @pl.when(qj == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    def _compute():
        s = _dot_t(q_ref[0], k_ref[0]) * scale        # (BQ, BK)
        p = jnp.exp(s - lse_ref[0, 0][:, None])
        if causal:
            mask = _causal_mask(qj, kb, block_q, block_k, s.shape)
            p = jnp.where(mask, p, 0.0)
        # dV += P^T dO ; dS = P∘(dO V^T − D) ; dK += dS^T Q
        dv_acc[:] = dv_acc[:] + _dot(p.T.astype(do_ref.dtype), do_ref[0])
        dp = _dot_t(do_ref[0], v_ref[0])
        ds = p * (dp - dvec_ref[0, 0][:, None]) * scale
        dk_acc[:] = dk_acc[:] + _dot(ds.T.astype(q_ref.dtype), q_ref[0])

    if causal:
        # skip q blocks entirely ABOVE this k block's diagonal
        pl.when(qj * block_q + block_q - 1 >= kb * block_k)(_compute)
    else:
        _compute()

    @pl.when(qj == n_qb - 1)
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_bwd_raw(qr, kr, vr, do, lse, dvec, *, causal, bq, bk, scale):
    bh, tq, dh = qr.shape
    tk = kr.shape[1]

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, causal=causal, scale=scale,
                          block_q=bq, block_k=bk),
        grid=(bh, tq // bq, tk // bk),
        in_specs=[
            pl.BlockSpec((1, bq, dh), lambda b, i, j: (b, i, 0)),  # q
            pl.BlockSpec((1, bk, dh), lambda b, i, j: (b, j, 0)),  # k
            pl.BlockSpec((1, bk, dh), lambda b, i, j: (b, j, 0)),  # v
            pl.BlockSpec((1, bq, dh), lambda b, i, j: (b, i, 0)),  # do
            pl.BlockSpec((1, 1, bq), lambda b, i, j: (b, 0, i)),   # lse
            pl.BlockSpec((1, 1, bq), lambda b, i, j: (b, 0, i)),   # dvec
        ],
        out_specs=pl.BlockSpec((1, bq, dh), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, tq, dh), qr.dtype),
        scratch_shapes=[pltpu.VMEM((bq, dh), jnp.float32)],
        interpret=_interpret(),
    )(qr, kr, vr, do, lse, dvec)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, causal=causal, scale=scale,
                          block_q=bq, block_k=bk),
        grid=(bh, tk // bk, tq // bq),
        in_specs=[
            pl.BlockSpec((1, bk, dh), lambda b, i, j: (b, i, 0)),  # k
            pl.BlockSpec((1, bk, dh), lambda b, i, j: (b, i, 0)),  # v
            pl.BlockSpec((1, bq, dh), lambda b, i, j: (b, j, 0)),  # q
            pl.BlockSpec((1, bq, dh), lambda b, i, j: (b, j, 0)),  # do
            pl.BlockSpec((1, 1, bq), lambda b, i, j: (b, 0, j)),   # lse
            pl.BlockSpec((1, 1, bq), lambda b, i, j: (b, 0, j)),   # dvec
        ],
        out_specs=[
            pl.BlockSpec((1, bk, dh), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, dh), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[jax.ShapeDtypeStruct((bh, tk, dh), kr.dtype),
                   jax.ShapeDtypeStruct((bh, tk, dh), vr.dtype)],
        scratch_shapes=[pltpu.VMEM((bk, dh), jnp.float32),
                        pltpu.VMEM((bk, dh), jnp.float32)],
        interpret=_interpret(),
    )(kr, vr, qr, do, lse, dvec)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------

def _to_bh(x):
    b, t, h, dh = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, t, dh)


def _from_bh(x, b, h):
    bh, t, dh = x.shape
    return x.reshape(b, h, t, dh).transpose(0, 2, 1, 3)


def _auto_block(t: int, dh: int) -> int:
    """Default block size: as LARGE as VMEM allows (measured r4 at
    T=8192/dh=64: 1024² blocks run the fused bwd 3.4× faster than the old
    128² default and 2.4× faster than XLA dense — the per-grid-step
    overhead and small-K matmuls dominated at 128).  The score block is
    b²·4 bytes of VMEM (f32), with 2-3 alive in the backward, so the cap
    shrinks as the head dim's tiles grow."""
    cap = 1024 if dh <= 64 else 512 if dh <= 128 else 256
    for b in (1024, 512, 256, 128):
        if b <= cap and t % b == 0:
            return b
    for b in range(min(128, t), 0, -1):  # awkward T: largest divisor
        if t % b == 0:
            return b
    return 1


def _blocks(tq, tk, block_q, block_k, dh):
    if block_q is None:
        block_q = _auto_block(tq, dh)
    if block_k is None:
        block_k = _auto_block(tk, dh)
    bq, bk = min(block_q, tq), min(block_k, tk)
    if tq % bq or tk % bk:
        raise ValueError(f"sequence lengths ({tq}, {tk}) must divide "
                         f"block sizes ({bq}, {bk})")
    return bq, bk


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention(q, k, v, causal: bool = False, block_q=None,
                    block_k=None):
    """Pallas flash attention; q/k/v (B, T, H, Dh) → (B, T, H, Dh).

    Numerically equal to ``dot_product_attention`` (tested, gradients
    included); O(T·D) HBM traffic on BOTH forward and backward (the
    backward kernels recompute P blockwise from the saved logsumexp).
    Precision follows the input dtype (see ``_dot``): f32 inputs are
    exact (multi-pass HIGHEST); bf16 inputs run the MXU at full rate
    with f32 accumulation and f32 online-softmax statistics.
    ``block_q``/``block_k`` default to the auto rule (``_auto_block``):
    the largest VMEM-fitting block dividing T — large blocks are where
    the kernels beat XLA dense (see BASELINE.md flash-vs-dense ladder).
    Interpret mode is selected automatically off TPU.
    """
    out, _ = _vjp_fwd(q, k, v, causal, block_q, block_k)
    return out


def _vjp_fwd(q, k, v, causal, block_q, block_k):
    if not _HAS_PLTPU:  # pragma: no cover
        raise RuntimeError("pallas TPU module unavailable; use "
                           "dot_product_attention")
    b, t, h, dh = q.shape
    bq, bk = _blocks(t, k.shape[1], block_q, block_k, dh)
    scale = 1.0 / math.sqrt(dh)
    out, lse = _flash_fwd_raw(_to_bh(q), _to_bh(k), _to_bh(v),
                              causal=causal, bq=bq, bk=bk, scale=scale)
    return _from_bh(out, b, h), (q, k, v, out, lse)


def _bwd_impl(causal, block_q, block_k, res, g_out, g_lse=None):
    """Shared backward: ``g_lse`` (the lse cotangent, (B, H, T)) folds
    into the softmax-grad correction term — ∂lse_i/∂s_ij = P_ij lands
    exactly where D_i enters dS = P∘(dP − D), so ``dvec − g_lse`` covers
    it with the kernels unchanged."""
    q, k, v, out_bh, lse = res
    b, t, h, dh = q.shape
    bq, bk = _blocks(t, k.shape[1], block_q, block_k, dh)
    scale = 1.0 / math.sqrt(dh)
    do = _to_bh(g_out.astype(q.dtype))
    # D_i = rowsum(dO_i ∘ O_i) — the softmax-grad correction term (f32)
    dvec = jnp.sum(do.astype(jnp.float32) * out_bh.astype(jnp.float32),
                   axis=-1)[:, None, :]
    if g_lse is not None:
        dvec = dvec - g_lse.astype(jnp.float32).reshape(b * h, 1, t)
    dq, dk, dv = _flash_bwd_raw(_to_bh(q), _to_bh(k), _to_bh(v), do, lse,
                                dvec, causal=causal, bq=bq, bk=bk,
                                scale=scale)
    return (_from_bh(dq, b, h).astype(q.dtype),
            _from_bh(dk, b, h).astype(k.dtype),
            _from_bh(dv, b, h).astype(v.dtype))


def _vjp_bwd(causal, block_q, block_k, res, g):
    return _bwd_impl(causal, block_q, block_k, res, g)


flash_attention.defvjp(_vjp_fwd, _vjp_bwd)


# ---------------------------------------------------------------------------
# flash attention WITH the logsumexp exposed (ring / cross-block merging)
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention_lse(q, k, v, causal: bool = False, block_q=None,
                        block_k=None):
    """Like :func:`flash_attention` but also returns the per-row
    logsumexp ``lse`` (B, H, T) in f32 — the statistic that lets callers
    merge attention over key/value BLOCKS exactly:

        lse_tot = logaddexp(lse_a, lse_b)
        out_tot = out_a·exp(lse_a − lse_tot) + out_b·exp(lse_b − lse_tot)

    (``parallel.ring`` uses this to run the fused kernel per ring hop.)
    Differentiable in BOTH outputs: an ``lse`` cotangent folds into the
    backward as ``dvec − g_lse`` — since ∂lse_i/∂s_ij = P_ij, the extra
    term lands exactly where the softmax-grad correction D_i already
    enters dS = P∘(dP − D), so the kernels are reused unchanged.
    """
    (out, lse), _ = _vjp_lse_fwd(q, k, v, causal, block_q, block_k)
    return out, lse


def _vjp_lse_fwd(q, k, v, causal, block_q, block_k):
    out, res = _vjp_fwd(q, k, v, causal, block_q, block_k)
    b, t, h, dh = q.shape
    lse = res[4].reshape(b, h, t)  # (BH, 1, T) -> (B, H, T), f32
    return (out, lse), res


def _vjp_lse_bwd(causal, block_q, block_k, res, cts):
    g_out, g_lse = cts
    return _bwd_impl(causal, block_q, block_k, res, g_out, g_lse)


flash_attention_lse.defvjp(_vjp_lse_fwd, _vjp_lse_bwd)

"""Loss functions.

The reference passes Keras loss *names* into trainers
(``distkeras/trainers.py`` — e.g. ``loss='categorical_crossentropy'``).  We
keep the same string surface, resolving to pure JAX functions
``loss(logits_or_probs, targets) -> scalar`` that differentiate and fuse
cleanly under jit.

Convention: the named crossentropy losses here treat model outputs as
*logits* (numerically stable log-softmax inside the loss).  The reference's
Keras models end in a softmax layer, so trainers detect a trailing softmax
and swap in the ``*_from_probs`` variants below (clipped-log, exactly the
Keras semantics) — the model surface stays identical to the reference and
nothing is stripped.
"""

from __future__ import annotations

from typing import Callable, Union

import jax
import jax.numpy as jnp


def categorical_crossentropy(logits, targets):
    """targets: one-hot (batch, classes); logits: (batch, classes)."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.mean(jnp.sum(targets * logp, axis=-1))


def sparse_categorical_crossentropy(logits, targets):
    """targets: int class ids, any shape matching logits' leading dims —
    (batch,) for classifiers, (batch, seq) for per-token LM loss."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.mean(jnp.take_along_axis(
        logp, targets.astype(jnp.int32)[..., None], axis=-1))


def binary_crossentropy(logits, targets):
    """targets in {0,1}, logits: raw scores (any shape)."""
    logits = logits.reshape(targets.shape)
    return jnp.mean(jnp.clip(logits, 0) - logits * targets
                    + jnp.log1p(jnp.exp(-jnp.abs(logits))))


def mean_squared_error(preds, targets):
    return jnp.mean((preds - targets) ** 2)


def mean_absolute_error(preds, targets):
    return jnp.mean(jnp.abs(preds - targets))


LOSSES: dict[str, Callable] = {
    "categorical_crossentropy": categorical_crossentropy,
    "sparse_categorical_crossentropy": sparse_categorical_crossentropy,
    "binary_crossentropy": binary_crossentropy,
    "mean_squared_error": mean_squared_error,
    "mse": mean_squared_error,
    "mean_absolute_error": mean_absolute_error,
    "mae": mean_absolute_error,
}


def get_loss(name_or_fn: Union[str, Callable]) -> Callable:
    if callable(name_or_fn):
        return name_or_fn
    return LOSSES[name_or_fn]


# -- on-probabilities variants (Keras semantics) ----------------------------
# The reference's models end in a softmax layer and its losses therefore see
# probabilities, not logits (Keras ``categorical_crossentropy``).  Trainers
# that detect a trailing softmax swap in these clipped-log variants so the
# model surface can stay identical to the reference.

_EPS = 1e-7


def categorical_crossentropy_from_probs(probs, targets):
    p = jnp.clip(probs, _EPS, 1.0)
    return -jnp.mean(jnp.sum(targets * jnp.log(p), axis=-1))


def sparse_categorical_crossentropy_from_probs(probs, targets):
    p = jnp.clip(probs, _EPS, 1.0)
    logp = jnp.log(p)
    return -jnp.mean(jnp.take_along_axis(
        logp, targets.astype(jnp.int32)[..., None], axis=-1))


def binary_crossentropy_from_probs(probs, targets):
    p = jnp.clip(probs.reshape(targets.shape), _EPS, 1.0 - _EPS)
    return -jnp.mean(targets * jnp.log(p) + (1 - targets) * jnp.log1p(-p))


_PROBS_VARIANTS: dict[str, Callable] = {
    "categorical_crossentropy": categorical_crossentropy_from_probs,
    "sparse_categorical_crossentropy": sparse_categorical_crossentropy_from_probs,
    "binary_crossentropy": binary_crossentropy_from_probs,
}


def probs_loss_variant(name: str):
    """On-probs variant of a named loss, or None if not a crossentropy."""
    return _PROBS_VARIANTS.get(name)

"""Loss functions.

The reference passes Keras loss *names* into trainers
(``distkeras/trainers.py`` — e.g. ``loss='categorical_crossentropy'``).  We
keep the same string surface, resolving to pure JAX functions
``loss(logits_or_probs, targets) -> scalar`` that differentiate and fuse
cleanly under jit.

Convention: model outputs are treated as *logits* for the crossentropy
losses (numerically stable log-softmax inside the loss) — models therefore
end in a linear layer, not a softmax.  A trailing ``softmax`` Activation is
detected by trainers and stripped for training (the reference's Keras
models end in softmax; this preserves that surface while staying stable).
"""

from __future__ import annotations

from typing import Callable, Union

import jax
import jax.numpy as jnp


def categorical_crossentropy(logits, targets):
    """targets: one-hot (batch, classes); logits: (batch, classes)."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.mean(jnp.sum(targets * logp, axis=-1))


def sparse_categorical_crossentropy(logits, targets):
    """targets: int class ids (batch,)."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.mean(jnp.take_along_axis(
        logp, targets.astype(jnp.int32)[:, None], axis=-1))


def binary_crossentropy(logits, targets):
    """targets in {0,1}, logits: raw scores (any shape)."""
    logits = logits.reshape(targets.shape)
    return jnp.mean(jnp.clip(logits, 0) - logits * targets
                    + jnp.log1p(jnp.exp(-jnp.abs(logits))))


def mean_squared_error(preds, targets):
    return jnp.mean((preds - targets) ** 2)


def mean_absolute_error(preds, targets):
    return jnp.mean(jnp.abs(preds - targets))


LOSSES: dict[str, Callable] = {
    "categorical_crossentropy": categorical_crossentropy,
    "sparse_categorical_crossentropy": sparse_categorical_crossentropy,
    "binary_crossentropy": binary_crossentropy,
    "mean_squared_error": mean_squared_error,
    "mse": mean_squared_error,
    "mean_absolute_error": mean_absolute_error,
    "mae": mean_absolute_error,
}


def get_loss(name_or_fn: Union[str, Callable]) -> Callable:
    if callable(name_or_fn):
        return name_or_fn
    return LOSSES[name_or_fn]

"""Attention ops + MultiHeadAttention layer.

The reference predates attention entirely (SURVEY.md §5.7: its sequence
models are small LSTMs).  Long-context support is first-class here, so the
framework ships a standard MXU-friendly attention stack:

* ``dot_product_attention`` — fused-softmax reference implementation (XLA
  fuses QK^T → softmax → PV into MXU-resident loops).
* ``MultiHeadAttention`` — a ``Layer`` usable in Sequential stacks.
* The sequence-parallel ring formulation lives in
  ``distkeras_tpu.parallel.ring`` and reuses the same online-softmax math.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

from ..models.layers import Layer, glorot_uniform, register, uniform_scale
from ..obs import get_logger

#: minimum sequence length for a causal mesh-attached layer to AUTO-pick
#: the zigzag ring layout (ADVICE r5): zigzag halves the causal ring's
#: executed FLOPs, but without :func:`models.optimize.zigzag_wrap` every
#: attention call pays a shuffle + unshuffle of its activations (two
#: global token-axis gathers) — a net loss at small T, where attention is
#: not the dominant cost.  Pin ``layer.ring_layout`` to override either
#: way; ``zigzag_wrap`` amortizes the stripe to once per batch and forces
#: zigzag regardless of this threshold.
ZIGZAG_AUTO_MIN_T = 256

#: layout decisions already logged (once per distinct choice, not per
#: trace/call — the auto-switch must not be silent, ADVICE r5)
_LAYOUT_LOGGED: set = set()


def _log_layout_choice(layout: str, t: int, sp: int) -> None:
    key = (layout, t, sp)
    if key in _LAYOUT_LOGGED:
        return
    _LAYOUT_LOGGED.add(key)
    why = (f"T={t} >= ZIGZAG_AUTO_MIN_T={ZIGZAG_AUTO_MIN_T}"
           if layout == "zigzag" else
           f"T={t} below ZIGZAG_AUTO_MIN_T={ZIGZAG_AUTO_MIN_T} or "
           f"not divisible by 2*|sp|={2 * sp}")
    get_logger("ops.attention").info(
        "causal ring auto-selected %r layout (%s); zigzag pays a per-call "
        "shuffle/unshuffle unless models.optimize.zigzag_wrap amortizes "
        "the stripe to once per batch; pin layer.ring_layout to override",
        layout, why)


def dot_product_attention(q, k, v, *, causal: bool = False):
    """Scaled dot-product attention.

    q: (B, Tq, H, Dh); k/v: (B, Tk, H, Dh) → (B, Tq, H, Dh).
    """
    dh = q.shape[-1]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / math.sqrt(dh)
    if causal:
        qi = jnp.arange(q.shape[1])[:, None]
        ki = jnp.arange(k.shape[1])[None, :]
        scores = jnp.where(ki <= qi, scores, jnp.finfo(scores.dtype).min)
    weights = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", weights, v)


def apply_rope(x, positions, base: float = 10000.0):
    """Rotary position embedding (RoPE, Su et al. 2021), HALF-SPLIT
    (GPT-NeoX-style) convention: dim i pairs with dim i + Dh/2 — NOT the
    interleaved (2i, 2i+1) layout some implementations use; weights are
    not portable between the two conventions without a permutation.
    Rotates each pair of ``x`` (…, T, H, Dh) by position-scaled angles.
    ``positions``: (T,) int — absolute positions of x's time axis (a
    scalar-position caller passes shape (1,)) — or (B, T) for PER-ROW
    positions (ragged cached decode: each row sits at its own absolute
    position).  Attention scores between RoPE'd q/k depend only on
    RELATIVE position, which is what lets a cached decode
    rotate-then-store."""
    dh = x.shape[-1]
    half = dh // 2
    freqs = base ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    ang = positions.astype(jnp.float32)[..., None] * freqs  # (…, T, half)
    if ang.ndim == 2:  # shared positions: broadcast over the batch
        ang = ang[None]
    cos = jnp.cos(ang)[:, :, None, :].astype(x.dtype)
    sin = jnp.sin(ang)[:, :, None, :].astype(x.dtype)
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate([x1 * cos - x2 * sin,
                            x2 * cos + x1 * sin], axis=-1)


_MIN_FLASH_BLOCK = 32  # below this the kernel grid degenerates (perf cliff)


def _largest_divisor_block(t: int, cap: int = 128) -> int:
    """Largest block size ≤ cap dividing t (flash kernels need whole
    blocks; T=200 → 100, T=256 → 128, prime T → 1)."""
    for b in range(min(cap, t), 0, -1):
        if t % b == 0:
            return b
    return 1


def _flash_with_blocking(q, k, v, causal: bool, t: int):
    """Run the Pallas flash kernel with a sane block size.

    Awkward sequence lengths (e.g. prime T) have no block-sized divisor;
    silently falling back to block=1 is a severe perf cliff on real TPU.
    For causal attention, end-padding T to a multiple of 128 is exact:
    padded KEY positions sit strictly after every real query (never
    attended), and padded QUERY rows are sliced off (their zero cotangent
    keeps gradients exact too).  Non-causal attention would attend the
    padded keys, so there we refuse loudly instead of degrading.
    """
    from .pallas_attention import flash_attention
    blk = _largest_divisor_block(t)
    if blk >= _MIN_FLASH_BLOCK or t <= _MIN_FLASH_BLOCK:
        # block sizes auto-tune inside the kernel (largest VMEM-fitting
        # divisor of T — the big-block regime is where flash beats dense)
        return flash_attention(q, k, v, causal)
    if not causal:
        raise ValueError(
            f"impl='flash' needs a sequence length with a block-sized "
            f"divisor; T={t}'s largest block is {blk} (< "
            f"{_MIN_FLASH_BLOCK}), which would run the kernel grid "
            f"degenerately slowly.  Pad T to a multiple of 128 (with key "
            f"masking) or use impl='dense'.")
    pad = -t % 128
    padded = [jnp.pad(a, ((0, 0), (0, pad), (0, 0), (0, 0))) for a in (q, k, v)]
    return flash_attention(*padded, True)[:, :t]


@register
class MultiHeadAttention(Layer):
    """Self-attention over (T, D) inputs; fused qkv projection (one
    MXU-shaped (D, D + 2·KV·Dh) GEMM — (D, 3D) in the classic
    full-head case) + output projection.

    ``impl``: ``"dense"`` (XLA-fused reference) or ``"flash"`` (the Pallas
    VMEM-resident kernels, ``ops.pallas_attention``: fused forward AND
    backward, both O(T·D) HBM — the forward saves only O and the per-row
    logsumexp, dQ/dK/dV recompute scores blockwise).  Flash scales a
    single chip to HBM-limited sequence lengths for training and
    inference; past one chip, attach a mesh (``layer.mesh = mesh``, find
    instances via ``model.iter_layers()``) to run the sequence-parallel
    ring path (``parallel.ring``): T shards over ``layer.ring_axis`` and
    K/V rotate via ppermute.  Like ``MoEDense.mesh`` this is TRACE-time
    runtime placement: attach before jitting, and it is not part of the
    serialized config.
    """

    time_mixing = True  # has its own apply_decode/apply_prefill rules

    def __init__(self, num_heads: int, causal: bool = False,
                 impl: str = "dense", num_kv_heads: Optional[int] = None,
                 rope: bool = False):
        if impl not in ("dense", "flash"):
            raise ValueError(f"impl must be 'dense' or 'flash', got {impl!r}")
        self.num_heads = int(num_heads)
        #: rotary position embeddings applied to q/k inside the layer
        #: (``apply_rope``) — pairs with ``zoo.gpt_lm(positional="rope")``,
        #: which then drops the learned PositionalEmbedding table
        self.rope = bool(rope)
        #: grouped-query attention (GQA; num_kv_heads=1 ≡ multi-query):
        #: K/V projections and the DECODE CACHE carry only this many
        #: heads — cache memory shrinks H/kv× — while query heads share
        #: each K/V group.  None keeps classic multi-head (and the
        #: fused-qkv parameter layout, so existing checkpoints load).
        self.num_kv_heads = None if num_kv_heads is None else int(num_kv_heads)
        if self.num_kv_heads is not None:
            if self.num_kv_heads < 1:
                raise ValueError(f"num_kv_heads must be >= 1, got "
                                 f"{num_kv_heads}")
            if self.num_heads % self.num_kv_heads:
                raise ValueError(
                    f"num_heads {num_heads} not divisible by num_kv_heads "
                    f"{num_kv_heads}")
        self.causal = bool(causal)
        self.impl = impl
        self.mesh = None        # runtime attachment → ring attention
        self.ring_axis = "sp"
        self.batch_axis = None  # optional dp axis for dp×sp composition
        #: ring hop compute: None → follow ``impl`` (flash layers ring
        #: with the fused kernel per hop, O(T_loc·D) memory); or set
        #: "blockwise"/"flash" explicitly
        self.ring_impl = None
        #: sequence layout for the causal ring: None → "zigzag" whenever
        #: causal and T divides 2·|sp| (the load-balanced schedule: every
        #: device computes the same ≈half-block work per hop instead of
        #: the contiguous layout's straggler shard); or pin
        #: "contiguous"/"zigzag" explicitly
        self.ring_layout = None
        #: set by ``models.optimize.zigzag_wrap``: activations arrive
        #: ALREADY zigzag-striped (the model re-stripes once per batch),
        #: so the per-call shuffle/unshuffle is skipped
        self.ring_pre_shuffled = False

    @property
    def _kv(self) -> int:
        return self.num_kv_heads if self.num_kv_heads is not None \
            else self.num_heads

    def init(self, rng, in_shape):
        t, d = in_shape
        if d % self.num_heads:
            raise ValueError(f"model dim {d} not divisible by "
                             f"{self.num_heads} heads")
        if self.rope and (d // self.num_heads) % 2:
            raise ValueError(
                f"rope=True needs an even head dim, got Dh = "
                f"{d // self.num_heads} (dim {d} / {self.num_heads} heads)")
        k1, k2 = jax.random.split(rng)
        dh = d // self.num_heads
        params = {
            # one fused projection for ALL head layouts: (D, D + 2·KV·Dh)
            # degenerates to the classic (D, 3D) when KV == H, so
            # pre-GQA checkpoints load unchanged and the single
            # MXU-shaped GEMM is kept under grouping too
            "qkv": glorot_uniform(k1, (d, d + 2 * self._kv * dh)),
            "out": glorot_uniform(k2, (d, d)),
        }
        return params, {}, in_shape

    def _project(self, params, x):
        """x (B, T, D) → q (B, T, H, Dh), k/v (B, T, KV, Dh) — one fused
        GEMM, split at [D, D + KV·Dh]."""
        b, t, d = x.shape
        h = self.num_heads
        kv = self._kv
        dh = d // h
        qkv = x @ params["qkv"].astype(x.dtype)   # (B, T, D + 2·KV·Dh)
        q = qkv[..., :d].reshape(b, t, h, dh)
        k = qkv[..., d:d + kv * dh].reshape(b, t, kv, dh)
        v = qkv[..., d + kv * dh:].reshape(b, t, kv, dh)
        return q, k, v

    def _expand_kv(self, k):
        """(B, T, KV, Dh) → (B, T, H, Dh): query groups share K/V heads
        (the attention ops and flash kernels take equal head counts;
        the decode CACHE stays KV-sized — that is where GQA saves)."""
        g = self.num_heads // self._kv
        return k if g == 1 else jnp.repeat(k, g, axis=2)

    def apply(self, params, state, x, *, train=False, rng=None):
        b, t, d = x.shape
        q, k, v = self._project(params, x)
        if self.rope:
            if self.mesh is not None:
                raise ValueError(
                    "rope=True with a mesh-attached (sequence-sharded) "
                    "layer is not supported: per-shard positions need "
                    "global offsets; detach the mesh or use the learned "
                    "PositionalEmbedding")
            pos = jnp.arange(t)
            q = apply_rope(q, pos)
            k = apply_rope(k, pos)
        k = self._expand_kv(k)
        v = self._expand_kv(v)
        if self.mesh is not None:
            from ..parallel.ring import ring_attention_sharded
            from ..ops.pallas_attention import _HAS_PLTPU
            # flash layers ring with the fused kernel per hop; fall back
            # to the einsum hops on builds without the pallas TPU module
            # (the ring itself runs anywhere)
            ring_impl = self.ring_impl or (
                "flash" if self.impl == "flash" and _HAS_PLTPU
                else "blockwise")
            layout = self.ring_layout
            if self.ring_pre_shuffled:
                layout = "zigzag"
            elif layout is None and ring_impl != "ulysses":
                # causal rings default to the load-balanced zigzag
                # stripe when the length allows (exact; ≈half the FLOPs)
                # AND the sequence is long enough for the saved FLOPs to
                # beat the per-call stripe gathers (ADVICE r5)
                sp = self.mesh.shape[self.ring_axis]
                layout = ("zigzag" if self.causal and t % (2 * sp) == 0
                          and t >= ZIGZAG_AUTO_MIN_T else "contiguous")
                if self.causal:
                    _log_layout_choice(layout, t, sp)
            o = ring_attention_sharded(self.mesh, q, k, v,
                                       axis=self.ring_axis,
                                       batch_axis=self.batch_axis,
                                       causal=self.causal,
                                       impl=ring_impl,
                                       layout=layout or "contiguous",
                                       pre_shuffled=self.ring_pre_shuffled)
        elif self.impl == "flash":
            o = _flash_with_blocking(q, k, v, self.causal, t)
        else:
            o = dot_product_attention(q, k, v, causal=self.causal)
        o = o.reshape(b, t, d)
        return o @ params["out"].astype(x.dtype), state

    def init_cache(self, batch, in_shape):
        t, d = in_shape
        dh = d // self.num_heads
        # KV-head-sized: THE GQA memory win — H/kv× smaller than the
        # activations' head count
        shape = (batch, t, self._kv, dh)
        return {"k": jnp.zeros(shape), "v": jnp.zeros(shape)}

    def apply_decode(self, params, state, x, cache, pos):
        """One-token cached decode: append this position's K/V to the
        cache, attend the single query over positions <= pos.  O(T·D)
        per token vs the recompute path's O(T²·D).  Grouped-query
        attention attends via a (KV, G) grouped einsum so the KV-sized
        cache is never expanded to H heads.  ``pos`` may be a scalar
        (uniform batch) or (B,) — PER-ROW positions for ragged prompts:
        each row writes its K/V at its own slot (indexed scatter) and
        masks at its own horizon.  Decoding is inherently causal — only
        meaningful for ``causal=True`` layers."""
        if not self.causal:
            raise ValueError("cached decode requires causal=True attention")
        b, d = x.shape
        h = self.num_heads
        kv = self._kv
        g = h // kv
        dh = d // h
        pos = jnp.asarray(pos)
        per_row = pos.ndim == 1
        q, k, v = self._project(params, x[:, None, :])
        if self.rope:
            # rotate-then-cache: scores depend on relative position only,
            # so rotated keys compose with rotated queries at any later pos
            p1 = pos[:, None] if per_row else pos[None]
            q = apply_rope(q, p1)
            k = apply_rope(k, p1)
        if per_row:
            # indexed scatter (one (KV, Dh) row per batch element) — the
            # one-hot blend formulation costs a full-buffer
            # read-modify-write per step (measured +20% on the ragged
            # decode rate)
            rows = jnp.arange(b)
            kc = cache["k"].at[rows, pos].set(
                k[:, 0].astype(cache["k"].dtype))
            vc = cache["v"].at[rows, pos].set(
                v[:, 0].astype(cache["v"].dtype))
        else:
            kc = jax.lax.dynamic_update_slice(
                cache["k"], k.astype(cache["k"].dtype), (0, pos, 0, 0))
            vc = jax.lax.dynamic_update_slice(
                cache["v"], v.astype(cache["v"].dtype), (0, pos, 0, 0))
        # head order matches _expand_kv's repeat: head = kv_idx·G + g
        qg = q[:, 0].reshape(b, kv, g, dh)
        s = jnp.einsum("bkgd,btkd->bkgt", qg, kc,
                       preferred_element_type=jnp.float32) / math.sqrt(dh)
        t_idx = jnp.arange(kc.shape[1])
        horizon = pos[:, None, None, None] if per_row else pos
        s = jnp.where(t_idx[None, None, None, :] <= horizon, s, -1e30)
        w = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bkgt,btkd->bkgd", w,
                       vc.astype(jnp.float32)).astype(x.dtype)
        return o.reshape(b, d) @ params["out"].astype(x.dtype), \
            {"k": kc, "v": vc}

    def apply_prefill(self, params, state, x, cache):
        """Batched prefill: one full causal forward over the buffer (via
        the layer's own configured attention impl — dense or flash) that
        also records every position's K/V into the cache.  Cache entries
        past the prompt are placeholders: masked during decode and
        overwritten position-by-position as tokens are generated."""
        if not self.causal:
            raise ValueError("cached decode requires causal=True attention")
        b, t, d = x.shape
        q, k, v = self._project(params, x)
        if self.rope:
            pos = jnp.arange(t)
            q = apply_rope(q, pos)
            k = apply_rope(k, pos)
        cache = {"k": k.astype(cache["k"].dtype),
                 "v": v.astype(cache["v"].dtype)}
        k = self._expand_kv(k)
        v = self._expand_kv(v)
        if self.impl == "flash":
            o = _flash_with_blocking(q, k, v, True, t)
        else:
            o = dot_product_attention(q, k, v, causal=True)
        return o.reshape(b, t, d) @ params["out"].astype(x.dtype), cache

    def get_config(self):
        return {"num_heads": self.num_heads, "causal": self.causal,
                "impl": self.impl, "num_kv_heads": self.num_kv_heads,
                "rope": self.rope}


@register
class LayerNorm(Layer):
    def __init__(self, epsilon: float = 1e-5):
        self.epsilon = float(epsilon)

    def init(self, rng, in_shape):
        d = in_shape[-1]
        return {"scale": jnp.ones((d,)), "bias": jnp.zeros((d,))}, {}, in_shape

    def apply(self, params, state, x, *, train=False, rng=None):
        mean = jnp.mean(x, axis=-1, keepdims=True)
        var = jnp.var(x, axis=-1, keepdims=True)
        y = (x - mean) * jax.lax.rsqrt(var + jnp.asarray(self.epsilon, x.dtype))
        return y * params["scale"].astype(x.dtype) \
            + params["bias"].astype(x.dtype), state

    def get_config(self):
        return {"epsilon": self.epsilon}


@register
class PositionalEmbedding(Layer):
    """Learned absolute position embeddings added to token embeddings:
    (T, D) -> (T, D).  The standard GPT-style position encoding; the
    table is sized at construction so shapes stay static under jit."""

    def __init__(self, max_len: int):
        self.max_len = int(max_len)

    def init(self, rng, in_shape):
        t, d = in_shape
        if t > self.max_len:
            raise ValueError(f"sequence length {t} exceeds "
                             f"max_len={self.max_len}")
        params = {"table": uniform_scale(rng, (self.max_len, d))}
        return params, {}, in_shape

    def apply(self, params, state, x, *, train=False, rng=None):
        t = x.shape[1]
        return x + params["table"][:t].astype(x.dtype), state

    def apply_decode(self, params, state, x, cache, pos):
        pos = jnp.asarray(pos)
        if pos.ndim == 1:  # per-row positions (ragged cached decode)
            rows = jnp.take(params["table"], pos, axis=0)  # (B, D)
            return x + rows.astype(x.dtype), cache
        row = jax.lax.dynamic_slice_in_dim(params["table"], pos, 1, 0)[0]
        return x + row.astype(x.dtype), cache

    def get_config(self):
        return {"max_len": self.max_len}


@register
class GlobalAvgPool1D(Layer):
    """Mean over the time axis: (T, D) -> (D,)."""
    time_mixing = True

    def out_shape(self, in_shape):
        return (in_shape[-1],)

    def apply(self, params, state, x, *, train=False, rng=None):
        return jnp.mean(x, axis=1), state

"""Continuous-batching decode engine (ISSUE 7 tentpole; ISSUE 11 made
the hot path fast: prefix KV cache, speculative decoding, dispatch-ahead).

One decode state for ``slots`` concurrent requests — buffer (B, T),
KV cache (B rows), per-row position/logits — advanced for every ACTIVE
row per ``step``, exactly the ragged per-row read/write machinery
``models.generation`` already compiles (one-hot position writes, (B,)
cache positions).  A new request does not wait for the batch to finish:
a **join** program prefills the prompt at its length bucket and scatters
the row (buffer, padded cache, position, first-token logits) into a
retired slot while the other rows keep decoding.

Compiled-program families, all static-shaped by construction:

* ``serve.join.l<L>`` — per prefill bucket L: single-row prefill of the
  (1, L) padded prompt + one-hot scatter into slot ``row``.  With the
  prefix cache on, the join also RETURNS the single-row full-length KV
  it just computed, so the host can cache it for later prompts sharing
  the prefix.  With speculative decode on, the join prefills the draft
  model's cache for the row too.
* ``serve.sjoin.s<S>`` — per suffix bucket S (prefix cache on): admit a
  prompt whose longest prefix is already cached by re-playing only the
  (1, S) padded *suffix* over the cached KV (a ``decode_window``) + the
  same one-hot scatter — warm time-to-first-token skips the O(L²)
  prefill entirely.
* ``serve.step`` — sample every active row's next token from its
  carried logits, write it at the row's own position, one cached decode
  forward for the next position's logits.  Inactive rows are masked
  no-ops.
* ``serve.spec_step`` (``spec_k > 0``, replaces ``serve.step``) — draft
  proposes k tokens per row, the target verifies all k in one batched
  window, up to k+1 tokens emitted per dispatch (``serve/spec.py``;
  greedy output provably equals ``generate_tokens``).
* Each program sits behind its own ``RetraceSentinel``
  (``jit.compiles``/``jit.retraces`` in the service registry) — after
  ``warmup()`` compiles the full ladder (buckets × {join, sjoin} + the
  step), steady-state serving is provably ``jit.retraces == 0`` (the
  drift-gated serving contract).

**Dispatch-ahead** (ISSUE 11 satellite): the decode loop dispatches
device step k+1 BEFORE doing step k's host bookkeeping (readback,
detokenize, retire, SLO stamping), so the host component overlaps the
in-flight device step instead of serializing with it — steady-state
step cadence approaches max(device, host) rather than device + host.
``serve.host_seconds`` records the per-step host component that is now
hidden.  Token attribution stays exact: each dispatch snapshots its
slot->request map, and a token computed for a row that retired (or
re-joined) after the dispatch is discarded by the snapshot check.
Under this overlap ``serve.step_seconds`` (and ``per_token_seconds``,
which replays it per token) measures a step's dispatch->retire wall —
one full loop iteration, INCLUDING the host work overlapped with the
in-flight step (previous retire, any admit-time prefill joins, the
next dispatch).  It is the steady-state step *cadence* the SLO gate
should track, not isolated device time; on a join-heavy workload its
tail moves with admission, which is precisely the latency a caller
experiences.

Scheduling is host-side and single-threaded: one decode thread owns the
device state and the slot table; ``submit()`` (any thread) only touches
the bounded admission queue.  SLO surface, all in the service registry:
``serve.queue_wait_seconds`` (submit -> slot), ``serve.ttft_seconds``
(submit -> first token; split ``serve.ttft_warm_seconds`` /
``serve.ttft_cold_seconds`` by prefix-cache outcome),
``serve.per_token_seconds`` (each emitted token's step cadence),
``serve.e2e_seconds`` (submit -> done), ``serve.step_seconds``,
``serve.host_seconds``, counters ``serve.requests`` /
``serve.admitted`` / ``serve.completed`` / ``serve.tokens_out`` /
``serve.rejected`` (split by reason), ``serve.prefix.*`` /
``serve.spec.*`` accelerator counters (pre-created, so a snapshot
always carries explicit zeros), gauges ``serve.queue_depth`` /
``serve.active_slots``.

Admission control: a full queue (or a draining engine) load-sheds with
``ServeRejected`` — every request either completes or is recorded under
``serve.rejected``; nothing drops silently (the graceful-drain
contract, including hard-stop aborts).
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Optional, Tuple

import numpy as np

from ..obs import Registry, TIME_BUCKETS
from ..obs.logging import get_logger
from ..obs.profile import RetraceSentinel
from ..models.generation import _model_cache, decode_window, sample_rowwise
from .config import ServeConfig
from .prefix import PrefixCache, PrefixEntry
from .spec import build_spec_step, validate_draft

_LOG = "serve.engine"

#: decode-thread wait quantum while idle (seconds) — submissions notify
#: the condition, so this only bounds shutdown latency
_IDLE_WAIT_S = 0.05


class ServeRejected(Exception):
    """A request the admission controller load-shed (queue full /
    draining / aborted by a hard stop).  ``reason`` names which."""

    def __init__(self, reason: str):
        super().__init__(f"request rejected: {reason}")
        self.reason = reason


class ServeRequest:
    """One in-flight generation: the handle ``submit()`` returns.

    ``wait(timeout)`` blocks until completion; ``result()`` returns the
    GENERATED token ids (eos included when sampled) as int32, raising
    ``ServeRejected`` if the engine aborted the request mid-flight.
    ``warm`` records the prefix-cache outcome at admission (None when
    the cache is disabled).

    ``temperature`` / ``top_k`` / ``top_p`` are the request's RESOLVED
    sampling params (ISSUE 14: they ride the request, not the engine
    config — one fleet serves every temperature): ``top_k == 0`` and
    ``top_p == 1.0`` are the disabled encodings the compiled step
    program understands."""

    __slots__ = ("prompt", "length", "max_new", "tokens", "error",
                 "submit_t", "admit_t", "first_token_t", "done_t",
                 "warm", "temperature", "top_k", "top_p", "_done")

    def __init__(self, prompt: np.ndarray, max_new: int,
                 temperature: float = 0.0, top_k: int = 0,
                 top_p: float = 1.0):
        self.prompt = prompt
        self.length = int(prompt.shape[0])
        self.max_new = int(max_new)
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.tokens: list = []
        self.error: Optional[str] = None
        self.submit_t = time.perf_counter()
        self.admit_t: Optional[float] = None
        self.first_token_t: Optional[float] = None
        self.done_t: Optional[float] = None
        self.warm: Optional[bool] = None
        self._done = threading.Event()

    @property
    def done(self) -> bool:
        return self._done.is_set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._done.wait(timeout)

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        if not self._done.wait(timeout):
            raise TimeoutError("request not complete")
        if self.error is not None:
            raise ServeRejected(self.error)
        return np.asarray(self.tokens, np.int32)


class _Slot:
    """Decode-thread-private per-row bookkeeping (no locking: one owner)."""

    __slots__ = ("request",)

    def __init__(self):
        self.request: Optional[ServeRequest] = None


class _Pending:
    """One dispatched-but-not-yet-retired device step: the device output
    handles plus the dispatch-time slot->request snapshot that makes
    token attribution exact under dispatch-ahead."""

    __slots__ = ("reqs", "tokens", "counts", "t0")

    def __init__(self, reqs, tokens, counts, t0):
        self.reqs = reqs          # slot->request snapshot at dispatch
        self.tokens = tokens      # device (B,) or (B, k+1) int32
        self.counts = counts      # device (B,) int32, or None (plain)
        self.t0 = t0


class DecodeEngine:
    """The scheduler/batcher.  ``start()`` spawns the decode thread;
    ``submit()`` is thread-safe; ``drain()`` stops admission and waits
    for in-flight work; ``stop()`` is drain + shutdown (hard stop after
    ``drain_timeout_s``, aborted requests recorded as rejections).

    ``draft_model``/``draft_variables`` (required iff
    ``config.spec_k > 0``): the small proposal model for speculative
    decoding — validated shape-compatible HERE, at construction, never
    discovered by the decode thread (the config-time-rejection
    precedent)."""

    def __init__(self, model, variables, config: Optional[ServeConfig] = None,
                 registry: Optional[Registry] = None, draft_model=None,
                 draft_variables=None):
        import jax

        self.model = model
        self.config = config if config is not None else ServeConfig()
        self.registry = registry if registry is not None else Registry()
        self._t = int(model.input_shape[0])
        self._b = int(self.config.slots)
        self._buckets = self.config.resolved_buckets(self._t)
        if self.config.max_new_tokens >= self._t:
            raise ValueError(
                f"max_new_tokens {self.config.max_new_tokens} must be < "
                f"the model's seq_len {self._t}")
        cache = _model_cache(model, self._b)
        if cache is None:
            raise ValueError(
                "the serve engine needs the KV-cached decode path "
                "(init_cache protocol, no mesh-attached attention, no "
                "time-mixing layer without a decode rule) — "
                "models.generation documents the contract")
        out_shape = model.output_shape
        self._vocab = int(out_shape[-1])

        # -- speculative decode (ISSUE 11): draft model, validated now --
        self._spec_k = int(self.config.spec_k)
        self.draft_model = draft_model
        if self._spec_k > 0:
            validate_draft(model, draft_model, draft_variables, self._b,
                           self._spec_k)
            self._draft_variables = jax.tree_util.tree_map(
                jax.numpy.asarray, draft_variables)
        else:
            if draft_model is not None or draft_variables is not None:
                raise ValueError(
                    "draft_model/draft_variables passed but spec_k == 0 "
                    "— speculative decode would silently never run; set "
                    "ServeConfig(spec_k=K) or drop the draft")
            self._draft_variables = None

        #: variables live on device once — per-call host->device transfer
        #: of the whole parameter tree would dwarf a decode step
        self._variables = jax.tree_util.tree_map(jax.numpy.asarray,
                                                 variables)

        # device-resident decode state (owned by the decode thread after
        # start(); construction happens-before the thread)
        self._init_state(cache)

        # compiled programs + their retrace sentinels (one per entry
        # point: every bucket join is its own program, so each compiles
        # exactly once and any later signature change is a real retrace)
        self._step_fn = None
        self._join_fns: dict = {}
        self._sjoin_fns: dict = {}
        self._sentinels: dict = {}
        # pre-create the sentinel counters so a snapshot taken before any
        # traffic carries an explicit 0 (a missing metric is only a drift
        # NOTE; a present 0 -> 1 jump is gated)
        self.registry.counter("jit.compiles")
        self.registry.counter("jit.retraces")

        reg = self.registry
        self._h_queue_wait = reg.histogram("serve.queue_wait_seconds",
                                           TIME_BUCKETS)
        self._h_ttft = reg.histogram("serve.ttft_seconds", TIME_BUCKETS)
        self._h_ttft_warm = reg.histogram("serve.ttft_warm_seconds",
                                          TIME_BUCKETS)
        self._h_ttft_cold = reg.histogram("serve.ttft_cold_seconds",
                                          TIME_BUCKETS)
        self._h_per_token = reg.histogram("serve.per_token_seconds",
                                          TIME_BUCKETS)
        self._h_e2e = reg.histogram("serve.e2e_seconds", TIME_BUCKETS)
        self._h_step = reg.histogram("serve.step_seconds", TIME_BUCKETS)
        self._h_host = reg.histogram("serve.host_seconds", TIME_BUCKETS)
        self._h_join = reg.histogram("serve.join_seconds", TIME_BUCKETS)
        self._c_requests = reg.counter("serve.requests")
        self._c_admitted = reg.counter("serve.admitted")
        self._c_completed = reg.counter("serve.completed")
        self._c_tokens = reg.counter("serve.tokens_out")
        self._c_steps = reg.counter("serve.steps")
        self._c_joins = reg.counter("serve.joins")
        self._c_promotions = reg.counter("serve.promotions")
        self._c_rejected = reg.counter("serve.rejected")
        self._c_rej_full = reg.counter("serve.rejected_queue_full")
        self._c_rej_drain = reg.counter("serve.rejected_draining")
        self._c_rej_abort = reg.counter("serve.rejected_aborted")
        self._g_queue = reg.gauge("serve.queue_depth")
        self._g_active = reg.gauge("serve.active_slots")
        # accelerator metrics are ALWAYS pre-created — a disabled
        # engine's snapshot carries explicit zeros, not missing metrics
        # (the drift gate's present-0 contract, and the bench satellite)
        self._c_spec_proposed = reg.counter("serve.spec.proposed")
        self._c_spec_accepted = reg.counter("serve.spec.accepted")
        self._g_accept_rate = reg.gauge("serve.spec.accept_rate")
        for name in ("hits", "misses", "inserts", "remote_inserts",
                     "evictions"):
            reg.counter(f"serve.prefix.{name}")
        reg.gauge("serve.prefix.bytes")
        reg.gauge("serve.prefix.entries")
        self._prefix = None
        if self.config.prefix_cache:
            self._prefix = PrefixCache(
                int(float(self.config.prefix_cache_mb) * 1024 * 1024),
                reg, block=int(self.config.prefix_block))
        #: KV checkpoint version (ISSUE 16): bumped by the DECODE thread
        #: at promotion adoption — the moment the weights that compute
        #: new cache entries actually change — so a fabric export/import
        #: double-reading it around a cache touch can prove which
        #: weight generation an entry belongs to (see kv_export /
        #: serve.kvfabric.admit_remote_entry).  ``_c_promotions`` keeps
        #: its caller-side count-of-promote-calls semantics.
        self._kv_version = 0

        #: admission queue + flags — the ONLY state shared across threads;
        #: every touch goes through _lock (slot table and device state are
        #: decode-thread-private)
        self._queue: collections.deque = collections.deque()
        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        self._draining = False
        self._pending_variables = None
        self._stop_evt = threading.Event()
        self._idle_evt = threading.Event()
        self._idle_evt.set()
        self._slots = [_Slot() for _ in range(self._b)]
        self._thread: Optional[threading.Thread] = None

    # -- device state -------------------------------------------------------
    def _init_state(self, cache=None):
        import jax
        import jax.numpy as jnp

        b, t = self._b, self._t
        self._buf = jnp.zeros((b, t), jnp.int32)
        self._cache = cache if cache is not None \
            else _model_cache(self.model, b)
        self._pos = jnp.zeros((b,), jnp.int32)
        self._logits = jnp.zeros((b, self._vocab), jnp.float32)
        self._rng = jax.random.PRNGKey(int(self.config.seed))
        # per-row sampling params (ISSUE 14): decode-thread-private host
        # arrays written at admit, shipped into the step program every
        # dispatch — value changes never re-trace (shape/dtype fixed),
        # so one compiled step serves every request's temperature/top-k/
        # top-p mix.  0 / 0 / 1.0 are the "greedy, unfiltered" encodings
        self._row_temp = np.zeros((b,), np.float32)
        self._row_topk = np.zeros((b,), np.int32)
        self._row_topp = np.ones((b,), np.float32)
        if self._spec_k > 0:
            self._dcache = _model_cache(self.draft_model, b)
            self._dlogits = jnp.zeros((b, self._vocab), jnp.float32)
        else:
            self._dcache = None
            self._dlogits = None

    def _single_row_cache(self, batch_cache):
        """A zeroed single-row, full-length cache tree shaped like one
        row of ``batch_cache`` — the warmup stand-in for a prefix-cache
        entry."""
        import jax
        import jax.numpy as jnp
        return jax.tree_util.tree_map(
            lambda c: jnp.zeros((1,) + c.shape[1:], c.dtype), batch_cache)

    # -- compiled programs --------------------------------------------------
    def _sentinel(self, name: str) -> RetraceSentinel:
        s = self._sentinels.get(name)
        if s is None:
            s = self._sentinels[name] = RetraceSentinel(
                f"serve.{name}", registry=lambda: self.registry)
        return s

    def _scatter_row(self, batch_tree, row_tree, oh):
        """Blend single-row ``row_tree`` (leaves (1, T, ...)) into slot
        ``oh`` (one-hot over B) of ``batch_tree`` — the join scatter."""
        import jax

        def scatter(c, c1):
            ohx = oh.reshape((self._b,) + (1,) * (c.ndim - 1)).astype(
                c.dtype)
            return c * (1 - ohx) + c1.astype(c.dtype) * ohx

        return jax.tree_util.tree_map(scatter, batch_tree, row_tree)

    def _join_fn(self, bucket: int):
        """The bucket's compiled join: single-row prefill of the (1, L)
        padded prompt + scatter into slot ``row`` of the batch state.
        With spec on, the draft prefills alongside; with the prefix
        cache on, the full-length single-row KV (and token row) it just
        computed is RETURNED for the host to cache."""
        import jax
        import jax.numpy as jnp

        fn = self._join_fns.get(bucket)
        if fn is not None:
            return fn
        model, b, t, length_cap = self.model, self._b, self._t, bucket
        draft = self.draft_model if self._spec_k > 0 else None
        capture = self._prefix is not None

        def _prefill_row(layer, params, state, prompt, length, cache):
            """Single-row bucket prefill -> (last logits (1, V),
            full-length row cache tree)."""
            cache1 = layer.init_cache(1, (length_cap,))
            y, cache1 = layer.apply_prefill(params, state, prompt, cache1)
            sel = jax.nn.one_hot(length - 1, length_cap, dtype=y.dtype)
            logits0 = jnp.einsum("btv,t->bv", y, sel)      # (1, V)

            def pad_full(c1, c):
                pad = [(0, 0)] * c1.ndim
                pad[1] = (0, c.shape[1] - c1.shape[1])
                return jnp.pad(c1, pad).astype(c.dtype)

            return logits0, jax.tree_util.tree_map(pad_full, cache1,
                                                   cache)

        def _join(variables, dvariables, buf, cache, pos, logits, dcache,
                  dlogits, prompt, length, row):
            params, state = variables["params"], variables["state"]
            logits0, c1p = _prefill_row(model.layer, params, state,
                                        prompt, length, cache)
            oh = jax.nn.one_hot(row, b)                     # (B,) float
            is_row = jnp.arange(b) == row
            cache = self._scatter_row(cache, c1p, oh)
            prow = jnp.zeros((t,), jnp.int32).at[:length_cap].set(prompt[0])
            ohi = oh.astype(jnp.int32)[:, None]
            buf = buf * (1 - ohi) + prow[None, :] * ohi
            pos = jnp.where(is_row, length, pos)
            logits = jnp.where(is_row[:, None],
                               logits0.astype(logits.dtype), logits)
            outs = [buf, cache, pos, logits]
            dc1p = None
            if draft is not None:
                dlogits0, dc1p = _prefill_row(
                    draft.layer, dvariables["params"],
                    dvariables["state"], prompt, length, dcache)
                outs += [self._scatter_row(dcache, dc1p, oh),
                         jnp.where(is_row[:, None],
                                   dlogits0.astype(dlogits.dtype),
                                   dlogits)]
            if capture:
                outs += [prow[None, :], c1p]
                if draft is not None:
                    outs.append(dc1p)
            return tuple(outs)

        fn = self._join_fns[bucket] = jax.jit(_join)
        return fn

    def _sjoin_fn(self, bucket: int):
        """The suffix bucket's compiled warm join (prefix cache on):
        re-play the (1, S) padded suffix over a cached single-row prefix
        KV with a ``decode_window``, then the same scatter the cold join
        does.  The advanced row cache (now prefix + suffix) is returned
        for the host to cache under the full prompt."""
        import jax
        import jax.numpy as jnp

        fn = self._sjoin_fns.get(bucket)
        if fn is not None:
            return fn
        model, b, t, s_cap = self.model, self._b, self._t, bucket
        draft = self.draft_model if self._spec_k > 0 else None

        def _replay(layer, params, state, suffix, slen, pcache, plen):
            win, pcache2 = decode_window(layer, params, state, suffix,
                                         pcache, plen, limit=t)
            sel = jax.nn.one_hot(slen - 1, s_cap, dtype=win.dtype)
            return jnp.einsum("bsv,s->bv", win, sel), pcache2

        def _sjoin(variables, dvariables, buf, cache, pos, logits,
                   dcache, dlogits, ptoks, pcache, pdcache, plen, suffix,
                   slen, row):
            params, state = variables["params"], variables["state"]
            logits0, pcache2 = _replay(model.layer, params, state,
                                       suffix, slen, pcache, plen)
            # token row: the cached prefix row with the suffix written at
            # plen .. plen+slen-1 (padded suffix positions masked out)
            sidx = jnp.arange(s_cap)
            wmat = jax.nn.one_hot(plen + sidx, t, dtype=jnp.int32) * \
                (sidx < slen)[:, None].astype(jnp.int32)    # (S, T)
            mask = wmat.sum(0)
            prow = ptoks[0] * (1 - mask) + \
                (suffix[0][:, None] * wmat).sum(0)
            oh = jax.nn.one_hot(row, b)
            is_row = jnp.arange(b) == row
            cache = self._scatter_row(cache, pcache2, oh)
            ohi = oh.astype(jnp.int32)[:, None]
            buf = buf * (1 - ohi) + prow[None, :] * ohi
            pos = jnp.where(is_row, plen + slen, pos)
            logits = jnp.where(is_row[:, None],
                               logits0.astype(logits.dtype), logits)
            outs = [buf, cache, pos, logits]
            pdcache2 = None
            if draft is not None:
                dlogits0, pdcache2 = _replay(
                    draft.layer, dvariables["params"],
                    dvariables["state"], suffix, slen, pdcache, plen)
                outs += [self._scatter_row(dcache, pdcache2, oh),
                         jnp.where(is_row[:, None],
                                   dlogits0.astype(dlogits.dtype),
                                   dlogits)]
            outs += [prow[None, :], pcache2]
            if draft is not None:
                outs.append(pdcache2)
            return tuple(outs)

        fn = self._sjoin_fns[bucket] = jax.jit(_sjoin)
        return fn

    def _build_step(self):
        """The per-dispatch decode program.  Plain mode: every ACTIVE
        row samples its next token from the carried logits, writes it at
        its own position, and runs one cached decode forward; inactive
        rows are masked no-ops (their state is replaced wholesale at
        join).  Spec mode (``spec_k > 0``): the draft-propose /
        target-verify window from ``serve/spec.py`` — up to k+1 tokens
        per row per dispatch."""
        import jax
        import jax.numpy as jnp

        if self._step_fn is not None:
            return self._step_fn
        if self._spec_k > 0:
            self._step_fn = jax.jit(build_spec_step(
                self.model, self.draft_model, self._spec_k))
            return self._step_fn
        model, t = self.model, self._t

        def _step(variables, buf, cache, pos, logits, active, temp,
                  topk, topp, rng):
            from jax import lax
            params, state = variables["params"], variables["state"]
            rng, sub = jax.random.split(rng)
            # per-row sampling (ISSUE 14): temp/topk/topp are TRACED
            # (B,) arrays — rows at temperature 0 take the exact argmax
            # inside sample_rowwise, so greedy parity holds row by row.
            # The sampled branch (two vocab-wide sorts, softmax,
            # categorical) runs only when SOME row actually samples: an
            # all-greedy batch — the default config — stays at the old
            # argmax-only cost through lax.cond, whose traced predicate
            # never re-traces
            nxt = lax.cond(
                jnp.any(jnp.asarray(temp) > 0.0),
                lambda _: sample_rowwise(sub, logits, temp, topk, topp),
                lambda _: jnp.argmax(logits, axis=-1).astype(jnp.int32),
                None)
            mask = active.astype(jnp.int32)
            w = jax.nn.one_hot(pos, t, dtype=jnp.int32) * mask[:, None]
            buf = buf * (1 - w) + nxt[:, None] * w
            # clamp retired rows' positions into range: their decode
            # output is discarded, but the cache scatter must stay
            # in-bounds
            pos_dec = jnp.minimum(pos, t - 1)
            logits2, cache = model.layer.apply_decode(params, state, nxt,
                                                      cache, pos_dec)
            logits = jnp.where(active[:, None],
                               logits2.astype(logits.dtype), logits)
            pos = pos + mask
            return buf, cache, pos, logits, rng, nxt

        self._step_fn = jax.jit(_step)
        return self._step_fn

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "DecodeEngine":
        if self._thread is not None:
            raise RuntimeError("engine already started")
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="serve-decode")
        self._thread.start()
        return self

    def _join_args(self, prompt, length, row):
        """The cold join's observed-arg tuple (everything but the
        variables trees) — ONE builder shared by warmup and _admit, so
        their signatures can never drift apart."""
        args = [self._buf, self._cache, self._pos, self._logits]
        if self._spec_k > 0:
            args += [self._dcache, self._dlogits]
        else:
            args += [None, None]
        return tuple(args) + (prompt, np.int32(length), np.int32(row))

    def _sjoin_args(self, entry_tokens, entry_cache, entry_dcache, plen,
                    suffix, slen, row):
        args = [self._buf, self._cache, self._pos, self._logits]
        if self._spec_k > 0:
            args += [self._dcache, self._dlogits]
        else:
            args += [None, None]
        return tuple(args) + (entry_tokens, entry_cache, entry_dcache,
                              np.int32(plen), suffix, np.int32(slen),
                              np.int32(row))

    def _step_args(self, active):
        sampling = (self._row_temp, self._row_topk, self._row_topp,
                    self._rng)
        if self._spec_k > 0:
            return (self._buf, self._cache, self._dcache, self._pos,
                    self._logits, self._dlogits, active) + sampling
        return (self._buf, self._cache, self._pos, self._logits,
                active) + sampling

    def warmup(self) -> "DecodeEngine":
        """Compile the full program ladder — every bucket's join, every
        suffix bucket's warm join when the prefix cache is on, and the
        (spec) step — against throwaway inputs, then reset the decode
        state: after this, serving traffic never cold-compiles and any
        retrace is a real bucketing bug (``jit.retraces`` stays 0).
        Call before ``start()`` (or at least before admitting
        traffic)."""
        import jax

        last = None
        for bucket in self._buckets:
            prompt = np.zeros((1, bucket), np.int32)
            args = self._join_args(prompt, 1, 0)
            self._sentinel(f"join.l{bucket}").observe(args)
            last = self._join_fn(bucket)(self._variables,
                                         self._draft_variables, *args)
        if self._prefix is not None:
            etoks = np.zeros((1, self._t), np.int32)
            ecache = self._single_row_cache(self._cache)
            edcache = self._single_row_cache(self._dcache) \
                if self._spec_k > 0 else None
            for bucket in self._buckets:
                suffix = np.zeros((1, bucket), np.int32)
                args = self._sjoin_args(etoks, ecache, edcache, 1,
                                        suffix, 1, 0)
                self._sentinel(f"sjoin.s{bucket}").observe(args)
                last = self._sjoin_fn(bucket)(
                    self._variables, self._draft_variables, *args)
        active = np.zeros((self._b,), bool)
        args = self._step_args(active)
        name = "spec_step" if self._spec_k > 0 else "step"
        self._sentinel(name).observe(args)
        if self._spec_k > 0:
            last = self._build_step()(self._variables,
                                      self._draft_variables, *args)
        else:
            last = self._build_step()(self._variables, *args)
        jax.block_until_ready(last[0])
        self._init_state()
        return self

    def stop(self, drain: bool = True,
             timeout: Optional[float] = None) -> None:
        """Shut the engine down.  ``drain=True`` (default) completes
        queued + in-flight requests first (bounded by ``timeout`` /
        ``drain_timeout_s``); anything still outstanding afterwards —
        or everything, with ``drain=False`` — is aborted with a recorded
        rejection."""
        if drain:
            self.drain(timeout=timeout)
        else:
            with self._lock:
                self._draining = True
        self._stop_evt.set()
        with self._lock:
            self._work.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=10)
        self._abort_outstanding("aborted: engine stopped")

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Stop admitting, wait for queue + slots to empty.  Returns True
        when fully drained within the timeout."""
        with self._lock:
            self._draining = True
            self._work.notify_all()
        timeout = self.config.drain_timeout_s if timeout is None \
            else float(timeout)
        return self._idle_evt.wait(timeout)

    def undrain(self) -> bool:
        """Re-open admission on a drained-but-running engine — the
        scale-UP primitive (ISSUE 17).  A drained engine keeps its
        decode thread, warm-compiled functions, and KV cache parked;
        un-draining it costs one flag flip, not a recompile — which is
        what lets the autoscaler's fleet keep ``jit.retraces == 0``
        across its whole scaling history.  Raises ``RuntimeError`` on a
        STOPPED engine (its decode thread is gone; only ``start`` on a
        fresh engine can serve again)."""
        if self._stop_evt.is_set() or (
                self._thread is not None and not self._thread.is_alive()):
            raise RuntimeError("cannot undrain a stopped engine")
        with self._lock:
            was = self._draining
            self._draining = False
            self._work.notify_all()
        if was:
            get_logger(_LOG).info("engine un-drained: admission reopened")
        return was

    def _abort_outstanding(self, reason: str) -> None:
        """Fail every request still queued or in a slot (post-stop): each
        is recorded under ``serve.rejected`` — the no-silent-drop
        contract.  The queue drains under the lock (atomic against a
        concurrent pop); the slot table is touched only when the decode
        thread is THIS thread (the crash handler) or provably dead — a
        join that timed out must not race slot writes against a decode
        thread still finishing a long step."""
        with self._lock:
            stranded = list(self._queue)
            self._queue.clear()
            self._g_queue.set(0)
        own_slots = self._thread is None \
            or self._thread is threading.current_thread() \
            or not self._thread.is_alive()
        if own_slots:
            for slot in self._slots:
                if slot.request is not None:
                    stranded.append(slot.request)
                    slot.request = None
        else:
            get_logger(_LOG).warning(
                "decode thread still running after stop timeout; leaving "
                "in-slot requests to it (queued requests aborted)")
        for req in stranded:
            self._c_rejected.inc()
            self._c_rej_abort.inc()
            req.error = reason
            req.done_t = time.perf_counter()
            req._done.set()
        if stranded:
            get_logger(_LOG).warning(
                "engine stop aborted %d outstanding request(s) "
                "(recorded under serve.rejected)", len(stranded))

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # -- checkpoint promotion (the online-learning "deploy" seam) -----------
    def promote(self, variables) -> None:
        """Swap the serving weights — checkpoint promotion, the seam the
        continual-training loop "deploys" through (ISSUE 8: gated on
        drift-clean windows by ``continual.DeployGate``).  The decode
        thread adopts the new tree at its next loop turn; shapes must
        match the current model, so no program re-traces, and in-flight
        requests simply continue under the promoted weights
        (online-learning semantics — a request is not a consistency
        domain here).

        **The prefix cache is flushed**: cached KV is a pure function of
        (tokens, weights), so every entry is stale under the promoted
        checkpoint.  Flushed here AND again when the decode thread
        adopts the tree — an admit racing between the two could insert
        one more old-weight entry, and the adoption-time flush drops it.

        The tree is validated HERE, on the caller's thread: a promote
        that would change the compiled programs' signatures (structure /
        leaf shape / dtype — e.g. a wire-shipped tree for a different
        model) raises ``ValueError`` to the caller (the ``promote`` RPC
        answers an error) instead of crashing the decode loop, whose
        death would strand every in-flight request."""
        import jax
        new = jax.tree_util.tree_map(jax.numpy.asarray, variables)
        cur = self._variables
        if jax.tree_util.tree_structure(new) != \
                jax.tree_util.tree_structure(cur):
            raise ValueError(
                "promoted variables tree structure does not match the "
                "serving model's")
        bad = [f"{getattr(n, 'shape', ())}/{getattr(n, 'dtype', '?')} != "
               f"{c.shape}/{c.dtype}"
               for n, c in zip(jax.tree_util.tree_leaves(new),
                               jax.tree_util.tree_leaves(cur))
               if getattr(n, "shape", None) != c.shape
               or getattr(n, "dtype", None) != c.dtype]
        if bad:
            raise ValueError(
                f"promoted variables would re-trace the decode programs "
                f"(leaf shape/dtype mismatch: {'; '.join(bad[:3])}"
                f"{' ...' if len(bad) > 3 else ''})")
        # flush BEFORE publishing: were the order reversed, the decode
        # thread could adopt + flush + insert a valid NEW-weight entry
        # in the window before this thread's flush, which would then
        # drop it — old-weight entries inserted in the remaining window
        # die at the adoption-time flush instead
        if self._prefix is not None:
            self._prefix.flush()
        with self._lock:
            self._pending_variables = new
            self._work.notify_all()
        self._c_promotions.inc()

    def _adopt_promotion(self) -> None:
        with self._lock:
            new = self._pending_variables
            self._pending_variables = None
        if new is not None:
            if self._prefix is not None:
                # close the promote()-to-adoption race: any entry a
                # concurrent admit inserted under the OLD weights after
                # the caller-side flush dies here, before the new
                # weights serve a single token
                self._prefix.flush()
            # flush -> bump -> swap, all on the decode thread (the only
            # inserter), is what makes the KV version stamp exact
            # (ISSUE 16): an entry visible while _kv_version reads v was
            # inserted before this flush under the OLD weights (gen v);
            # one visible after the bump was inserted after the swap
            # under the NEW weights (gen v+1) — no interleaving can put
            # an insert between these three statements.  kv_export's
            # read-version / peek / re-read-version sequence (and the
            # fabric's check-insert-recheck) therefore refuses every
            # cross-generation race instead of mis-stamping it.
            self._kv_version += 1
            self._variables = new

    # -- KV fabric (ISSUE 16): cached prefix KV as a fleet resource ---------
    @property
    def kv_version(self) -> int:
        """The serving checkpoint generation KV transfers are stamped
        with — bumped at promotion ADOPTION, the moment newly inserted
        cache entries start being computed under the new weights (see
        ``_adopt_promotion``)."""
        return int(self._kv_version)

    def _entry_doc(self, entry: PrefixEntry) -> dict:
        """One cache entry as a host-side wire document (device -> host
        readback; the arrays ride the v2 zero-copy tensor frames)."""
        import jax
        doc = {"host_tokens": np.asarray(entry.host_tokens, np.int32),
               "cache": jax.tree_util.tree_map(np.asarray, entry.cache)}
        if entry.draft_cache is not None:
            doc["draft_cache"] = jax.tree_util.tree_map(
                np.asarray, entry.draft_cache)
        return doc

    def kv_export(self, prompt) -> Optional[dict]:
        """The longest cached prefix entry for ``prompt`` as a wire doc
        ``{"entries": [...], "version": v}`` — what the ``kv_fetch`` RPC
        answers a replication-on-spill request with.  Returns ``None``
        when the cache is off/cold for this prompt, or when a promotion
        raced the export: the version is read before AND after the cache
        probe, and a mismatch means the probed entry's weight generation
        is ambiguous — refusing to ship it is the conservative side of
        the never-join-stale-KV contract."""
        if self._prefix is None:
            return None
        v0 = self._kv_version
        hit = self._prefix.peek(np.asarray(prompt, np.int32).reshape(-1))
        if hit is None:
            return None
        entry, _ = hit
        doc = {"entries": [self._entry_doc(entry)], "version": int(v0)}
        if self._kv_version != v0:
            return None
        return doc

    def kv_export_hottest(self, max_entries: int,
                          budget_bytes: int) -> Optional[dict]:
        """The MRU-side working set as a wire doc — what a draining /
        soon-to-be-evicted engine answers a migration ``kv_fetch`` with
        (hottest first, entry- and byte-bounded by the CALLER's budget).
        Same double-read promotion refusal as :meth:`kv_export`."""
        if self._prefix is None:
            return None
        v0 = self._kv_version
        entries = self._prefix.hottest(max_entries, budget_bytes)
        if not entries:
            return None
        doc = {"entries": [self._entry_doc(e) for e in entries],
               "version": int(v0)}
        if self._kv_version != v0:
            return None
        return doc

    def kv_import(self, doc: dict, version: int) -> Tuple[bool, str]:
        """Admit ONE peer-exported cache entry (an ``_entry_doc``)
        stamped with checkpoint ``version``; returns ``(joined,
        reason)``.  Validation mirrors ``promote()``'s caller-thread
        discipline: tree leaves are checked against this engine's own
        single-row cache template HERE, so the decode thread can never
        trip over a foreign-model tree.  The stale-version refusal
        itself (checked before and after the insert) lives in the
        ``serve.kvfabric`` seam — the only legitimate ``insert_remote``
        caller (dklint rule 9, ``kv-version-guard``)."""
        import jax
        import jax.numpy as jnp
        from .kvfabric import admit_remote_entry

        if self._prefix is None:
            return False, "prefix cache disabled"
        # copy out of the receive arena: a retained view would pin the
        # pooled multi-MB buffer for the lifetime of the cache entry
        host_tokens = np.array(doc.get("host_tokens"),
                               np.int32).reshape(-1)
        length = int(host_tokens.shape[0])
        if not 1 <= length <= self._t:
            return False, f"entry length {length} outside [1, {self._t}]"

        def _device_tree(got, template, what):
            tleaves, tdef = jax.tree_util.tree_flatten(template)
            leaves = [np.asarray(leaf) for leaf in
                      jax.tree_util.tree_leaves(got)]
            if len(leaves) != len(tleaves):
                raise ValueError(f"{what}: {len(leaves)} leaves != "
                                 f"{len(tleaves)} expected")
            bad = [f"{g.shape}/{g.dtype} != {t.shape}/{t.dtype}"
                   for g, t in zip(leaves, tleaves)
                   if g.shape != t.shape or g.dtype != t.dtype]
            if bad:
                raise ValueError(f"{what} leaf mismatch: "
                                 f"{'; '.join(bad[:3])}"
                                 f"{' ...' if len(bad) > 3 else ''}")
            return jax.tree_util.tree_unflatten(
                tdef, [jnp.asarray(leaf) for leaf in leaves])

        try:
            cache = _device_tree(doc.get("cache"),
                                 self._single_row_cache(self._cache),
                                 "cache")
            if self._spec_k > 0:
                if doc.get("draft_cache") is None:
                    return False, "draft cache missing (spec_k > 0)"
                draft_cache = _device_tree(
                    doc.get("draft_cache"),
                    self._single_row_cache(self._dcache), "draft cache")
            else:
                draft_cache = None
        except (ValueError, TypeError) as e:
            return False, str(e)
        tokens = np.zeros((1, self._t), np.int32)
        tokens[0, :length] = host_tokens
        entry = PrefixEntry(host_tokens, jnp.asarray(tokens), cache,
                            draft_cache)
        return admit_remote_entry(self, entry, int(version))

    # -- admission ----------------------------------------------------------
    def submit(self, prompt, max_new_tokens: Optional[int] = None,
               temperature: Optional[float] = None,
               top_k: Optional[int] = None,
               top_p: Optional[float] = None) -> ServeRequest:
        """Queue one generation request.  Raises ``ValueError`` for
        malformed requests (client error) and ``ServeRejected`` when the
        admission controller load-sheds (queue full / draining).

        ``temperature`` / ``top_k`` / ``top_p`` override the engine
        defaults PER REQUEST (ISSUE 14): the params ride into the one
        compiled step program as per-row device values, so any mix of
        greedy and sampled requests shares a batch without re-tracing —
        ``jit.retraces`` stays 0."""
        self._c_requests.inc()
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.shape[0] < 1:
            raise ValueError("prompt must hold at least one token")
        max_new = self.config.max_new_tokens if max_new_tokens is None \
            else int(max_new_tokens)
        if not 1 <= max_new <= self.config.max_new_tokens:
            raise ValueError(
                f"max_new_tokens must lie in [1, "
                f"{self.config.max_new_tokens}], got {max_new}")
        temperature = float(self.config.temperature) \
            if temperature is None else float(temperature)
        if not temperature >= 0.0:  # not-form: NaN must fail too
            raise ValueError(
                f"temperature must be >= 0, got {temperature}")
        top_k = self.config.top_k if top_k is None else top_k
        top_k = 0 if top_k is None else int(top_k)   # 0 = disabled
        if top_k < 0:
            raise ValueError(
                f"top_k must be >= 0 (0/None disable it), got {top_k}")
        top_p = self.config.top_p if top_p is None else top_p
        top_p = 1.0 if top_p is None else float(top_p)  # 1.0 = disabled
        if not 0.0 < top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {top_p}")
        # validates the prompt fits a bucket too
        self.config.bucket_for(int(prompt.shape[0]), self._t)
        if int(prompt.shape[0]) + max_new > self._t:
            raise ValueError(
                f"prompt length {prompt.shape[0]} + {max_new} new tokens "
                f"exceeds the model's seq_len {self._t}")
        req = ServeRequest(prompt, max_new, temperature=temperature,
                           top_k=top_k, top_p=top_p)
        with self._lock:
            if self._draining:
                self._c_rejected.inc()
                self._c_rej_drain.inc()
                raise ServeRejected("draining")
            if len(self._queue) >= self.config.max_queue:
                self._c_rejected.inc()
                self._c_rej_full.inc()
                raise ServeRejected("queue full")
            self._queue.append(req)
            self._g_queue.set(len(self._queue))
            self._idle_evt.clear()
            self._work.notify_all()
        return req

    # -- decode loop --------------------------------------------------------
    def _free_slot(self) -> Optional[int]:
        for i, slot in enumerate(self._slots):
            if slot.request is None:
                return i
        return None

    def _active_count(self) -> int:
        return sum(1 for s in self._slots if s.request is not None)

    def _adopt_state(self, outs, capture: bool):
        """Unpack a join program's outputs into the engine state and
        return the captured prefix entry arrays (or None)."""
        self._buf, self._cache, self._pos, self._logits = outs[:4]
        n = 4
        if self._spec_k > 0:
            self._dcache, self._dlogits = outs[4:6]
            n = 6
        if not capture:
            return None
        etoks, ecache = outs[n], outs[n + 1]
        edcache = outs[n + 2] if self._spec_k > 0 else None
        return etoks, ecache, edcache

    def _join_cold(self, req: ServeRequest, row: int):
        bucket = self.config.bucket_for(req.length, self._t)
        prompt = np.zeros((1, bucket), np.int32)
        prompt[0, :req.length] = req.prompt
        args = self._join_args(prompt, req.length, row)
        self._sentinel(f"join.l{bucket}").observe(args)
        outs = self._join_fn(bucket)(self._variables,
                                     self._draft_variables, *args)
        return self._adopt_state(outs, self._prefix is not None)

    def _join_warm(self, req: ServeRequest, row: int,
                   entry: PrefixEntry, plen: int):
        s = req.length - plen
        bucket = self.config.bucket_for(s, self._t)
        suffix = np.zeros((1, bucket), np.int32)
        suffix[0, :s] = req.prompt[plen:]
        args = self._sjoin_args(entry.tokens, entry.cache,
                                entry.draft_cache, plen, suffix, s, row)
        self._sentinel(f"sjoin.s{bucket}").observe(args)
        outs = self._sjoin_fn(bucket)(self._variables,
                                      self._draft_variables, *args)
        return self._adopt_state(outs, True)

    def _admit(self) -> int:
        """Move queued requests into free slots (prefill + scatter — or,
        on a prefix-cache hit, a suffix re-play over the cached KV).
        Decode-thread only; the queue pop is the one locked touch."""
        admitted = 0
        while True:
            row = self._free_slot()
            if row is None:
                return admitted
            with self._lock:
                if not self._queue:
                    return admitted
                req = self._queue.popleft()
                self._g_queue.set(len(self._queue))
            req.admit_t = time.perf_counter()
            self._h_queue_wait.observe(req.admit_t - req.submit_t)
            t0 = time.perf_counter()
            if self._prefix is not None:
                hit = self._prefix.lookup(req.prompt)
                if hit is not None:
                    req.warm = True
                    captured = self._join_warm(req, row, *hit)
                else:
                    req.warm = False
                    captured = self._join_cold(req, row)
                if captured is not None:
                    self._prefix.insert(PrefixEntry(req.prompt, *captured))
            else:
                self._join_cold(req, row)
            self._h_join.observe(time.perf_counter() - t0)
            # the row adopts the request's sampling params (decode-
            # thread-private arrays, shipped into every step dispatch)
            self._row_temp[row] = req.temperature
            self._row_topk[row] = req.top_k
            self._row_topp[row] = req.top_p
            self._slots[row].request = req
            self._c_admitted.inc()
            self._c_joins.inc()
            admitted += 1
            self._g_active.set(self._active_count())

    def _finish(self, row: int, now: float) -> None:
        slot = self._slots[row]
        req = slot.request
        slot.request = None
        req.done_t = now
        self._c_completed.inc()
        self._h_e2e.observe(now - req.submit_t)
        req._done.set()

    def _dispatch_step(self) -> _Pending:
        """Dispatch ONE device step (plain or speculative) and return
        the pending handle — no host readback here; that happens in
        ``_retire_step``, overlapped with the NEXT dispatched step."""
        active = np.array([s.request is not None for s in self._slots],
                          bool)
        reqs = [s.request for s in self._slots]
        t0 = time.perf_counter()
        args = self._step_args(active)
        if self._spec_k > 0:
            self._sentinel("spec_step").observe(args)
            (self._buf, self._cache, self._dcache, self._pos,
             self._logits, self._dlogits, self._rng, tokens, counts) = \
                self._build_step()(self._variables,
                                   self._draft_variables, *args)
        else:
            self._sentinel("step").observe(args)
            (self._buf, self._cache, self._pos, self._logits, self._rng,
             tokens) = self._build_step()(self._variables, *args)
            counts = None
        return _Pending(reqs, tokens, counts, t0)

    def _drain_certain(self, pending: Optional[_Pending]) -> bool:
        """True when the un-retired ``pending`` step is guaranteed to
        retire EVERY currently-active row (each was in the pending
        snapshot and needs at most the one token every step is
        guaranteed to emit), so dispatching another step now would be
        pure waste — its outputs discarded row-by-row by the snapshot
        check.  Host-side knowledge only: eos can finish a row early
        but never makes this True spuriously."""
        if pending is None:
            return False
        for row, slot in enumerate(self._slots):
            req = slot.request
            if req is None:
                continue
            if pending.reqs[row] is not req or \
                    len(req.tokens) + 1 < req.max_new:
                return False
        return True

    def _retire_step(self, pending: _Pending) -> None:
        """Host bookkeeping for a previously dispatched step: block on
        its outputs, attribute tokens via the dispatch-time snapshot
        (a row that retired or re-joined since the dispatch is skipped),
        stamp SLOs, retire finished rows.

        ``dt`` below is the step's dispatch->retire wall: one loop
        iteration under dispatch-ahead, so it includes the overlapped
        host work between the two points (see the module docstring) —
        step cadence, not isolated device time."""
        tokens = np.asarray(pending.tokens)    # the per-step readback
        counts = None if pending.counts is None \
            else np.asarray(pending.counts)
        now = time.perf_counter()
        dt = now - pending.t0
        self._h_step.observe(dt)
        self._c_steps.inc()
        eos = self.config.eos_id
        k = self._spec_k
        for row, req in enumerate(pending.reqs):
            if req is None or req.done:
                continue
            if counts is None:
                emitted = [int(tokens[row])]
            else:
                emitted = [int(v) for v in tokens[row, :int(counts[row])]]
                self._c_spec_proposed.inc(k)
                self._c_spec_accepted.inc(int(counts[row]) - 1)
            for tok in emitted:
                req.tokens.append(tok)
                self._c_tokens.inc()
                self._h_per_token.observe(dt)
                if req.first_token_t is None:
                    req.first_token_t = now
                    self._h_ttft.observe(now - req.submit_t)
                    if req.warm is True:
                        self._h_ttft_warm.observe(now - req.submit_t)
                    elif req.warm is False:
                        self._h_ttft_cold.observe(now - req.submit_t)
                if len(req.tokens) >= req.max_new or \
                        (eos is not None and tok == int(eos)):
                    # tokens past the stop condition (possible inside a
                    # speculative window) are discarded — the slot's
                    # device state is replaced wholesale at re-join
                    self._finish(row, now)
                    break
        if counts is not None:
            prop = self._c_spec_proposed.value
            if prop:
                self._g_accept_rate.set(
                    self._c_spec_accepted.value / prop)
        self._g_active.set(self._active_count())
        self._h_host.observe(time.perf_counter() - now)

    def _loop(self) -> None:
        pending: Optional[_Pending] = None
        try:
            while True:
                # a hard stop (stop(drain=False)) exits immediately; the
                # graceful path only sets the stop event once drained, so
                # queued + in-flight work always finishes first.  The loop
                # aborts its own slots on the way out — it is the slot
                # owner, so this cannot race a step in progress
                if self._stop_evt.is_set():
                    self._abort_outstanding("aborted: engine stopped")
                    return
                self._adopt_promotion()
                self._admit()
                # dispatch-ahead: device step k+1 goes out BEFORE step
                # k's host bookkeeping, so detokenize/retire/SLO work
                # overlaps the in-flight device step.  Exception: when
                # step k is certain to drain the whole batch, step k+1
                # would be dispatched only to be discarded — skip it
                nxt = self._dispatch_step() \
                    if self._active_count() and \
                    not self._drain_certain(pending) else None
                if pending is not None:
                    self._retire_step(pending)
                pending = nxt
                if pending is not None:
                    continue
                with self._lock:
                    if self._queue:
                        continue
                    self._idle_evt.set()
                    self._work.wait(_IDLE_WAIT_S)
        except Exception:
            # a dead decode thread must not strand waiters on requests
            # that will never complete: fail them loudly as rejections
            get_logger(_LOG).exception("decode loop crashed; aborting "
                                       "outstanding requests")
            with self._lock:
                self._draining = True
            self._idle_evt.set()
            self._abort_outstanding("aborted: decode loop crashed")

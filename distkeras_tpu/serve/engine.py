"""Continuous-batching decode engine (ISSUE 7 tentpole).

One decode state for ``slots`` concurrent requests — buffer (B, T),
KV cache (B rows), per-row position/logits — advanced one token per
``step`` for every ACTIVE row, exactly the ragged per-row read/write
machinery ``models.generation`` already compiles (one-hot position
writes, (B,) cache positions).  A new request does not wait for the
batch to finish: a **join** program prefills the prompt at its length
bucket and scatters the row (buffer, padded cache, position, first-token
logits) into a retired slot while the other rows keep decoding.

Three compiled-program families, all static-shaped by construction:

* ``serve.join.l<L>`` — per prefill bucket L: single-row prefill of the
  (1, L) padded prompt + one-hot scatter into slot ``row``.
* ``serve.step`` — sample every active row's next token from its carried
  logits, write it at the row's own position, one cached decode forward
  for the next position's logits.  Inactive rows are masked no-ops.
* Each program sits behind its own ``RetraceSentinel``
  (``jit.compiles``/``jit.retraces`` in the service registry) — after
  ``warmup()`` compiles the full bucket ladder, steady-state serving is
  provably ``jit.retraces == 0`` (the drift-gated serving contract).

Scheduling is host-side and single-threaded: one decode thread owns the
device state and the slot table; ``submit()`` (any thread) only touches
the bounded admission queue.  SLO surface, all in the service registry:
``serve.queue_wait_seconds`` (submit -> slot), ``serve.ttft_seconds``
(submit -> first token), ``serve.per_token_seconds`` (each emitted
token's step wall), ``serve.e2e_seconds`` (submit -> done),
``serve.step_seconds``, counters ``serve.requests`` / ``serve.admitted``
/ ``serve.completed`` / ``serve.tokens_out`` / ``serve.rejected`` (split
by reason), gauges ``serve.queue_depth`` / ``serve.active_slots``.

Admission control: a full queue (or a draining engine) load-sheds with
``ServeRejected`` — every request either completes or is recorded under
``serve.rejected``; nothing drops silently (the graceful-drain
contract, including hard-stop aborts).
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Optional

import numpy as np

from ..obs import Registry, TIME_BUCKETS
from ..obs.logging import get_logger
from ..obs.profile import RetraceSentinel
from ..models.generation import _filter_logits, _model_cache
from .config import ServeConfig

_LOG = "serve.engine"

#: decode-thread wait quantum while idle (seconds) — submissions notify
#: the condition, so this only bounds shutdown latency
_IDLE_WAIT_S = 0.05


class ServeRejected(Exception):
    """A request the admission controller load-shed (queue full /
    draining / aborted by a hard stop).  ``reason`` names which."""

    def __init__(self, reason: str):
        super().__init__(f"request rejected: {reason}")
        self.reason = reason


class ServeRequest:
    """One in-flight generation: the handle ``submit()`` returns.

    ``wait(timeout)`` blocks until completion; ``result()`` returns the
    GENERATED token ids (eos included when sampled) as int32, raising
    ``ServeRejected`` if the engine aborted the request mid-flight."""

    __slots__ = ("prompt", "length", "max_new", "tokens", "error",
                 "submit_t", "admit_t", "first_token_t", "done_t",
                 "_done")

    def __init__(self, prompt: np.ndarray, max_new: int):
        self.prompt = prompt
        self.length = int(prompt.shape[0])
        self.max_new = int(max_new)
        self.tokens: list = []
        self.error: Optional[str] = None
        self.submit_t = time.perf_counter()
        self.admit_t: Optional[float] = None
        self.first_token_t: Optional[float] = None
        self.done_t: Optional[float] = None
        self._done = threading.Event()

    @property
    def done(self) -> bool:
        return self._done.is_set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._done.wait(timeout)

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        if not self._done.wait(timeout):
            raise TimeoutError("request not complete")
        if self.error is not None:
            raise ServeRejected(self.error)
        return np.asarray(self.tokens, np.int32)


class _Slot:
    """Decode-thread-private per-row bookkeeping (no locking: one owner)."""

    __slots__ = ("request",)

    def __init__(self):
        self.request: Optional[ServeRequest] = None


class DecodeEngine:
    """The scheduler/batcher.  ``start()`` spawns the decode thread;
    ``submit()`` is thread-safe; ``drain()`` stops admission and waits
    for in-flight work; ``stop()`` is drain + shutdown (hard stop after
    ``drain_timeout_s``, aborted requests recorded as rejections)."""

    def __init__(self, model, variables, config: Optional[ServeConfig] = None,
                 registry: Optional[Registry] = None):
        import jax

        self.model = model
        self.config = config if config is not None else ServeConfig()
        self.registry = registry if registry is not None else Registry()
        self._t = int(model.input_shape[0])
        self._b = int(self.config.slots)
        self._buckets = self.config.resolved_buckets(self._t)
        if self.config.max_new_tokens >= self._t:
            raise ValueError(
                f"max_new_tokens {self.config.max_new_tokens} must be < "
                f"the model's seq_len {self._t}")
        cache = _model_cache(model, self._b)
        if cache is None:
            raise ValueError(
                "the serve engine needs the KV-cached decode path "
                "(init_cache protocol, no mesh-attached attention, no "
                "time-mixing layer without a decode rule) — "
                "models.generation documents the contract")
        out_shape = model.output_shape
        self._vocab = int(out_shape[-1])

        #: variables live on device once — per-call host->device transfer
        #: of the whole parameter tree would dwarf a decode step
        self._variables = jax.tree_util.tree_map(jax.numpy.asarray,
                                                 variables)

        # device-resident decode state (owned by the decode thread after
        # start(); construction happens-before the thread)
        self._init_state(cache)

        # compiled programs + their retrace sentinels (one per entry
        # point: every bucket join is its own program, so each compiles
        # exactly once and any later signature change is a real retrace)
        self._step_fn = None
        self._join_fns: dict = {}
        self._sentinels: dict = {}
        # pre-create the sentinel counters so a snapshot taken before any
        # traffic carries an explicit 0 (a missing metric is only a drift
        # NOTE; a present 0 -> 1 jump is gated)
        self.registry.counter("jit.compiles")
        self.registry.counter("jit.retraces")

        reg = self.registry
        self._h_queue_wait = reg.histogram("serve.queue_wait_seconds",
                                           TIME_BUCKETS)
        self._h_ttft = reg.histogram("serve.ttft_seconds", TIME_BUCKETS)
        self._h_per_token = reg.histogram("serve.per_token_seconds",
                                          TIME_BUCKETS)
        self._h_e2e = reg.histogram("serve.e2e_seconds", TIME_BUCKETS)
        self._h_step = reg.histogram("serve.step_seconds", TIME_BUCKETS)
        self._h_join = reg.histogram("serve.join_seconds", TIME_BUCKETS)
        self._c_requests = reg.counter("serve.requests")
        self._c_admitted = reg.counter("serve.admitted")
        self._c_completed = reg.counter("serve.completed")
        self._c_tokens = reg.counter("serve.tokens_out")
        self._c_steps = reg.counter("serve.steps")
        self._c_joins = reg.counter("serve.joins")
        self._c_promotions = reg.counter("serve.promotions")
        self._c_rejected = reg.counter("serve.rejected")
        self._c_rej_full = reg.counter("serve.rejected_queue_full")
        self._c_rej_drain = reg.counter("serve.rejected_draining")
        self._c_rej_abort = reg.counter("serve.rejected_aborted")
        self._g_queue = reg.gauge("serve.queue_depth")
        self._g_active = reg.gauge("serve.active_slots")

        #: admission queue + flags — the ONLY state shared across threads;
        #: every touch goes through _lock (slot table and device state are
        #: decode-thread-private)
        self._queue: collections.deque = collections.deque()
        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        self._draining = False
        self._pending_variables = None
        self._stop_evt = threading.Event()
        self._idle_evt = threading.Event()
        self._idle_evt.set()
        self._slots = [_Slot() for _ in range(self._b)]
        self._thread: Optional[threading.Thread] = None

    # -- device state -------------------------------------------------------
    def _init_state(self, cache=None):
        import jax
        import jax.numpy as jnp

        b, t = self._b, self._t
        self._buf = jnp.zeros((b, t), jnp.int32)
        self._cache = cache if cache is not None \
            else _model_cache(self.model, b)
        self._pos = jnp.zeros((b,), jnp.int32)
        self._logits = jnp.zeros((b, self._vocab), jnp.float32)
        self._rng = jax.random.PRNGKey(int(self.config.seed))

    # -- compiled programs --------------------------------------------------
    def _sentinel(self, name: str) -> RetraceSentinel:
        s = self._sentinels.get(name)
        if s is None:
            s = self._sentinels[name] = RetraceSentinel(
                f"serve.{name}", registry=lambda: self.registry)
        return s

    def _join_fn(self, bucket: int):
        """The bucket's compiled join: single-row prefill of the (1, L)
        padded prompt + scatter into slot ``row`` of the batch state."""
        import jax
        import jax.numpy as jnp

        fn = self._join_fns.get(bucket)
        if fn is not None:
            return fn
        model, b, t, length_cap = self.model, self._b, self._t, bucket

        def _join(variables, buf, cache, pos, logits, prompt, length, row):
            params, state = variables["params"], variables["state"]
            cache1 = model.layer.init_cache(1, (length_cap,))
            y, cache1 = model.layer.apply_prefill(params, state, prompt,
                                                  cache1)
            sel = jax.nn.one_hot(length - 1, length_cap, dtype=y.dtype)
            logits0 = jnp.einsum("btv,t->bv", y, sel)      # (1, V)

            oh = jax.nn.one_hot(row, b)                     # (B,) float
            is_row = jnp.arange(b) == row

            def scatter(c, c1):
                pad = [(0, 0)] * c1.ndim
                pad[1] = (0, c.shape[1] - c1.shape[1])
                c1p = jnp.pad(c1, pad).astype(c.dtype)
                ohx = oh.reshape((b,) + (1,) * (c.ndim - 1)).astype(c.dtype)
                return c * (1 - ohx) + c1p * ohx

            cache = jax.tree_util.tree_map(scatter, cache, cache1)
            prow = jnp.zeros((t,), jnp.int32).at[:length_cap].set(prompt[0])
            ohi = oh.astype(jnp.int32)[:, None]
            buf = buf * (1 - ohi) + prow[None, :] * ohi
            pos = jnp.where(is_row, length, pos)
            logits = jnp.where(is_row[:, None],
                               logits0.astype(logits.dtype), logits)
            return buf, cache, pos, logits

        fn = self._join_fns[bucket] = jax.jit(_join)
        return fn

    def _build_step(self):
        """One continuous-batching decode step: every ACTIVE row samples
        its next token from the carried logits, writes it at its own
        position, and runs one cached decode forward; inactive rows are
        masked no-ops (their state is replaced wholesale at join)."""
        import jax
        import jax.numpy as jnp

        if self._step_fn is not None:
            return self._step_fn
        model, t = self.model, self._t
        temperature = float(self.config.temperature)
        top_k, top_p = self.config.top_k, self.config.top_p

        def _step(variables, buf, cache, pos, logits, active, rng):
            params, state = variables["params"], variables["state"]
            if temperature > 0.0:
                rng, sub = jax.random.split(rng)
                filtered = _filter_logits(logits / temperature, top_k,
                                          top_p)
                nxt = jax.random.categorical(sub, filtered, axis=-1)
            else:
                nxt = jnp.argmax(logits, axis=-1)
            nxt = nxt.astype(jnp.int32)
            mask = active.astype(jnp.int32)
            w = jax.nn.one_hot(pos, t, dtype=jnp.int32) * mask[:, None]
            buf = buf * (1 - w) + nxt[:, None] * w
            # clamp retired rows' positions into range: their decode
            # output is discarded, but the cache scatter must stay
            # in-bounds
            pos_dec = jnp.minimum(pos, t - 1)
            logits2, cache = model.layer.apply_decode(params, state, nxt,
                                                      cache, pos_dec)
            logits = jnp.where(active[:, None],
                               logits2.astype(logits.dtype), logits)
            pos = pos + mask
            return buf, cache, pos, logits, rng, nxt

        self._step_fn = jax.jit(_step)
        return self._step_fn

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "DecodeEngine":
        if self._thread is not None:
            raise RuntimeError("engine already started")
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="serve-decode")
        self._thread.start()
        return self

    def warmup(self) -> "DecodeEngine":
        """Compile the full program ladder (every bucket's join + the
        step) against throwaway inputs, then reset the decode state —
        after this, serving traffic never cold-compiles and any retrace
        is a real bucketing bug (``jit.retraces`` stays 0).  Call before
        ``start()`` (or at least before admitting traffic)."""
        import jax

        state = (self._buf, self._cache, self._pos, self._logits)
        for bucket in self._buckets:
            prompt = np.zeros((1, bucket), np.int32)
            # observed args must mirror _admit's exactly — a differing
            # signature here would make the first real join a "retrace"
            args = state + (prompt, np.int32(1), np.int32(0))
            self._sentinel(f"join.l{bucket}").observe(args)
            state = self._join_fn(bucket)(self._variables, *args)
        active = np.zeros((self._b,), bool)
        args = state + (active, self._rng)
        self._sentinel("step").observe(args)
        out = self._build_step()(self._variables, *args)
        jax.block_until_ready(out[0])
        self._init_state()
        return self

    def stop(self, drain: bool = True,
             timeout: Optional[float] = None) -> None:
        """Shut the engine down.  ``drain=True`` (default) completes
        queued + in-flight requests first (bounded by ``timeout`` /
        ``drain_timeout_s``); anything still outstanding afterwards —
        or everything, with ``drain=False`` — is aborted with a recorded
        rejection."""
        if drain:
            self.drain(timeout=timeout)
        else:
            with self._lock:
                self._draining = True
        self._stop_evt.set()
        with self._lock:
            self._work.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=10)
        self._abort_outstanding("aborted: engine stopped")

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Stop admitting, wait for queue + slots to empty.  Returns True
        when fully drained within the timeout."""
        with self._lock:
            self._draining = True
            self._work.notify_all()
        timeout = self.config.drain_timeout_s if timeout is None \
            else float(timeout)
        return self._idle_evt.wait(timeout)

    def _abort_outstanding(self, reason: str) -> None:
        """Fail every request still queued or in a slot (post-stop): each
        is recorded under ``serve.rejected`` — the no-silent-drop
        contract.  The queue drains under the lock (atomic against a
        concurrent pop); the slot table is touched only when the decode
        thread is THIS thread (the crash handler) or provably dead — a
        join that timed out must not race slot writes against a decode
        thread still finishing a long step."""
        with self._lock:
            stranded = list(self._queue)
            self._queue.clear()
            self._g_queue.set(0)
        own_slots = self._thread is None \
            or self._thread is threading.current_thread() \
            or not self._thread.is_alive()
        if own_slots:
            for slot in self._slots:
                if slot.request is not None:
                    stranded.append(slot.request)
                    slot.request = None
        else:
            get_logger(_LOG).warning(
                "decode thread still running after stop timeout; leaving "
                "in-slot requests to it (queued requests aborted)")
        for req in stranded:
            self._c_rejected.inc()
            self._c_rej_abort.inc()
            req.error = reason
            req.done_t = time.perf_counter()
            req._done.set()
        if stranded:
            get_logger(_LOG).warning(
                "engine stop aborted %d outstanding request(s) "
                "(recorded under serve.rejected)", len(stranded))

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # -- checkpoint promotion (the online-learning "deploy" seam) -----------
    def promote(self, variables) -> None:
        """Swap the serving weights — checkpoint promotion, the seam the
        continual-training loop "deploys" through (ISSUE 8: gated on
        drift-clean windows by ``continual.DeployGate``).  The decode
        thread adopts the new tree at its next loop turn; shapes must
        match the current model, so no program re-traces, and in-flight
        requests simply continue under the promoted weights
        (online-learning semantics — a request is not a consistency
        domain here).

        The tree is validated HERE, on the caller's thread: a promote
        that would change the compiled programs' signatures (structure /
        leaf shape / dtype — e.g. a wire-shipped tree for a different
        model) raises ``ValueError`` to the caller (the ``promote`` RPC
        answers an error) instead of crashing the decode loop, whose
        death would strand every in-flight request."""
        import jax
        new = jax.tree_util.tree_map(jax.numpy.asarray, variables)
        cur = self._variables
        if jax.tree_util.tree_structure(new) != \
                jax.tree_util.tree_structure(cur):
            raise ValueError(
                "promoted variables tree structure does not match the "
                "serving model's")
        bad = [f"{getattr(n, 'shape', ())}/{getattr(n, 'dtype', '?')} != "
               f"{c.shape}/{c.dtype}"
               for n, c in zip(jax.tree_util.tree_leaves(new),
                               jax.tree_util.tree_leaves(cur))
               if getattr(n, "shape", None) != c.shape
               or getattr(n, "dtype", None) != c.dtype]
        if bad:
            raise ValueError(
                f"promoted variables would re-trace the decode programs "
                f"(leaf shape/dtype mismatch: {'; '.join(bad[:3])}"
                f"{' ...' if len(bad) > 3 else ''})")
        with self._lock:
            self._pending_variables = new
            self._work.notify_all()
        self._c_promotions.inc()

    def _adopt_promotion(self) -> None:
        with self._lock:
            new = self._pending_variables
            self._pending_variables = None
        if new is not None:
            self._variables = new

    # -- admission ----------------------------------------------------------
    def submit(self, prompt, max_new_tokens: Optional[int] = None
               ) -> ServeRequest:
        """Queue one generation request.  Raises ``ValueError`` for
        malformed requests (client error) and ``ServeRejected`` when the
        admission controller load-sheds (queue full / draining)."""
        self._c_requests.inc()
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.shape[0] < 1:
            raise ValueError("prompt must hold at least one token")
        max_new = self.config.max_new_tokens if max_new_tokens is None \
            else int(max_new_tokens)
        if not 1 <= max_new <= self.config.max_new_tokens:
            raise ValueError(
                f"max_new_tokens must lie in [1, "
                f"{self.config.max_new_tokens}], got {max_new}")
        # validates the prompt fits a bucket too
        self.config.bucket_for(int(prompt.shape[0]), self._t)
        if int(prompt.shape[0]) + max_new > self._t:
            raise ValueError(
                f"prompt length {prompt.shape[0]} + {max_new} new tokens "
                f"exceeds the model's seq_len {self._t}")
        req = ServeRequest(prompt, max_new)
        with self._lock:
            if self._draining:
                self._c_rejected.inc()
                self._c_rej_drain.inc()
                raise ServeRejected("draining")
            if len(self._queue) >= self.config.max_queue:
                self._c_rejected.inc()
                self._c_rej_full.inc()
                raise ServeRejected("queue full")
            self._queue.append(req)
            self._g_queue.set(len(self._queue))
            self._idle_evt.clear()
            self._work.notify_all()
        return req

    # -- decode loop --------------------------------------------------------
    def _free_slot(self) -> Optional[int]:
        for i, slot in enumerate(self._slots):
            if slot.request is None:
                return i
        return None

    def _active_count(self) -> int:
        return sum(1 for s in self._slots if s.request is not None)

    def _admit(self) -> int:
        """Move queued requests into free slots (prefill + scatter).
        Decode-thread only; the queue pop is the one locked touch."""
        admitted = 0
        while True:
            row = self._free_slot()
            if row is None:
                return admitted
            with self._lock:
                if not self._queue:
                    return admitted
                req = self._queue.popleft()
                self._g_queue.set(len(self._queue))
            req.admit_t = time.perf_counter()
            self._h_queue_wait.observe(req.admit_t - req.submit_t)
            bucket = self.config.bucket_for(req.length, self._t)
            prompt = np.zeros((1, bucket), np.int32)
            prompt[0, :req.length] = req.prompt
            t0 = time.perf_counter()
            self._sentinel(f"join.l{bucket}").observe(
                (self._buf, self._cache, self._pos, self._logits, prompt,
                 np.int32(req.length), np.int32(row)))
            self._buf, self._cache, self._pos, self._logits = \
                self._join_fn(bucket)(
                    self._variables, self._buf, self._cache, self._pos,
                    self._logits, prompt, np.int32(req.length),
                    np.int32(row))
            self._h_join.observe(time.perf_counter() - t0)
            self._slots[row].request = req
            self._c_admitted.inc()
            self._c_joins.inc()
            admitted += 1
            self._g_active.set(self._active_count())

    def _finish(self, row: int, now: float) -> None:
        slot = self._slots[row]
        req = slot.request
        slot.request = None
        req.done_t = now
        self._c_completed.inc()
        self._h_e2e.observe(now - req.submit_t)
        req._done.set()

    def _step_once(self) -> None:
        active = np.array([s.request is not None for s in self._slots],
                          bool)
        t0 = time.perf_counter()
        self._sentinel("step").observe(
            (self._buf, self._cache, self._pos, self._logits, active,
             self._rng))
        self._buf, self._cache, self._pos, self._logits, self._rng, nxt = \
            self._build_step()(self._variables, self._buf, self._cache,
                               self._pos, self._logits, active, self._rng)
        tokens = np.asarray(nxt)       # the per-step host readback
        now = time.perf_counter()
        dt = now - t0
        self._h_step.observe(dt)
        self._c_steps.inc()
        eos = self.config.eos_id
        for row, slot in enumerate(self._slots):
            req = slot.request
            if req is None:
                continue
            tok = int(tokens[row])
            req.tokens.append(tok)
            self._c_tokens.inc()
            self._h_per_token.observe(dt)
            if req.first_token_t is None:
                req.first_token_t = now
                self._h_ttft.observe(now - req.submit_t)
            if len(req.tokens) >= req.max_new or \
                    (eos is not None and tok == int(eos)):
                self._finish(row, now)
        self._g_active.set(self._active_count())

    def _loop(self) -> None:
        try:
            while True:
                # a hard stop (stop(drain=False)) exits immediately; the
                # graceful path only sets the stop event once drained, so
                # queued + in-flight work always finishes first.  The loop
                # aborts its own slots on the way out — it is the slot
                # owner, so this cannot race a step in progress
                if self._stop_evt.is_set():
                    self._abort_outstanding("aborted: engine stopped")
                    return
                self._adopt_promotion()
                self._admit()
                if self._active_count():
                    # _idle_evt was cleared (under the lock) by the
                    # submit() that queued this work
                    self._step_once()
                    continue
                with self._lock:
                    if self._queue:
                        continue
                    self._idle_evt.set()
                    self._work.wait(_IDLE_WAIT_S)
        except Exception:
            # a dead decode thread must not strand waiters on requests
            # that will never complete: fail them loudly as rejections
            get_logger(_LOG).exception("decode loop crashed; aborting "
                                       "outstanding requests")
            with self._lock:
                self._draining = True
            self._idle_evt.set()
            self._abort_outstanding("aborted: decode loop crashed")

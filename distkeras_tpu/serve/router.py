"""Engine-fleet front door — ``ServeRouter`` (ISSUE 14 tentpole).

PR 7/11 made a single ``DecodeEngine`` fast; one engine on one host is
still the throughput ceiling.  The router turns N engines into ONE
service: it terminates client connections on the shared
``ps.networking.FrameServer`` frame (the third subclass — the ISSUE 8
extraction was done for exactly this) speaking the same
hello-negotiated v1/v2 wire every serve client already speaks, and
forwards each ``generate`` to one backend engine over pooled persistent
``ServeClient`` connections.

**Routing is two-tier:**

1. **Prefix-affinity first.**  The router hashes the request's leading
   ``affinity_block``-aligned token blocks (one incremental pass, the
   ``PrefixCache`` idiom) and prefers the engine that served this
   prefix before — that engine's ``PrefixCache`` likely holds the KV,
   so the request warm-joins instead of re-prefilling.  The affinity
   table is maintained from ROUTED HISTORY (every routed request
   registers its block keys against its engine, bounded LRU) and
   VALIDATED against each engine's live ``serve.prefix.hits`` counter:
   the health poller compares the hits an engine actually scored with
   the affinity-routed requests it was sent, and when the ratio
   collapses (a promote flushed the cache, an entry was evicted, the
   table is stale) the engine's affinity entries are dropped —
   misrouted affinity DECAYS instead of pinning traffic to a cold
   engine (``serve.router.affinity_decays``).
2. **Least-loaded otherwise.**  Non-affine requests (and affine
   requests whose engine is at its in-flight bound) go to the
   admissible engine with the lowest load — router-tracked in-flight
   plus the queue-depth/occupancy from the engine's last ``stats``
   poll.  ``max_inflight`` bounds per-engine in-flight admission, so
   one hot prefix cannot wedge an engine: overflow spills to the
   least-loaded survivor, and only a fleet-wide full house load-sheds.

**Fleet semantics:**

* ``stats`` merges every engine's registry snapshot plus the router's
  own into one SLO view (``Registry.merge_snapshots`` — the same
  primitive the sharded-PS fleet view uses) and carries a per-engine
  balance list for ``obsview --serve``.
* ``promote`` fans ONE checkpoint out to every engine, so the continual
  ``DeployGate`` drives the whole fleet; partial failure is reported
  per engine in the reply, and the router keeps the latest tree —
  an engine that was down (or failed the push) is ROLLED FORWARD the
  moment the poller sees it healthy again
  (``serve.router.promote_rollforwards``), so the fleet converges on
  the deployed version without operator action.
* A dead or wedged engine is EVICTED the way ``FleetSupervisor``
  handles workers: a ``generate`` whose connection dies (or times out —
  the wedge detector) is re-queued to a surviving engine, never
  silently dropped; the evicted engine's affinity entries are purged
  and the poller keeps probing it, re-admitting it on recovery
  (``serve.router.evictions`` / ``requeues`` / ``rejoins``).  The
  router-level accounting stays exact:
  ``serve.router.requests == completed + rejected``.

**KV fabric (ISSUE 16):** the affinity table holds up to TWO owners per
prefix (primary + replicated secondary).  A routed request whose
longest mapped prefix belongs to a live engine it was NOT sent to is a
**spill** — the router enqueues a ``serve.kvfabric.KVFabric``
replication (fetch the owner's cache entry, push it to the spill
target, single-flight + budget-bounded), records the target as a
secondary owner on completion so repeat overflow routes warm
(``serve.router.affinity_secondary_hits``), and splits the spilled
request's engine-reported TTFT into
``serve.router.ttft_spill_warm_seconds`` /
``ttft_spill_cold_seconds`` by the engine's prefix-cache outcome — the
warm-vs-cold spill proof pair.  Planned transitions migrate instead of
discard: ``drain`` with an ``engine`` address migrates the victim's
hottest entries to survivors before draining it, and a router evict
enqueues the same migration best-effort.

Metrics (router registry, all pre-created): counters
``serve.router.{requests,completed,rejected}`` (rejected split
``_no_backend`` / ``_backend`` / ``_error`` / ``_draining``),
``serve.router.{requeues,evictions,rejoins}``,
``serve.router.affinity_{hits,misses,decays,secondary_hits}``,
``serve.router.{promotes,promote_failures,promote_rollforwards}``,
``serve.router.kv_{replications,migrations,push_bytes,refused_stale}``;
histograms ``serve.router.e2e_seconds`` / ``route_seconds`` /
``ttft_spill_warm_seconds`` / ``ttft_spill_cold_seconds``; gauges
``serve.router.engines_alive`` / ``affinity_entries`` /
``affinity_hit_rate`` (the fleet-wide engine-measured prefix hit rate
the ``obsview`` MISROUTED alarm watches).
"""

from __future__ import annotations

import dataclasses
import hashlib
import socket
import threading
import time
from collections import OrderedDict
from typing import List, Optional, Sequence, Tuple, Union

import numpy as np

from ..obs import Registry, TIME_BUCKETS
from ..obs.logging import get_logger
from ..ps.networking import WIRE_VERSION, FrameServer
from .client import ServeClient
from .kvfabric import KVFabric

_LOG = "serve.router"


def _parse_targets(engines) -> List[Tuple[str, int]]:
    """Accept ``[(host, port), ...]`` or ``["host:port", ...]`` (or a
    mix); at least one engine is required."""
    targets = []
    for e in engines or ():
        if isinstance(e, str):
            host, _, port = e.rpartition(":")
            if not host or not port.isdigit():
                raise ValueError(f"engine target {e!r} is not HOST:PORT")
            targets.append((host, int(port)))
        else:
            host, port = e
            targets.append((str(host), int(port)))
    if not targets:
        raise ValueError("ServeRouter needs at least one engine target")
    return targets


@dataclasses.dataclass
class RouterConfig:
    """Knobs for the fleet front door.

    * ``affinity_block`` — affinity-hash granularity in tokens; match
      the engines' ``ServeConfig.prefix_block`` so an affinity hit lands
      on an engine whose cache can actually serve the prefix.
    * ``affinity_max_blocks`` — boundaries hashed per prompt (caps the
      per-request hashing; the LONGEST registered boundary wins).
    * ``affinity_max`` — affinity-table bound (LRU beyond it).
    * ``max_inflight`` — per-engine in-flight admission bound: an affine
      engine at the bound spills to least-loaded, a fleet at the bound
      load-sheds with a recorded rejection.
    * ``stats_interval_s`` — health/occupancy poll cadence.
    * ``evict_failures`` — consecutive poll failures before a quiet
      engine is evicted (a failed ``generate`` forward evicts
      immediately — the wedge/death signal is unambiguous there).
    * ``decay_ratio`` / ``decay_min_routed`` — affinity validation: in a
      poll window where an engine received ``decay_min_routed``+
      affinity-routed requests AND its admit-time lookups kept pace
      with everything routed to it (queued traffic never reads as
      misses), scoring hits for under ``decay_ratio`` of the
      affinity-routed count drops its affinity entries (the cache no
      longer holds what the table says); cold lookups from
      least-loaded-routed NEW prefixes never condemn the table.
    * ``request_timeout_s`` — per-forward socket timeout: the WEDGED-
      engine detector (None keeps the client default of 30s).
    * ``connect_retries`` / ``dial_timeout_s`` — backend dial attempts
      and per-attempt connect timeout (both small: a partitioned host
      blackholing SYNs must cost the router seconds, not client-grade
      patience — the sequential health poller and any in-flight forward
      wait behind the dial).
    * ``kv_fabric`` — ISSUE 16: run the fleet KV fabric (hot-prefix
      replication on spill, KV migration on planned drain/evict).  Off
      keeps routing identical but every spill cold-prefills and every
      evict discards its cache.
    * ``kv_fabric_mb`` — in-flight transfer budget: the fabric never
      holds more than this many MB of fetched-but-not-yet-pushed KV
      (a fetch that would exceed it is skipped, retried on the next
      spill).
    * ``kv_link_inflight`` — per ``(owner, target)`` link cap on
      queued+running replication jobs: a spill storm between two
      engines collapses to this many transfers, the rest dedup away.
    * ``kv_migrate_entries`` — how many MRU entries a planned
      drain/evict migrates off the victim (still bounded by
      ``kv_fabric_mb`` bytes).
    """

    affinity_block: int = 16
    affinity_max_blocks: int = 8
    affinity_max: int = 4096
    max_inflight: int = 32
    stats_interval_s: float = 0.25
    evict_failures: int = 2
    decay_ratio: float = 0.5
    decay_min_routed: int = 8
    request_timeout_s: Optional[float] = None
    connect_retries: int = 2
    dial_timeout_s: float = 2.0
    kv_fabric: bool = True
    kv_fabric_mb: float = 64.0
    kv_link_inflight: int = 1
    kv_migrate_entries: int = 8

    def __post_init__(self):
        if not float(self.kv_fabric_mb) > 0:
            raise ValueError(f"kv_fabric_mb must be > 0, got "
                             f"{self.kv_fabric_mb}")
        for name in ("affinity_block", "affinity_max_blocks",
                     "affinity_max", "max_inflight", "evict_failures",
                     "connect_retries", "kv_link_inflight",
                     "kv_migrate_entries"):
            if int(getattr(self, name)) < 1:
                raise ValueError(f"{name} must be >= 1, got "
                                 f"{getattr(self, name)}")
        if not float(self.stats_interval_s) > 0:
            raise ValueError(f"stats_interval_s must be > 0, got "
                             f"{self.stats_interval_s}")
        if not 0.0 <= float(self.decay_ratio) <= 1.0:
            raise ValueError(f"decay_ratio must be in [0, 1], got "
                             f"{self.decay_ratio}")
        if self.request_timeout_s is not None and \
                not float(self.request_timeout_s) > 0:
            raise ValueError(f"request_timeout_s must be > 0 (or None), "
                             f"got {self.request_timeout_s}")
        if not float(self.dial_timeout_s) > 0:
            raise ValueError(f"dial_timeout_s must be > 0, got "
                             f"{self.dial_timeout_s}")


class _Backend:
    """Router-side state for one engine: address, a pool of idle
    persistent ``ServeClient`` connections, and the load/affinity
    bookkeeping.  The bookkeeping fields are guarded by the ROUTER's
    ``_lock``; the connection pool has its own lock (a dial must not
    stall routing decisions)."""

    def __init__(self, host: str, port: int, idx: int):
        self.host = host
        self.port = int(port)
        self.idx = int(idx)
        self.addr = f"{host}:{port}"
        # guarded by ServeRouter._lock --------------------------------
        self.alive = True
        self.inflight = 0
        self.fails = 0
        self.queue_depth = 0
        self.active_slots = 0
        self.requests = 0
        self.completed = 0
        self.affinity_routed = 0     # since the last poll window
        self.window_routed = 0       # ALL routed since the last poll
        self.prefix_hits = 0         # engine counters at the last poll
        self.prefix_misses = 0
        self.promote_version = 0
        # pool ---------------------------------------------------------
        self._pool_lock = threading.Lock()
        self._idle: list = []

    def acquire(self, registry, wire_version, retries: int,
                dial_timeout: float,
                timeout: Optional[float]) -> ServeClient:
        """An idle pooled connection, or a fresh dial (raises
        ConnectionError when the engine is unreachable)."""
        with self._pool_lock:
            if self._idle:
                return self._idle.pop()
        client = ServeClient(self.host, self.port, registry=registry,
                             wire_version=wire_version,
                             connect_retries=retries,
                             connect_timeout=dial_timeout)
        # the dial timeout persists on the socket but must not bound
        # the FORWARD (a generate legitimately blocks for the whole
        # decode): past the handshake the connection adopts
        # request_timeout_s — the wedge budget — defaulting to the
        # historical 30s client patience
        client.sock.settimeout(30.0 if timeout is None
                               else float(timeout))
        return client

    def release(self, client: ServeClient) -> None:
        with self._pool_lock:
            self._idle.append(client)

    def close_pool(self) -> None:
        with self._pool_lock:
            idle, self._idle[:] = list(self._idle), []
        for client in idle:
            client.close()


class ServeRouter(FrameServer):
    """The fleet front door: a third ``FrameServer`` subclass routing
    ``generate`` across N engines with prefix-affinity + least-loaded
    placement, fleet-merged ``stats``, fan-out ``promote``, and
    evict/requeue/rejoin failure handling (module docstring).

    ``engines`` is a sequence of ``(host, port)`` tuples or
    ``"host:port"`` strings — the backend ``ServeServer`` addresses.
    ``engine_wire_version`` pins the BACKEND connections' frame format
    (None negotiates per engine, so a v1-pinned legacy engine simply
    interops at v1 while its siblings ride v2)."""

    metric_prefix = "serve.router"

    def __init__(self, engines: Sequence[Union[str, Tuple[str, int]]],
                 host: str = "127.0.0.1", port: int = 0,
                 registry: Optional[Registry] = None,
                 config: Optional[RouterConfig] = None,
                 max_wire_version: int = WIRE_VERSION,
                 engine_wire_version: Optional[int] = None):
        registry = registry if registry is not None else Registry()
        super().__init__(registry, host=host, port=port,
                         max_wire_version=max_wire_version)
        self.config = config if config is not None else RouterConfig()
        self._engine_wire_version = engine_wire_version
        self.backends = [_Backend(h, p, i)
                         for i, (h, p) in
                         enumerate(_parse_targets(engines))]
        #: routing state lock: backend bookkeeping + the affinity table.
        #: Values are OWNER LISTS (ISSUE 16): up to two engine idxs per
        #: prefix key, primary first — the secondary is a fabric
        #: replication target that now holds the same KV
        self._lock = threading.Lock()
        self._affinity: "OrderedDict[tuple, list]" = OrderedDict()
        self._draining = False
        #: serializes promote fan-outs and guards the roll-forward tree
        self._promote_lock = threading.Lock()
        self._promote_version = 0
        self._promote_tree = None
        self._poll_stop = threading.Event()
        self._poll_thread: Optional[threading.Thread] = None

        reg = registry
        self._c_requests = reg.counter("serve.router.requests")
        self._c_completed = reg.counter("serve.router.completed")
        self._c_rejected = reg.counter("serve.router.rejected")
        self._c_rej_nobackend = reg.counter(
            "serve.router.rejected_no_backend")
        self._c_rej_backend = reg.counter("serve.router.rejected_backend")
        self._c_rej_error = reg.counter("serve.router.rejected_error")
        self._c_rej_drain = reg.counter("serve.router.rejected_draining")
        self._c_requeues = reg.counter("serve.router.requeues")
        self._c_evictions = reg.counter("serve.router.evictions")
        self._c_rejoins = reg.counter("serve.router.rejoins")
        self._c_aff_hits = reg.counter("serve.router.affinity_hits")
        self._c_aff_misses = reg.counter("serve.router.affinity_misses")
        self._c_aff_decays = reg.counter("serve.router.affinity_decays")
        self._c_aff_secondary = reg.counter(
            "serve.router.affinity_secondary_hits")
        self._c_kv_replications = reg.counter(
            "serve.router.kv_replications")
        self._c_kv_migrations = reg.counter("serve.router.kv_migrations")
        self._c_kv_push_bytes = reg.counter("serve.router.kv_push_bytes")
        self._c_kv_refused_stale = reg.counter(
            "serve.router.kv_refused_stale")
        self._c_promotes = reg.counter("serve.router.promotes")
        self._c_promote_failures = reg.counter(
            "serve.router.promote_failures")
        self._c_promote_rollforwards = reg.counter(
            "serve.router.promote_rollforwards")
        self._h_e2e = reg.histogram("serve.router.e2e_seconds",
                                    TIME_BUCKETS)
        self._h_route = reg.histogram("serve.router.route_seconds",
                                      TIME_BUCKETS)
        self._h_ttft_spill_warm = reg.histogram(
            "serve.router.ttft_spill_warm_seconds", TIME_BUCKETS)
        self._h_ttft_spill_cold = reg.histogram(
            "serve.router.ttft_spill_cold_seconds", TIME_BUCKETS)
        self._g_alive = reg.gauge("serve.router.engines_alive")
        self._g_alive.set(len(self.backends))
        self._g_aff_entries = reg.gauge("serve.router.affinity_entries")
        self._g_aff_rate = reg.gauge("serve.router.affinity_hit_rate")

        #: ISSUE 16: the fleet KV fabric (replication on spill,
        #: migration on drain/evict); None when configured off
        self._kv_fabric: Optional[KVFabric] = \
            KVFabric(self) if self.config.kv_fabric else None

    # -- backend connections ------------------------------------------------
    def _acquire(self, be: _Backend) -> ServeClient:
        return be.acquire(self.registry, self._engine_wire_version,
                          self.config.connect_retries,
                          float(self.config.dial_timeout_s),
                          self.config.request_timeout_s)

    # -- affinity -----------------------------------------------------------
    def _affinity_keys(self, prompt: np.ndarray) -> list:
        """Block-boundary keys for ``prompt``, LONGEST first — one
        incremental hash pass (the ``PrefixCache`` idiom), capped at
        ``affinity_max_blocks`` boundaries."""
        block = int(self.config.affinity_block)
        nblocks = min(int(prompt.shape[0]) // block,
                      int(self.config.affinity_max_blocks))
        if nblocks < 1:
            return []
        data = np.ascontiguousarray(prompt[:nblocks * block]).tobytes()
        keys = []
        h = hashlib.sha1()
        for i in range(nblocks):
            h.update(data[i * block * 4:(i + 1) * block * 4])
            keys.append(((i + 1) * block, h.copy().digest()))
        keys.reverse()
        return keys

    def _admissible(self, be: _Backend, exclude) -> bool:
        # dklint: holds=_lock
        return be.alive and be.idx not in exclude \
            and be.inflight < int(self.config.max_inflight)

    def _route(self, prompt: np.ndarray, exclude=frozenset(),
               spill_out: Optional[list] = None):
        """Pick a backend for ``prompt``: affinity first, least-loaded
        otherwise; registers the routed keys and takes an in-flight
        slot.  Returns ``(backend, was_affine)`` or ``(None, False)``
        when no engine is admissible.

        ISSUE 16: overflow routes report to ``spill_out`` (when given).
        A pick that is NOT an owner of the longest mapped prefix while
        a live owner exists appends ``("spill", key, owner_idx,
        target_idx)`` — the fabric's replication trigger; a pick that is
        the replicated SECONDARY owner appends ``("secondary", ...)`` —
        already-replicated overflow, no new transfer, but still spill
        traffic for the warm-vs-cold TTFT split."""
        t0 = time.perf_counter()
        keys = self._affinity_keys(prompt)
        with self._lock:
            target, affine, sec_spill = None, False, None
            for key in keys:
                owners = self._affinity.get(key)
                if not owners:
                    continue
                for rank, idx in enumerate(owners):
                    if self._admissible(self.backends[idx], exclude):
                        target, affine = self.backends[idx], True
                        if rank > 0:
                            sec_spill = ("secondary", key, owners[0],
                                         idx)
                        self._affinity.move_to_end(key)
                        break
                if target is not None:
                    break
            if target is None:
                cands = [be for be in self.backends
                         if self._admissible(be, exclude)]
                if not cands:
                    return None, False
                # least-loaded: router-tracked in-flight (exact) plus
                # the engine's last-polled queue/occupancy (near-live);
                # ties break by fewest-routed so an idle fleet SPREADS
                # new prefixes instead of pinning them all to engine 0
                target = min(cands,
                             key=lambda be: (be.inflight + be.queue_depth
                                             + be.active_slots,
                                             be.requests, be.idx))
            (self._c_aff_hits if affine else self._c_aff_misses).inc()
            if sec_spill is not None:
                self._c_aff_secondary.inc()
            spill = None
            seen_mapped = False
            for key in keys:
                owners = self._affinity.get(key)
                if not owners:
                    self._affinity[key] = [target.idx]
                    self._affinity.move_to_end(key)
                    continue
                longest_mapped = not seen_mapped
                seen_mapped = True
                if target.idx in owners:
                    self._affinity.move_to_end(key)
                    continue
                live = [i for i in owners if self.backends[i].alive]
                if live:
                    # a LIVE engine already owns this prefix: a
                    # transient spill (owner at its in-flight bound)
                    # must not steal the mapping and strand the owner's
                    # warm KV — the owner serves the prefix again the
                    # moment it is admissible.  Dead owners' keys were
                    # purged at eviction; stale live mappings decay.
                    # The LONGEST foreign-owned mapped key is the KV
                    # fabric's replication trigger — shorter keys under
                    # a target-owned longer one are not (the target
                    # already holds a covering entry).  Only a SINGLY-
                    # owned prefix replicates: once a replica exists
                    # (two live owners) a further overflow means the
                    # whole fleet is saturated, and shipping a third
                    # copy would evict the second and thrash transfer
                    # bandwidth without adding warm capacity
                    if longest_mapped and spill is None \
                            and len(live) == 1:
                        spill = ("spill", key, live[0], target.idx)
                    continue
                self._affinity[key] = [target.idx]
                self._affinity.move_to_end(key)
            if spill_out is not None:
                if spill is not None:
                    spill_out.append(spill)
                elif sec_spill is not None:
                    spill_out.append(sec_spill)
            while len(self._affinity) > int(self.config.affinity_max):
                self._affinity.popitem(last=False)
            self._g_aff_entries.set(len(self._affinity))
            target.inflight += 1
            target.requests += 1
            target.window_routed += 1
            if affine:
                target.affinity_routed += 1
        self._h_route.observe(time.perf_counter() - t0)
        return target, affine

    def _add_secondary(self, key, idx: int) -> None:
        """Record engine ``idx`` as a secondary owner of affinity
        ``key`` — the fabric's post-replication hook, bounding each
        prefix to TWO owners (primary + the freshest replica; a third
        replication replaces the older secondary)."""
        with self._lock:
            owners = self._affinity.get(key)
            if owners is None:
                # the key aged out of the LRU while the transfer ran:
                # the replica is real, so re-map it as primary
                self._affinity[key] = [int(idx)]
                self._g_aff_entries.set(len(self._affinity))
                return
            if int(idx) in owners:
                return
            if len(owners) >= 2:
                owners[-1] = int(idx)
            else:
                owners.append(int(idx))

    def _reown_affinity(self, host_tokens: np.ndarray, victim_idx: int,
                        new_idx: int) -> None:
        """Re-point a migrated entry's affinity keys from ``victim_idx``
        at its recipient ``new_idx`` (the fabric's post-migration hook):
        traffic for the moved prefix follows the KV to the survivor
        instead of cold-starting wherever least-loaded lands it."""
        keys = self._affinity_keys(
            np.asarray(host_tokens, np.int32).reshape(-1))
        with self._lock:
            for key in keys:
                owners = self._affinity.get(key)
                if owners is None:
                    self._affinity[key] = [int(new_idx)]
                elif int(new_idx) in owners:
                    if int(victim_idx) in owners:
                        owners.remove(int(victim_idx))
                elif int(victim_idx) in owners:
                    owners[owners.index(int(victim_idx))] = int(new_idx)
                elif len(owners) < 2:
                    owners.append(int(new_idx))
            self._g_aff_entries.set(len(self._affinity))

    def _drop_affinity(self, idx: int) -> int:  # dklint: holds=_lock
        dropped = 0
        for k in [k for k, owners in self._affinity.items()
                  if idx in owners]:
            owners = self._affinity[k]
            owners.remove(idx)
            dropped += 1
            if not owners:
                # a surviving co-owner keeps the key: its replica of
                # the prefix is still warm and still routable
                del self._affinity[k]
        self._g_aff_entries.set(len(self._affinity))
        return dropped

    # -- eviction / rejoin --------------------------------------------------
    def _evict(self, be: _Backend, reason: str,
               migrate: bool = True) -> None:
        with self._lock:
            if not be.alive:
                return
            be.alive = False
            be.fails = 0
            self._c_evictions.inc()
            dropped = self._drop_affinity(be.idx)
            self._g_alive.set(sum(b.alive for b in self.backends))
        be.close_pool()
        if migrate and self._kv_fabric is not None:
            # best-effort KV rescue (ISSUE 16): a DEAD victim fails the
            # fabric's fetch fast and the job ends silently; a wedged-
            # but-answering one still gets its warm set copied to
            # survivors.  The planned-drain path passes migrate=False —
            # it already migrated synchronously, before the drain
            self._kv_fabric.note_eviction(be.idx)
        get_logger(_LOG).warning(
            "evicted engine %s (%s); %d affinity entries dropped, "
            "traffic re-queued to survivors", be.addr, reason, dropped)

    def _note_poll_failure(self, be: _Backend, err) -> None:
        with self._lock:
            be.fails += 1
            evict = be.alive and \
                be.fails >= int(self.config.evict_failures)
        if evict:
            self._evict(be, f"stats poll failed x{be.fails}: {err}")

    def _adopt_stats(self, be: _Backend, reply: dict) -> None:
        """Fold one engine's ``stats`` reply into the routing state:
        occupancy for least-loaded, prefix counters for affinity
        validation/decay, liveness (a dead engine answering again is a
        REJOIN — rolled forward onto the fleet's promoted version)."""
        stats = reply.get("stats", {}) or {}

        def _v(name):
            return int(stats.get(name, {}).get("value", 0) or 0)

        hits, misses = _v("serve.prefix.hits"), _v("serve.prefix.misses")
        rejoined = False
        with self._lock:
            be.fails = 0
            if not be.alive:
                if reply.get("draining"):
                    # a planned-drained engine still answers stats but
                    # admits NOTHING — rejoining it would only bounce
                    # traffic off its "draining" rejection.  It stays
                    # evicted until it answers un-draining (a restart)
                    return
                be.alive = True
                rejoined = True
                self._c_rejoins.inc()
                self._g_alive.set(sum(b.alive for b in self.backends))
            be.queue_depth = int(reply.get("queue_depth", 0) or 0)
            be.active_slots = int(reply.get("active_slots", 0) or 0)
            d_hits = hits - be.prefix_hits
            d_looked = d_hits + (misses - be.prefix_misses)
            routed_aff = be.affinity_routed
            routed_total = be.window_routed
            be.affinity_routed = 0
            be.window_routed = 0
            be.prefix_hits, be.prefix_misses = hits, misses
            # affinity validation: the engine was sent `routed_aff`
            # requests BECAUSE its cache supposedly held their prefixes;
            # scoring hits for under decay_ratio of them means the table
            # is stale (promote flush, LRU eviction) — decay it.  Two
            # guards keep the signal honest: the window must have
            # admitted at least what was routed (a routed-but-still-
            # QUEUED request has not done its admit-time lookup and must
            # not read as a miss), and hits are compared against the
            # AFFINITY-routed count, not all lookups — least-loaded-
            # routed new prefixes necessarily cold-miss and must not
            # condemn a perfectly accurate table
            if routed_aff >= int(self.config.decay_min_routed) and \
                    d_looked >= routed_total and \
                    d_hits < float(self.config.decay_ratio) * routed_aff:
                self._drop_affinity(be.idx)
                self._c_aff_decays.inc()
            looked = sum(b.prefix_hits + b.prefix_misses
                         for b in self.backends)
            if looked:
                self._g_aff_rate.set(
                    sum(b.prefix_hits for b in self.backends) / looked)
        if rejoined:
            get_logger(_LOG).warning("engine %s rejoined the fleet",
                                     be.addr)

    # -- health poller ------------------------------------------------------
    def _poll_once(self) -> None:
        for be in self.backends:
            try:
                client = self._acquire(be)
                try:
                    # retry=False: a dead engine must cost ONE failed
                    # read, not the client's full reconnect-backoff
                    # ladder — the poll loop is sequential, and every
                    # other engine's occupancy refresh waits behind it
                    reply = client.stats(retry=False)
                except BaseException:
                    client.close()
                    raise
                be.release(client)
            except (ConnectionError, OSError, socket.timeout) as e:
                self._note_poll_failure(be, e)
                continue
            self._adopt_stats(be, reply)
            # telemetry plane (ISSUE 20): the poll this router already
            # runs IS the fleet's engine-stats source — fold each reply
            # into the aggregator so obsview/alerts read one live series
            # instead of adding their own N poll loops
            stats = reply.get("stats")
            if isinstance(stats, dict):
                store = self.telemetry or self.enable_telemetry()
                store.ingest_total(f"engine:{be.addr}", stats)
            self._rollforward(be)
        if self.alerts is not None:
            self.alerts.evaluate()

    def _poll_loop(self) -> None:
        while not self._poll_stop.wait(float(self.config.stats_interval_s)):
            try:
                self._poll_once()
            except Exception:
                # the poller must outlive any single bad reply; the
                # failure is recorded per backend above
                get_logger(_LOG).exception("router poll iteration failed")

    # -- promote fan-out ----------------------------------------------------
    def _rollforward(self, be: _Backend) -> None:
        """Push the fleet's promoted checkpoint to an engine that is
        behind (it was down — or failed the push — during the fan-out):
        the partial-failure repair that makes a fleet promote converge."""
        with self._promote_lock:
            ver, tree = self._promote_version, self._promote_tree
            if tree is None:
                return
            with self._lock:
                if not be.alive or be.promote_version >= ver:
                    return
            try:
                client = self._acquire(be)
                try:
                    reply = client.promote(tree)
                except BaseException:
                    client.close()
                    raise
                be.release(client)
            except (ConnectionError, OSError, socket.timeout) as e:
                get_logger(_LOG).warning(
                    "promote roll-forward to %s failed (%s); will retry "
                    "on the next poll", be.addr, e)
                return
            if reply.get("ok"):
                with self._lock:
                    be.promote_version = ver
                self._c_promote_rollforwards.inc()
                get_logger(_LOG).warning(
                    "rolled engine %s forward to promoted version %d",
                    be.addr, ver)

    def _handle_promote(self, msg: dict) -> dict:
        variables = msg.get("variables")
        if variables is None:
            return {"ok": False, "error": "promote needs a variables tree"}
        with self._promote_lock:
            self._promote_version += 1
            ver = self._promote_version
            self._promote_tree = variables
            results = {}
            n_ok = 0
            for be in self.backends:
                with self._lock:
                    alive = be.alive
                if not alive:
                    results[be.addr] = {
                        "ok": False,
                        "error": "engine evicted; rolls forward on "
                                 "rejoin"}
                    self._c_promote_failures.inc()
                    continue
                try:
                    client = self._acquire(be)
                    try:
                        reply = client.promote(variables)
                    except BaseException:
                        client.close()
                        raise
                    be.release(client)
                except (ConnectionError, OSError, socket.timeout) as e:
                    self._c_promote_failures.inc()
                    results[be.addr] = {"ok": False, "error": str(e)}
                    continue
                if reply.get("ok"):
                    with self._lock:
                        be.promote_version = max(be.promote_version, ver)
                    n_ok += 1
                    results[be.addr] = {"ok": True}
                else:
                    self._c_promote_failures.inc()
                    results[be.addr] = {
                        "ok": False, "error": reply.get("error", "?")}
            self._c_promotes.inc()
        return {"ok": n_ok == len(self.backends), "promoted": n_ok,
                "failed": len(self.backends) - n_ok, "version": ver,
                "engines": results}

    # -- generate forwarding ------------------------------------------------
    def _forward(self, be: _Backend, msg: dict,
                 prompt: np.ndarray) -> dict:
        """One forward on a pooled connection; releases the in-flight
        slot whatever happens.  A connection that errored mid-request is
        CLOSED, never pooled (its stream state is unknown)."""
        try:
            client = self._acquire(be)
            try:
                reply = client.generate(
                    prompt, msg.get("max_new_tokens"),
                    temperature=msg.get("temperature"),
                    top_k=msg.get("top_k"), top_p=msg.get("top_p"))
            except BaseException:
                client.close()
                raise
            be.release(client)
            return reply
        finally:
            with self._lock:
                be.inflight -= 1

    def _handle_generate(self, msg: dict) -> dict:
        prompt = msg.get("prompt")
        if prompt is None:
            return {"ok": False, "error": "generate needs a prompt"}
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        self._c_requests.inc()
        with self._lock:
            draining = self._draining
        if draining:
            self._c_rejected.inc()
            self._c_rej_drain.inc()
            return {"ok": False, "rejected": True, "reason": "draining"}
        t0 = time.perf_counter()
        tried: set = set()
        while True:
            spill: list = []
            be, _affine = self._route(
                prompt, exclude=tried,
                spill_out=spill if self._kv_fabric is not None else None)
            if be is None:
                self._c_rejected.inc()
                self._c_rej_nobackend.inc()
                reason = "no admissible engine" if not tried else \
                    f"engines {sorted(tried)} failed or shed; no " \
                    f"admissible survivor"
                return {"ok": False, "rejected": True, "reason": reason}
            try:
                reply = self._forward(be, msg, prompt)
            except (ValueError, TypeError) as e:
                # a malformed FIELD (e.g. a non-numeric max_new_tokens
                # or temperature riding the wire) fails client-side
                # serialization: answer it like the engine front-end
                # would AND count it, so requests == completed +
                # rejected stays exact
                self._c_rejected.inc()
                self._c_rej_error.inc()
                return {"ok": False, "error": str(e)}
            except (ConnectionError, OSError, socket.timeout) as e:
                # the engine died or wedged mid-request: evict it and
                # RE-QUEUE the request on a survivor — never silently
                # dropped.  (The dead engine cannot double-serve the
                # request; a wedged one may eventually finish a decode
                # nobody reads — wasted compute, never wrong output.)
                self._evict(be, f"generate forward failed: {e}")
                tried.add(be.idx)
                self._c_requeues.inc()
                continue
            with self._lock:
                be.fails = 0
                if reply.get("ok"):
                    be.completed += 1
            if reply.get("ok"):
                self._c_completed.inc()
                self._h_e2e.observe(time.perf_counter() - t0)
                if spill and reply.get("ttft_s") is not None \
                        and reply.get("warm") is not None:
                    # the warm-vs-cold spill TTFT split (ISSUE 16): the
                    # engine reports its admit-time prefix outcome, so
                    # a spill that landed AFTER the fabric replicated
                    # reads warm — the fabric's payoff, measured
                    (self._h_ttft_spill_warm if reply["warm"]
                     else self._h_ttft_spill_cold).observe(
                        float(reply["ttft_s"]))
            else:
                if reply.get("rejected") and \
                        reply.get("reason") in ("queue full", "draining"):
                    # the ENGINE load-shed, but that is its own
                    # admission verdict, not the fleet's: a sibling may
                    # have capacity (the affinity path admits up to
                    # max_inflight regardless of the engine's queue
                    # bound).  Re-queue on the survivors — only a
                    # fleet-wide full house reaches the client, and it
                    # is counted exactly once, on the final outcome
                    tried.add(be.idx)
                    self._c_requeues.inc()
                    continue
                self._c_rejected.inc()
                if reply.get("rejected"):
                    self._c_rej_backend.inc()
                else:
                    # a malformed request the engine answered with
                    # "error": counted here so the router's
                    # requests == completed + rejected stays exact
                    self._c_rej_error.inc()
            if spill and spill[0][0] == "spill":
                # the pick was NOT an owner of this prompt's longest
                # mapped prefix: replicate the owner's KV to it so the
                # NEXT overflow of this prefix lands warm.  Triggered
                # AFTER this request's reply so the transfer can never
                # race its admission (the spilled request is cold by
                # construction — the proof split stays exact) and the
                # fetch never steals CPU from the very prefill it is
                # trying to make unnecessary.  ("secondary" overflow
                # already holds the replica — no new transfer, just
                # the TTFT attribution above)
                _kind, key, owner_idx, target_idx = spill[0]
                self._kv_fabric.note_spill(key, owner_idx, target_idx,
                                           prompt)
            reply["engine"] = be.addr
            return reply

    # -- fleet stats --------------------------------------------------------
    def _handle_stats(self) -> dict:
        """One merged fleet SLO view (``Registry.merge_snapshots`` over
        every live engine's snapshot plus the router's own) + the
        per-engine balance list ``obsview --serve`` renders."""
        parts = []
        engines = []
        slots = queue_depth = active = 0
        fleet_hits = fleet_misses = 0
        model = seq_len = buckets = None
        for be in self.backends:
            with self._lock:
                entry = {"addr": be.addr, "alive": be.alive,
                         "inflight": be.inflight,
                         "requests": be.requests,
                         "completed": be.completed,
                         "promote_version": be.promote_version}
                alive = be.alive
            if alive:
                try:
                    client = self._acquire(be)
                    try:
                        reply = client.stats(retry=False)
                    except BaseException:
                        client.close()
                        raise
                    be.release(client)
                except (ConnectionError, OSError, socket.timeout) as e:
                    entry["error"] = str(e)
                else:
                    stats = reply.get("stats", {}) or {}
                    parts.append(stats)
                    model = model or reply.get("model")
                    seq_len = seq_len or reply.get("seq_len")
                    buckets = buckets or reply.get("prefill_buckets")
                    slots += int(reply.get("slots", 0) or 0)
                    queue_depth += int(reply.get("queue_depth", 0) or 0)
                    active += int(reply.get("active_slots", 0) or 0)

                    def _v(name):
                        return stats.get(name, {}).get("value", 0)

                    fleet_hits += int(_v("serve.prefix.hits") or 0)
                    fleet_misses += int(_v("serve.prefix.misses") or 0)
                    entry.update(
                        queue_depth=reply.get("queue_depth"),
                        active_slots=reply.get("active_slots"),
                        slots=reply.get("slots"),
                        draining=reply.get("draining"),
                        engine_requests=_v("serve.requests"),
                        engine_completed=_v("serve.completed"),
                        prefix_hits=_v("serve.prefix.hits"),
                        prefix_misses=_v("serve.prefix.misses"),
                        stats=stats)
            engines.append(entry)
        with self._lock:
            draining = self._draining
            alive_n = sum(b.alive for b in self.backends)
        if fleet_hits + fleet_misses:
            # the obsview MISROUTED alarm's signal — refreshed here from
            # the counters just fetched, so a stats poll never reads a
            # stale poller tick
            self._g_aff_rate.set(fleet_hits / (fleet_hits + fleet_misses))
        merged = Registry.merge_snapshots(self.registry.snapshot(),
                                          *parts)
        return {"stats": merged, "server": type(self).__name__,
                "model": model, "seq_len": seq_len,
                "prefill_buckets": buckets, "engines": engines,
                "num_engines": len(self.backends),
                "engines_alive": alive_n,
                "slots": slots, "queue_depth": queue_depth,
                "active_slots": active, "draining": draining}

    def _drain_engine(self, addr: str, timeout_s) -> dict:
        """Planned SINGLE-engine drain (ISSUE 16): migrate the victim's
        hottest KV entries to survivors synchronously — the warm set
        crosses the wire while the victim still answers — THEN drain it
        and take it out of rotation.  The fleet keeps serving; the
        victim's prefixes keep hitting, now on the recipients."""
        be = next((b for b in self.backends if b.addr == addr), None)
        if be is None:
            return {"ok": False, "error": f"unknown engine {addr!r}"}
        with self._lock:
            alive = be.alive
        if not alive:
            return {"ok": False, "engine": be.addr,
                    "error": "engine already evicted"}
        migrated = 0
        if self._kv_fabric is not None:
            migrated = self._kv_fabric.migrate_now(be.idx)
        try:
            client = self._acquire(be)
            try:
                result = client.drain(timeout_s)
            except BaseException:
                client.close()
                raise
            be.release(client)
        except (ConnectionError, OSError, socket.timeout) as e:
            result = {"ok": False, "error": str(e)}
        self._evict(be, "planned drain", migrate=False)
        reply = {"ok": bool(result.get("ok")), "engine": be.addr,
                 "migrated": migrated,
                 "drained": result.get("drained")}
        if result.get("error"):
            reply["error"] = result["error"]
        return reply

    def _handle_drain(self, msg: dict) -> dict:
        """Fleet drain: stop admitting at the front door, then fan the
        drain to every live engine (idempotent, like the engine's).
        With an ``engine`` address (ISSUE 16) it is instead a PLANNED
        single-engine drain — migrate-then-drain, fleet stays up."""
        addr = msg.get("engine")
        if addr is not None:
            return self._drain_engine(str(addr), msg.get("timeout_s"))
        with self._lock:
            self._draining = True
        results = {}
        for be in self.backends:
            with self._lock:
                alive = be.alive
            if not alive:
                results[be.addr] = {"ok": False, "error": "evicted"}
                continue
            try:
                client = self._acquire(be)
                try:
                    results[be.addr] = client.drain(msg.get("timeout_s"))
                except BaseException:
                    client.close()
                    raise
                be.release(client)
            except (ConnectionError, OSError, socket.timeout) as e:
                results[be.addr] = {"ok": False, "error": str(e)}
        return {"ok": True, "engines": results}

    # -- autoscaler seam (ISSUE 17) -----------------------------------------
    def scale_down(self, addr: str,
                   timeout_s: Optional[float] = None) -> dict:
        """Take one engine out of rotation — an alias for the planned
        single-engine drain (migrate hot KV to survivors → drain →
        evict).  The drained engine PARKS: its server keeps answering
        stats (refusing rejoin while draining) with the warm-compiled
        model intact, so :meth:`scale_up` can re-admit it without a
        recompile."""
        return self._drain_engine(str(addr), timeout_s)

    def scale_up(self, addr: str) -> dict:
        """Re-admit a parked engine: send ``undrain`` to reopen its
        admission, then probe stats and re-adopt it through the SAME
        rejoin path a recovered engine takes (synchronously — the
        autoscaler must not wait a poller tick for capacity it just
        asked for).  Roll-forward runs too, so an engine parked across
        a promote rejoins on the fleet's current version."""
        be = next((b for b in self.backends if b.addr == addr), None)
        if be is None:
            return {"ok": False, "error": f"unknown engine {addr!r}"}
        with self._lock:
            if be.alive:
                return {"ok": True, "engine": be.addr,
                        "already_alive": True}
        try:
            client = self._acquire(be)
            try:
                result = client.undrain()
                reply = client.stats(retry=False)
            except BaseException:
                client.close()
                raise
            be.release(client)
        except (ConnectionError, OSError, socket.timeout) as e:
            return {"ok": False, "engine": be.addr, "error": str(e)}
        if not result.get("ok"):
            return {"ok": False, "engine": be.addr,
                    "error": result.get("error", "undrain refused")}
        self._adopt_stats(be, reply)
        self._rollforward(be)
        with self._lock:
            alive = be.alive
        return {"ok": alive, "engine": be.addr,
                "was_draining": bool(result.get("was_draining"))}

    def _handle_undrain(self, msg: dict) -> dict:
        addr = msg.get("engine")
        if addr is None:
            return {"ok": False,
                    "error": "router undrain needs an engine address"}
        return self.scale_up(str(addr))

    # -- FrameServer plumbing -----------------------------------------------
    def handle_request(self, action, msg: dict, ver: int,
                       conn: socket.socket):
        if action == "generate":
            return self._handle_generate(msg)
        if action == "stats":
            return self._handle_stats()
        if action == "promote":
            return self._handle_promote(msg)
        if action == "drain":
            return self._handle_drain(msg)
        if action == "undrain":
            return self._handle_undrain(msg)
        return None

    def _on_start(self) -> None:
        self._poll_stop.clear()
        self._poll_thread = threading.Thread(
            target=self._poll_loop, daemon=True,
            name="serve-router-poll")
        self._poll_thread.start()
        if self._kv_fabric is not None:
            self._kv_fabric.start()

    def _before_close_connections(self) -> None:
        # let handler threads flush replies for forwards that are about
        # to complete before their sockets are closed under them
        deadline = time.monotonic() + 5.0
        while self._g_inflight.value > 0 and time.monotonic() < deadline:
            time.sleep(0.01)

    def stop(self) -> None:
        if self._kv_fabric is not None:
            # before the poller and listener: in-flight transfers
            # finish or die with their sockets, no new jobs enqueue
            self._kv_fabric.stop()
        self._poll_stop.set()
        if self._poll_thread is not None:
            self._poll_thread.join(timeout=5)
            self._poll_thread = None
        super().stop()
        for be in self.backends:
            be.close_pool()

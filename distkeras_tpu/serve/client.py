"""Client for the decode service (ISSUE 7) — one persistent connection
speaking the shared PS wire framing, hello-negotiated v1/v2 per
connection exactly like ``PSClient`` (the ``networking.client_handshake``
seam).

``generate()`` returns the server's reply dict verbatim — ``ok`` True
with an int32 ``tokens`` array (zero-copy on v2 connections) and the
server-side timings, or ``ok`` False with either ``rejected`` (the
admission controller load-shed — an OPERATIONAL outcome the caller
handles, not an exception) or ``error`` (a malformed request).  The
client observes its own SLO view: ``serve.client.e2e_seconds`` per
generate round-trip, ``serve.client.requests`` / ``serve.client.rejected``
counters — the load-generator side of ``bench.py --serve`` merges these
per-thread registries into the persisted snapshot.

``stats()`` transparently reconnects-and-retries once (idempotent read);
``generate`` does NOT auto-retry — the server may have admitted (and be
decoding) the request even though the connection died, and a resend
would double-spend slots.
"""

from __future__ import annotations

import time
from typing import Any, Optional

import numpy as np

from ..obs import TIME_BUCKETS, Registry, default_registry
from ..ps.networking import (client_handshake, connect,
                             pinned_wire_version, recv_msg, recv_pull,
                             retry_with_backoff, send_msg)


class ServeClient:
    def __init__(self, host: str, port: int,
                 registry: Optional[Registry] = None,
                 wire_version: Optional[int] = None,
                 connect_retries: int = 20,
                 connect_timeout: float = 30.0):
        self.host = host
        self.port = port
        #: dial retries / per-attempt connect timeout before the
        #: constructor raises — the router dials with small values so a
        #: dead engine costs milliseconds per probe and a PARTITIONED
        #: one (SYNs blackholed) seconds, not the default client
        #: patience
        self.connect_retries = max(1, int(connect_retries))
        self.connect_timeout = float(connect_timeout)
        self.registry = registry if registry is not None \
            else default_registry()
        self._h_e2e = self.registry.histogram("serve.client.e2e_seconds",
                                              TIME_BUCKETS)
        self._c_requests = self.registry.counter("serve.client.requests")
        self._c_rejected = self.registry.counter("serve.client.rejected")
        self._c_reconnects = self.registry.counter(
            "serve.client.reconnects")
        self._c_reconnect_failures = self.registry.counter(
            "serve.client.reconnect_failures")
        #: ``None`` negotiates; ``1`` pins legacy (also via DKTPU_WIRE=1)
        self._want_version = pinned_wire_version(wire_version)
        self.sock = connect(host, port, timeout=self.connect_timeout,
                            retries=self.connect_retries)
        self.wire_version = client_handshake(self.sock,
                                             registry=self.registry,
                                             want=self._want_version)
        #: pooled receive arenas for streamed ``kv_fetch`` replies (the
        #: DKW4 pull path, ISSUE 16) — steady-state fabric transfers
        #: reuse one buffer instead of allocating multi-MB per fetch
        self._kv_scratch: list = []

    def reconnect(self, attempts: int = 6, base_delay: float = 0.1,
                  max_delay: float = 2.0) -> None:
        """Re-dial + re-negotiate with capped exponential backoff +
        jitter (ISSUE 9 satellite — same policy as ``PSClient``): a
        draining/restarting service takes seconds to come back, and a
        client pool re-dialing in lockstep is a thundering herd.  Each
        failed attempt counts under ``serve.client.reconnect_failures``;
        the final one re-raises."""
        try:
            self.sock.close()
        except OSError:
            pass

        def dial():
            self.sock = connect(self.host, self.port, retries=1)
            self.wire_version = client_handshake(
                self.sock, registry=self.registry,
                want=self._want_version)

        retry_with_backoff(dial, attempts, base_delay, max_delay,
                           self._c_reconnect_failures.inc,
                           f"reconnect to {self.host}:{self.port}",
                           "serve.client")
        self._c_reconnects.inc()

    def _rpc(self, msg: dict, retry: bool = False) -> Any:
        try:
            send_msg(self.sock, msg, registry=self.registry,
                     version=self.wire_version)
            return recv_msg(self.sock, registry=self.registry)
        except (ConnectionError, OSError):
            if not retry:
                raise
            self.reconnect()
            send_msg(self.sock, msg, registry=self.registry,
                     version=self.wire_version)
            return recv_msg(self.sock, registry=self.registry)

    def generate(self, prompt, max_new_tokens: Optional[int] = None,
                 temperature: Optional[float] = None,
                 top_k: Optional[int] = None,
                 top_p: Optional[float] = None) -> dict:
        """One generation round-trip; blocks until the server finishes
        (or load-sheds) the request.  Returns the reply dict — check
        ``reply["ok"]``; on success ``reply["tokens"]`` holds the
        generated int32 ids.

        ``temperature`` / ``top_k`` / ``top_p`` ride the request (ISSUE
        14) and override the engine's defaults for THIS generation only;
        omitted params keep the service defaults.  Extra msgpack keys —
        old servers ignore them (and sample at their configured
        defaults), per the wire's extension contract."""
        msg: dict = {"action": "generate",
                     "prompt": np.asarray(prompt, np.int32).reshape(-1)}
        if max_new_tokens is not None:
            msg["max_new_tokens"] = int(max_new_tokens)
        if temperature is not None:
            msg["temperature"] = float(temperature)
        if top_k is not None:
            msg["top_k"] = int(top_k)
        if top_p is not None:
            msg["top_p"] = float(top_p)
        self._c_requests.inc()
        t0 = time.perf_counter()
        reply = self._rpc(msg)
        self._h_e2e.observe(time.perf_counter() - t0)
        if not reply.get("ok") and reply.get("rejected"):
            self._c_rejected.inc()
        return reply

    def stats(self, retry: bool = True) -> dict:
        """Poll the service's live telemetry (registry snapshot + queue/
        slot state) — no decode work, safe under load.  ``retry=False``
        skips the reconnect-and-retry (idempotent-read) path — the
        router's health poller probes with it so a dead engine costs one
        failed read, not a full backoff ladder."""
        return self._rpc({"action": "stats"}, retry=retry)

    def promote(self, variables) -> dict:
        """Hot-swap the service's serving weights with ``variables`` —
        the cross-process deploy seam (ISSUE 8): the continual trainer
        promotes drift-clean checkpoints through this RPC, the tree
        riding the v2 zero-copy tensor frame.  Returns the reply dict —
        ``{"ok": True, "promotions": n}`` or ``{"ok": False, "error"}``
        when the tree does not match the serving model.  No auto-retry:
        like ``generate``, the server may have adopted the tree even
        though the connection died, and a resend would double-promote."""
        return self._rpc({"action": "promote", "variables": variables})

    def kv_fetch(self, prompt=None, hottest: Optional[int] = None,
                 budget_bytes: Optional[int] = None) -> dict:
        """Pull cached prefix KV from the service for the fleet fabric
        (ISSUE 16): the longest cached entry matching ``prompt``
        (replication-on-spill), or the ``hottest`` MRU entries bounded
        by ``budget_bytes`` (migration off a draining engine).  Returns
        ``{"ok", "found", "entries", "version"}`` — on a v2 connection
        the reply arrives as a DKW4 chunked stream, its tensor leaves
        decoded zero-copy into this client's pooled receive arena
        (``recv_pull``, exactly the PS streamed-pull path).  No
        auto-retry: the fabric re-fetches on its next spill instead."""
        msg: dict = {"action": "kv_fetch"}
        if hottest is not None:
            msg["hottest"] = int(hottest)
            if budget_bytes is not None:
                msg["budget_bytes"] = int(budget_bytes)
        else:
            if prompt is None:
                raise ValueError("kv_fetch needs a prompt or hottest")
            msg["prompt"] = np.asarray(prompt, np.int32).reshape(-1)
        send_msg(self.sock, msg, registry=self.registry,
                 version=self.wire_version)
        doc, _ = recv_pull(self.sock, registry=self.registry,
                           scratch=self._kv_scratch)
        return doc

    def kv_push(self, entries, version: int) -> dict:
        """Push exported KV ``entries`` (``kv_fetch`` documents) to the
        service, stamped with the checkpoint ``version`` they were
        computed under.  The service joins each through its
        version-guarded fabric seam or refuses it — reply carries
        ``joined`` / ``refused_stale`` / ``refused`` counts.  No
        auto-retry (a reconnect-resend could double-push)."""
        return self._rpc({"action": "kv_push", "entries": list(entries),
                          "version": int(version)})

    def drain(self, timeout_s: Optional[float] = None,
              engine: Optional[str] = None) -> dict:
        """Ask the server to drain gracefully (idempotent).  Against a
        ``ServeRouter``, ``engine="host:port"`` names ONE backend for a
        planned drain (its hot KV migrates to survivors, then the
        victim drains and leaves rotation — the fleet keeps serving);
        without it the whole front door drains."""
        msg: dict = {"action": "drain"}
        if timeout_s is not None:
            msg["timeout_s"] = float(timeout_s)
        if engine is not None:
            msg["engine"] = str(engine)
        return self._rpc(msg)

    def undrain(self, engine: Optional[str] = None) -> dict:
        """Reopen admission on a parked (drained-but-running) service —
        the scale-UP seam (ISSUE 17), the inverse of single-engine
        ``drain``.  Against a ``ServeRouter``, ``engine="host:port"``
        names the parked backend to un-drain and re-adopt into
        rotation; against an engine server it un-drains that engine."""
        msg: dict = {"action": "undrain"}
        if engine is not None:
            msg["engine"] = str(engine)
        return self._rpc(msg)

    def close(self) -> None:
        try:
            send_msg(self.sock, {"action": "stop"}, registry=self.registry,
                     version=self.wire_version)
            recv_msg(self.sock, registry=self.registry)
        except (ConnectionError, OSError):
            pass
        finally:
            try:
                self.sock.close()
            except OSError:
                pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

"""Online inference serving — the continuous-batching decode service
(ISSUE 7 tentpole).

The reference ships distributed inference as a first-class layer
(``ModelPredictor`` batched jit inference); this package is its ONLINE
counterpart for the ``gpt_lm`` family: a request queue + continuous
batcher over the ragged KV-cached decode (``models.generation`` — per-row
cache positions let a new request join a running batch slot as finished
rows retire), served over the v2 zero-copy tensor wire
(``ps.networking``), with per-request SLO histograms, admission control,
graceful drain, and a live ``stats`` RPC the same ``obs`` tooling reads.

Layers:

* ``config``  — ``ServeConfig``: batch slots, prefill length buckets,
  sampling controls, admission bounds.
* ``engine``  — ``DecodeEngine``: the scheduler/batcher and its three
  compiled-per-bucket programs (join = prefill + scatter into a slot,
  step = one token for every active slot), each behind its own retrace
  sentinel so steady-state serving is provably ``jit.retraces == 0``.
* ``server``  — ``ServeServer``: TCP front-end speaking the PS wire
  framing (hello/generate/stats/drain/stop) with v1/v2 negotiation.
* ``client``  — ``ServeClient``: the worker-side connection.
* ``router``  — ``ServeRouter`` (ISSUE 14): the engine-fleet front door
  — prefix-affinity + least-loaded routing across N engines, fleet-
  merged stats, fan-out ``promote`` with roll-forward on reconnect,
  evict/requeue/rejoin failure handling.
"""

from .config import ServeConfig  # noqa: F401
from .engine import DecodeEngine, ServeRejected, ServeRequest  # noqa: F401
from .server import ServeServer  # noqa: F401
from .client import ServeClient  # noqa: F401
from .router import RouterConfig, ServeRouter  # noqa: F401

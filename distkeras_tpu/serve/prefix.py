"""Prefix KV cache — decode accelerator #1 (ISSUE 11).

Production traffic shares long system-prompt prefixes, so most prefill
work is redundant: the engine caches every admitted prompt's device-side
KV slices (one single-row cache pytree padded to the model's full
``seq_len``, plus the token row itself) keyed by its token content.  A
later ``_admit`` looks up the **longest cached prefix** of its prompt
and dispatches a *suffix join* — a short compiled ``decode_window``
over only the uncached tail — instead of re-prefilling from token 0.
Time-to-first-token on a warm prefix collapses from O(prompt²·D)
prefill to O(suffix·prompt·D) replay.

**Block-aligned matching.**  An entry is registered under a lookup key
at every ``block`` boundary of its content (plus its full length), so
two prompts sharing a system prefix hit each other's entries without
either being a strict prefix of the other — the actual production
shape (``system + user_a`` vs ``system + user_b``).  A hit at matched
length ``m`` uses only cache positions ``< m``; the entry's own
continuation beyond ``m`` is *stale for this prompt* but provably
inert: a row's attention horizon is its own position, and every
position is overwritten by a real write before any kept logit attends
it (the same placeholder contract as prefill padding — see
``decode_window``).  Matches are verified token-by-token after the
hash, so a collision can never serve another prompt's KV.

This module is the HOST side only: an LRU of device-array entries with
byte accounting.  All device math (the per-bucket suffix-join programs,
entry capture inside the cold join) lives in ``engine.py``; exactness
holds because prefill and cached decode write identical K/V for
identical tokens at identical positions (the ``generate_tokens`` parity
contract ``models.generation`` already tests).

Bounds and invalidation:

* The LRU is bounded in **bytes** (``ServeConfig.prefix_cache_mb``) —
  entries are full-length KV slices, exactly one decode slot's worth of
  HBM each, so the budget composes with the ``mem.*`` watermark gauges
  the profiler already samples.  Inserting past the budget evicts
  least-recently-used entries (``serve.prefix.evictions``).
* ``DecodeEngine.promote()`` **flushes the cache**: cached KV is a pure
  function of (tokens, weights), so a checkpoint swap invalidates every
  entry.  Serving correctness never depends on the cache — only ttft
  does.

Thread-safety: one internal lock.  The decode thread looks up / inserts
on every admit; ``promote()`` flushes from the caller's thread.

Metrics (service registry): counters ``serve.prefix.hits`` /
``serve.prefix.misses`` / ``serve.prefix.inserts`` /
``serve.prefix.evictions``, gauges ``serve.prefix.bytes`` /
``serve.prefix.entries``.  The engine splits ttft into
``serve.ttft_warm_seconds`` / ``serve.ttft_cold_seconds`` on top of the
combined histogram.
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from typing import Optional

import numpy as np


def tree_nbytes(tree) -> int:
    """Total bytes of a pytree of (device) arrays."""
    import jax
    return sum(int(getattr(leaf, "nbytes",
                           np.asarray(leaf).nbytes))
               for leaf in jax.tree_util.tree_leaves(tree))


class PrefixEntry:
    """One cached prompt: its token row padded to ``seq_len`` (device),
    the single-row KV cache pytree(s) padded to ``seq_len`` (device),
    and the host-side token content for exact-match verification."""

    __slots__ = ("host_tokens", "length", "tokens", "cache", "draft_cache",
                 "nbytes", "alias_keys", "all_keys")

    def __init__(self, host_tokens: np.ndarray, tokens, cache,
                 draft_cache=None):
        self.host_tokens = np.asarray(host_tokens, np.int32)
        self.length = int(self.host_tokens.shape[0])
        self.tokens = tokens            # (1, T) int32, device
        self.cache = cache              # single-row KV pytree, device
        self.draft_cache = draft_cache  # ditto for the draft, or None
        self.nbytes = (tree_nbytes(cache) + int(tokens.nbytes)
                       + (0 if draft_cache is None
                          else tree_nbytes(draft_cache)))
        self.alias_keys: list = []      # lookup keys this entry OWNS
        self.all_keys: list = []        # every boundary key it can serve


class PrefixCache:
    """Byte-bounded LRU of :class:`PrefixEntry`, block-alias-keyed by
    token content.

    One entry, many keys: ``(L, sha1(tokens[:L]))`` for every ``block``
    multiple ``L`` of the entry's content plus its full length.  Lookup
    probes the registered lengths ascending in ONE incremental hash
    pass over the prompt (hash-state copy per boundary, then an exact
    token compare; the longest verified match wins) and caps the match
    at ``len(prompt) - 1``: the
    suffix join always re-plays at least one token, so it always
    produces fresh last-token logits and no zero-length-suffix program
    is needed."""

    def __init__(self, budget_bytes: int, registry, block: int = 16):
        self.budget = int(budget_bytes)
        self.block = max(1, int(block))
        self._entries: "OrderedDict[tuple, PrefixEntry]" = OrderedDict()
        self._alias: dict = {}       # (L, digest) -> primary key
        self._lengths: dict = {}     # alias length -> alias count
        #: (L, digest) -> set of primaries whose content STARTS with
        #: those bytes — every candidate heir for an alias whose owner
        #: evicts, exact by construction (each holder registered the
        #: digest of its OWN first L tokens)
        self._holders: dict = {}
        self._bytes = 0
        self._lock = threading.Lock()
        self._c_hits = registry.counter("serve.prefix.hits")
        self._c_misses = registry.counter("serve.prefix.misses")
        self._c_inserts = registry.counter("serve.prefix.inserts")
        self._c_remote_inserts = registry.counter(
            "serve.prefix.remote_inserts")
        self._c_evictions = registry.counter("serve.prefix.evictions")
        self._g_bytes = registry.gauge("serve.prefix.bytes")
        self._g_entries = registry.gauge("serve.prefix.entries")

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    @property
    def nbytes(self) -> int:
        with self._lock:
            return self._bytes

    def _alias_lengths(self, length: int):
        """The lookup lengths an entry of ``length`` registers: every
        block multiple plus the full length."""
        ls = set(range(self.block, length + 1, self.block))
        ls.add(length)
        return sorted(ls)

    def lookup(self, prompt: np.ndarray) -> Optional[tuple]:
        """Longest cached prefix of ``prompt`` as ``(entry,
        matched_len)`` (LRU-refreshed), or None.  ``matched_len`` is
        capped at ``len(prompt) - 1`` — an entry covering the WHOLE
        prompt (e.g. a resubmission) still re-plays the last token,
        regenerating its logits exactly.  Counts one hit or miss."""
        prompt = np.asarray(prompt, np.int32)
        n = int(prompt.shape[0])
        if n <= 1:  # matched_len is capped at n-1; nothing can match
            self._c_misses.inc()
            return None
        with self._lock:
            lengths = sorted(self._lengths)
        # ONE incremental hash pass over the prompt, OUTSIDE the lock
        # (the sha1 work dominates; this sits on the decode thread's
        # ttft-critical admit path and promote()'s flush must not stall
        # behind it).  A stale lengths snapshot only costs a benign
        # one-time miss at a just-registered boundary.
        data = np.ascontiguousarray(prompt).tobytes()
        digests = []
        h = hashlib.sha1()
        hashed = 0  # bytes of ``data`` folded into ``h`` so far
        for length in lengths:
            if length > n:
                break  # ascending: no later length can match
            h.update(data[hashed:length * 4])
            hashed = length * 4
            digests.append((length, h.copy().digest()))
        with self._lock:
            best = None
            for length, digest in digests:
                primary = self._alias.get((length, digest))
                if primary is None:
                    continue
                entry = self._entries[primary]
                if not np.array_equal(entry.host_tokens[:length],
                                      prompt[:length]):
                    continue
                best = (primary, length)  # ascending: keep the longest
            if best is None:
                self._c_misses.inc()
                return None
            primary, length = best
            self._entries.move_to_end(primary)
            self._c_hits.inc()
            return self._entries[primary], min(length, n - 1)

    def peek(self, prompt: np.ndarray) -> Optional[tuple]:
        """The longest cached prefix of ``prompt`` as ``(entry,
        matched_len)`` WITHOUT observing it: no hit/miss counters, no
        LRU refresh, no ``n - 1`` cap.  The KV-fabric export path
        (ISSUE 16) reads through this — the router's affinity-decay
        validation compares ``serve.prefix.hits``/``misses`` against
        routed traffic, and a fabric export probing the cache must not
        pollute that signal (or reorder the LRU the migration exporter
        is about to walk)."""
        prompt = np.asarray(prompt, np.int32)
        n = int(prompt.shape[0])
        if n < 1:
            return None
        with self._lock:
            lengths = sorted(self._lengths)
        data = np.ascontiguousarray(prompt).tobytes()
        digests = []
        h = hashlib.sha1()
        hashed = 0
        for length in lengths:
            if length > n:
                break
            h.update(data[hashed:length * 4])
            hashed = length * 4
            digests.append((length, h.copy().digest()))
        with self._lock:
            best = None
            for length, digest in digests:
                primary = self._alias.get((length, digest))
                if primary is None:
                    continue
                entry = self._entries[primary]
                if not np.array_equal(entry.host_tokens[:length],
                                      prompt[:length]):
                    continue
                best = (primary, length)
            if best is None:
                return None
            primary, length = best
            return self._entries[primary], length

    def hottest(self, max_entries: int, budget_bytes: int) -> list:
        """The MRU-side entries, most-recently-used first, stopping at
        ``max_entries`` or ``budget_bytes`` — the migration exporter's
        unit (ISSUE 16): a draining/evicting engine ships its hottest
        working set to survivors, bounded so a big cache never stalls
        the planned transition behind a bulk transfer."""
        out: list = []
        total = 0
        with self._lock:
            for primary in reversed(self._entries):
                entry = self._entries[primary]
                if len(out) >= int(max_entries) or \
                        total + entry.nbytes > int(budget_bytes):
                    break
                out.append(entry)
                total += entry.nbytes
        return out

    def insert_remote(self, entry: PrefixEntry) -> None:
        """Insert an entry whose KV arrived OVER THE WIRE from a peer
        engine — the KV-fabric landing seam (ISSUE 16), counted
        separately (``serve.prefix.remote_inserts``) so a snapshot shows
        how much of the cache was replicated vs locally computed.

        dklint rule 9 (``kv-version-guard``) restricts callers to
        ``serve/kvfabric.py``: remote KV is only valid under the
        checkpoint version it was computed for, and that stamp is
        checked (before AND after the insert) only inside the fabric
        seam — any other call site could join stale KV."""
        self._c_remote_inserts.inc()
        self.insert(entry)

    def insert(self, entry: PrefixEntry) -> None:
        """Insert (dedup by content: an existing identical entry is only
        LRU-refreshed, and an entry whose every lookup key is already
        owned — its content fully covered by an older entry — refreshes
        that owner instead of storing unreachable KV), then evict LRU
        entries past the byte budget."""
        # ONE incremental hash pass builds every boundary key, outside
        # the lock (like lookup()'s hash pass: the decode thread's
        # ttft-critical admit path); the full length is always the last
        # boundary, so the primary key falls out for free
        data = np.ascontiguousarray(entry.host_tokens).tobytes()
        keys = []
        h = hashlib.sha1()
        hashed = 0
        for length in self._alias_lengths(entry.length):
            h.update(data[hashed:length * 4])
            hashed = length * 4
            keys.append((length, h.copy().digest()))
        primary = keys[-1]
        with self._lock:
            if primary in self._entries:
                self._entries.move_to_end(primary)
                return
            self._entries[primary] = entry
            for key in keys:
                # first writer wins an alias: the older entry's prefix
                # KV is byte-identical for the shared tokens
                if key not in self._alias:
                    self._alias[key] = primary
                    entry.alias_keys.append(key)
                    self._lengths[key[0]] = \
                        self._lengths.get(key[0], 0) + 1
            if not entry.alias_keys:
                # every lookup key this entry could answer is owned by
                # an entry already holding these exact prefix bytes, so
                # it could never be hit — spend no budget on dead KV;
                # LRU-refresh the covering owner instead (the
                # dedup-by-content contract, extended to coverage)
                del self._entries[primary]
                owner = self._alias.get(primary)
                if owner is not None:
                    self._entries.move_to_end(owner)
                return
            entry.all_keys = keys
            for key in keys:
                self._holders.setdefault(key, set()).add(primary)
            self._bytes += entry.nbytes
            self._c_inserts.inc()
            while self._bytes > self.budget and self._entries:
                self._evict_lru()
            self._g_bytes.set(self._bytes)
            self._g_entries.set(len(self._entries))

    def _evict_lru(self) -> None:  # dklint: holds=_lock
        old_primary, old = self._entries.popitem(last=False)
        self._bytes -= old.nbytes
        for key in old.all_keys:
            held = self._holders.get(key)
            if held is not None:
                held.discard(old_primary)
                if not held:
                    del self._holders[key]
        for key in old.alias_keys:
            # First-writer-wins means the evictee may own lookup keys
            # whose prefix bytes other live entries still hold (their
            # KV for the shared tokens is byte-identical) — re-point
            # the alias at a surviving holder instead of dropping it
            # and forcing an avoidable cold prefill.  The holders index
            # makes this an exact O(1) probe: every candidate registered
            # the digest of its OWN first ``key[0]`` tokens, and lookup
            # still token-verifies after the hash, so a collision can
            # never serve foreign KV.
            held = self._holders.get(key)
            if held:
                heir = next(iter(held))
                self._alias[key] = heir
                self._entries[heir].alias_keys.append(key)
                continue
            self._alias.pop(key, None)
            length = key[0]
            left = self._lengths.get(length, 1) - 1
            if left:
                self._lengths[length] = left
            else:
                self._lengths.pop(length, None)
        self._c_evictions.inc()

    def flush(self) -> int:
        """Drop every entry (checkpoint promotion: cached KV is a pure
        function of the weights).  Returns the number dropped."""
        with self._lock:
            n = len(self._entries)
            self._entries.clear()
            self._alias.clear()
            self._lengths.clear()
            self._holders.clear()
            self._bytes = 0
            self._g_bytes.set(0)
            self._g_entries.set(0)
            return n

"""TCP front-end for the decode service (ISSUE 7).

Speaks the PS wire (``ps.networking`` framing — v2 zero-copy tensor
segments with per-connection v1/v2 hello negotiation, the exact seam the
parameter-server stack uses), one handler thread per connection, every
request one framed msgpack map with an ``action`` key:

* ``hello``    — wire-format negotiation (shared ``choose_wire_version``).
* ``generate`` — ``{"prompt": int32 array, "max_new_tokens": int?}`` ->
  ``{"ok": True, "tokens": int32 array, ...timings}`` or a load-shed
  ``{"ok": False, "rejected": True, "reason": ...}`` (admission control)
  or ``{"ok": False, "error": ...}`` for malformed requests.  Prompt and
  tokens ride as tensors — zero-copy on v2 connections.
* ``stats``    — live registry snapshot + queue/slot state, no decode
  work: the ``obsview --serve`` poll path.
* ``drain``    — start a graceful drain (admission closes, in-flight
  completes); idempotent.
* ``stop``     — close THIS connection (parity with the PS protocol).

``stop(drain=True)`` (default, also the context-manager exit) closes the
listener, drains the engine — every in-flight request completes, every
request refused after the drain began is a recorded rejection — then
closes live connections.
"""

from __future__ import annotations

import socket
import threading
import time
from typing import Optional

import numpy as np

from ..obs.logging import get_logger
from ..ps.networking import (WIRE_VERSION, choose_wire_version, recv_msg,
                             send_msg)
from .engine import DecodeEngine, ServeRejected

_LOG = "serve.server"


class ServeServer:
    """Accept loop + per-connection handlers over a ``DecodeEngine``.

    The engine's registry is the server's too (``serve.connections`` and
    the wire byte counts land beside the SLO histograms), so one
    ``stats`` reply describes the whole service."""

    def __init__(self, engine: DecodeEngine, host: str = "127.0.0.1",
                 port: int = 0, max_wire_version: int = WIRE_VERSION):
        self.engine = engine
        self.host = host
        self.port = port
        #: pin to 1 to emulate (and interop-test against) a legacy server
        self.max_wire_version = int(max_wire_version)
        self.registry = engine.registry
        self._sock: Optional[socket.socket] = None
        self._threads: list = []
        self._conns: list = []
        self._conn_lock = threading.Lock()
        self._running = threading.Event()
        self._g_conns = self.registry.gauge("serve.connections")
        self._g_inflight = self.registry.gauge("serve.inflight")

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "ServeServer":
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((self.host, self.port))
        self.port = self._sock.getsockname()[1]
        self._sock.listen(128)
        self._running.set()
        if self.engine._thread is None:
            self.engine.start()
        t = threading.Thread(target=self._accept_loop, daemon=True,
                             name="serve-accept")
        # same _threads contract as the PS front-end: index 0 is always
        # the accept thread; every touch goes through _conn_lock
        with self._conn_lock:
            self._threads.append(t)
        t.start()
        return self

    def stop(self, drain: bool = True,
             timeout: Optional[float] = None) -> None:
        """Shut down: close the listener first (no NEW connections), then
        drain the engine (in-flight generates complete and their replies
        go out), then unblock idle handlers by closing live sockets."""
        self._running.clear()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        self.engine.stop(drain=drain, timeout=timeout)
        # let handlers flush replies for requests the drain just
        # completed before their sockets are pulled out from under them
        deadline = time.monotonic() + 5.0
        while self._g_inflight.value > 0 and time.monotonic() < deadline:
            time.sleep(0.01)
        with self._conn_lock:
            conns = list(self._conns)
            threads = list(self._threads)
        for c in conns:
            try:
                c.close()
            except OSError:
                pass
        for t in threads[1:]:
            t.join(timeout=5)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # -- loops --------------------------------------------------------------
    def _accept_loop(self):
        while self._running.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return  # listener closed by stop()
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._conn_lock:
                self._conns.append(conn)
            self._g_conns.inc()
            t = threading.Thread(target=self._handle_connection,
                                 args=(conn,), daemon=True,
                                 name="serve-conn")
            t.start()
            with self._conn_lock:
                # prune finished handlers so a long-lived server (one
                # short connection per obsview poll) never accumulates
                # dead Thread objects; index 0 stays the accept thread
                self._threads[1:] = [h for h in self._threads[1:]
                                     if h.is_alive()]
                self._threads.append(t)

    def _stats_reply(self) -> dict:
        eng = self.engine
        with eng._lock:
            queued = len(eng._queue)
            draining = eng._draining
        return {"stats": self.registry.snapshot(),
                "server": type(self).__name__,
                "model": getattr(eng.model, "name", "?"),
                "slots": eng._b,
                "seq_len": eng._t,
                "prefill_buckets": list(eng._buckets),
                "queue_depth": queued,
                "active_slots": eng._active_count(),
                "draining": bool(draining)}

    def _handle_generate(self, msg: dict) -> dict:
        prompt = msg.get("prompt")
        if prompt is None:
            return {"ok": False, "error": "generate needs a prompt"}
        try:
            req = self.engine.submit(np.asarray(prompt),
                                     msg.get("max_new_tokens"))
        except ServeRejected as e:
            return {"ok": False, "rejected": True, "reason": e.reason}
        except (ValueError, TypeError) as e:
            return {"ok": False, "error": str(e)}
        req.wait()
        if req.error is not None:
            # aborted mid-flight (hard stop): already counted under
            # serve.rejected by the engine
            return {"ok": False, "rejected": True, "reason": req.error}
        reply = {"ok": True,
                 "tokens": np.asarray(req.tokens, np.int32),
                 "e2e_s": req.done_t - req.submit_t}
        if req.admit_t is not None:
            reply["queue_wait_s"] = req.admit_t - req.submit_t
        if req.first_token_t is not None:
            reply["ttft_s"] = req.first_token_t - req.submit_t
        return reply

    def _handle_connection(self, conn: socket.socket):
        reg = self.registry
        ver = 1  # per-connection wire version; hello upgrades it
        try:
            while self._running.is_set():
                try:
                    msg = recv_msg(conn, registry=reg)
                except (ConnectionError, OSError):
                    return
                action = msg.get("action")
                self._g_inflight.inc()
                try:
                    if action == "hello":
                        ver = choose_wire_version(msg.get("versions"),
                                                  self.max_wire_version)
                        # reply stays v1-framed: the client switches only
                        # after reading it
                        send_msg(conn, {"ok": True, "version": ver},
                                 registry=reg)
                    elif action == "generate":
                        send_msg(conn, self._handle_generate(msg),
                                 registry=reg, version=ver)
                    elif action == "stats":
                        send_msg(conn, self._stats_reply(), registry=reg,
                                 version=ver)
                    elif action == "drain":
                        drained = self.engine.drain(
                            timeout=msg.get("timeout_s"))
                        send_msg(conn, {"ok": True, "drained": drained},
                                 registry=reg, version=ver)
                    elif action == "stop":
                        send_msg(conn, {"ok": True}, registry=reg,
                                 version=ver)
                        return
                    else:
                        send_msg(conn,
                                 {"ok": False,
                                  "error": f"unknown action {action!r}"},
                                 registry=reg, version=ver)
                except (ConnectionError, OSError) as e:
                    get_logger(_LOG).warning(
                        "reply to %r failed (peer gone?): %s", action, e)
                    return
                except Exception as e:
                    # a malformed FIELD (e.g. a non-numeric version list)
                    # must answer like any bad request, not kill the
                    # handler and drop the connection replyless
                    get_logger(_LOG).warning("action %r failed: %s",
                                             action, e)
                    try:
                        send_msg(conn, {"ok": False, "error": str(e)},
                                 registry=reg, version=ver)
                    except (ConnectionError, OSError):
                        return
                finally:
                    self._g_inflight.dec()
        finally:
            try:
                conn.close()
            except OSError:
                pass
            with self._conn_lock:
                if conn in self._conns:
                    self._conns.remove(conn)
            self._g_conns.dec()

"""TCP front-end for the decode service (ISSUE 7).

Speaks the PS wire (``ps.networking`` framing — v2 zero-copy tensor
segments with per-connection v1/v2 hello negotiation) on the shared
``networking.FrameServer`` frame (ISSUE 8: the accept loop, handler-
thread bookkeeping and stop sequencing previously mirrored between this
module and ``ps.servers`` live there once).  Every request is one framed
msgpack map with an ``action`` key:

* ``hello``    — wire-format negotiation (``FrameServer``).
* ``generate`` — ``{"prompt": int32 array, "max_new_tokens": int?,
  "temperature": float?, "top_k": int?, "top_p": float?}`` ->
  ``{"ok": True, "tokens": int32 array, ...timings}`` or a load-shed
  ``{"ok": False, "rejected": True, "reason": ...}`` (admission control)
  or ``{"ok": False, "error": ...}`` for malformed requests.  Prompt and
  tokens ride as tensors — zero-copy on v2 connections.  The sampling
  keys are per-request overrides of the engine defaults (ISSUE 14);
  old servers ignore them, per the wire's extension contract.
* ``stats``    — live registry snapshot + queue/slot state, no decode
  work: the ``obsview --serve`` / ``--continual`` poll path.
* ``promote``  — ``{"variables": pytree}`` -> checkpoint hot-swap via
  ``engine.promote()`` (ISSUE 8: the cross-process deploy seam the
  continual trainer uses; the tree rides the v2 zero-copy frame).  A
  tree that does not match the serving model's answers ``{"ok": False,
  "error": ...}`` — the decode loop never sees it.
* ``drain``    — start a graceful drain (admission closes, in-flight
  completes); idempotent.
* ``stop``     — close THIS connection (``FrameServer``).

``stop(drain=True)`` (default, also the context-manager exit) closes the
listener, drains the engine — every in-flight request completes, every
request refused after the drain began is a recorded rejection — then
closes live connections.
"""

from __future__ import annotations

import socket
import time
from typing import Optional

import numpy as np

from ..ps.networking import (REPLY_SENT, STREAM_CHUNK_BYTES,
                             WIRE_VERSION, FrameServer, pack_stream,
                             send_stream)
from .engine import DecodeEngine, ServeRejected


class ServeServer(FrameServer):
    """Accept loop + per-connection handlers over a ``DecodeEngine``,
    on the shared TCP front-end frame.

    The engine's registry is the server's too (``serve.connections`` and
    the wire byte counts land beside the SLO histograms), so one
    ``stats`` reply describes the whole service."""

    metric_prefix = "serve"

    def __init__(self, engine: DecodeEngine, host: str = "127.0.0.1",
                 port: int = 0, max_wire_version: int = WIRE_VERSION):
        super().__init__(engine.registry, host=host, port=port,
                         max_wire_version=max_wire_version)
        self.engine = engine
        # stop() parameters stashed for the frame's drain hook
        self._stop_drain = True
        self._stop_timeout: Optional[float] = None

    # -- lifecycle hooks ----------------------------------------------------
    def _on_start(self) -> None:
        if self.engine._thread is None:
            self.engine.start()

    def stop(self, drain: bool = True,
             timeout: Optional[float] = None) -> None:
        """Shut down: close the listener first (no NEW connections), then
        drain the engine (in-flight generates complete and their replies
        go out), then unblock idle handlers by closing live sockets."""
        self._stop_drain = bool(drain)
        self._stop_timeout = timeout
        super().stop()

    def _before_close_connections(self) -> None:
        self.engine.stop(drain=self._stop_drain, timeout=self._stop_timeout)
        # let handlers flush replies for requests the drain just
        # completed before their sockets are pulled out from under them
        deadline = time.monotonic() + 5.0
        while self._g_inflight.value > 0 and time.monotonic() < deadline:
            time.sleep(0.01)

    # -- request handlers ---------------------------------------------------
    def _stats_reply(self) -> dict:
        eng = self.engine
        with eng._lock:
            queued = len(eng._queue)
            draining = eng._draining
        return {"stats": self.registry.snapshot(),
                "server": type(self).__name__,
                "model": getattr(eng.model, "name", "?"),
                "slots": eng._b,
                "seq_len": eng._t,
                "prefill_buckets": list(eng._buckets),
                "queue_depth": queued,
                "active_slots": eng._active_count(),
                "draining": bool(draining)}

    def _handle_generate(self, msg: dict) -> dict:
        prompt = msg.get("prompt")
        if prompt is None:
            return {"ok": False, "error": "generate needs a prompt"}
        try:
            req = self.engine.submit(np.asarray(prompt),
                                     msg.get("max_new_tokens"),
                                     temperature=msg.get("temperature"),
                                     top_k=msg.get("top_k"),
                                     top_p=msg.get("top_p"))
        except ServeRejected as e:
            return {"ok": False, "rejected": True, "reason": e.reason}
        except (ValueError, TypeError) as e:
            return {"ok": False, "error": str(e)}
        req.wait()
        if req.error is not None:
            # aborted mid-flight (hard stop): already counted under
            # serve.rejected by the engine
            return {"ok": False, "rejected": True, "reason": req.error}
        reply = {"ok": True,
                 "tokens": np.asarray(req.tokens, np.int32),
                 "e2e_s": req.done_t - req.submit_t}
        if req.admit_t is not None:
            reply["queue_wait_s"] = req.admit_t - req.submit_t
        if req.first_token_t is not None:
            reply["ttft_s"] = req.first_token_t - req.submit_t
        if req.warm is not None:
            # the admit-time prefix-cache outcome (ISSUE 16): the router
            # splits its spill TTFT histograms on this — a spill that
            # warm-joined proves the fabric replicated in time.  Old
            # clients ignore the key, per the wire's extension contract
            reply["warm"] = bool(req.warm)
        return reply

    def _handle_promote(self, msg: dict) -> dict:
        """Checkpoint hot-swap over the wire — the deploy seam a
        cross-process continual trainer promotes through (ISSUE 8)."""
        variables = msg.get("variables")
        if variables is None:
            return {"ok": False, "error": "promote needs a variables tree"}
        try:
            self.engine.promote(variables)
        except (ValueError, TypeError) as e:
            # a mismatched tree is a BAD REQUEST: answer it, don't hand
            # the decode thread state it would crash on
            return {"ok": False, "error": str(e)}
        return {"ok": True,
                "promotions":
                    int(self.engine._c_promotions.value)}

    def _handle_kv_fetch(self, msg: dict, ver: int, conn) -> object:
        """Export cached prefix KV for the fleet fabric (ISSUE 16):
        the longest entry matching ``prompt`` (replication-on-spill),
        or the ``hottest`` MRU entries within ``budget_bytes``
        (migration).  On a v2 connection the reply — megabytes of KV —
        rides the ``DKW4`` chunked stream frame (the PR 15 pull path,
        reused): the peer decodes chunk k while k+1 is in flight,
        landing the leaves in its pooled receive arena.  v1 peers get
        the same document monolithic."""
        if not self.engine.config.kv_fabric:
            return {"ok": False, "error": "kv fabric disabled"}
        hottest = msg.get("hottest")
        if hottest is not None:
            doc = self.engine.kv_export_hottest(
                int(hottest),
                int(msg.get("budget_bytes") or 64 * 1024 * 1024))
        else:
            prompt = msg.get("prompt")
            if prompt is None:
                return {"ok": False,
                        "error": "kv_fetch needs a prompt or hottest"}
            doc = self.engine.kv_export(np.asarray(prompt))
        reply = {"ok": True, "found": doc is not None,
                 "entries": (doc or {}).get("entries", []),
                 "version": (doc or {}).get(
                     "version", self.engine.kv_version)}
        if ver >= 2 and doc is not None:
            send_stream(
                conn, pack_stream(reply, STREAM_CHUNK_BYTES, version=ver),
                registry=self.registry,
                count_as=f"{self.metric_prefix}.wire.bytes_down",
                action="kv_fetch_stream")
            return REPLY_SENT
        return reply

    def _handle_kv_push(self, msg: dict) -> dict:
        """Admit peer-exported KV entries stamped with a checkpoint
        ``version`` (ISSUE 16).  Every entry either joins through the
        version-guarded ``serve.kvfabric`` seam or is refused with a
        reason — a stale stamp is refused, never joined."""
        if not self.engine.config.kv_fabric:
            return {"ok": False, "error": "kv fabric disabled"}
        entries = msg.get("entries")
        if not entries:
            return {"ok": False, "error": "kv_push needs entries"}
        version = msg.get("version")
        if version is None:
            return {"ok": False,
                    "error": "kv_push needs a version stamp"}
        joined = refused_stale = refused_other = 0
        reason = None
        for doc in entries:
            ok, why = self.engine.kv_import(doc, int(version))
            if ok:
                joined += 1
            elif why == "stale":
                refused_stale += 1
            else:
                refused_other += 1
                reason = why
        reply = {"ok": True, "joined": joined,
                 "refused_stale": refused_stale,
                 "refused": refused_stale + refused_other}
        if reason is not None:
            reply["reason"] = reason
        return reply

    def handle_request(self, action, msg: dict, ver: int,
                       conn: socket.socket):
        """Serve protocol body on the shared frame (``hello``/``stop``/
        errors live in ``FrameServer``)."""
        if action == "generate":
            return self._handle_generate(msg)
        if action == "stats":
            return self._stats_reply()
        if action == "promote":
            return self._handle_promote(msg)
        if action == "drain":
            drained = self.engine.drain(timeout=msg.get("timeout_s"))
            return {"ok": True, "drained": drained}
        if action == "undrain":
            # scale-up seam (ISSUE 17): reopen admission on a parked
            # (drained-but-running) engine
            try:
                was = self.engine.undrain()
            except RuntimeError as e:
                return {"ok": False, "error": str(e)}
            return {"ok": True, "was_draining": was}
        if action == "kv_fetch":
            return self._handle_kv_fetch(msg, ver, conn)
        if action == "kv_push":
            return self._handle_kv_push(msg)
        return None

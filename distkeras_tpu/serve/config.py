"""Serving configuration — the knob bundle ``DecodeEngine``/``ServeServer``
share (ISSUE 7).

The one load-bearing choice is **bucketing**: every compiled program's
shapes are fixed by ``(slots, seq_len)`` plus a small ascending set of
prefill lengths (``prefill_buckets``).  A request's prompt is right-padded
to the smallest bucket that holds it, so the whole service compiles
``len(buckets) + 1`` programs total (one join per bucket + one step) and
then NEVER re-traces — the property the PR 6 retrace sentinel gates at
``jit.retraces == 0`` in steady state.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple

#: smallest derived prefill bucket — below this, halving buckets buys
#: little prefill time and costs a compiled program each
_MIN_BUCKET = 32

#: derived bucket count cap (largest is always the full seq_len)
_MAX_BUCKETS = 4


@dataclasses.dataclass
class ServeConfig:
    """Knobs for the continuous-batching decode service.

    * ``slots`` — continuous-batch width: how many requests decode
      concurrently (the B of every compiled program).
    * ``max_queue`` — admission bound: every request transits the queue
      (the decode thread drains it into slots), so this bounds the
      admitted-but-not-yet-slotted backlog; a full queue load-sheds
      (``serve.rejected``).  Must be >= 1 — a zero-length queue would
      reject everything even with every slot idle.
    * ``max_new_tokens`` — per-request generation cap (and the default
      when a request names none); admission enforces
      ``prompt_len + max_new <= seq_len``.
    * ``prefill_buckets`` — ascending prompt-pad lengths; None derives
      a geometric ladder ending at the model's ``seq_len``.
    * ``temperature`` / ``top_k`` / ``top_p`` / ``eos_id`` — sampling
      controls, identical semantics to
      ``models.generation.generate_tokens`` (0.0 = greedy; ``eos_id``
      finishes a row early).  ISSUE 14: the first three are the
      per-request DEFAULTS — ``submit()`` / the ``generate`` RPC may
      override them per request, and the params ride into the one
      compiled step program as per-row traced values, so any mix of
      greedy and sampled requests shares a batch at
      ``jit.retraces == 0``.
    * ``seed`` — sampling PRNG seed (one stream for the whole service;
      with ``temperature == 0`` decoding is deterministic per request).
    * ``drain_timeout_s`` — graceful-drain bound: how long ``drain()``
      waits for in-flight requests before aborting them (aborts are
      recorded as rejections — nothing drops silently).
    * ``prefix_cache`` / ``prefix_cache_mb`` — ISSUE 11 decode
      accelerator #1: cache admitted prompts' device-side KV slices
      keyed by their token prefix, so a later prompt sharing a prefix
      re-plays only its *suffix* over the cached KV (a short compiled
      decode window) instead of re-prefilling from token 0.  The LRU
      over cached slices is bounded by ``prefix_cache_mb`` (must be > 0
      when the cache is enabled — an unbounded device-memory cache is a
      config error, the ``max_queue=0`` rejection precedent).
    * ``prefix_block`` — prefix-match granularity in tokens: every
      cached prompt is findable at each ``prefix_block`` boundary of
      its content, so two prompts sharing a system prefix hit each
      other's entries without either being a strict prefix of the
      other.  Smaller blocks match more, cost more lookup hashing.
    * ``kv_fabric`` — ISSUE 16: answer the fleet KV fabric's
      ``kv_fetch``/``kv_push`` RPCs (export cached prefix KV to peer
      engines, admit version-stamped pushes from them).  Requires the
      prefix cache; on a cache-less engine the RPCs answer "disabled"
      and the router's fabric simply never warms spills to it.  Off
      turns an engine into a fabric island — its cache neither
      replicates out nor accepts pushes (e.g. an engine serving a
      different checkpoint lineage).
    * ``spec_k`` — ISSUE 11 decode accelerator #2: speculative decoding.
      0 disables; k >= 1 makes a small *draft* model (passed to
      ``DecodeEngine``) propose k tokens per active row per step, which
      the target verifies in ONE batched decode window — accepted-prefix
      rollback keeps the ragged KV cache exact and greedy output
      provably equals ``generate_tokens``.  ISSUE 14: composes with
      ``temperature > 0`` — sampled rows run the distribution-preserving
      accept/reject (``serve/spec.py``), greedy rows keep the provably
      parity-exact argmax chain.
    """

    slots: int = 4
    max_queue: int = 32
    max_new_tokens: int = 64
    prefill_buckets: Optional[Sequence[int]] = None
    temperature: float = 0.0
    top_k: Optional[int] = None
    top_p: Optional[float] = None
    eos_id: Optional[int] = None
    seed: int = 0
    drain_timeout_s: float = 30.0
    prefix_cache: bool = False
    prefix_cache_mb: float = 64.0
    prefix_block: int = 16
    kv_fabric: bool = True
    spec_k: int = 0

    def __post_init__(self):
        if int(self.slots) < 1:
            raise ValueError(f"slots must be >= 1, got {self.slots}")
        if int(self.max_queue) < 1:
            raise ValueError(f"max_queue must be >= 1 (admission flows "
                             f"through the queue), got {self.max_queue}")
        if int(self.max_new_tokens) < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got "
                             f"{self.max_new_tokens}")
        if float(self.temperature) < 0.0:
            raise ValueError(f"temperature must be >= 0, got "
                             f"{self.temperature}")
        if self.top_k is not None and int(self.top_k) < 1:
            raise ValueError(f"top_k must be >= 1, got {self.top_k}")
        if self.top_p is not None and not 0.0 < float(self.top_p) <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {self.top_p}")
        # the new-knob validation happens HERE, at config time — the
        # max_queue=0 precedent: a config that can only misbehave is
        # rejected before an engine (or a fleet of them) is built on it
        if self.prefix_cache and not float(self.prefix_cache_mb) > 0.0:
            raise ValueError(
                f"prefix_cache_mb must be > 0 when the prefix cache is "
                f"enabled (it bounds the device-side KV LRU), got "
                f"{self.prefix_cache_mb}")
        if int(self.prefix_block) < 1:
            raise ValueError(f"prefix_block must be >= 1, got "
                             f"{self.prefix_block}")
        if int(self.spec_k) < 0:
            raise ValueError(f"spec_k must be >= 0 (0 disables "
                             f"speculative decode), got {self.spec_k}")

    def resolved_buckets(self, seq_len: int) -> Tuple[int, ...]:
        """The ascending prefill-bucket lengths for a ``seq_len`` model:
        the explicit ``prefill_buckets`` (validated, largest must cover
        the longest admissible prompt = ``seq_len``), or a derived
        geometric ladder ``(..., seq_len/4, seq_len/2, seq_len)``."""
        seq_len = int(seq_len)
        if self.prefill_buckets is not None:
            buckets = sorted({int(b) for b in self.prefill_buckets})
            if not buckets or buckets[0] < 1 or buckets[-1] > seq_len:
                raise ValueError(
                    f"prefill_buckets must lie in [1, {seq_len}], got "
                    f"{self.prefill_buckets}")
            if buckets[-1] != seq_len:
                buckets.append(seq_len)
            return tuple(buckets)
        buckets = [seq_len]
        while buckets[0] // 2 >= _MIN_BUCKET and len(buckets) < _MAX_BUCKETS:
            buckets.insert(0, buckets[0] // 2)
        return tuple(buckets)

    def bucket_for(self, prompt_len: int, seq_len: int) -> int:
        """Smallest bucket holding ``prompt_len`` (ValueError when none)."""
        for b in self.resolved_buckets(seq_len):
            if prompt_len <= b:
                return b
        raise ValueError(f"prompt length {prompt_len} exceeds the largest "
                         f"prefill bucket "
                         f"{self.resolved_buckets(seq_len)[-1]}")

    def config_row(self, seq_len: int) -> dict:
        """Plain-data config for obs snapshots / the bench row — the
        fields that make two runs comparable (drift gate ``config``)."""
        return {
            "slots": int(self.slots),
            "max_queue": int(self.max_queue),
            "max_new_tokens": int(self.max_new_tokens),
            "prefill_buckets": list(self.resolved_buckets(seq_len)),
            "temperature": float(self.temperature),
            "top_k": None if self.top_k is None else int(self.top_k),
            "top_p": None if self.top_p is None else float(self.top_p),
            "eos_id": None if self.eos_id is None else int(self.eos_id),
            "prefix_cache": bool(self.prefix_cache),
            "prefix_cache_mb": float(self.prefix_cache_mb)
            if self.prefix_cache else None,
            "prefix_block": int(self.prefix_block)
            if self.prefix_cache else None,
            "kv_fabric": bool(self.kv_fabric and self.prefix_cache),
            "spec_k": int(self.spec_k),
        }

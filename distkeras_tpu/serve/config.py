"""Serving configuration — the knob bundle ``DecodeEngine``/``ServeServer``
share (ISSUE 7).

The one load-bearing choice is **bucketing**: every compiled program's
shapes are fixed by ``(slots, seq_len)`` plus a small ascending set of
prefill lengths (``prefill_buckets``).  A request's prompt is right-padded
to the smallest bucket that holds it, so the whole service compiles
``len(buckets) + 1`` programs total (one join per bucket + one step) and
then NEVER re-traces — the property the PR 6 retrace sentinel gates at
``jit.retraces == 0`` in steady state.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple

#: smallest derived prefill bucket — below this, halving buckets buys
#: little prefill time and costs a compiled program each
_MIN_BUCKET = 32

#: derived bucket count cap (largest is always the full seq_len)
_MAX_BUCKETS = 4


@dataclasses.dataclass
class ServeConfig:
    """Knobs for the continuous-batching decode service.

    * ``slots`` — continuous-batch width: how many requests decode
      concurrently (the B of every compiled program).
    * ``max_queue`` — admission bound: every request transits the queue
      (the decode thread drains it into slots), so this bounds the
      admitted-but-not-yet-slotted backlog; a full queue load-sheds
      (``serve.rejected``).  Must be >= 1 — a zero-length queue would
      reject everything even with every slot idle.
    * ``max_new_tokens`` — per-request generation cap (and the default
      when a request names none); admission enforces
      ``prompt_len + max_new <= seq_len``.
    * ``prefill_buckets`` — ascending prompt-pad lengths; None derives
      a geometric ladder ending at the model's ``seq_len``.
    * ``temperature`` / ``top_k`` / ``top_p`` / ``eos_id`` — service-level
      sampling controls, identical semantics to
      ``models.generation.generate_tokens`` (0.0 = greedy; ``eos_id``
      finishes a row early).
    * ``seed`` — sampling PRNG seed (one stream for the whole service;
      with ``temperature == 0`` decoding is deterministic per request).
    * ``drain_timeout_s`` — graceful-drain bound: how long ``drain()``
      waits for in-flight requests before aborting them (aborts are
      recorded as rejections — nothing drops silently).
    """

    slots: int = 4
    max_queue: int = 32
    max_new_tokens: int = 64
    prefill_buckets: Optional[Sequence[int]] = None
    temperature: float = 0.0
    top_k: Optional[int] = None
    top_p: Optional[float] = None
    eos_id: Optional[int] = None
    seed: int = 0
    drain_timeout_s: float = 30.0

    def __post_init__(self):
        if int(self.slots) < 1:
            raise ValueError(f"slots must be >= 1, got {self.slots}")
        if int(self.max_queue) < 1:
            raise ValueError(f"max_queue must be >= 1 (admission flows "
                             f"through the queue), got {self.max_queue}")
        if int(self.max_new_tokens) < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got "
                             f"{self.max_new_tokens}")
        if float(self.temperature) < 0.0:
            raise ValueError(f"temperature must be >= 0, got "
                             f"{self.temperature}")
        if self.top_k is not None and int(self.top_k) < 1:
            raise ValueError(f"top_k must be >= 1, got {self.top_k}")
        if self.top_p is not None and not 0.0 < float(self.top_p) <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {self.top_p}")

    def resolved_buckets(self, seq_len: int) -> Tuple[int, ...]:
        """The ascending prefill-bucket lengths for a ``seq_len`` model:
        the explicit ``prefill_buckets`` (validated, largest must cover
        the longest admissible prompt = ``seq_len``), or a derived
        geometric ladder ``(..., seq_len/4, seq_len/2, seq_len)``."""
        seq_len = int(seq_len)
        if self.prefill_buckets is not None:
            buckets = sorted({int(b) for b in self.prefill_buckets})
            if not buckets or buckets[0] < 1 or buckets[-1] > seq_len:
                raise ValueError(
                    f"prefill_buckets must lie in [1, {seq_len}], got "
                    f"{self.prefill_buckets}")
            if buckets[-1] != seq_len:
                buckets.append(seq_len)
            return tuple(buckets)
        buckets = [seq_len]
        while buckets[0] // 2 >= _MIN_BUCKET and len(buckets) < _MAX_BUCKETS:
            buckets.insert(0, buckets[0] // 2)
        return tuple(buckets)

    def bucket_for(self, prompt_len: int, seq_len: int) -> int:
        """Smallest bucket holding ``prompt_len`` (ValueError when none)."""
        for b in self.resolved_buckets(seq_len):
            if prompt_len <= b:
                return b
        raise ValueError(f"prompt length {prompt_len} exceeds the largest "
                         f"prefill bucket "
                         f"{self.resolved_buckets(seq_len)[-1]}")

    def config_row(self, seq_len: int) -> dict:
        """Plain-data config for obs snapshots / the bench row — the
        fields that make two runs comparable (drift gate ``config``)."""
        return {
            "slots": int(self.slots),
            "max_queue": int(self.max_queue),
            "max_new_tokens": int(self.max_new_tokens),
            "prefill_buckets": list(self.resolved_buckets(seq_len)),
            "temperature": float(self.temperature),
            "top_k": None if self.top_k is None else int(self.top_k),
            "top_p": None if self.top_p is None else float(self.top_p),
            "eos_id": None if self.eos_id is None else int(self.eos_id),
        }

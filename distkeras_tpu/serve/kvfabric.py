"""Fleet KV fabric (ISSUE 16): cached prefix KV as a FLEET resource.

PR 11's ``PrefixCache`` made one engine warm; PR 13's affinity router
keeps each prefix's traffic on the engine that holds its KV.  But the
cache is strictly engine-local: when the affine owner's in-flight bound
fills, overflow spills to a COLD sibling and pays the full cold-prefill
time-to-first-token, and a drain/evict throws the victim's whole warm
set away.  This module moves the KV instead of recomputing it, over the
``kv_fetch``/``kv_push`` RPC pair on the serve wire (the ``kv_fetch``
reply rides the ``DKW4`` chunked zero-copy stream frame PR 15 built —
reused through ``ps.networking``, not forked):

* **Replication on spill** — when the router routes a request to a
  non-owner of its longest affinity prefix, it enqueues a fabric job:
  fetch the owner's longest matching cache entry, push it to the spill
  target.  Jobs are single-flight per (target, prefix-key), bounded per
  link (``kv_link_inflight`` queued-or-running jobs per (owner, target)
  pair) and by an in-flight byte budget (``kv_fabric_mb``), and run on
  ONE background worker thread — at most one transfer rides any wire at
  a time, so replication never starves decode traffic.  A completed
  replication registers the target as a SECONDARY owner in the router's
  affinity table, so repeat overflow routes warm without re-fetching.
* **Migration on planned transitions** — a planned single-engine drain
  (and, best-effort, a router evict) first pulls the victim's hottest
  entries (MRU side of its LRU, entry- and byte-bounded) and pushes
  them to the least-loaded survivors, re-pointing the victim's affinity
  keys at the recipients — the warm set survives the engine going dark.

**The version-stamp refusal rule.**  Cached KV is a pure function of
(tokens, weights), and ``promote()`` flushes it on every checkpoint
swap — KV that crosses the wire must carry the same guarantee.  Every
export is stamped with the source engine's ``kv_version`` (bumped by
the decode thread at promotion ADOPTION, the exact moment new inserts
start being computed under the new weights).  :func:`admit_remote_entry`
— the ONE code path allowed to call ``PrefixCache.insert_remote``
(dklint rule 9, ``kv-version-guard``) — checks the stamp against the
importing engine's version before the insert AND re-checks it after:
a promotion racing the import flushes the cache and answers "stale"
instead of ever letting foreign-generation KV serve a token.  Combined
with the exporter's own double-read (``DecodeEngine.kv_export``) and
the cache's hash-then-exact-token-compare on lookup, neither a version
race nor a hash collision can serve wrong KV — a refused push costs
one cold prefill, never correctness.

Metrics land in the ROUTER registry: counters
``serve.router.kv_replications`` / ``kv_migrations`` /
``kv_push_bytes`` / ``kv_refused_stale``, plus the spill TTFT split
(``serve.router.ttft_spill_warm_seconds`` / ``ttft_spill_cold_seconds``)
the router's forward path attributes — the proof pair ``bench.py
--serve`` and the ``obsview`` COLD-SPILL alarm read.
"""

from __future__ import annotations

import collections
import socket
import threading
from typing import Optional

import numpy as np

from ..obs.logging import get_logger

_LOG = "serve.kvfabric"


def entries_nbytes(entries) -> int:
    """Total tensor bytes across a list of wire entry docs (host_tokens
    + cache/draft_cache leaves) — the fabric's budget/telemetry unit."""
    import jax
    return sum(int(np.asarray(leaf).nbytes)
               for doc in entries
               for leaf in jax.tree_util.tree_leaves(doc))


def admit_remote_entry(engine, entry, version: int):
    """Insert one validated peer-exported ``PrefixEntry`` into
    ``engine``'s cache iff its checkpoint ``version`` stamp matches the
    engine's current ``kv_version`` — the version-guarded fabric seam,
    the ONLY legitimate ``PrefixCache.insert_remote`` caller (dklint
    rule 9).  Returns ``(joined, reason)``.

    The stamp is checked before the insert and RE-checked after: the
    engine's decode thread bumps ``kv_version`` with its adoption-time
    flush (flush -> bump -> weight swap, all on the one inserting
    thread), so a promotion that lands between this thread's pre-check
    and its insert is always visible to the post-check — the entry may
    have slipped into the post-flush cache, and the second flush here
    drops it before the new weights could ever serve it."""
    if int(version) != engine.kv_version:
        return False, "stale"
    engine._prefix.insert_remote(entry)
    if engine.kv_version != int(version):
        # a promotion adopted between the pre-check and the insert: the
        # entry may have landed after the adoption flush, inside the
        # new-generation cache — flush again so old-weight KV can never
        # serve under the promoted checkpoint
        engine._prefix.flush()
        return False, "stale"
    return True, "joined"


class KVFabric:
    """The router-side transfer engine: one worker thread draining a
    bounded job queue of replications (spill-triggered) and migrations
    (drain/evict-triggered), moving KV between engines over the
    router's own pooled ``ServeClient`` connections — engines never
    dial each other, the fabric topology is exactly the routing
    topology.

    Every fabric failure is best-effort-silent by design (logged,
    counted nowhere fatal): a failed transfer costs one cold prefill,
    and liveness verdicts stay with the health poller — the fabric
    never evicts."""

    def __init__(self, router):
        self.router = router
        cfg = router.config
        self._budget = int(float(cfg.kv_fabric_mb) * 1024 * 1024)
        self._max_link = int(cfg.kv_link_inflight)
        self._migrate_entries = int(cfg.kv_migrate_entries)
        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        self._jobs: collections.deque = collections.deque()
        #: single-flight keys: ("replicate", target_idx, prefix_key) /
        #: ("migrate", victim_idx) queued or running right now
        self._inflight: set = set()
        self._link_jobs: dict = {}   # (owner_idx, target_idx) -> count
        self._inflight_bytes = 0
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "KVFabric":
        if self._thread is None:
            self._stop_evt.clear()
            self._thread = threading.Thread(target=self._loop,
                                            daemon=True,
                                            name="serve-kv-fabric")
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop_evt.set()
        with self._lock:
            self._work.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    # -- job intake ---------------------------------------------------------
    def note_spill(self, key, owner_idx: int, target_idx: int,
                   prompt: np.ndarray) -> bool:
        """Enqueue a replication for a spill the router just routed:
        ``target`` should fetch the owner's entry for affinity ``key``.
        Returns False (no job) when single-flight already covers the
        (target, key) pair, the link is at its job cap, or the fabric
        is stopping — dedup IS the spill-storm defense."""
        fkey = ("replicate", int(target_idx), key)
        link = (int(owner_idx), int(target_idx))
        with self._lock:
            if self._stop_evt.is_set() or fkey in self._inflight:
                return False
            if self._link_jobs.get(link, 0) >= self._max_link:
                return False
            self._inflight.add(fkey)
            self._link_jobs[link] = self._link_jobs.get(link, 0) + 1
            self._jobs.append((fkey, link, key, int(owner_idx),
                               int(target_idx),
                               np.array(prompt, np.int32)))
            self._work.notify()
        return True

    def note_eviction(self, victim_idx: int) -> bool:
        """Enqueue a best-effort migration for an engine the router is
        evicting.  The victim is usually already dead (that is why it
        is being evicted) — the fetch then fails fast on the router's
        small dial budget and the job ends silently; a victim that
        wedged-but-answers still gets its warm set out."""
        fkey = ("migrate", int(victim_idx))
        with self._lock:
            if self._stop_evt.is_set() or fkey in self._inflight:
                return False
            self._inflight.add(fkey)
            self._jobs.append((fkey, None, None, int(victim_idx), None,
                               None))
            self._work.notify()
        return True

    def migrate_now(self, victim_idx: int) -> int:
        """Synchronous migration — the PLANNED drain path: the caller
        (the router's single-engine ``drain`` handler) needs the warm
        set copied out BEFORE it drains the victim and marks it dark.
        Returns the number of entries that joined a survivor."""
        return self._run_migrate(int(victim_idx))

    # -- worker -------------------------------------------------------------
    def _loop(self) -> None:
        while True:
            with self._lock:
                while not self._jobs and not self._stop_evt.is_set():
                    self._work.wait(0.1)
                if self._stop_evt.is_set():
                    # pending jobs die with the fabric: replication is
                    # an optimization, and the planned-drain migration
                    # path is synchronous — nothing correctness-bearing
                    # is queued here
                    self._inflight.clear()
                    self._link_jobs.clear()
                    self._jobs.clear()
                    return
                job = self._jobs.popleft()
            fkey, link = job[0], job[1]
            try:
                if fkey[0] == "replicate":
                    self._run_replicate(job[2], job[3], job[4], job[5])
                else:
                    self._run_migrate(job[3])
            except Exception:
                # a fabric job must never kill the worker: the cost of
                # any failure here is one cold prefill, already paid
                get_logger(_LOG).exception("kv fabric job failed")
            finally:
                with self._lock:
                    self._inflight.discard(fkey)
                    if link is not None:
                        left = self._link_jobs.get(link, 1) - 1
                        if left > 0:
                            self._link_jobs[link] = left
                        else:
                            self._link_jobs.pop(link, None)

    # -- transfers ----------------------------------------------------------
    def _rpc(self, be, fn, what: str):
        """One client round-trip against backend ``be`` on the router's
        pool; socket failures log-and-return-None (best-effort: the
        poller owns liveness, the fabric never evicts)."""
        r = self.router
        try:
            client = r._acquire(be)
            try:
                reply = fn(client)
            except BaseException:
                client.close()
                raise
            be.release(client)
            return reply
        except (ConnectionError, OSError, socket.timeout) as e:
            get_logger(_LOG).info("kv fabric %s via %s failed: %s",
                                  what, be.addr, e)
            return None

    def _run_replicate(self, key, owner_idx: int, target_idx: int,
                       prompt: np.ndarray) -> None:
        r = self.router
        owner, target = r.backends[owner_idx], r.backends[target_idx]
        with r._lock:
            if not (owner.alive and target.alive):
                return
        doc = self._rpc(owner,
                        lambda c: c.kv_fetch(prompt=prompt),
                        "kv_fetch")
        if not doc or not doc.get("ok") or not doc.get("entries"):
            return
        entries = doc["entries"]
        nbytes = entries_nbytes(entries)
        with self._lock:
            if self._inflight_bytes + nbytes > self._budget:
                get_logger(_LOG).info(
                    "kv replication %s -> %s skipped: %d bytes would "
                    "exceed the kv_fabric_mb in-flight budget",
                    owner.addr, target.addr, nbytes)
                return
            self._inflight_bytes += nbytes
        try:
            reply = self._rpc(
                target,
                lambda c: c.kv_push(entries, doc.get("version")),
                "kv_push")
        finally:
            with self._lock:
                self._inflight_bytes -= nbytes
        if not reply:
            return
        stale = int(reply.get("refused_stale", 0) or 0)
        if stale:
            r._c_kv_refused_stale.inc(stale)
        if int(reply.get("joined", 0) or 0) > 0:
            r._c_kv_replications.inc()
            r._c_kv_push_bytes.inc(nbytes)
            r._add_secondary(key, target_idx)

    def _run_migrate(self, victim_idx: int) -> int:
        r = self.router
        victim = r.backends[victim_idx]
        with r._lock:
            survivors = [be for be in r.backends
                         if be.alive and be.idx != victim_idx]
            # least-loaded first: migrated KV should land where spilled
            # traffic will be routed
            survivors.sort(key=lambda be: (be.inflight + be.queue_depth
                                           + be.active_slots, be.idx))
        if not survivors:
            return 0
        doc = self._rpc(
            victim,
            lambda c: c.kv_fetch(hottest=self._migrate_entries,
                                 budget_bytes=self._budget),
            "kv_fetch(hottest)")
        if not doc or not doc.get("ok") or not doc.get("entries"):
            return 0
        version = doc.get("version")
        moved = 0
        for i, entry_doc in enumerate(doc["entries"]):
            target = survivors[i % len(survivors)]
            nbytes = entries_nbytes([entry_doc])
            reply = self._rpc(
                target, lambda c: c.kv_push([entry_doc], version),
                "kv_push")
            if not reply:
                continue
            stale = int(reply.get("refused_stale", 0) or 0)
            if stale:
                r._c_kv_refused_stale.inc(stale)
            if int(reply.get("joined", 0) or 0) > 0:
                moved += 1
                r._c_kv_migrations.inc()
                r._c_kv_push_bytes.inc(nbytes)
                r._reown_affinity(
                    np.asarray(entry_doc.get("host_tokens"),
                               np.int32).reshape(-1),
                    victim_idx, target.idx)
        if moved:
            get_logger(_LOG).warning(
                "migrated %d hot KV entr%s off %s to %d survivor(s)",
                moved, "y" if moved == 1 else "ies", victim.addr,
                len(survivors))
        return moved

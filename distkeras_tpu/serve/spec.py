"""Speculative decoding — decode accelerator #2 (ISSUE 11).

One-token-per-step decode leaves the target model memory-bound: every
step reads the full parameter set to produce ONE token per row.  A small
**draft** model (the ``gpt_lm`` family already scales down) proposes
``k`` tokens per active row; the target then verifies all ``k`` in ONE
batched ``decode_window`` — the accepted prefix ships ``m + 1`` tokens
(the ``m`` matching proposals plus the target's own next token) for a
single target-weight read plus one fix-up decode.

Greedy-only, with provable parity: a proposal ``x_i`` is accepted iff it
equals the target's own argmax given the previously accepted context, so
every emitted token is exactly the token ``generate_tokens`` would have
produced — at ANY draft quality.  A bad draft costs speed (low accept
rate), never correctness.

**Accepted-prefix rollback keeps the ragged KV cache exact** without
copying anything back: the verify window writes K/V for all ``k``
proposals, but a row's attention horizon is its own position, so K/V at
positions past ``pos + m`` is never attended before the row's later
decode *overwrites* it (the same placeholder contract as prefill
padding).  Rolling back IS just not advancing ``pos``.

The whole step — draft propose scan, target verify window, acceptance
arithmetic, buffer scatter, target + draft fix-up decode — is one
compiled program behind one retrace sentinel, so steady-state serving
stays ``jit.retraces == 0``.

Metrics (service registry, recorded by the engine): counters
``serve.spec.proposed`` / ``serve.spec.accepted``, gauge
``serve.spec.accept_rate`` (running ratio; ``obsview --serve`` renders a
LOW-ACCEPT alarm when it collapses).
"""

from __future__ import annotations

from ..models.generation import _model_cache, decode_window


def validate_draft(model, draft_model, draft_variables, batch: int,
                   spec_k: int) -> None:
    """Config-time rejection (the ``max_queue=0`` precedent) for a draft
    that cannot verify against this target: checked when the engine is
    built, never discovered by the decode thread."""
    if draft_model is None or draft_variables is None:
        raise ValueError(
            f"spec_k={spec_k} needs a draft model: pass draft_model= and "
            f"draft_variables= to DecodeEngine (the gpt_lm family scales "
            f"down to draft size)")
    vocab = int(model.output_shape[-1])
    dvocab = int(draft_model.output_shape[-1])
    if dvocab != vocab:
        raise ValueError(
            f"draft checkpoint is not shape-compatible with the target: "
            f"draft vocab {dvocab} != target vocab {vocab} (proposals "
            f"are verified token-by-token in one shared id space)")
    t = int(model.input_shape[0])
    dt = int(draft_model.input_shape[0])
    if dt != t:
        raise ValueError(
            f"draft seq_len {dt} != target seq_len {t}: the draft's KV "
            f"cache tracks the same absolute positions as the target's")
    if _model_cache(draft_model, batch) is None:
        raise ValueError(
            "the draft model does not support the KV-cached decode path "
            "(init_cache protocol) — speculative proposal is a cached "
            "decode scan")


def build_spec_step(model, draft_model, spec_k: int):
    """The compiled speculative step for ``DecodeEngine``.

    Returns ``fn(variables, dvariables, buf, cache, dcache, pos, logits,
    dlogits, active) -> (buf, cache, dcache, pos, logits, dlogits,
    emitted, counts)`` where ``emitted`` is (B, k+1) int32 — row r's
    tokens for positions ``pos_r .. pos_r + counts_r - 1`` — and
    ``counts`` is (B,) int32 in [1, k+1] (valid only where ``active``).

    Alignment invariant (matches the engine's carried state): ``logits``
    / ``dlogits`` are each model's distribution for the token AT ``pos``.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    k = int(spec_k)
    t = int(model.input_shape[0])

    def _spec_step(variables, dvariables, buf, cache, dcache, pos,
                   logits, dlogits, active):
        params, state = variables["params"], variables["state"]
        dparams, dstate = dvariables["params"], dvariables["state"]
        b = buf.shape[0]

        # 1) draft proposes k tokens: x_i = argmax of its carried
        # distribution, fed back at position pos + i (clamped like every
        # possibly-overrunning write; see decode_window's contract)
        def propose(carry, i):
            dl, dc = carry
            x = jnp.argmax(dl, axis=-1).astype(jnp.int32)
            p = jnp.minimum(pos + i, t - 1)
            dl2, dc = draft_model.layer.apply_decode(dparams, dstate, x,
                                                     dc, p)
            return (dl2, dc), x

        (_, dcache), xs = lax.scan(propose, (dlogits, dcache),
                                   jnp.arange(k))
        proposals = jnp.moveaxis(xs, 0, 1)                  # (B, k)

        # 2) target verifies all k proposals in one batched window
        win, cache = decode_window(model.layer, params, state, proposals,
                                   cache, pos, limit=t)     # (B, k, V)

        # 3) acceptance: the target's own argmax chain.  targets[:, i]
        # is the target token AT pos+i given proposals[:, :i] — valid
        # exactly when those proposals were all accepted, which the
        # cumulative product encodes.
        y0 = jnp.argmax(logits, axis=-1).astype(jnp.int32)[:, None]
        yw = jnp.argmax(win, axis=-1).astype(jnp.int32)     # (B, k)
        targets = jnp.concatenate([y0, yw], axis=1)         # (B, k+1)
        accepted = jnp.cumprod(
            (proposals == targets[:, :k]).astype(jnp.int32), axis=1)
        m = accepted.sum(axis=1)                            # (B,) in [0,k]
        counts = m + 1

        # 4) emit targets[:, :m+1] into the buffer at pos .. pos+m (a
        # write past seq_len one-hots to the zero vector — dropped, the
        # row is retiring anyway)
        idx = jnp.arange(k + 1)[None, :]
        keep = (idx <= m[:, None]) & active[:, None]        # (B, k+1)
        w = jax.nn.one_hot(pos[:, None] + idx, t,
                           dtype=jnp.int32) * keep[..., None].astype(
                               jnp.int32)                   # (B, k+1, T)
        buf = buf * (1 - w.sum(1)) + (targets[..., None] * w).sum(1)

        # 5) fix-up decode of the LAST emitted token (the correction /
        # bonus the draft never saw): gives the carried logits for
        # pos+m+1 and overwrites the one wrong K/V slot a rejected
        # proposal left at pos+m — both models stay exactly in sync
        # with the emitted context
        last = jnp.take_along_axis(targets, m[:, None], axis=1)[:, 0]
        pfix = jnp.minimum(pos + m, t - 1)
        l2, cache = model.layer.apply_decode(params, state, last, cache,
                                             pfix)
        logits = jnp.where(active[:, None], l2.astype(logits.dtype),
                           logits)
        dl2, dcache = draft_model.layer.apply_decode(dparams, dstate,
                                                     last, dcache, pfix)
        dlogits = jnp.where(active[:, None], dl2.astype(dlogits.dtype),
                            dlogits)
        pos = pos + counts * active.astype(jnp.int32)
        return buf, cache, dcache, pos, logits, dlogits, targets, counts

    return _spec_step

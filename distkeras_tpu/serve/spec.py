"""Speculative decoding — decode accelerator #2 (ISSUE 11; ISSUE 14
makes it DISTRIBUTION-PRESERVING, so ``spec_k`` composes with
``temperature > 0``).

One-token-per-step decode leaves the target model memory-bound: every
step reads the full parameter set to produce ONE token per row.  A small
**draft** model (the ``gpt_lm`` family already scales down) proposes
``k`` tokens per active row; the target then verifies all ``k`` in ONE
batched ``decode_window`` — the accepted prefix ships ``m + 1`` tokens
(the ``m`` accepted proposals plus one final token) for a single
target-weight read plus one fix-up decode.

Acceptance is per-row, under the row's OWN sampling params (they ride
the request — ISSUE 14):

* **Greedy rows** (``temperature == 0``): a proposal ``x_i`` is accepted
  iff it equals the target's own argmax given the previously accepted
  context, so every emitted token is exactly the token
  ``generate_tokens`` would have produced — at ANY draft quality.  A bad
  draft costs speed (low accept rate), never correctness.  This path is
  provably parity-exact and unchanged by the sampling extension.
* **Sampled rows** (``temperature > 0``): the classic
  speculative-*sampling* accept/reject — the draft proposes
  ``x_i ~ q_i`` (its own tempered, filtered distribution), the target
  accepts with probability ``min(1, p_i(x_i) / q_i(x_i))`` where ``p_i``
  is ITS tempered, filtered distribution given the accepted context; on
  the first rejection the final token is drawn from the normalized
  residual ``max(p_i - q_i, 0)``, and after ``k`` acceptances a bonus
  token is drawn from the target's next-position distribution.  The
  emitted sequence is distributed EXACTLY as sampling from the target
  alone — the residual construction makes the marginal at every
  position ``p_i`` regardless of draft quality (the standard
  speculative-sampling identity).

**Accepted-prefix rollback keeps the ragged KV cache exact** without
copying anything back: the verify window writes K/V for all ``k``
proposals, but a row's attention horizon is its own position, so K/V at
positions past ``pos + m`` is never attended before the row's later
decode *overwrites* it (the same placeholder contract as prefill
padding).  Rolling back IS just not advancing ``pos``.

The whole step — draft propose scan, target verify window, acceptance
arithmetic, buffer scatter, target + draft fix-up decode — is one
compiled program behind one retrace sentinel; the sampling params are
TRACED per-row arrays, so steady-state serving stays
``jit.retraces == 0`` across any mix of greedy and sampled requests.

Metrics (service registry, recorded by the engine): counters
``serve.spec.proposed`` / ``serve.spec.accepted``, gauge
``serve.spec.accept_rate`` (running ratio; ``obsview --serve`` renders a
LOW-ACCEPT alarm when it collapses).
"""

from __future__ import annotations

from ..models.generation import (_model_cache, decode_window,
                                 rowwise_dist)

#: floor added before ``log`` on probability tensors — keeps zero-mass
#: entries at -inf-ish log-probability without producing NaN
_TINY = 1e-30


def validate_draft(model, draft_model, draft_variables, batch: int,
                   spec_k: int) -> None:
    """Config-time rejection (the ``max_queue=0`` precedent) for a draft
    that cannot verify against this target: checked when the engine is
    built, never discovered by the decode thread."""
    if draft_model is None or draft_variables is None:
        raise ValueError(
            f"spec_k={spec_k} needs a draft model: pass draft_model= and "
            f"draft_variables= to DecodeEngine (the gpt_lm family scales "
            f"down to draft size)")
    vocab = int(model.output_shape[-1])
    dvocab = int(draft_model.output_shape[-1])
    if dvocab != vocab:
        raise ValueError(
            f"draft checkpoint is not shape-compatible with the target: "
            f"draft vocab {dvocab} != target vocab {vocab} (proposals "
            f"are verified token-by-token in one shared id space)")
    t = int(model.input_shape[0])
    dt = int(draft_model.input_shape[0])
    if dt != t:
        raise ValueError(
            f"draft seq_len {dt} != target seq_len {t}: the draft's KV "
            f"cache tracks the same absolute positions as the target's")
    if _model_cache(draft_model, batch) is None:
        raise ValueError(
            "the draft model does not support the KV-cached decode path "
            "(init_cache protocol) — speculative proposal is a cached "
            "decode scan")


def build_spec_step(model, draft_model, spec_k: int):
    """The compiled speculative step for ``DecodeEngine``.

    Returns ``fn(variables, dvariables, buf, cache, dcache, pos, logits,
    dlogits, active, temp, topk, topp, rng) -> (buf, cache, dcache, pos,
    logits, dlogits, rng, emitted, counts)`` where ``emitted`` is
    (B, k+1) int32 — row r's tokens for positions
    ``pos_r .. pos_r + counts_r - 1`` — and ``counts`` is (B,) int32 in
    [1, k+1] (valid only where ``active``).  ``temp``/``topk``/``topp``
    are the per-row sampling params ((B,) arrays; ``temp == 0`` selects
    the greedy argmax-acceptance path for that row).

    Alignment invariant (matches the engine's carried state): ``logits``
    / ``dlogits`` are each model's distribution for the token AT ``pos``.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    k = int(spec_k)
    t = int(model.input_shape[0])

    def _spec_step(variables, dvariables, buf, cache, dcache, pos,
                   logits, dlogits, active, temp, topk, topp, rng):
        params, state = variables["params"], variables["state"]
        dparams, dstate = dvariables["params"], dvariables["state"]
        b = buf.shape[0]
        temp = jnp.asarray(temp, logits.dtype)
        greedy = temp <= 0.0                                # (B,)
        #: traced batch-level predicate: every sampled-path computation
        #: below (draft distributions, acceptance ratios, residual
        #: draws — sorts and softmaxes the greedy chain never reads)
        #: sits behind a lax.cond on it, so an all-greedy batch pays
        #: the PR 11 argmax-only cost; the cond never re-traces
        any_sampled = jnp.any(~greedy)

        # 1) draft proposes k tokens: greedy rows take its carried
        # argmax, sampled rows draw x_i ~ q_i (the draft's tempered,
        # filtered distribution — RECORDED, the acceptance test and the
        # residual both need q), each fed back at position pos + i
        # (clamped like every possibly-overrunning write)
        def propose(carry, i):
            dl, dc, r = carry
            r, sub = jax.random.split(r)

            def q_sample(_):
                q = rowwise_dist(dl, temp, topk, topp)      # (B, V)
                xs = jax.random.categorical(sub, jnp.log(q + _TINY),
                                            axis=-1)
                return q, xs.astype(jnp.int32)

            def q_skip(_):
                # all-greedy: q is never read downstream (acceptance
                # and residual live behind the same predicate)
                return (jnp.zeros_like(dl),
                        jnp.zeros((b,), jnp.int32))

            q, xs = lax.cond(any_sampled, q_sample, q_skip, None)
            x = jnp.where(greedy, jnp.argmax(dl, axis=-1),
                          xs).astype(jnp.int32)
            p = jnp.minimum(pos + i, t - 1)
            dl2, dc = draft_model.layer.apply_decode(dparams, dstate, x,
                                                     dc, p)
            return (dl2, dc, r), (x, q)

        (_, dcache, rng), (xs, qs) = lax.scan(
            propose, (dlogits, dcache, rng), jnp.arange(k))
        proposals = jnp.moveaxis(xs, 0, 1)                  # (B, k)
        qs = jnp.moveaxis(qs, 0, 1)                         # (B, k, V)

        # 2) target verifies all k proposals in one batched window
        win, cache = decode_window(model.layer, params, state, proposals,
                                   cache, pos, limit=t)     # (B, k, V)

        # 3a) greedy acceptance: the target's own argmax chain
        y0 = jnp.argmax(logits, axis=-1).astype(jnp.int32)[:, None]
        yw = jnp.argmax(win, axis=-1).astype(jnp.int32)     # (B, k)
        targets = jnp.concatenate([y0, yw], axis=1)         # (B, k+1)
        acc_g = proposals == targets[:, :k]

        # 3b) stochastic acceptance: u <= p(x)/q(x) (q(x) > 0 — x was
        # drawn from q), the distribution-preserving test.  ``ps`` is
        # the target's tempered/filtered distribution for the token AT
        # pos+i given proposals[:, :i] — valid exactly when those
        # proposals were all accepted, which the cumulative product
        # below encodes
        rng, sub = jax.random.split(rng)

        def acc_sampled(_):
            tgt = jnp.concatenate([logits[:, None, :],
                                   win[:, :k - 1, :]],
                                  axis=1)                   # (B, k, V)
            ps = rowwise_dist(tgt.reshape(b * k, -1),
                              jnp.repeat(temp, k),
                              jnp.repeat(topk, k),
                              jnp.repeat(topp, k)).reshape(b, k, -1)
            p_x = jnp.take_along_axis(ps, proposals[..., None],
                                      axis=-1)[..., 0]      # (B, k)
            q_x = jnp.take_along_axis(qs, proposals[..., None],
                                      axis=-1)[..., 0]
            u = jax.random.uniform(sub, (b, k), dtype=p_x.dtype)
            return jnp.where(greedy[:, None], acc_g,
                             u * q_x <= p_x), ps

        def acc_greedy(_):
            return acc_g, jnp.zeros((b, k, win.shape[-1]), win.dtype)

        acc, ps = lax.cond(any_sampled, acc_sampled, acc_greedy, None)
        accepted = jnp.cumprod(acc.astype(jnp.int32), axis=1)
        m = accepted.sum(axis=1)                            # (B,) in [0,k]
        counts = m + 1

        # 4) the final (m-th) emitted token per row: greedy -> the
        # target chain's own token; sampled + rejection at m < k -> a
        # draw from the normalized residual max(p_m - q_m, 0) (rejection
        # implies positive residual mass; the epsilon fallback to p_m
        # covers numerically-tied p == q); sampled + all k accepted ->
        # a bonus draw from the target's next-position distribution
        rng, sub = jax.random.split(rng)
        f_g = jnp.take_along_axis(targets, m[:, None], axis=1)[:, 0]

        def final_sampled(ps):
            bonus = rowwise_dist(win[:, k - 1, :], temp, topk, topp)
            m_idx = jnp.minimum(m, k - 1)[:, None, None]
            resid = jnp.take_along_axis(jnp.maximum(ps - qs, 0.0),
                                        m_idx, axis=1)[:, 0, :]  # (B, V)
            mass = resid.sum(axis=-1, keepdims=True)
            p_m = jnp.take_along_axis(ps, m_idx, axis=1)[:, 0, :]
            resid = jnp.where(mass > 1e-9,
                              resid / jnp.maximum(mass, _TINY), p_m)
            final_dist = jnp.where((m == k)[:, None], bonus, resid)
            f_s = jax.random.categorical(sub,
                                         jnp.log(final_dist + _TINY),
                                         axis=-1)
            return jnp.where(greedy, f_g, f_s).astype(jnp.int32)

        final = lax.cond(any_sampled, final_sampled,
                         lambda _: f_g.astype(jnp.int32), ps)

        # row r emits proposals[:m_r] then `final` at index m_r (greedy
        # rows: identical to the old targets[:, :m+1] emission — an
        # accepted proposal IS the target's token there)
        idx = jnp.arange(k + 1)[None, :]
        prop_pad = jnp.concatenate([proposals, proposals[:, -1:]],
                                   axis=1)                  # (B, k+1)
        emitted = jnp.where(idx == m[:, None], final[:, None], prop_pad)

        # 5) emit into the buffer at pos .. pos+m (a write past seq_len
        # one-hots to the zero vector — dropped, the row is retiring)
        keep = (idx <= m[:, None]) & active[:, None]        # (B, k+1)
        w = jax.nn.one_hot(pos[:, None] + idx, t,
                           dtype=jnp.int32) * keep[..., None].astype(
                               jnp.int32)                   # (B, k+1, T)
        buf = buf * (1 - w.sum(1)) + (emitted[..., None] * w).sum(1)

        # 6) fix-up decode of the LAST emitted token (the correction /
        # bonus the draft never saw): gives the carried logits for
        # pos+m+1 and overwrites the one wrong K/V slot a rejected
        # proposal left at pos+m — both models stay exactly in sync
        # with the emitted context
        pfix = jnp.minimum(pos + m, t - 1)
        l2, cache = model.layer.apply_decode(params, state, final, cache,
                                             pfix)
        logits = jnp.where(active[:, None], l2.astype(logits.dtype),
                           logits)
        dl2, dcache = draft_model.layer.apply_decode(dparams, dstate,
                                                     final, dcache, pfix)
        dlogits = jnp.where(active[:, None], dl2.astype(dlogits.dtype),
                            dlogits)
        pos = pos + counts * active.astype(jnp.int32)
        return (buf, cache, dcache, pos, logits, dlogits, rng, emitted,
                counts)

    return _spec_step

"""Nested timed scopes — the trace half of the telemetry layer.

A ``SpanTracer`` keeps a thread-local span stack and emits one record per
closed span into the SAME JSONL sink the metrics use (``MetricsLogger`` —
traces and metrics share one stream, so ``scripts/obsview.py`` reads both
from a single file).  Each record carries the span name, its full
``parent/child`` path, nesting depth and wall seconds::

    tracer = SpanTracer(metrics_logger)
    with tracer.span("train"):
        with tracer.span("jit_compile"):
            ...   # -> {"event": "span", "name": "jit_compile",
                  #     "path": "train/jit_compile", "depth": 1,
                  #     "seconds": 1.83}

Optionally a ``Registry`` accumulates per-name duration histograms
(``span.<name>.seconds``) so cumulative span time shows up in ``STATS``
snapshots too.  A process-wide default tracer (``obs.span``) serves ad-hoc
call sites; components that own a metrics sink build their own.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Optional

from .registry import Registry, TIME_BUCKETS


class SpanTracer:
    """Thread-local nested span stack bound to an optional JSONL sink
    (anything with ``.log(event, **fields)``) and an optional registry."""

    def __init__(self, sink=None, registry: Optional[Registry] = None):
        self.sink = sink
        self.registry = registry
        self._local = threading.local()

    def _stack(self) -> list:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    @property
    def depth(self) -> int:
        return len(self._stack())

    def current_path(self) -> str:
        return "/".join(self._stack())

    @contextlib.contextmanager
    def span(self, name: str, **fields):
        """Time a scope; emits on exit (exceptions included — a crashed
        span still records its duration, flagged ``error=True``)."""
        stack = self._stack()
        stack.append(name)
        path = "/".join(stack)
        depth = len(stack) - 1
        t0 = time.perf_counter()
        try:
            yield self
        except BaseException:
            self._emit(name, path, depth, time.perf_counter() - t0,
                       dict(fields, error=True))
            raise
        else:
            self._emit(name, path, depth, time.perf_counter() - t0, fields)
        finally:
            stack.pop()

    def _emit(self, name: str, path: str, depth: int, seconds: float,
              fields: dict) -> None:
        if self.sink is not None:
            self.sink.log("span", name=name, path=path, depth=depth,
                          seconds=seconds, **fields)
        if self.registry is not None:
            self.registry.histogram(f"span.{name}.seconds",
                                    TIME_BUCKETS).observe(seconds)


_DEFAULT = SpanTracer()


def default_tracer() -> SpanTracer:
    return _DEFAULT


def span(name: str, **fields):
    """Ad-hoc span on the process-wide tracer (silent until a sink is
    attached via ``set_default_sink``; nesting/paths always tracked)."""
    return _DEFAULT.span(name, **fields)


def set_default_sink(sink, registry: Optional[Registry] = None) -> None:
    """Point the process-wide tracer at a JSONL sink (and optionally a
    registry) — e.g. one line in a script turns on ad-hoc tracing."""
    _DEFAULT.sink = sink
    if registry is not None:
        _DEFAULT.registry = registry

"""Nested timed scopes — the trace half of the telemetry layer.

A ``SpanTracer`` keeps a thread-local span stack and emits one record per
closed span into the SAME JSONL sink the metrics use (``MetricsLogger`` —
traces and metrics share one stream, so ``scripts/obsview.py`` reads both
from a single file).  Each record carries the span name, its full
``parent/child`` path, nesting depth and wall seconds::

    tracer = SpanTracer(metrics_logger)
    with tracer.span("train"):
        with tracer.span("jit_compile"):
            ...   # -> {"event": "span", "name": "jit_compile",
                  #     "path": "train/jit_compile", "depth": 1,
                  #     "seconds": 1.83}

Every span additionally carries identity (ISSUE 5): a thread-local
``trace_id`` (settable — async workers pin theirs to ``w<worker_id>`` so
one trace follows one worker) and a per-span ``span_id``; nested spans
record the enclosing span as ``parent_span``.  The ids are what lets a
span CROSS a process boundary: the PS client ships its open commit span's
``(trace_id, span_id)`` over the wire and the server's apply span adopts
them as its ``trace_id``/``parent_span`` — ``scripts/obsview.py`` then
links server applies back to the worker windows that caused them.

Optionally a ``Registry`` accumulates per-name duration histograms
(``span.<name>.seconds``) so cumulative span time shows up in ``STATS``
snapshots too.  A process-wide default tracer (``obs.span``) serves ad-hoc
call sites; components that own a metrics sink build their own.
"""

from __future__ import annotations

import contextlib
import itertools
import threading
import time
import uuid
from typing import Optional, Tuple

from .registry import Registry, TIME_BUCKETS

#: span ids are ``<trace_id>.<salt><seq>``: a process-wide monotone
#: counter plus a per-process random salt.  The salt is what keeps ids
#: unique when several PROCESSES (or sequential runs) append to one JSONL
#: sink under the same pinned trace tag (``w0`` restarts with the worker)
#: — without it, run 2's ``w0.5`` would collide with run 1's and obsview
#: would link spans across runs.
_SPAN_SEQ = itertools.count(1)
#: 8 hex chars = 32 bits: birthday collision across runs sharing a sink
#: stays negligible into the tens of thousands of appended runs (4 chars
#: would collide ~50% by ~256 runs, and colliding runs collide id-for-id
#: because the sequence restarts at 1)
_SPAN_SALT = uuid.uuid4().hex[:8]


class SpanTracer:
    """Thread-local nested span stack bound to an optional JSONL sink
    (anything with ``.log(event, **fields)``) and an optional registry."""

    def __init__(self, sink=None, registry: Optional[Registry] = None):
        self.sink = sink
        self.registry = registry
        self._local = threading.local()

    def _stack(self) -> list:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    @property
    def depth(self) -> int:
        return len(self._stack())

    def current_path(self) -> str:
        return "/".join(name for name, _ in self._stack())

    # -- trace identity ------------------------------------------------------
    def set_trace_id(self, trace_id: str) -> None:
        """Pin THIS thread's trace id (e.g. ``w3`` for async worker 3) —
        every span the thread opens afterwards belongs to that trace."""
        self._local.trace_id = str(trace_id)

    def trace_id(self) -> str:
        """This thread's trace id (lazily minted when never pinned)."""
        tid = getattr(self._local, "trace_id", None)
        if tid is None:
            tid = self._local.trace_id = f"t{uuid.uuid4().hex[:8]}"
        return tid

    def current_span_id(self) -> Optional[str]:
        stack = self._stack()
        return stack[-1][1] if stack else None

    def context(self) -> Tuple[str, Optional[str]]:
        """``(trace_id, current_span_id)`` — the wire header the PS client
        attaches to commit/pull RPCs so remote spans can link back here."""
        return self.trace_id(), self.current_span_id()

    @contextlib.contextmanager
    def span(self, name: str, **fields):
        """Time a scope; emits on exit (exceptions included — a crashed
        span still records its duration, flagged ``error=True``).
        ``trace_id``/``parent_span`` keyword fields override the automatic
        thread-local ones — the server-side hook for adopting a REMOTE
        caller's trace context."""
        stack = self._stack()
        # a span adopting a REMOTE trace (explicit trace_id field — the
        # server-side hook) mints its id under THAT trace, so span-id
        # prefixes never name a trace absent from the stream
        tid = fields.get("trace_id") or self.trace_id()
        span_id = f"{tid}.{_SPAN_SALT}{next(_SPAN_SEQ)}"
        parent = stack[-1][1] if stack else None
        stack.append((name, span_id))
        path = "/".join(n for n, _ in stack)
        depth = len(stack) - 1
        t0 = time.perf_counter()
        try:
            yield self
        except BaseException:
            self._emit(name, path, depth, time.perf_counter() - t0,
                       span_id, parent, dict(fields, error=True))
            raise
        else:
            self._emit(name, path, depth, time.perf_counter() - t0,
                       span_id, parent, fields)
        finally:
            stack.pop()

    def _emit(self, name: str, path: str, depth: int, seconds: float,
              span_id: str, parent: Optional[str], fields: dict) -> None:
        if self.sink is not None:
            rec = dict(fields)
            # only the trace-adoption keys are caller-overridable; the
            # structural keys below are authoritative (a field named
            # "seconds" must not silently replace the measured duration)
            rec.setdefault("trace_id", self.trace_id())
            if parent is not None:
                rec.setdefault("parent_span", parent)
            rec.update(name=name, path=path, depth=depth, seconds=seconds,
                       span_id=span_id)
            self.sink.log("span", **rec)
        if self.registry is not None:
            self.registry.histogram(f"span.{name}.seconds",
                                    TIME_BUCKETS).observe(seconds)


_DEFAULT = SpanTracer()


def default_tracer() -> SpanTracer:
    return _DEFAULT


def span(name: str, **fields):
    """Ad-hoc span on the process-wide tracer (silent until a sink is
    attached via ``set_default_sink``; nesting/paths always tracked)."""
    return _DEFAULT.span(name, **fields)


def set_default_sink(sink, registry: Optional[Registry] = None) -> None:
    """Point the process-wide tracer at a JSONL sink (and optionally a
    registry) — e.g. one line in a script turns on ad-hoc tracing."""
    _DEFAULT.sink = sink
    if registry is not None:
        _DEFAULT.registry = registry

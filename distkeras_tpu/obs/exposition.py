"""Prometheus text exposition for registry snapshots.

``to_prometheus_text(registry_or_snapshot)`` renders the standard
text format (``# TYPE`` lines, ``_total`` counters, cumulative
``_bucket{le=...}`` histogram series) so a poller that speaks Prometheus
can scrape a ``STATS`` reply — or a file dumped by ``obsview`` — without
any adapter.  Instrument names are dotted (``ps.commits``); exposition
maps them to the legal Prometheus charset (``ps_commits``).
"""

from __future__ import annotations

import re

from .registry import Registry

_ILLEGAL = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
    n = _ILLEGAL.sub("_", name)
    if n and n[0].isdigit():
        n = "_" + n
    return n


def _fmt(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    f = float(v)
    return str(int(f)) if f == int(f) else repr(f)


def to_prometheus_text(source) -> str:
    """Registry (or plain snapshot dict) -> Prometheus text format."""
    snap = source.snapshot() if isinstance(source, Registry) else source
    lines = []
    for name in sorted(snap):
        s = snap[name]
        pname = _prom_name(name)
        kind = s["type"]
        if kind == "counter":
            lines.append(f"# TYPE {pname}_total counter")
            lines.append(f"{pname}_total {_fmt(s['value'])}")
        elif kind == "gauge":
            lines.append(f"# TYPE {pname} gauge")
            lines.append(f"{pname} {_fmt(s['value'])}")
        elif kind == "histogram":
            lines.append(f"# TYPE {pname} histogram")
            cum = 0
            for bound, c in zip(list(s["bounds"]) + [float("inf")],
                                s["counts"]):
                cum += c
                lines.append(
                    f'{pname}_bucket{{le="{_fmt(bound)}"}} {cum}')
            lines.append(f"{pname}_sum {_fmt(s['sum'])}")
            lines.append(f"{pname}_count {s['count']}")
        else:  # pragma: no cover - snapshots only carry the three kinds
            raise TypeError(f"unknown instrument type {kind!r} for {name!r}")
    return "\n".join(lines) + ("\n" if lines else "")

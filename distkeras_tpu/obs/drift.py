"""Cross-run drift detection over registry snapshots (ISSUE 5 tentpole).

The benchmark harness persists obs registry snapshots beside its wall-clock
rows (``BENCH_PS_OBS.json`` / ``BENCH_TRAINER_OBS.json``) precisely so runs
can be compared as *distributions*, not single numbers (the BASELINE
round-5 host-contention bias).  This module is the comparator:

* **counters** — relative delta ``|cand − base| / base`` against a
  ``counter_rel`` threshold (commit/pull/byte counts are deterministic for
  a fixed config, so these are tight);
* **histograms** — bucket-wise **PSI** (population stability index,
  ``Σ (q_i − p_i)·ln(q_i/p_i)`` over smoothed bucket probabilities — the
  standard distribution-shift score; 0.1 ≈ moderate, 0.25 ≈ major) plus
  interpolated **p50/p99 shift factors**, each with its own threshold;
* **gauges** — levels have no meaningful cross-run delta; skipped unless a
  per-metric ``gauge_abs`` threshold opts one in.

Thresholds resolve in three layers: built-in defaults ← the committed
``OBS_BASELINE.json``'s global ``thresholds`` ← its per-metric ``metrics``
overrides (fnmatch patterns; ``ignore`` patterns drop metrics entirely).
The baseline file schema (``dktpu-obs-baseline/v1``)::

    {"schema": "dktpu-obs-baseline/v1",
     "thresholds": {"counter_rel": 0.25, "psi": 0.25, ...},
     "metrics":   {"*rtt_seconds": {"psi": 1.5, "p50_factor": 10}},
     "ignore":    ["*encode_seconds"],
     "snapshots": {"ps_bench": "BENCH_PS_OBS.json",
                   "trainer_bench": "BENCH_TRAINER_OBS.json"}}

``snapshots`` names the committed baseline file per bench mode —
``bench.py`` diffs a fresh run against it before overwriting, and
``scripts/obsview.py --diff A B`` exposes the same comparison as a CLI
(exit 0 clean / 1 drift / 2 usage error) for CI.

ISSUE 8 adds the **windowed diff** over a rolling window of snapshots
from ONE live run (the continual-training deploy gate): cumulative
registry snapshots taken at interval edges are first differenced into
per-interval deltas (:func:`snapshot_delta` — counters/histograms
subtract so each interval describes what happened *during* it, not since
process start), then :func:`classify_window` tells a **step change**
(some consecutive interval pair drifts — an abrupt distribution jump)
from a **gradual trend** (every consecutive pair is under threshold but
the window's first→last cumulative diff drifts — slow creep no pairwise
gate can see).  A window is *stable* only when neither fires; that is
the drift-clean condition continual deploys gate on.
"""

from __future__ import annotations

import fnmatch
import json
import math
import os
from typing import Dict, List, Optional, Sequence, Tuple

from .registry import snapshot_quantile

BASELINE_SCHEMA = "dktpu-obs-baseline/v1"

#: built-in thresholds — deliberately forgiving for wall-clock-shaped
#: metrics (the committed baseline tightens/loosens per metric)
DEFAULT_THRESHOLDS: Dict[str, float] = {
    "counter_rel": 0.25,   # counters: |cand-base|/base beyond this drifts
    "counter_abs": 0.0,    # counters: absolute deltas <= this never drift
                           # (the only way to tolerate a 0 -> small change,
                           # where the relative delta is infinite)
    "psi": 0.25,           # histograms: PSI beyond this drifts
    "p50_factor": 3.0,     # histograms: p50 shift factor (either way)
    "p99_factor": 4.0,     # histograms: p99 shift factor (either way)
    "min_count": 16,       # histograms thinner than this are skipped
}

_EPS = 1e-9


def is_registry_snapshot(d) -> bool:
    """True for a plain-data ``Registry.snapshot()`` dict."""
    return isinstance(d, dict) and bool(d) and all(
        isinstance(v, dict) and "type" in v for v in d.values())


def named_registries(doc: dict) -> Dict[str, dict]:
    """A persisted snapshot document -> {registry name: snapshot}.  Both
    shapes the harness writes are accepted: a multi-registry document
    (``{"config": ..., "client": <snap>, "server": <snap>}``) and a bare
    registry snapshot (``{"ps.commits": {...}, ...}``)."""
    named = {k: v for k, v in doc.items() if is_registry_snapshot(v)}
    if not named and is_registry_snapshot(doc):
        named = {"registry": doc}
    return named


def load_baseline(path: str) -> dict:
    """Read + validate an ``OBS_BASELINE.json`` config."""
    with open(path) as f:
        cfg = json.load(f)
    if not isinstance(cfg, dict) or cfg.get("schema") != BASELINE_SCHEMA:
        raise ValueError(
            f"{path}: not an obs baseline (want schema={BASELINE_SCHEMA!r}, "
            f"got {cfg.get('schema') if isinstance(cfg, dict) else type(cfg).__name__!r})")
    return cfg


def find_baseline(start_dir: str) -> Optional[str]:
    """Walk up from ``start_dir`` to the repo root looking for the
    committed ``OBS_BASELINE.json`` (same discovery rule as
    ``dklint_baseline.json``).  The walk stops at the first ``.git``
    marker: snapshots outside any repo must not silently adopt a stray
    config from an unrelated ancestor directory."""
    d = os.path.abspath(start_dir)
    while True:
        p = os.path.join(d, "OBS_BASELINE.json")
        if os.path.exists(p):
            return p
        if os.path.exists(os.path.join(d, ".git")):
            return None  # repo root reached without a baseline
        parent = os.path.dirname(d)
        if parent == d:
            return None
        d = parent


class _Thresholds:
    """Three-layer threshold resolution: defaults <- baseline globals <-
    per-metric fnmatch overrides; plus ignore patterns."""

    def __init__(self, baseline: Optional[dict] = None):
        baseline = baseline or {}
        self.base = dict(DEFAULT_THRESHOLDS)
        self.base.update(baseline.get("thresholds") or {})
        self.per_metric: Dict[str, dict] = dict(baseline.get("metrics") or {})
        self.ignore: List[str] = list(baseline.get("ignore") or [])

    def ignored(self, metric: str) -> bool:
        names = (metric, metric.split("/", 1)[-1])
        return any(fnmatch.fnmatch(n, pat)
                   for pat in self.ignore for n in names)

    def for_metric(self, metric: str) -> dict:
        th = dict(self.base)
        names = (metric, metric.split("/", 1)[-1])
        # authoring-order layering: a later entry in the config file
        # overrides an earlier one, so specificity is expressed by
        # writing broad patterns first (JSON object order is preserved).
        # Lexical sorting could never let a part-scoped pattern like
        # "scenario_*/serve.*" override an exact "serve.*" name.
        for pat in self.per_metric:
            if any(fnmatch.fnmatch(n, pat) for n in names):
                th.update(self.per_metric[pat])
        return th


def psi(base: dict, cand: dict) -> float:
    """Bucket-wise population stability index between two histogram
    snapshots with identical bounds.  Bucket probabilities are Laplace-
    smoothed so empty buckets never produce infinities."""
    bc, cc = base["counts"], cand["counts"]
    nb, nc = max(1, base["count"]), max(1, cand["count"])
    k = len(bc)
    score = 0.0
    for b, c in zip(bc, cc):
        p = (b + 0.5) / (nb + 0.5 * k)
        q = (c + 0.5) / (nc + 0.5 * k)
        score += (q - p) * math.log(q / p)
    return score


def _shift_factor(base_q: float, cand_q: float) -> float:
    """Symmetric quantile shift factor ≥ 1 (1 = no shift)."""
    b, c = base_q + _EPS, cand_q + _EPS
    return max(b / c, c / b)


class Finding(dict):
    """One per-metric comparison result — a dict (JSON-friendly) with
    attribute sugar for the fields every consumer reads."""

    @property
    def drifted(self) -> bool:
        return bool(self.get("drifted"))


def _compare_metric(metric: str, b: dict, c: dict, th: dict) -> Finding:
    if b["type"] != c["type"]:
        return Finding(metric=metric, kind="type", drifted=True,
                       detail=f"type {b['type']} -> {c['type']}")
    if b["type"] == "counter":
        bv, cv = float(b["value"]), float(c["value"])
        if abs(cv - bv) <= th.get("counter_abs", 0.0):
            return Finding(metric=metric, kind="counter", drifted=False,
                           rel=0.0, base=bv, cand=cv)
        rel = abs(cv - bv) / abs(bv) if bv else math.inf
        return Finding(metric=metric, kind="counter", base=bv, cand=cv,
                       rel=rel, threshold=th["counter_rel"],
                       drifted=rel > th["counter_rel"],
                       detail=f"{bv:g} -> {cv:g} "
                              f"(Δ{rel * 100 if math.isfinite(rel) else math.inf:.0f}% "
                              f"vs {th['counter_rel'] * 100:.0f}%)")
    if b["type"] == "gauge":
        gauge_abs = th.get("gauge_abs")
        if gauge_abs is None:
            return Finding(metric=metric, kind="gauge", drifted=False,
                           skipped=True, detail="gauges skipped by default")
        delta = abs(float(c["value"]) - float(b["value"]))
        return Finding(metric=metric, kind="gauge", base=b["value"],
                       cand=c["value"], threshold=gauge_abs,
                       drifted=delta > gauge_abs,
                       detail=f"{b['value']:g} -> {c['value']:g}")
    # histogram
    if list(b["bounds"]) != list(c["bounds"]):
        return Finding(metric=metric, kind="bounds", drifted=True,
                       detail="bucket bounds differ (schema change)")
    if b["count"] < th["min_count"] or c["count"] < th["min_count"]:
        return Finding(metric=metric, kind="histogram", drifted=False,
                       skipped=True,
                       detail=f"too thin (n={b['count']}/{c['count']} < "
                              f"{th['min_count']})")
    score = psi(b, c)
    p50b, p50c = snapshot_quantile(b, 0.5), snapshot_quantile(c, 0.5)
    p99b, p99c = snapshot_quantile(b, 0.99), snapshot_quantile(c, 0.99)
    f50, f99 = _shift_factor(p50b, p50c), _shift_factor(p99b, p99c)
    reasons = []
    if score > th["psi"]:
        reasons.append(f"psi={score:.3f}>{th['psi']:g}")
    if f50 > th["p50_factor"]:
        reasons.append(f"p50 {p50b:.3g}->{p50c:.3g} "
                       f"({f50:.1f}x>{th['p50_factor']:g}x)")
    if f99 > th["p99_factor"]:
        reasons.append(f"p99 {p99b:.3g}->{p99c:.3g} "
                       f"({f99:.1f}x>{th['p99_factor']:g}x)")
    return Finding(metric=metric, kind="histogram", psi=score,
                   p50=(p50b, p50c), p99=(p99b, p99c),
                   p50_factor=f50, p99_factor=f99,
                   drifted=bool(reasons),
                   detail="  ".join(reasons) if reasons else
                          f"psi={score:.3f} p50x{f50:.2f} p99x{f99:.2f}")


class DriftReport:
    """Comparison of two snapshot documents: per-metric findings plus a
    render for humans; ``drifted`` drives the CI exit code."""

    def __init__(self, base_name: str, cand_name: str,
                 findings: List[Finding], notes: List[str]):
        self.base_name = base_name
        self.cand_name = cand_name
        self.findings = findings
        self.notes = notes

    @property
    def drifted(self) -> bool:
        return any(f.drifted for f in self.findings)

    @property
    def drifted_metrics(self) -> List[str]:
        return [f["metric"] for f in self.findings if f.drifted]

    def lines(self) -> List[str]:
        out = [f"== Obs drift: {self.base_name} -> {self.cand_name} =="]
        out.extend(f"note  {n}" for n in self.notes)
        width = max((len(f["metric"]) for f in self.findings), default=0)
        compared = skipped = 0
        for f in sorted(self.findings,
                        key=lambda f: (not f.drifted, f["metric"])):
            if f.get("skipped"):
                skipped += 1
                continue
            compared += 1
            tag = "DRIFT" if f.drifted else "ok   "
            out.append(f"{tag} {f['metric']:<{width}}  {f.get('detail', '')}"
                       .rstrip())
        n_drift = len(self.drifted_metrics)
        out.append(f"{compared} compared, {n_drift} drifted, "
                   f"{skipped} skipped")
        return out

    def render(self) -> str:
        return "\n".join(self.lines())


def diff_docs(base_doc: dict, cand_doc: dict,
              baseline: Optional[dict] = None,
              base_name: str = "base", cand_name: str = "candidate"
              ) -> DriftReport:
    """Diff two persisted snapshot documents (multi-registry or bare).

    Metrics are keyed ``<registry>/<instrument>``; a metric missing from
    the candidate (instrumentation removed) or newly appearing (added) is
    a note, not drift — the gate is about distributions moving, schema
    evolution is reviewed in the diff that changes it."""
    th = _Thresholds(baseline)
    base_regs, cand_regs = named_registries(base_doc), named_registries(cand_doc)
    findings: List[Finding] = []
    notes: List[str] = []

    bcfg, ccfg = base_doc.get("config"), cand_doc.get("config")
    if isinstance(bcfg, dict) and isinstance(ccfg, dict) and bcfg != ccfg:
        diff_keys = sorted(k for k in set(bcfg) | set(ccfg)
                           if bcfg.get(k) != ccfg.get(k))
        notes.append("config differs (" + ", ".join(
            f"{k}: {bcfg.get(k)!r}->{ccfg.get(k)!r}" for k in diff_keys)
            + ") — deltas may reflect the config, not a regression")

    for reg in sorted(set(base_regs) | set(cand_regs)):
        if reg not in cand_regs:
            notes.append(f"registry {reg!r} missing from {cand_name}")
            continue
        if reg not in base_regs:
            notes.append(f"registry {reg!r} new in {cand_name}")
            continue
        b, c = base_regs[reg], cand_regs[reg]
        prefix = f"{reg}/" if len(base_regs) > 1 or reg != "registry" else ""
        for name in sorted(set(b) | set(c)):
            metric = prefix + name
            if th.ignored(metric):
                continue
            if name not in c:
                notes.append(f"{metric} missing from {cand_name}")
                continue
            if name not in b:
                notes.append(f"{metric} new in {cand_name}")
                continue
            findings.append(
                _compare_metric(metric, b[name], c[name],
                                th.for_metric(metric)))
    return DriftReport(base_name, cand_name, findings, notes)


# ---------------------------------------------------------------------------
# windowed diff over one live run (ISSUE 8: the continual deploy gate)
# ---------------------------------------------------------------------------

#: the three windowed-diff outcomes, in increasing order of alarm
WINDOW_KINDS = ("stable", "step", "trend")


def _instrument_delta(base: dict, cand: dict) -> dict:
    """One instrument's interval delta (see :func:`snapshot_delta`)."""
    if base.get("type") != cand.get("type"):
        return dict(cand)  # instrument re-registered as a new kind
    if cand["type"] == "counter":
        d = float(cand["value"]) - float(base["value"])
        # a negative delta means the process restarted mid-window; the
        # cand value IS that fresh process's interval
        return {"type": "counter", "value": d if d >= 0 else cand["value"]}
    if cand["type"] == "gauge":
        return dict(cand)  # levels have no meaningful subtraction
    if list(base["bounds"]) != list(cand["bounds"]):
        return dict(cand)  # schema change: start the series over
    counts = [c - b for b, c in zip(base["counts"], cand["counts"])]
    if any(c < 0 for c in counts):
        return dict(cand)  # restart mid-window
    return {"type": "histogram", "bounds": list(cand["bounds"]),
            "counts": counts, "sum": cand["sum"] - base["sum"],
            "count": cand["count"] - base["count"]}


def snapshot_delta(base: dict, cand: dict) -> dict:
    """Interval delta between two cumulative ``Registry.snapshot()``s of
    the SAME live registry taken at t0 < t1: counters and histograms
    subtract (the delta describes what happened *during* [t0, t1]),
    gauges keep the later level.  Metrics born mid-interval enter at
    their cand value; metrics that vanished are dropped.  This is what
    makes a long-running process's snapshots comparable as a series —
    raw cumulative counters only ever grow, so consecutive raw snapshots
    would always "drift"."""
    out = {}
    for name, c in cand.items():
        b = base.get(name)
        out[name] = _instrument_delta(b, c) if b is not None else dict(c)
    return out


class WindowVerdict(dict):
    """One windowed-diff classification — a plain dict (JSON-friendly,
    rides obs documents and the deploy log) with the sugar consumers
    read: ``kind`` ∈ :data:`WINDOW_KINDS`, ``clean`` gates deploys."""

    @property
    def kind(self) -> str:
        return self.get("kind", "stable")

    @property
    def clean(self) -> bool:
        return self.kind == "stable"

    @property
    def dirty_metrics(self) -> List[str]:
        return sorted(set(self.get("step_metrics", []))
                      | set(self.get("trend_metrics", [])))


def classify_window(intervals: Sequence[dict], baseline: Optional[dict] = None
                    ) -> WindowVerdict:
    """Classify a rolling window of per-interval snapshots (the outputs
    of :func:`snapshot_delta`, oldest first) as ``stable`` / ``step`` /
    ``trend``:

    * **step** — some *consecutive* interval pair drifts under the
      normal :func:`diff_docs` thresholds: an abrupt jump.  The verdict
      stays dirty until the offending pair slides out of the window —
      i.e. until every retained interval is post-jump and mutually
      stable again.
    * **trend** — no consecutive pair drifts, but the window's first →
      last cumulative diff does: gradual creep, each step under
      threshold, the sum over the window past it (the drift item's
      long-open step-vs-trend distinction).
    * **stable** — neither; the drift-clean condition deploys gate on.

    Fewer than 2 intervals classify ``stable`` with ``intervals`` naming
    how thin the evidence is — warm-up gating is the deploy gate's job
    (``min_history``), not the classifier's."""
    intervals = list(intervals)
    n = len(intervals)
    verdict = WindowVerdict(kind="stable", intervals=n,
                            step_metrics=[], trend_metrics=[], details=[])
    if n < 2:
        verdict["details"] = ["fewer than 2 intervals: nothing to compare"]
        return verdict
    step: Dict[str, str] = {}
    for i in range(n - 1):
        rep = diff_docs(intervals[i], intervals[i + 1], baseline=baseline,
                        base_name=f"interval[{i}]",
                        cand_name=f"interval[{i + 1}]")
        for f in rep.findings:
            if f.drifted and f["metric"] not in step:
                step[f["metric"]] = (f"step {i}->{i + 1}: "
                                     f"{f.get('detail', '')}".rstrip())
    cum = diff_docs(intervals[0], intervals[-1], baseline=baseline,
                    base_name="interval[0]", cand_name=f"interval[{n - 1}]")
    trend = {f["metric"]: f"trend 0->{n - 1}: {f.get('detail', '')}".rstrip()
             for f in cum.findings
             if f.drifted and f["metric"] not in step}
    verdict["step_metrics"] = sorted(step)
    verdict["trend_metrics"] = sorted(trend)
    verdict["details"] = [step[m] for m in sorted(step)] + \
                         [trend[m] for m in sorted(trend)]
    verdict["kind"] = "step" if step else ("trend" if trend else "stable")
    return verdict


def diff_files(base_path: str, cand_path: str,
               baseline: Optional[dict] = None) -> DriftReport:
    """Diff two snapshot JSON files (the ``obsview --diff`` body)."""
    docs = []
    for p in (base_path, cand_path):
        with open(p) as f:
            doc = json.load(f)
        if not isinstance(doc, dict) or not named_registries(doc):
            raise ValueError(f"{p}: no registry snapshot found "
                             "(is this a JSONL record stream?)")
        docs.append(doc)
    return diff_docs(docs[0], docs[1], baseline=baseline,
                     base_name=os.path.basename(base_path),
                     cand_name=os.path.basename(cand_path))

"""Straggler detection over per-window worker heartbeats (ISSUE 5).

The async trainers fail *statistically*: a slow worker never raises — it
just stretches the staleness/latency distributions (the exact failure
mode the paper's DynSGD rule exists to tolerate).  This module turns the
per-window heartbeat cadence the workers already emit into a live signal:

* ``StragglerDetector`` keeps a rolling EWMA of each worker's
  heartbeat gap (monotonic seconds between committed windows, shipped on
  the commit RPC as ``gap_s``) and flags any worker whose EWMA exceeds
  ``k×`` the fleet median.  Flagged count lands in a ``ps.stragglers``
  gauge (visible in the live ``stats`` RPC / ``obsview --ps``), per-worker
  EWMAs in ``ps.heartbeat_gap_ewma.worker<k>`` gauges, and the FIRST time
  a worker is flagged a single warn log names it — one line per incident,
  not one per window.

* ``detect_from_heartbeats`` replays the same detector over a recorded
  JSONL heartbeat stream (records carrying ``worker_id``/``gap_s``) — the
  post-mortem path ``scripts/obsview.py`` uses on run files.

* ``LinkQuality`` (ISSUE 15) is the **link half** of the same picture,
  living on the CLIENT next to the adaptive DOWN-codec policy: per-link
  pull/commit RTT EWMAs with a degradation edge against the best RTT the
  link has shown.  The adaptive policy consumes ``degraded()`` to
  downshift the codec (and tighten its reprobe schedule) BEFORE the
  worker's stretched window gap gets it flagged here, and the client
  ships its EWMA on every commit (``link_rtt_s``) so the server-side
  detector's snapshot renders gap and link side by side — a stretched
  gap whose link stretched equally is wire-degraded, not compute-stuck.

Thresholding is median-relative, not absolute: window wall time is
workload-dependent, but the *fleet* trains identical windows, so a worker
k× slower than the median is anomalous at any absolute scale.  The
``min_gap_s`` floor keeps sub-millisecond jitter on toy workloads from
flagging anything.
"""

from __future__ import annotations

import bisect
import math
import statistics
import threading
from typing import Dict, List, Optional, Sequence

from .logging import get_logger
from .registry import Registry


def _loo_median(vals_sorted: Sequence[float], i: int) -> float:
    """Median of ``vals_sorted`` with the element at index ``i`` removed
    (for equal values any occurrence's removal leaves the same multiset).
    Index math over the shared sort — the O(1) inner step that keeps the
    per-commit re-evaluation at one sort total."""
    m = len(vals_sorted) - 1

    def at(j: int) -> float:  # j-th element of the remainder
        return vals_sorted[j if j < i else j + 1]

    if m % 2:                        # odd remainder: single middle value
        return at(m // 2)
    return (at(m // 2 - 1) + at(m // 2)) / 2.0


class LinkQuality:
    """Per-link RTT EWMAs (pull + commit) with a degradation edge
    (ISSUE 15).  One instance per PS connection, on the CLIENT — the end
    that actually measures the link.

    The pull EWMA folds the VISIBLE pull wait (blocked-on-reply ->
    decoded): for a sequential pull that is the wire RTT; for a
    dispatch-ahead pull it is the drain left after compute — the pull's
    critical-path cost either way, and deliberately NOT the
    send-to-decode span, which under overlap would count the caller's
    whole device step as link time.  The commit EWMA is a full
    synchronous wire RTT.  Either direction's degradation trips the
    edge.

    ``degraded()`` is True while either direction's EWMA exceeds
    ``degrade_factor`` × the best EWMA that direction has shown (floored
    at ``min_rtt_s`` so toy-fast links never read as degraded).  After a
    consumer ACTS on the edge (the adaptive policy's codec downshift),
    :meth:`rebase` adopts the current EWMAs as the new baseline — the
    link's byte profile just changed, so the old best is no longer the
    comparison point (and the edge self-cools instead of re-firing every
    pull).  Thread-safe; hostile inputs (NaN, negative) are rejected
    before they can poison an EWMA."""

    def __init__(self, alpha: float = 0.25, degrade_factor: float = 2.5,
                 min_rtt_s: float = 1e-3, registry=None):
        if degrade_factor <= 1.0:
            raise ValueError(f"degrade_factor must exceed 1, "
                             f"got {degrade_factor}")
        self.alpha = float(alpha)
        self.degrade_factor = float(degrade_factor)
        self.min_rtt_s = float(min_rtt_s)
        self.registry = registry
        self._lock = threading.Lock()
        self._ewma: Dict[str, Optional[float]] = {"pull": None,
                                                  "commit": None}
        self._best: Dict[str, Optional[float]] = {"pull": None,
                                                  "commit": None}

    def _fold(self, kind: str, rtt_s) -> None:
        try:
            r = float(rtt_s)
        except (TypeError, ValueError):
            return
        if not math.isfinite(r) or r < 0:
            return
        with self._lock:
            prev = self._ewma[kind]
            cur = r if prev is None \
                else self.alpha * r + (1.0 - self.alpha) * prev
            self._ewma[kind] = cur
            best = self._best[kind]
            if best is None or cur < best:
                self._best[kind] = cur
        if self.registry is not None:
            self.registry.gauge(f"ps.link.{kind}_rtt_ewma").set(cur)

    def observe_pull(self, rtt_s) -> None:
        self._fold("pull", rtt_s)

    def observe_commit(self, rtt_s) -> None:
        self._fold("commit", rtt_s)

    @property
    def ewma(self) -> Optional[float]:
        """The link's representative RTT EWMA — the pull direction when
        it has samples (pulls carry the center, the dominant bytes),
        else the commit direction."""
        with self._lock:
            return self._ewma["pull"] if self._ewma["pull"] is not None \
                else self._ewma["commit"]

    def degraded(self) -> bool:
        with self._lock:
            return any(
                e is not None and b is not None
                and e > self.degrade_factor * max(b, self.min_rtt_s)
                for e, b in ((self._ewma[k], self._best[k])
                             for k in ("pull", "commit")))

    def rebase(self) -> None:
        """Adopt the current EWMAs as the new baseline (called after a
        consumer acted on the degradation edge)."""
        with self._lock:
            for k in ("pull", "commit"):
                self._best[k] = self._ewma[k]

    def snapshot(self) -> dict:
        with self._lock:
            return {"ewma_s": dict(self._ewma), "best_s": dict(self._best),
                    "degrade_factor": self.degrade_factor}


class StragglerDetector:
    """Rolling heartbeat-gap EWMA per worker, fleet-median flagging.

    ``record(worker_id, gap_s)`` is called once per committed window (the
    PS server feeds it from the commit RPC's ``gap_s`` field); it updates
    the worker's EWMA, re-evaluates the fleet, and maintains the
    ``ps.stragglers`` gauge.  Thread-safe — handler threads call it
    concurrently.
    """

    def __init__(self, k: float = 3.0, alpha: float = 0.25,
                 min_workers: int = 2, min_gap_s: float = 1e-3,
                 weight_floor: float = 0.1,
                 registry: Optional[Registry] = None):
        if k <= 1.0:
            raise ValueError(f"straggler threshold k must exceed 1, got {k}")
        self.k = float(k)
        self.alpha = float(alpha)
        #: a fleet of one has no peers to straggle behind
        self.min_workers = int(min_workers)
        #: median floor: below this the fleet is too fast for a multiple
        #: of the median to mean anything (toy tests, cache-warm windows)
        self.min_gap_s = float(min_gap_s)
        #: down-weighting floor (ISSUE 9): a flagged worker's commits are
        #: never scaled below this — evict-and-respawn, not starvation, is
        #: the remedy for a worker this far gone
        self.weight_floor = float(weight_floor)
        self.registry = registry
        self._lock = threading.Lock()
        self._ewma: Dict[int, float] = {}
        self._flagged: set = set()   # currently over threshold
        #: per-worker link RTT EWMAs + codec-downshift tallies shipped on
        #: the commit RPC (ISSUE 15) — already EWMAs client-side, so the
        #: latest value wins; rendered next to the gap EWMAs so the
        #: numbers that justify (or excuse) a flag sit side by side
        self._link: Dict[int, float] = {}
        self._link_downshifts: Dict[int, int] = {}
        self._log = get_logger("obs.stragglers")

    def record(self, worker_id, gap_s) -> bool:
        """Fold one heartbeat gap in; returns True iff ``worker_id`` is
        currently flagged as a straggler."""
        try:
            w = int(worker_id)
            gap = float(gap_s)
        except (TypeError, ValueError):
            return False
        # gap_s arrives off the untrusted wire: one NaN would poison the
        # EWMA forever (alpha·gap + (1−alpha)·NaN stays NaN) and a NaN
        # member breaks every peer's median — reject non-finite outright
        if not math.isfinite(gap) or gap < 0:
            return False
        with self._lock:
            prev = self._ewma.get(w)
            cur = gap if prev is None \
                else self.alpha * gap + (1.0 - self.alpha) * prev
            self._ewma[w] = cur
            # rising-edge logging: one warn per INCIDENT — a worker that
            # recovers and later straggles again crosses the edge again
            prev_flagged = set(self._flagged)
            flagged = self._reeval(updated=w)
            newly = flagged - prev_flagged
            ewma = dict(self._ewma)
        for nw in sorted(newly):
            peers = [v for p, v in ewma.items() if p != nw]
            self._log.warning(
                "straggler: worker %d heartbeat-gap EWMA %.3fs exceeds "
                "%.1fx peer median %.3fs", nw, ewma[nw], self.k,
                statistics.median(peers) if peers else 0.0)
        return w in flagged

    def _reeval(self, updated=None) -> set:  # caller holds self._lock
        ewma = self._ewma
        if len(ewma) >= self.min_workers:
            # leave-one-out median: each worker is judged against its
            # PEERS.  A self-inclusive median breaks down on small fleets
            # — with 2 workers the straggler pulls the median halfway to
            # itself and k=3 becomes mathematically unreachable.  This
            # runs on the commit hot path under the detector lock, so the
            # per-worker medians come from ONE shared sort (index math
            # removes each worker's own value) — O(W log W) per commit,
            # not O(W² log W).
            vals = sorted(ewma.values())
            flagged = set()
            for w, e in ewma.items():
                median = _loo_median(vals, bisect.bisect_left(vals, e))
                if e > self.k * max(median, self.min_gap_s):
                    flagged.add(w)
            self._flagged = flagged
        else:
            self._flagged = set()
        if self.registry is not None:
            self.registry.gauge("ps.stragglers").set(len(self._flagged))
            # only the recorded worker's EWMA moved; peers' gauges were
            # set when THEY last recorded
            targets = ewma if updated is None or updated not in ewma \
                else {updated: ewma[updated]}
            for w, e in targets.items():
                # labeled series (ISSUE 20); flattens to the legacy
                # ps.heartbeat_gap_ewma.worker<k> name
                self.registry.gauge("ps.heartbeat_gap_ewma",
                                    labels={"worker": w}).set(e)
        return set(self._flagged)

    def record_link(self, worker_id, rtt_s, downshifts=None) -> None:
        """Fold one worker's reported link RTT EWMA (the commit RPC's
        ``link_rtt_s`` field — ISSUE 15) and, when present, its
        cumulative codec-downshift count.  Hostile values are rejected
        like ``record``'s ``gap_s``."""
        try:
            w = int(worker_id)
            r = float(rtt_s)
        except (TypeError, ValueError):
            return
        if not math.isfinite(r) or r < 0:
            return
        with self._lock:
            self._link[w] = r
            if downshifts is not None:
                try:
                    self._link_downshifts[w] = int(downshifts)
                except (TypeError, ValueError):
                    pass
        if self.registry is not None:
            self.registry.gauge("ps.link.rtt_ewma",
                                labels={"worker": w}).set(r)

    def commit_weight(self, worker_id) -> float:
        """DynSGD-style down-weighting multiplier for this worker's NEXT
        commit (ISSUE 9 rung 1): an unflagged worker commits at full
        weight 1.0; a flagged straggler's commits are scaled by its peer
        median over its own EWMA — a worker whose cadence is 5× the
        fleet's contributes 1/5 of its delta, exactly the shape of
        DynSGD's 1/(staleness+1) rule but driven by the *liveness*
        signal instead of the update counter.  Floored at
        ``weight_floor``; restored to 1.0 the moment the flag clears."""
        try:
            w = int(worker_id)
        except (TypeError, ValueError):
            return 1.0
        with self._lock:
            if w not in self._flagged:
                return 1.0
            ewma = self._ewma.get(w)
            peers = [v for p, v in self._ewma.items() if p != w]
            if not peers or not ewma or ewma <= 0:
                return 1.0
            median = max(statistics.median(peers), self.min_gap_s)
            return max(self.weight_floor, min(1.0, median / ewma))

    @property
    def stragglers(self) -> List[int]:
        with self._lock:
            return sorted(self._flagged)

    def snapshot(self) -> dict:
        """Plain-data state for the ``stats`` RPC reply / post-mortems.
        ``peer_median_s`` is each worker's LEAVE-ONE-OUT peer median — the
        same quantity the flag threshold multiplies, so the rendered
        numbers always justify the flags shown next to them."""
        with self._lock:
            ewma = dict(self._ewma)
            flagged = sorted(self._flagged)
            link = dict(self._link)
            downshifts = dict(self._link_downshifts)
        return {"k": self.k, "alpha": self.alpha,
                "min_gap_s": self.min_gap_s,
                "link_rtt_s": {str(w): link[w] for w in sorted(link)},
                "link_downshifts": {str(w): downshifts[w]
                                    for w in sorted(downshifts)},
                "gap_ewma_s": {str(w): ewma[w] for w in sorted(ewma)},
                "peer_median_s": {
                    str(w): statistics.median(
                        [v for p, v in ewma.items() if p != w])
                    if len(ewma) > 1 else 0.0
                    for w in sorted(ewma)},
                "stragglers": flagged}


def detect_from_heartbeats(records, k: float = 3.0, alpha: float = 0.25,
                           min_workers: int = 2,
                           min_gap_s: float = 1e-3) -> dict:
    """Replay a recorded heartbeat stream through the detector — the
    offline half (``obsview`` run files).  ``records`` are JSONL dicts;
    only ``event == "heartbeat"`` entries carrying ``gap_s`` count (old
    streams without ``gap_s`` yield an empty fleet, never a crash)."""
    det = StragglerDetector(k=k, alpha=alpha, min_workers=min_workers,
                            min_gap_s=min_gap_s)
    for r in records:
        if r.get("event") != "heartbeat" or r.get("gap_s") is None:
            continue
        w = r.get("worker_id", r.get("worker"))
        if w is not None:
            det.record(w, r["gap_s"])
            if r.get("link_rtt_s") is not None:
                # the heartbeat-borne link half (ISSUE 15) replays too
                det.record_link(w, r["link_rtt_s"],
                                r.get("link_downshifts"))
    return det.snapshot()

"""Library logging + the one console seam.

Two distinct audiences, two functions:

* ``get_logger(name)`` — stdlib ``logging`` under the ``distkeras_tpu``
  namespace for diagnostics.  Library-friendly: a ``NullHandler`` is
  installed so importing the package never configures global logging;
  ``enable_stderr_logging()`` opts a script into visible output.
* ``emit(msg, err=False)`` — deliberate CLI output (usage lines, result
  tables).  Library code contains **no bare ``print(`` calls** (a tier-1
  test greps for them); anything user-facing goes through this seam, so
  output destinations stay swappable and auditable.  Streams are looked
  up at call time (``sys.stdout``/``sys.stderr``) so capture/redirection
  works.
"""

from __future__ import annotations

import logging
import sys
from typing import Optional

_ROOT = "distkeras_tpu"

logging.getLogger(_ROOT).addHandler(logging.NullHandler())


def get_logger(name: Optional[str] = None) -> logging.Logger:
    """Namespaced library logger (``distkeras_tpu`` or a child)."""
    if not name:
        return logging.getLogger(_ROOT)
    if not name.startswith(_ROOT):
        name = f"{_ROOT}.{name}"
    return logging.getLogger(name)


def enable_stderr_logging(level: int = logging.INFO) -> logging.Logger:
    """Attach a stderr handler to the package logger (idempotent) — the
    opt-in for scripts that want diagnostics on the terminal."""
    logger = logging.getLogger(_ROOT)
    if not any(isinstance(h, logging.StreamHandler)
               and not isinstance(h, logging.NullHandler)
               for h in logger.handlers):
        h = logging.StreamHandler()
        h.setFormatter(logging.Formatter(
            "%(asctime)s %(name)s %(levelname)s %(message)s"))
        logger.addHandler(h)
    logger.setLevel(level)
    return logger


def emit(msg: str = "", *, err: bool = False, flush: bool = True) -> None:
    """Deliberate console output (CLI tables, usage strings).  The only
    sanctioned stdout/stderr write in library code."""
    stream = sys.stderr if err else sys.stdout
    stream.write(str(msg) + "\n")
    if flush:
        try:
            stream.flush()
        except OSError:  # pragma: no cover - broken pipe on teardown
            pass

"""Live alert evaluation over the fleet time series (ISSUE 20 rung 3).

The drift gate (``obs.drift``) judges a run AFTER it ends; this module
judges it WHILE it runs.  :class:`AlertEngine` continuously evaluates
two rule kinds over a :class:`~.timeseries.TimeSeriesStore`:

* **threshold** — the OBS_BASELINE shape: a metric's merged cumulative
  value must stay at/below ``max_value`` (``jit.retraces`` at 0, leak
  counters at 0), or its counter RATE over ``window_s`` must stay
  at/below ``max_rate``.
* **burn_rate** — the SLO-debt rule: over a SHORT and a LONG trailing
  window, attainment = fraction of a latency histogram's observations
  ≤ ``bound_s`` (the scenario ``hist_fraction_le`` math, replicated
  here so obs never imports the scenario layer); burn = (1 − attainment)
  / (1 − target attainment).  Burn 1.0 spends SLO budget exactly at the
  sustainable rate; the rule breaches only when BOTH windows exceed
  ``max_burn`` — the classic multiwindow guard: the short window makes
  alerts fast, the long window keeps a single slow request from paging.

Hysteresis so noise never flaps: a breach must PERSIST ``for_s``
seconds before the rule fires, and a firing rule must stay clean
``clear_s`` seconds before it resolves.  Every transition is an
``obs.alerts.{fired,resolved}`` counter (labeled by rule, flattened
per the ISSUE 20 rule) plus an optional JSONL ``alert`` record; rapid
transitions additionally count ``obs.alerts.flaps`` — the obsview
ALERT-FLAP signal.  Rules whose series carry no (or not enough) data
hold their current state: absence of evidence neither fires nor
resolves.  Hostile series never reach the math — the store rejects
non-finite input at ingest, and the engine re-checks every value it
reads.

Rules load from the committed baseline contract: an ``"alerts"`` list
in ``OBS_BASELINE.json``, each entry a plain dict (see
:func:`parse_rules`) — statically linted by the dklint metric-contract
rule like every other baseline pattern.
"""

from __future__ import annotations

import bisect
import collections
import dataclasses
import math
import threading
import time
from typing import Callable, Dict, List, Optional

from .logging import get_logger
from .registry import Registry, flat_name
from .timeseries import TimeSeriesStore

_LOG = "obs.alerts"

#: label keys the telemetry plane blesses — the dklint metric-contract
#: extension flags creation sites and alert rules using keys outside
#: this vocabulary (a typo'd key silently forks a new series)
KNOWN_LABEL_KEYS = ("engine", "phase", "rule", "shard", "source",
                    "tenant", "version", "worker")

#: transitions within this window before a rule counts as flapping
FLAP_WINDOW_S = 60.0
FLAP_TRANSITIONS = 4


def hist_fraction_le(snap: Optional[dict], bound: float) -> Optional[float]:
    """Fraction of a histogram snapshot's observations ≤ ``bound`` —
    exact on bucket boundaries, conservative (next-lower bound)
    otherwise; ``None`` with nothing to read.  Mirrors
    ``scenario.slo.hist_fraction_le`` (obs cannot import scenario)."""
    if not snap or snap.get("type") != "histogram" or not snap.get("count"):
        return None
    k = bisect.bisect_right(list(snap["bounds"]), bound)
    return sum(snap["counts"][:k]) / snap["count"]


@dataclasses.dataclass(frozen=True)
class AlertRule:
    """One rule, parsed and validated.  ``kind`` selects which fields
    matter: threshold rules use ``max_value``/``max_rate`` +
    ``window_s``; burn-rate rules use ``bound_s``/``attainment``/
    ``short_s``/``long_s``/``max_burn``/``min_samples``.  ``for_s`` /
    ``clear_s`` are the hysteresis pair on both kinds."""

    name: str
    kind: str                       # "threshold" | "burn_rate"
    metric: str                     # flat metric name
    labels: Optional[dict] = None   # informational label filter
    # threshold
    max_value: Optional[float] = None
    max_rate: Optional[float] = None
    window_s: float = 30.0
    # burn rate
    bound_s: Optional[float] = None
    attainment: float = 0.95
    short_s: float = 5.0
    long_s: float = 30.0
    max_burn: float = 2.0
    min_samples: int = 8
    # hysteresis
    for_s: float = 0.0
    clear_s: float = 1.0

    def flat_metric(self) -> str:
        return flat_name(self.metric, self.labels)


_RULE_KEYS = {f.name for f in dataclasses.fields(AlertRule)} | {"_comment"}


def parse_rules(doc) -> List[AlertRule]:
    """Rules from a baseline document (or a bare list of rule dicts).
    Malformed rules raise — a typo'd alert contract must fail loudly at
    load, not silently gate nothing (the dead-threshold precedent)."""
    raw = doc.get("alerts", []) if isinstance(doc, dict) else doc
    rules: List[AlertRule] = []
    seen = set()
    for i, r in enumerate(raw or []):
        if not isinstance(r, dict):
            raise ValueError(f"alert rule #{i}: not a mapping: {r!r}")
        unknown = set(r) - _RULE_KEYS
        if unknown:
            raise ValueError(f"alert rule #{i}: unknown keys "
                             f"{sorted(unknown)}")
        kw = {k: v for k, v in r.items() if k != "_comment"}
        try:
            rule = AlertRule(**kw)
        except TypeError as e:
            raise ValueError(f"alert rule #{i}: {e}") from None
        if not rule.name or rule.name in seen:
            raise ValueError(f"alert rule #{i}: missing or duplicate "
                             f"name {rule.name!r}")
        seen.add(rule.name)
        if rule.kind == "threshold":
            if rule.max_value is None and rule.max_rate is None:
                raise ValueError(f"alert rule {rule.name!r}: threshold "
                                 f"needs max_value or max_rate")
        elif rule.kind == "burn_rate":
            if rule.bound_s is None:
                raise ValueError(f"alert rule {rule.name!r}: burn_rate "
                                 f"needs bound_s")
            if not 0.0 < rule.attainment < 1.0:
                raise ValueError(f"alert rule {rule.name!r}: attainment "
                                 f"must be in (0, 1)")
            if rule.short_s > rule.long_s:
                raise ValueError(f"alert rule {rule.name!r}: short_s "
                                 f"must not exceed long_s")
        else:
            raise ValueError(f"alert rule {rule.name!r}: unknown kind "
                             f"{rule.kind!r}")
        if rule.labels:
            for k in rule.labels:
                if k not in KNOWN_LABEL_KEYS:
                    raise ValueError(
                        f"alert rule {rule.name!r}: unknown label key "
                        f"{k!r} (known: {', '.join(KNOWN_LABEL_KEYS)})")
        rules.append(rule)
    return rules


class _RuleState:
    __slots__ = ("firing", "breach_since", "clean_since", "fired",
                 "resolved", "transitions", "measure")

    def __init__(self):
        self.firing = False
        self.breach_since: Optional[float] = None
        self.clean_since: Optional[float] = None
        self.fired = 0
        self.resolved = 0
        #: transition timestamps for flap detection
        self.transitions: collections.deque = collections.deque(maxlen=16)
        #: last measurement doc (value / burn_short / burn_long / ...)
        self.measure: dict = {}


class AlertEngine:
    """Evaluate rules over a store; keep hysteresis state per rule.

    Evaluation is PULL-driven and rate-limited (``eval_interval_s``):
    callers invoke :meth:`evaluate` from whatever cadence they already
    own — a telemetry ingest, an autoscaler tick, an ``alerts`` RPC —
    and redundant calls inside the interval are free.  No thread of its
    own, so attaching an engine to a server adds no lock-order or
    shutdown sequencing surface.

    ``source_registry`` makes a standalone server alertable with zero
    extra plumbing: each evaluation first self-ingests that registry's
    cumulative snapshot into the store under source ``_local``.
    """

    def __init__(self, store: TimeSeriesStore, rules: List[AlertRule], *,
                 registry: Optional[Registry] = None,
                 events=None,
                 source_registry: Optional[Registry] = None,
                 eval_interval_s: float = 0.25,
                 clock: Callable[[], float] = time.monotonic):
        self.store = store
        self.rules = list(rules)
        self.registry = registry
        self.events = events
        self.source_registry = source_registry
        self.eval_interval_s = float(eval_interval_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._last_eval: Optional[float] = None
        self._state: Dict[str, _RuleState] = {
            r.name: _RuleState() for r in self.rules}
        self.log = get_logger(_LOG)
        if registry is not None:
            # pre-created so a clean run reports 0 instead of omitting
            # the counters (the drift gate's present-0 contract)
            self._c_fired = registry.counter("obs.alerts.fired")
            self._c_resolved = registry.counter("obs.alerts.resolved")
            self._c_flaps = registry.counter("obs.alerts.flaps")
            self._g_firing = registry.gauge("obs.alerts.firing")
        else:
            self._c_fired = self._c_resolved = self._c_flaps = None
            self._g_firing = None

    # -- measurement --------------------------------------------------------
    def _measure(self, rule: AlertRule, now: float) -> Optional[bool]:
        """One rule's verdict: True breach, False clean, None no data
        (hold state)."""
        metric = rule.flat_metric()
        if rule.kind == "threshold":
            if rule.max_value is not None:
                e = self.store.latest().get(metric)
                v = e.get("value") if isinstance(e, dict) else None
                if v is None or not math.isfinite(float(v)):
                    return None
                st = self._state[rule.name]
                st.measure = {"value": float(v), "max_value": rule.max_value}
                return float(v) > float(rule.max_value)
            d = self.store.window_delta(metric, rule.window_s, now)
            if d is None or d.get("type") != "counter":
                return None
            rate = float(d["value"]) / max(rule.window_s, 1e-9)
            st = self._state[rule.name]
            st.measure = {"rate": rate, "max_rate": rule.max_rate}
            return rate > float(rule.max_rate)
        # burn_rate
        burns, fracs = {}, {}
        for tag, w in (("short", rule.short_s), ("long", rule.long_s)):
            d = self.store.window_delta(metric, w, now)
            if d is None or d.get("count", 0) < rule.min_samples:
                return None
            frac = hist_fraction_le(d, float(rule.bound_s))
            if frac is None or not math.isfinite(frac):
                return None
            fracs[tag] = frac
            burns[tag] = (1.0 - frac) / max(1.0 - rule.attainment, 1e-9)
        st = self._state[rule.name]
        st.measure = {"burn_short": burns["short"],
                      "burn_long": burns["long"],
                      "attainment_short": fracs["short"],
                      "attainment_long": fracs["long"],
                      "max_burn": rule.max_burn}
        return burns["short"] > rule.max_burn and \
            burns["long"] > rule.max_burn

    # -- evaluation ---------------------------------------------------------
    def evaluate(self, now: Optional[float] = None,
                 force: bool = False) -> List[dict]:
        """One evaluation pass; returns the transition events it caused
        (also logged/counted).  Rate-limited unless ``force``."""
        now = self._clock() if now is None else float(now)
        with self._lock:
            if not force and self._last_eval is not None and \
                    now - self._last_eval < self.eval_interval_s:
                return []
            self._last_eval = now
        if self.source_registry is not None:
            self.store.ingest_total("_local",
                                    self.source_registry.snapshot(), now)
        events: List[dict] = []
        with self._lock:
            for rule in self.rules:
                st = self._state[rule.name]
                breach = self._measure(rule, now)
                if breach is None:
                    continue  # hold state on missing evidence
                if breach:
                    st.clean_since = None
                    if st.breach_since is None:
                        st.breach_since = now
                    if not st.firing and \
                            now - st.breach_since >= rule.for_s:
                        events.append(self._transition(rule, st, now,
                                                       firing=True))
                else:
                    st.breach_since = None
                    if st.clean_since is None:
                        st.clean_since = now
                    if st.firing and \
                            now - st.clean_since >= rule.clear_s:
                        events.append(self._transition(rule, st, now,
                                                       firing=False))
            n_firing = sum(s.firing for s in self._state.values())
        if self._g_firing is not None:
            self._g_firing.set(n_firing)
        for ev in events:
            self._emit(ev)
        return events

    def _transition(self, rule: AlertRule, st: _RuleState, now: float,
                    *, firing: bool) -> dict:  # dklint: holds=_lock
        st.firing = firing
        st.transitions.append(now)
        if firing:
            st.fired += 1
        else:
            st.resolved += 1
        recent = [t for t in st.transitions if now - t <= FLAP_WINDOW_S]
        flapping = len(recent) >= FLAP_TRANSITIONS
        return {"rule": rule.name, "state": "firing" if firing
                else "resolved", "kind": rule.kind,
                "metric": rule.flat_metric(), "flapping": flapping,
                **self._state[rule.name].measure}

    def _emit(self, ev: dict) -> None:
        if self.registry is not None:
            what = "fired" if ev["state"] == "firing" else "resolved"
            (self._c_fired if what == "fired" else self._c_resolved).inc()
            # labeled per-rule tally; flattens to obs.alerts.<what>.rule<name>
            self.registry.counter(f"obs.alerts.{what}",
                                  labels={"rule": ev["rule"]}).inc()
            if ev.get("flapping"):
                self._c_flaps.inc()
        (self.log.warning if ev["state"] == "firing"
         else self.log.info)("alert %s: %s (%s)", ev["state"], ev["rule"],
                             ev.get("metric"))
        if self.events is not None:
            self.events.log("alert", **ev)

    # -- read ---------------------------------------------------------------
    def firing(self) -> List[str]:
        with self._lock:
            return sorted(n for n, s in self._state.items() if s.firing)

    def counts(self) -> dict:
        with self._lock:
            return {"fired": sum(s.fired for s in self._state.values()),
                    "resolved": sum(s.resolved
                                    for s in self._state.values()),
                    "firing": sum(s.firing for s in self._state.values())}

    def attainment_signal(self) -> Optional[float]:
        """The min short-window attainment across burn-rate rules with
        evidence — the alert-plane replacement for the autoscaler's own
        interval-delta poll math.  ``None`` with no evidence."""
        with self._lock:
            vals = [s.measure["attainment_short"]
                    for r in self.rules
                    for s in (self._state[r.name],)
                    if r.kind == "burn_rate"
                    and "attainment_short" in s.measure]
        return min(vals) if vals else None

    def state_doc(self) -> dict:
        """Plain-data engine state — the ``alerts`` RPC reply body and
        the obsview --alerts panel source."""
        now = self._clock()
        with self._lock:
            rules = []
            for r in self.rules:
                s = self._state[r.name]
                recent = [t for t in s.transitions
                          if now - t <= FLAP_WINDOW_S]
                rules.append({
                    "name": r.name, "kind": r.kind,
                    "metric": r.flat_metric(), "firing": s.firing,
                    "fired": s.fired, "resolved": s.resolved,
                    "flapping": len(recent) >= FLAP_TRANSITIONS,
                    "measure": dict(s.measure)})
        doc = {"rules": rules, "counts": self.counts(),
               "store": self.store.summary()}
        return doc

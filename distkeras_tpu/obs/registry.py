"""Metric instruments + registry — the telemetry core (ISSUE 2 tentpole).

The reference leaned entirely on Spark's web UI for visibility; our stack
needs first-class in-process instruments before any path can be trusted or
optimized.  Three instrument kinds, all thread-safe and all reducible to a
plain-data snapshot (dicts/lists/numbers only — msgpack- and JSON-safe, so
a snapshot travels over the PS wire as a ``STATS`` reply and into the JSONL
metrics stream unchanged):

* ``Counter``   — monotone float/int accumulator (commits, bytes, batches).
* ``Gauge``     — last-write-wins level (queue depth, prefetch occupancy).
* ``Histogram`` — fixed-bucket (cumulative-``le`` boundaries), mergeable
  across instances/snapshots: per-bucket counts + sum + count, with an
  interpolated quantile read-out for summaries.

A ``Registry`` is a name → instrument map with get-or-create semantics; the
process-wide ``default_registry()`` serves call sites with no better home
(networking byte counts, streaming prefetch), while servers/trainers own
private registries so their snapshots describe exactly one component.
"""

from __future__ import annotations

import bisect
import re
import threading
from typing import Dict, Mapping, Optional, Sequence, Union

Number = Union[int, float]

#: label keys are identifier-shaped; values concatenate into the flat
#: name, so anything that would start a new ``.``-segment is rejected
_LABEL_KEY = re.compile(r"^[a-z][a-z0-9_]*$")
_LABEL_VALUE = re.compile(r"^[A-Za-z0-9_:-]+$")


def flat_name(name: str, labels: Optional[Mapping[str, object]] = None
              ) -> str:
    """The back-compat flattening rule (ISSUE 20): a labeled instrument
    lives in the registry under ``name + ".<key><value>"`` per label in
    sorted key order — ``("ps.staleness", {"worker": 3})`` flattens to
    ``"ps.staleness.worker3"``, exactly the name the pre-label
    ``worker<k>`` families used, so OBS_BASELINE patterns, obsview
    renderers and the dklint metric-contract gate keep matching
    unchanged."""
    if not labels:
        return name
    parts = []
    for k in sorted(labels):
        if not isinstance(k, str) or not _LABEL_KEY.match(k):
            raise ValueError(
                f"metric {name!r}: bad label key {k!r} (want "
                f"[a-z][a-z0-9_]*)")
        v = str(labels[k])
        if not _LABEL_VALUE.match(v):
            raise ValueError(
                f"metric {name!r}: bad label value {v!r} for key {k!r} "
                f"(no whitespace/dots — it embeds in the flat name)")
        parts.append(f".{k}{v}")
    return name + "".join(parts)


def flatten_snapshot(snap: dict) -> dict:
    """Strip label metadata from a (possibly labeled) snapshot, leaving
    the plain flat-name form every pre-label consumer reads.  Entries
    are already keyed by flat name, so flattening never merges or drops
    a series — it only removes the ``name``/``labels`` keys."""
    return {k: {kk: vv for kk, vv in e.items()
                if kk not in ("name", "labels")}
            for k, e in snap.items()}

#: latency buckets (seconds): 100 µs .. 10 s, roughly log-spaced — spans
#: the sub-ms localhost PS round-trip and the multi-second compile
TIME_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)

#: small-integer buckets for staleness / queue depths
COUNT_BUCKETS = (0, 1, 2, 3, 4, 6, 8, 12, 16, 24, 32, 48, 64, 96, 128)


class Counter:
    """Monotonically-increasing accumulator."""

    __slots__ = ("name", "base_name", "labels", "_value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self.base_name = name
        self.labels: Optional[dict] = None
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, n: Number = 1) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name}: negative increment {n}")
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        return self._value

    def snapshot(self) -> dict:
        return {"type": "counter", "value": self._value}


class Gauge:
    """Last-write-wins level; ``inc``/``dec`` for up-down tracking."""

    __slots__ = ("name", "base_name", "labels", "_value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self.base_name = name
        self.labels: Optional[dict] = None
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, v: Number) -> None:
        with self._lock:
            self._value = float(v)

    def inc(self, n: Number = 1) -> None:
        with self._lock:
            self._value += n

    def dec(self, n: Number = 1) -> None:
        with self._lock:
            self._value -= n

    @property
    def value(self) -> float:
        return self._value

    def snapshot(self) -> dict:
        return {"type": "gauge", "value": self._value}


class Histogram:
    """Fixed-bucket histogram: ``buckets`` are ascending upper bounds
    (cumulative ``le`` semantics à la Prometheus; an implicit +Inf bucket
    catches the tail).  Mergeable: two histograms with identical bounds
    add elementwise — the property that lets per-worker staleness
    histograms roll up into one distribution."""

    __slots__ = ("name", "base_name", "labels", "bounds", "counts",
                 "_sum", "_count", "_lock")

    def __init__(self, name: str, buckets: Sequence[Number] = TIME_BUCKETS):
        if list(buckets) != sorted(buckets):
            raise ValueError(f"histogram {name}: buckets must be ascending")
        self.name = name
        self.base_name = name
        self.labels: Optional[dict] = None
        self.bounds = tuple(float(b) for b in buckets)
        self.counts = [0] * (len(self.bounds) + 1)  # +1: the +Inf bucket
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def observe(self, v: Number) -> None:
        i = bisect.bisect_left(self.bounds, v)
        with self._lock:
            self.counts[i] += 1
            self._sum += v
            self._count += 1

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    @property
    def mean(self) -> float:
        return self._sum / self._count if self._count else 0.0

    def merge(self, other: Union["Histogram", dict]) -> None:
        """Add ``other`` (a Histogram or a histogram snapshot) into this
        one; bucket bounds must match."""
        snap = other.snapshot() if isinstance(other, Histogram) else other
        if tuple(snap["bounds"]) != self.bounds:
            raise ValueError(
                f"histogram {self.name}: cannot merge bounds "
                f"{tuple(snap['bounds'])} into {self.bounds}")
        with self._lock:
            for i, c in enumerate(snap["counts"]):
                self.counts[i] += c
            self._sum += snap["sum"]
            self._count += snap["count"]

    def quantile(self, q: float) -> float:
        """Approximate quantile by linear interpolation within the bucket
        holding the q-th observation (the standard fixed-bucket estimate;
        exact enough for run summaries)."""
        return _snapshot_quantile(self.snapshot(), q)

    def snapshot(self) -> dict:
        with self._lock:
            return {"type": "histogram", "bounds": list(self.bounds),
                    "counts": list(self.counts), "sum": self._sum,
                    "count": self._count}


def _snapshot_quantile(snap: dict, q: float) -> float:
    if not 0.0 <= q <= 1.0:
        raise ValueError(f"quantile {q} outside [0, 1]")
    total = snap["count"]
    if total == 0:
        return 0.0
    bounds, counts = list(snap["bounds"]), snap["counts"]
    target = q * total
    seen = 0.0
    lo = 0.0 if not bounds or bounds[0] >= 0 else bounds[0]
    for i, c in enumerate(counts):
        if seen + c >= target and c:
            hi = bounds[i] if i < len(bounds) else bounds[-1]
            frac = (target - seen) / c
            return lo + (hi - lo) * frac
        seen += c
        if i < len(bounds):
            lo = bounds[i]
    return bounds[-1] if bounds else 0.0


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class Registry:
    """Name → instrument map with get-or-create semantics.

    ``snapshot()`` reduces every instrument to plain data;
    ``merge_snapshots`` folds such snapshots together (counters/histograms
    add, gauges take the later value) — the cross-process aggregation
    primitive for multi-worker roll-ups."""

    def __init__(self):
        self._instruments: Dict[str, object] = {}
        self._lock = threading.Lock()

    def _get(self, name: str, kind: type,
             labels: Optional[Mapping[str, object]] = None, **kw):
        flat = flat_name(name, labels)
        with self._lock:
            inst = self._instruments.get(flat)
            if inst is None:
                inst = self._instruments[flat] = kind(flat, **kw)
                if labels:
                    inst.base_name = name
                    inst.labels = {k: str(labels[k]) for k in sorted(labels)}
            elif not isinstance(inst, kind):
                raise TypeError(
                    f"instrument {flat!r} already registered as "
                    f"{type(inst).__name__}, requested {kind.__name__}")
            return inst

    def counter(self, name: str,
                labels: Optional[Mapping[str, object]] = None) -> Counter:
        return self._get(name, Counter, labels=labels)

    def gauge(self, name: str,
              labels: Optional[Mapping[str, object]] = None) -> Gauge:
        return self._get(name, Gauge, labels=labels)

    def histogram(self, name: str,
                  buckets: Sequence[Number] = TIME_BUCKETS, *,
                  labels: Optional[Mapping[str, object]] = None) -> Histogram:
        return self._get(name, Histogram, labels=labels, buckets=buckets)

    def get(self, name: str,
            labels: Optional[Mapping[str, object]] = None):
        return self._instruments.get(flat_name(name, labels))

    def names(self) -> list:
        with self._lock:
            return sorted(self._instruments)

    def snapshot(self, labeled: bool = False) -> dict:
        """{flat name: instrument snapshot} — plain data, wire/JSON-safe.

        ``labeled=True`` adds ``name``/``labels`` metadata keys to every
        entry whose instrument carries labels; keys stay the FLAT names
        either way, so flattening (``flatten_snapshot``) and merging
        commute — label-merge-then-flatten == flatten-then-merge."""
        with self._lock:
            insts = dict(self._instruments)
        out = {}
        for name, inst in sorted(insts.items()):
            e = inst.snapshot()
            if labeled and inst.labels:
                e["name"] = inst.base_name
                e["labels"] = dict(inst.labels)
            out[name] = e
        return out

    @staticmethod
    def merge_snapshots(*snaps: dict) -> dict:
        """Fold plain-data snapshots: counters and histograms add, gauges
        keep the last value seen (there is no meaningful sum of levels)."""
        out: dict = {}
        for snap in snaps:
            for name, s in snap.items():
                cur = out.get(name)
                if cur is None:
                    out[name] = {**s, "counts": list(s["counts"])} \
                        if s["type"] == "histogram" else dict(s)
                    continue
                if cur["type"] != s["type"]:
                    raise TypeError(f"instrument {name!r}: cannot merge "
                                    f"{s['type']} into {cur['type']}")
                if s["type"] == "counter":
                    cur["value"] += s["value"]
                elif s["type"] == "gauge":
                    cur["value"] = s["value"]
                else:
                    if list(cur["bounds"]) != list(s["bounds"]):
                        raise ValueError(
                            f"histogram {name!r}: bucket bounds differ")
                    cur["counts"] = [a + b for a, b in
                                     zip(cur["counts"], s["counts"])]
                    cur["sum"] += s["sum"]
                    cur["count"] += s["count"]
        return out


_DEFAULT = Registry()


def default_registry() -> Registry:
    """The process-wide registry — call sites with no component-scoped
    registry (networking byte counts, streaming prefetch) land here."""
    return _DEFAULT


def snapshot_quantile(snap: dict, q: float) -> float:
    """Quantile estimate straight from a histogram snapshot (obsview and
    other consumers that never held the live instrument)."""
    return _snapshot_quantile(snap, q)

"""Fleet time-series aggregation (ISSUE 20 rung 2).

Every obs layer before this one was pull-based: obsview polled N stats
RPCs, the drift gate compared snapshots after the run, the autoscaler
re-derived interval deltas from its own polls.  This module is the push
half of the telemetry plane:

* :class:`TimeSeriesStore` — the aggregator.  Sources (workers, shards,
  engines, a router's health poller) feed it ``snapshot_delta``
  increments (the PR 8 series semantics: counters/histograms subtract,
  gauges keep the later level); it keeps a bounded ring of timestamped
  increments per flat metric name plus a cumulative per-source total,
  so consumers read ONE live fleet series — windowed deltas for alert
  math, merged totals for panels — instead of running their own poll
  loops.
* :class:`TelemetryShipper` — the producer side: wraps a registry, and
  on each ``maybe_ship`` past ``period_s`` computes the delta since its
  previous snapshot and hands it to a ``send`` callable (a
  ``PSClient.ship_telemetry`` RPC, or a direct in-process
  ``store.ingest_delta`` for thread-placement fleets).

Timestamps are stamped by the RECEIVER's monotonic clock at ingest —
shipped frames carry no trusted time, so cross-process clock skew can
never tear a window.  Hostile input (non-finite values, negative
counts, malformed entries) is rejected per entry and counted in
``obs.telemetry.rejected`` — one poisoned worker must not NaN the
fleet series (the LinkQuality folding rule).
"""

from __future__ import annotations

import collections
import math
import threading
import time
from typing import Callable, Dict, Optional

from .drift import snapshot_delta
from .logging import get_logger
from .registry import Registry

#: ring-buffer points kept per metric: at the default 1 s ship cadence
#: this retains minutes of history — enough for any burn-rate window
#: pair while bounding a long-lived aggregator's memory
DEFAULT_MAX_POINTS = 720

#: distinct metric series accepted before new names are dropped (and
#: counted) — a hostile source can't balloon the aggregator
DEFAULT_MAX_SERIES = 8192


def _finite(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool) \
        and math.isfinite(v)


def _valid_entry(e) -> bool:
    """One shipped instrument entry, validated before folding."""
    if not isinstance(e, dict):
        return False
    t = e.get("type")
    if t in ("counter", "gauge"):
        return _finite(e.get("value"))
    if t == "histogram":
        bounds, counts = e.get("bounds"), e.get("counts")
        if not isinstance(bounds, (list, tuple)) or \
                not isinstance(counts, (list, tuple)) or \
                len(counts) != len(bounds) + 1:
            return False
        if list(bounds) != sorted(bounds) or \
                not all(_finite(b) for b in bounds):
            return False
        if not all(_finite(c) and c >= 0 for c in counts):
            return False
        return _finite(e.get("sum")) and _finite(e.get("count")) \
            and e["count"] >= 0
    return False


def _zero_delta(e: dict) -> bool:
    """True when an increment carries no information (skip the ring)."""
    if e["type"] == "counter":
        return e["value"] == 0
    if e["type"] == "histogram":
        return e["count"] == 0 and not any(e["counts"])
    return False  # a gauge level is always news


def _strip(e: dict) -> dict:
    """Drop label metadata (a labeled snapshot ships ``name``/``labels``
    keys) — the store series are keyed by flat name already."""
    return {k: v for k, v in e.items() if k not in ("name", "labels")}


class TimeSeriesStore:
    """Bounded per-metric ring buffers of shipped increments + merged
    cumulative totals per source.  Thread-safe; every method takes and
    returns plain data only, so replies ride the wire unchanged."""

    def __init__(self, registry: Optional[Registry] = None, *,
                 max_points: int = DEFAULT_MAX_POINTS,
                 max_series: int = DEFAULT_MAX_SERIES,
                 clock: Callable[[], float] = time.monotonic):
        self._lock = threading.Lock()
        self._clock = clock
        self.max_points = int(max_points)
        self.max_series = int(max_series)
        #: flat metric name -> deque[(ts, entry-delta dict)]
        self._rings: Dict[str, collections.deque] = {}
        #: source -> cumulative merged snapshot of everything it shipped
        self._totals: Dict[str, dict] = {}
        #: source -> last raw cumulative snapshot (ingest_total deltas)
        self._last_cum: Dict[str, dict] = {}
        self._last_seen: Dict[str, float] = {}
        reg = registry
        self._c_frames = reg.counter("obs.telemetry.frames") if reg else None
        self._c_rejected = reg.counter("obs.telemetry.rejected") \
            if reg else None
        self._g_series = reg.gauge("obs.telemetry.series") if reg else None
        self._g_sources = reg.gauge("obs.telemetry.sources") if reg else None

    # -- ingest -------------------------------------------------------------
    def ingest_delta(self, source: str, delta: dict,
                     ts: Optional[float] = None) -> int:
        """Fold one shipped increment frame; returns accepted entries.
        Invalid entries are rejected individually — the rest of the
        frame still lands."""
        now = self._clock() if ts is None else float(ts)
        if not isinstance(delta, dict):
            delta = {}
        accepted = rejected = 0
        with self._lock:
            src = str(source)
            self._last_seen[src] = now
            totals = self._totals.setdefault(src, {})
            for name, raw in sorted(delta.items()):
                if not isinstance(name, str) or not _valid_entry(raw):
                    rejected += 1
                    continue
                e = _strip(raw)
                self._fold_total(totals, name, e)
                if _zero_delta(e):
                    accepted += 1
                    continue
                ring = self._rings.get(name)
                if ring is None:
                    if len(self._rings) >= self.max_series:
                        rejected += 1
                        continue
                    ring = self._rings[name] = collections.deque(
                        maxlen=self.max_points)
                ring.append((now, e))
                accepted += 1
            n_series, n_sources = len(self._rings), len(self._last_seen)
        if self._c_frames is not None:
            self._c_frames.inc()
            if rejected:
                self._c_rejected.inc(rejected)
            self._g_series.set(n_series)
            self._g_sources.set(n_sources)
        return accepted

    def ingest_total(self, source: str, snap: dict,
                     ts: Optional[float] = None) -> int:
        """Fold one CUMULATIVE registry snapshot from a poll-fed source
        (the router's health poller, an in-process supervisor): the
        store derives the increment against the source's previous
        snapshot itself, with the ``snapshot_delta`` restart clamp."""
        if not isinstance(snap, dict):
            snap = {}
        with self._lock:
            prev = self._last_cum.get(str(source), {})
        delta = snapshot_delta(prev, snap)
        n = self.ingest_delta(source, delta, ts=ts)
        with self._lock:
            self._last_cum[str(source)] = snap
        return n

    @staticmethod
    def _fold_total(totals: dict, name: str, e: dict) -> None:
        cur = totals.get(name)
        if cur is None or cur["type"] != e["type"]:
            totals[name] = {**e, "counts": list(e["counts"])} \
                if e["type"] == "histogram" else dict(e)
            return
        if e["type"] == "counter":
            cur["value"] += e["value"]
        elif e["type"] == "gauge":
            cur["value"] = e["value"]
        elif list(cur["bounds"]) == list(e["bounds"]):
            cur["counts"] = [a + b for a, b in zip(cur["counts"],
                                                   e["counts"])]
            cur["sum"] += e["sum"]
            cur["count"] += e["count"]
        else:  # bucket schema changed mid-run: restart the series
            totals[name] = {**e, "counts": list(e["counts"])}

    # -- read ---------------------------------------------------------------
    def latest(self) -> dict:
        """One merged fleet cumulative snapshot across every source."""
        with self._lock:
            parts = [dict(t) for t in self._totals.values()]
        return Registry.merge_snapshots(*parts) if parts else {}

    def window_delta(self, name: str, window_s: float,
                     now: Optional[float] = None) -> Optional[dict]:
        """The merged increment for ``name`` over the trailing window:
        counters sum, histograms add elementwise, gauges keep the latest
        level.  ``None`` when the window holds no points."""
        now = self._clock() if now is None else float(now)
        cut = now - float(window_s)
        with self._lock:
            ring = self._rings.get(name)
            pts = [e for ts, e in ring if ts >= cut] if ring else []
        if not pts:
            return None
        acc: dict = {}
        for e in pts:
            self._fold_total(acc, name, e)
        return acc.get(name)

    def series(self, name: str, window_s: Optional[float] = None) -> list:
        """Raw ``(ts, scalar)`` points for rendering: counter increment,
        gauge level, or histogram count increment."""
        now = self._clock()
        cut = now - float(window_s) if window_s is not None \
            else -math.inf
        with self._lock:
            ring = self._rings.get(name)
            pts = [(ts, e) for ts, e in ring if ts >= cut] if ring else []
        return [(ts, e["count"] if e["type"] == "histogram"
                 else e["value"]) for ts, e in pts]

    def names(self) -> list:
        with self._lock:
            return sorted(self._rings)

    def sources(self) -> dict:
        """source -> seconds since it last shipped."""
        now = self._clock()
        with self._lock:
            return {s: now - ts for s, ts in sorted(self._last_seen.items())}

    def summary(self) -> dict:
        """Plain-data description for the ``alerts`` RPC / obsview."""
        with self._lock:
            n_series = len(self._rings)
            n_points = sum(len(r) for r in self._rings.values())
        return {"series": n_series, "points": n_points,
                "sources": self.sources()}


class TelemetryShipper:
    """Periodic ``snapshot_delta`` shipping from one registry to one
    ``send(payload)`` callable.  Send failures are swallowed and counted
    (``obs.telemetry.ship_errors``) — telemetry must never take down the
    training/serving loop it instruments; the increment that failed to
    ship is NOT lost, it rides the next frame (the delta base only
    advances on success)."""

    def __init__(self, registry: Registry, send: Callable[[dict], object],
                 *, source: str, period_s: float = 1.0,
                 clock: Callable[[], float] = time.monotonic):
        self.registry = registry
        self.send = send
        self.source = str(source)
        self.period_s = float(period_s)
        self._clock = clock
        self._last_snap: dict = {}
        self._last_ship: Optional[float] = None
        self._c_ships = registry.counter("obs.telemetry.ships")
        self._c_errors = registry.counter("obs.telemetry.ship_errors")

    def maybe_ship(self, now: Optional[float] = None) -> bool:
        """Ship if ``period_s`` has elapsed since the last attempt (the
        first call always ships); returns True when a frame went out."""
        now = self._clock() if now is None else float(now)
        if self._last_ship is not None and \
                now - self._last_ship < self.period_s:
            return False
        return self.ship(now)

    def ship(self, now: Optional[float] = None) -> bool:
        now = self._clock() if now is None else float(now)
        self._last_ship = now
        cur = self.registry.snapshot()
        delta = {k: v for k, v in snapshot_delta(self._last_snap,
                                                 cur).items()
                 if not _zero_delta(v)}
        if not delta:
            self._last_snap = cur
            return False
        try:
            self.send({"action": "telemetry", "source": self.source,
                       "delta": delta})
        except Exception as e:
            self._c_errors.inc()
            get_logger("obs.telemetry").warning(
                "telemetry ship from %s failed (increments ride the next "
                "frame): %s", self.source, e)
            return False
        self._last_snap = cur
        self._c_ships.inc()
        return True

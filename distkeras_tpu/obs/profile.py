"""Profiling layer over the registries/spans (ISSUE 6 tentpole).

PR 2 gave the stack metrics and spans, PR 5 a drift gate — but none of it
answers the three questions a perf regression actually raises: *did
something recompile*, *where did the HBM go*, and *is the step host-bound
or device-bound*.  Four instruments, all feeding the existing registries
so ``obs.drift`` gates them like any other metric:

* **Recompilation sentinel** (``RetraceSentinel``) — tracks the arg
  signature (pytree structure + per-leaf shape/dtype) of every jit entry
  point.  The first signature is the cold compile (``jit.compiles``);
  any NEW signature later is a retrace (``jit.retraces``), the silent
  throughput killer SURVEY.md §7 names — logged once per signature with
  the offending shape/dtype hash, and drift-gated by the committed
  ``OBS_BASELINE.json`` (any increase fails ``obsview --diff``).
* **Memory watermarks** (``memory_snapshot`` / ``observe_memory``) —
  live device-array bytes (``jax.live_arrays()``), array count, a
  max-tracked ``mem.peak_live_bytes`` gauge, and the backend allocator's
  ``peak_bytes_in_use`` where the platform reports it (TPU/GPU; CPU
  returns none).  Sampled at the existing heartbeat points: trainer
  epoch records and async-worker window heartbeats.
* **Step-time split** (``step_split``) — wraps a step/window function so
  every call observes host dispatch time (call → return, i.e. trace +
  enqueue) and device execution time (return → ``block_until_ready``)
  into separate ``step.host_seconds`` / ``step.device_seconds``
  histograms.  Opt-in via ``ProfileConfig.step_split``: the hard sync
  per call defeats the epoch pipelining the trainers use for honest
  headline timing, so it is a profiling mode, not a default.
* **Device trace seam** (``device_trace``) — the one sanctioned
  ``jax.profiler`` start/stop wrapper: announces the output dir once via
  ``obs.logging``, and never leaks an open trace session on exception
  paths (a failing ``stop_trace`` is logged, not allowed to mask the
  body's error).  ``utils.metrics.profile_trace`` delegates here, and
  ``ProfileConfig.trace_dir`` requests per-epoch captures from trainer
  config.

``ProfileConfig`` is the trainer-facing knob bundle
(``Trainer(..., profile=...)`` accepts a ``ProfileConfig``, a dict of
its fields, or a bare path string meaning ``trace_dir``).
"""

from __future__ import annotations

import contextlib
import dataclasses
import hashlib
import threading
from typing import Any, Callable, Optional, Sequence, Tuple, Union

from .logging import get_logger
from .registry import Registry, TIME_BUCKETS, default_registry

#: live-byte buckets for the optional watermark histogramming — gauges are
#: the primary surface (levels), these exist for callers that want a
#: distribution over a long run
_LOG = "obs.profile"


# ---------------------------------------------------------------------------
# recompilation sentinel
# ---------------------------------------------------------------------------

def tree_signature(args: Any) -> Tuple:
    """Hashable retrace signature of a call's arguments: the pytree
    structure plus each array leaf's ``(shape, dtype)``.  Non-array leaves
    contribute their type only (jit specializes on structure and
    shape/dtype, not on array values; hashing Python scalar VALUES would
    report a retrace for every new step count).  Matches what actually
    triggers an XLA re-trace for the static-shape programs this repo
    compiles."""
    import jax
    leaves, treedef = jax.tree_util.tree_flatten(args)
    sig = []
    for leaf in leaves:
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is not None and dtype is not None:
            sig.append((tuple(shape), str(dtype)))
        else:
            sig.append(type(leaf).__name__)
    return treedef, tuple(sig)


def signature_digest(sig: Tuple) -> str:
    """Short stable hash of a ``tree_signature`` — what the one-time
    retrace log (and the JSONL ``retrace`` record) names, so two runs can
    be compared by signature without dumping whole shape trees."""
    return hashlib.sha1(repr(sig).encode()).hexdigest()[:12]


class RetraceSentinel:
    """Counts cold compiles and retraces of ONE jit entry point.

    ``observe(args)`` returns ``"cold"`` (first signature ever),
    ``"warm"`` (seen before — the steady state) or ``"retrace"`` (a NEW
    signature after the first: XLA recompiles synchronously inside this
    call).  Counters land in ``registry`` — an ``obs.Registry``, a
    zero-arg callable returning one (resolved per event, so a registry
    attached after construction still receives the counts), or None for
    the process-wide default.  Retraces log once per signature (warning —
    they are the regression this sentinel exists to catch) and, with a
    ``sink``, emit a ``retrace`` record into the JSONL stream.

    ``observe_key`` (ISSUE 7) is the variant for entry points that manage
    their own compiled-program cache keyed by MORE than arg shapes (the
    decode runners in ``models.generation``: temperature, top-k, beam
    width ... all bake into the program): the caller's hashable cache key
    IS the signature, so value-level program changes the shape signature
    cannot see still count.  ``warn=False`` keeps the counters but
    silences the once-per-signature log — for entry points where many
    signatures are a legitimate workload (offline eval/bench sweeps),
    not a regression."""

    def __init__(self, name: str, registry=None, sink=None,
                 warn: bool = True):
        self.name = name
        self._registry = registry
        self.sink = sink
        self.warn = bool(warn)
        self._sigs: dict = {}   # signature -> digest
        self._lock = threading.Lock()

    def _reg(self) -> Registry:
        reg = self._registry() if callable(self._registry) else self._registry
        return reg if reg is not None else default_registry()

    @property
    def compiles(self) -> int:
        return len(self._sigs)

    def observe(self, args: Any) -> str:
        return self._observe_sig(tree_signature(args))

    def observe_key(self, key: Any) -> str:
        """Count a call by the caller's own hashable program-cache key
        (same cold/warm/retrace semantics as ``observe``) — for entry
        points whose compiled program depends on more than arg shapes."""
        return self._observe_sig(("key", key))

    def _observe_sig(self, sig: Any) -> str:
        with self._lock:
            if sig in self._sigs:
                return "warm"
            first = not self._sigs
            digest = signature_digest(sig)
            self._sigs[sig] = digest
            n_retrace = len(self._sigs) - 1
        reg = self._reg()
        reg.counter("jit.compiles").inc()
        if first:
            return "cold"
        reg.counter("jit.retraces").inc()
        # once per signature by construction: a signature enters _sigs
        # exactly once, and only that insertion reaches this path
        if self.warn:
            get_logger(_LOG).warning(
                "%s: retrace #%d — new arg signature %s (shapes/dtypes "
                "changed since the cold compile; steady-state steps should "
                "never re-trace)", self.name, n_retrace, digest)
        if self.sink is not None:
            self.sink.log("retrace", entry=self.name, signature=digest,
                          retraces=n_retrace)
        return "retrace"

    def wrap(self, fn: Callable) -> Callable:
        """``fn`` with every call observed (counting only — the cold/warm
        split callers like the trainers' ``jit_compile`` span need is
        theirs to build from ``observe``)."""
        def wrapped(*args):
            self.observe(args)
            return fn(*args)
        return wrapped


# ---------------------------------------------------------------------------
# memory watermarks
# ---------------------------------------------------------------------------

#: guards the read-modify-write on the max-tracked peak gauges (Gauge ops
#: are individually locked, but max() needs the pair to be atomic across
#: concurrently-heartbeating workers)
_PEAK_LOCK = threading.Lock()


def memory_snapshot() -> dict:
    """Point-in-time device-memory accounting: ``live_bytes`` /
    ``live_arrays`` from ``jax.live_arrays()`` (every live ``jax.Array``
    this process holds), plus ``device_peak_bytes`` — the backend
    allocator's ``peak_bytes_in_use`` summed over devices — where the
    platform reports it (TPU/GPU; CPU's ``memory_stats()`` is None)."""
    import jax
    live_bytes = 0
    count = 0
    for a in jax.live_arrays():
        try:
            live_bytes += int(a.nbytes)
            count += 1
        except RuntimeError:
            continue  # deleted/donated between enumeration and read
    snap = {"live_bytes": live_bytes, "live_arrays": count,
            "device_peak_bytes": None}
    peak = 0
    seen = False
    for d in jax.devices():
        try:
            stats = d.memory_stats()
        except (RuntimeError, NotImplementedError, AttributeError):
            stats = None
        if stats and stats.get("peak_bytes_in_use") is not None:
            peak += int(stats["peak_bytes_in_use"])
            seen = True
    if seen:
        snap["device_peak_bytes"] = peak
    return snap


def observe_memory(registry: Optional[Registry] = None) -> dict:
    """Sample ``memory_snapshot`` into watermark gauges:
    ``mem.live_bytes`` / ``mem.live_arrays`` (levels),
    ``mem.peak_live_bytes`` (max over every sample this registry saw —
    the HBM high-water mark the OOM postmortem wants), and
    ``mem.device_peak_bytes`` when the backend reports it.  Returns the
    snapshot so call sites (epoch records, worker heartbeats) can stamp
    the bytes into their JSONL record too."""
    snap = memory_snapshot()
    reg = registry if registry is not None else default_registry()
    reg.gauge("mem.live_bytes").set(snap["live_bytes"])
    reg.gauge("mem.live_arrays").set(snap["live_arrays"])
    with _PEAK_LOCK:
        peak = reg.gauge("mem.peak_live_bytes")
        if snap["live_bytes"] > peak.value:
            peak.set(snap["live_bytes"])
    if snap["device_peak_bytes"] is not None:
        reg.gauge("mem.device_peak_bytes").set(snap["device_peak_bytes"])
    return snap


# ---------------------------------------------------------------------------
# step-time split
# ---------------------------------------------------------------------------

def step_split(fn: Callable, registry=None, prefix: str = "step") -> Callable:
    """Wrap a step/window function with the host/device time split: the
    call itself is host work (trace + dispatch — jit returns at enqueue
    time), the ``block_until_ready`` that follows is device execution.
    Observations land in ``<prefix>.host_seconds`` /
    ``<prefix>.device_seconds`` histograms in ``registry`` (instance,
    zero-arg callable, or None for the default registry).

    The hard sync per call is exactly what the trainers' epoch pipelining
    exists to avoid — this is a profiling mode (``ProfileConfig.
    step_split``), not a default."""
    import time

    import jax

    def wrapped(*args):
        reg = registry() if callable(registry) else registry
        reg = reg if reg is not None else default_registry()
        t0 = time.perf_counter()
        out = fn(*args)
        t1 = time.perf_counter()
        jax.block_until_ready(out)
        t2 = time.perf_counter()
        reg.histogram(f"{prefix}.host_seconds", TIME_BUCKETS).observe(t1 - t0)
        reg.histogram(f"{prefix}.device_seconds",
                      TIME_BUCKETS).observe(t2 - t1)
        return out
    return wrapped


# ---------------------------------------------------------------------------
# device trace seam (jax.profiler)
# ---------------------------------------------------------------------------

#: dirs already announced — the capture log is once per destination, not
#: once per epoch
_ANNOUNCED: set = set()
_ANNOUNCE_LOCK = threading.Lock()


@contextlib.contextmanager
def device_trace(log_dir: str):
    """Capture a ``jax.profiler`` trace of the wrapped region (open the
    result in TensorBoard or Perfetto).  The one sanctioned start/stop
    pair: announces the output dir once via ``obs.logging``, and on an
    exception inside the region the trace session is still closed — a
    ``stop_trace`` failure there is logged instead of masking the body's
    error (the old ``utils.metrics.profile_trace`` leaked the open
    session exactly that way)."""
    import jax
    log = get_logger(_LOG)
    with _ANNOUNCE_LOCK:
        if log_dir not in _ANNOUNCED:
            _ANNOUNCED.add(log_dir)
            log.info("device trace capture -> %s (open with TensorBoard or "
                     "ui.perfetto.dev)", log_dir)
    jax.profiler.start_trace(log_dir)
    try:
        yield log_dir
    except BaseException:
        try:
            jax.profiler.stop_trace()
        except RuntimeError as e:
            # the body's exception is the story; a stop failure on the
            # unwind path must not replace it (but must not hide either)
            log.warning("device trace %s: stop_trace failed during "
                        "exception unwind: %s", log_dir, e)
        raise
    else:
        jax.profiler.stop_trace()


# ---------------------------------------------------------------------------
# trainer-facing config
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class ProfileConfig:
    """Profiling knobs a trainer accepts as ``profile=``.

    * ``trace_dir`` — request per-epoch ``jax.profiler`` captures into
      ``<trace_dir>/epoch<k>`` for every epoch in ``trace_epochs``
      (None = no device capture).
    * ``trace_epochs`` — which epochs to capture (default: epoch 0, the
      compile-heavy one); None means every epoch.
    * ``step_split`` — wrap the step/window programs in the
      ``block_until_ready`` host/device split (defeats epoch pipelining;
      profiling runs only).
    * ``memory`` — sample memory watermarks at the existing heartbeat
      points (per-epoch records, per-window worker heartbeats)."""

    trace_dir: Optional[str] = None
    trace_epochs: Optional[Sequence[int]] = (0,)
    step_split: bool = False
    memory: bool = True

    def trace_epoch(self, epoch: int) -> bool:
        """Should ``epoch`` run under a device capture?"""
        if not self.trace_dir:
            return False
        return self.trace_epochs is None or epoch in tuple(self.trace_epochs)

    @staticmethod
    def resolve(spec: Union[None, str, dict, "ProfileConfig"]
                ) -> "ProfileConfig":
        """``None`` (defaults) | a path string (= ``trace_dir``) | a dict
        of fields | a ready ProfileConfig."""
        if spec is None:
            return ProfileConfig()
        if isinstance(spec, ProfileConfig):
            return spec
        if isinstance(spec, str):
            return ProfileConfig(trace_dir=spec)
        if isinstance(spec, dict):
            return ProfileConfig(**spec)
        raise TypeError(f"profile= expects None, a trace dir path, a dict "
                        f"of ProfileConfig fields, or a ProfileConfig "
                        f"(got {type(spec).__name__})")

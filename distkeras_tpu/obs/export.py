"""Chrome Trace Event Format export of the telemetry JSONL (ISSUE 6).

``scripts/obsview.py`` renders a run as ASCII tables; this module turns
the same record stream into a Chrome/Perfetto trace
(``obsview RUN.jsonl --export-trace out.json``) so a multi-worker async
run opens as ONE linked timeline at ``ui.perfetto.dev`` instead of a
table — the PR 5 cross-process span identity (``trace_id`` / ``span_id``
/ ``parent_span``, workers pinned to ``w<k>``) becomes visual structure:

* one **process row per trace id** — each async worker is a pid named
  ``worker <k>``, the trainer's lazily-minted trace is ``process
  <trace_id>``;
* two **thread rows per worker process** — the worker's own spans
  (``ps.commit`` / ``ps.pull`` / windows) on tid 0, the SERVER spans that
  adopted its trace over the wire (``ps.apply`` / ``ps.serve_pull``) on
  tid 1, so a server apply nests visually under the worker commit that
  caused it;
* **flow arrows** (``ph: s``/``f``) for every cross-thread parent link —
  the wire-carried ``parent_span`` drawn as an arrow from the worker
  commit span to the server apply span;
* heartbeats as instant events, per-epoch records as duration events on
  a ``run`` process, and ``live_bytes`` memory samples as Chrome counter
  tracks.

Everything is a pure function over plain record dicts (same contract as
``obsview.summarize``) so tests re-parse the export and assert the
linkage survived the round-trip.
"""

from __future__ import annotations

import json
import math
import re
from typing import Dict, List, Optional, Tuple

#: span names emitted by the SERVER side of the PS wire while adopting a
#: remote trace — rendered as a separate thread row inside the adopting
#: worker's process so parent/child shows as nesting, not interleaving
SERVER_SPAN_NAMES = ("ps.apply", "ps.serve_pull")

#: MetricsLogger's json_safe writes non-finite floats as these strings
_NONFINITE = {"NaN": math.nan, "Infinity": math.inf, "-Infinity": -math.inf}

_WORKER_TRACE = re.compile(r"^w(\d+)$")


def _num(v, default: float = math.nan) -> float:
    if isinstance(v, str):
        v = _NONFINITE.get(v, v)
    try:
        return float(v)
    except (TypeError, ValueError):
        return default


def _finite(v) -> Optional[float]:
    f = _num(v)
    return f if math.isfinite(f) else None


def _trace_sort_key(trace_id: str) -> Tuple:
    """Workers first, numerically (``w10`` after ``w2``), then everything
    else lexicographically — stable pids across exports of the same run."""
    m = _WORKER_TRACE.match(trace_id)
    if m:
        return (0, int(m.group(1)), trace_id)
    return (1, 0, trace_id)


def _process_name(trace_id: str) -> str:
    m = _WORKER_TRACE.match(trace_id)
    if m:
        return f"worker {m.group(1)}"
    return f"process {trace_id}"


def records_to_chrome_trace(records: List[dict]) -> dict:
    """Telemetry records -> a Chrome Trace Event Format document
    (``{"traceEvents": [...], "displayTimeUnit": "ms"}``).

    Timestamps: span records are stamped at CLOSE (``ts`` is the emit
    wall clock, ``seconds`` the duration), so each event starts at
    ``ts - seconds``; the whole trace is rebased to the earliest start so
    Perfetto opens at t=0 regardless of wall-clock epoch."""
    spans, heartbeats, epochs = [], [], []
    for r in records:
        ev = r.get("event")
        if ev == "span" and _finite(r.get("ts")) is not None \
                and _finite(r.get("seconds")) is not None:
            spans.append(r)
        elif ev == "heartbeat" and _finite(r.get("ts")) is not None:
            heartbeats.append(r)
        elif ev == "epoch" and _finite(r.get("ts")) is not None:
            epochs.append(r)

    #: the run pid hosts trace-less records (per-epoch rows)
    RUN_PID = 0
    trace_ids = sorted({str(s.get("trace_id", "?")) for s in spans}
                      | {f"w{h['worker_id']}" for h in heartbeats
                         if h.get("worker_id") is not None},
                      key=_trace_sort_key)
    pid_of = {t: i + 1 for i, t in enumerate(trace_ids)}

    def span_tid(s: dict) -> int:
        return 1 if s.get("name") in SERVER_SPAN_NAMES else 0

    # rebase: earliest event start anywhere in the stream
    starts = [_num(s["ts"]) - _num(s["seconds"]) for s in spans]
    starts += [_num(h["ts"]) for h in heartbeats]
    starts += [_num(e["ts"]) - _num(e.get("epoch_seconds"), 0.0)
               for e in epochs]
    t0 = min((t for t in starts if math.isfinite(t)), default=0.0)

    def us(wall: float) -> float:
        return max(0.0, (wall - t0) * 1e6)

    events: List[dict] = []
    if epochs or not trace_ids:
        events.append({"ph": "M", "name": "process_name", "pid": RUN_PID,
                       "args": {"name": "run"}})
        events.append({"ph": "M", "name": "process_sort_index",
                       "pid": RUN_PID, "args": {"sort_index": -1}})
    for t, pid in pid_of.items():
        events.append({"ph": "M", "name": "process_name", "pid": pid,
                       "args": {"name": _process_name(t)}})
        events.append({"ph": "M", "name": "process_sort_index", "pid": pid,
                       "args": {"sort_index": pid}})
        events.append({"ph": "M", "name": "thread_name", "pid": pid,
                       "tid": 0, "args": {"name": "worker"
                                          if _WORKER_TRACE.match(t)
                                          else "main"}})
        if any(str(s.get("trace_id")) == t and span_tid(s) == 1
               for s in spans):
            events.append({"ph": "M", "name": "thread_name", "pid": pid,
                           "tid": 1, "args": {"name": "ps server"}})

    # where each span landed, for flow-arrow endpoints
    placed: Dict[str, Tuple[int, int, float]] = {}
    for s in spans:
        pid = pid_of.get(str(s.get("trace_id", "?")), RUN_PID)
        tid = span_tid(s)
        dur_s = _num(s["seconds"])
        start = us(_num(s["ts"]) - dur_s)
        args = {"span_id": s.get("span_id"), "trace_id": s.get("trace_id"),
                "path": s.get("path"), "depth": s.get("depth")}
        if s.get("parent_span") is not None:
            args["parent_span"] = s["parent_span"]
        if s.get("worker") is not None:
            args["worker"] = s["worker"]
        if s.get("error"):
            args["error"] = True
        events.append({"name": s.get("name", "?"), "cat": "span",
                       "ph": "X", "pid": pid, "tid": tid, "ts": start,
                       "dur": max(0.0, dur_s * 1e6), "args": args})
        if s.get("span_id") is not None:
            placed[str(s["span_id"])] = (pid, tid, start)

    # flow arrows for parent links that CROSS a thread/process row —
    # same-thread nesting already reads as containment
    flow_id = 0
    for s in spans:
        parent = s.get("parent_span")
        if parent is None or str(parent) not in placed:
            continue
        child_pid = pid_of.get(str(s.get("trace_id", "?")), RUN_PID)
        child_tid = span_tid(s)
        p_pid, p_tid, p_start = placed[str(parent)]
        if (p_pid, p_tid) == (child_pid, child_tid):
            continue
        flow_id += 1
        child_start = us(_num(s["ts"]) - _num(s["seconds"]))
        events.append({"name": "trace", "cat": "flow", "ph": "s",
                       "id": flow_id, "pid": p_pid, "tid": p_tid,
                       "ts": p_start,
                       "args": {"span_id": str(parent)}})
        events.append({"name": "trace", "cat": "flow", "ph": "f",
                       "bp": "e", "id": flow_id, "pid": child_pid,
                       "tid": child_tid, "ts": child_start,
                       "args": {"span_id": s.get("span_id")}})

    for h in heartbeats:
        w = h.get("worker_id", h.get("worker"))
        pid = pid_of.get(f"w{w}", RUN_PID)
        args = {k: h[k] for k in ("window", "epoch", "gap_s", "mean_loss")
                if h.get(k) is not None}
        events.append({"name": "heartbeat", "cat": "heartbeat", "ph": "i",
                       "s": "t", "pid": pid, "tid": 0,
                       "ts": us(_num(h["ts"])), "args": args})
        live = _finite(h.get("live_bytes"))
        if live is not None:
            events.append({"name": "live_bytes", "cat": "memory",
                           "ph": "C", "pid": pid, "tid": 0,
                           "ts": us(_num(h["ts"])),
                           "args": {"bytes": live}})

    for e in epochs:
        dur_s = max(0.0, _num(e.get("epoch_seconds"), 0.0))
        events.append({"name": f"epoch {e.get('epoch', '?')}",
                       "cat": "epoch", "ph": "X", "pid": RUN_PID, "tid": 0,
                       "ts": us(_num(e["ts"]) - dur_s),
                       "dur": dur_s * 1e6,
                       "args": {k: e[k] for k in
                                ("trainer", "epoch", "mean_loss",
                                 "samples_per_sec") if e.get(k) is not None}})
        live = _finite(e.get("live_bytes"))
        if live is not None:
            events.append({"name": "live_bytes", "cat": "memory", "ph": "C",
                           "pid": RUN_PID, "tid": 0,
                           "ts": us(_num(e["ts"])),
                           "args": {"bytes": live}})

    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": {"format": "distkeras_tpu obs export",
                          "traces": {t: pid_of[t] for t in trace_ids}}}


def write_chrome_trace(records: List[dict], path: str) -> dict:
    """Export ``records`` to ``path`` as Chrome trace JSON; returns the
    document (callers report event counts without re-reading)."""
    doc = records_to_chrome_trace(records)
    with open(path, "w") as f:
        json.dump(doc, f)
    return doc

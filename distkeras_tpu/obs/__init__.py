"""Telemetry subsystem — counters/gauges/histograms, spans, exposition.

The observability layer the reference out-sourced to Spark's web UI
(SURVEY/PAPER §5) and this reproduction lacked entirely: process-local
instruments with mergeable plain-data snapshots (``registry``), nested
timed scopes sharing the JSONL metrics stream (``spans``), Prometheus text
rendering (``exposition``) and the library logging/console seam
(``logging``).  Threaded through the hot layers: the parameter-server
stack exposes a live ``STATS`` RPC returning a registry snapshot, the
networking layer counts bytes/round-trips, streaming counts
batches/stalls, trainers split compile time from steady-state and async
workers heartbeat — all readable by ``scripts/obsview.py``.

On top of the raw telemetry sits the regression-tracking layer (ISSUE 5):
``drift`` diffs persisted registry snapshots across runs (counter ratio
deltas, bucket-wise PSI + quantile shift, thresholds from the committed
``OBS_BASELINE.json``) and ``stragglers`` turns per-window worker
heartbeat gaps into a live ``ps.stragglers`` gauge.

The telemetry plane (ISSUE 20): instruments take an optional
``labels={...}`` dimension that flattens into the legacy dotted names
(``registry.flat_name``), ``timeseries`` aggregates push-shipped
``snapshot_delta`` increments into one bounded live fleet series, and
``alerts`` evaluates threshold + SLO burn-rate rules over it with
hysteresis — the live half of the drift gate's contract.

The profiling layer (ISSUE 6): ``profile`` adds the recompilation
sentinel (``jit.compiles``/``jit.retraces``, drift-gated), memory
watermarks (``mem.*`` gauges sampled at the heartbeat points), the
opt-in ``block_until_ready`` host/device step-time split, and the one
sanctioned ``jax.profiler`` capture seam; ``export`` renders the
span/heartbeat JSONL as a Chrome/Perfetto trace
(``obsview --export-trace``) with the PR 5 cross-process links drawn as
flow arrows.
"""

from .registry import (  # noqa: F401
    COUNT_BUCKETS,
    TIME_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    Registry,
    default_registry,
    flat_name,
    flatten_snapshot,
    snapshot_quantile,
)
from .spans import SpanTracer, default_tracer, set_default_sink, span  # noqa: F401
from .exposition import to_prometheus_text  # noqa: F401
from .logging import emit, enable_stderr_logging, get_logger  # noqa: F401
from .stragglers import (  # noqa: F401
    LinkQuality,
    StragglerDetector,
    detect_from_heartbeats,
)
from .profile import (  # noqa: F401
    ProfileConfig,
    RetraceSentinel,
    device_trace,
    memory_snapshot,
    observe_memory,
    step_split,
    tree_signature,
)
from .export import records_to_chrome_trace, write_chrome_trace  # noqa: F401
from .drift import (  # noqa: F401
    BASELINE_SCHEMA,
    DEFAULT_THRESHOLDS,
    WINDOW_KINDS,
    DriftReport,
    WindowVerdict,
    classify_window,
    diff_docs,
    diff_files,
    find_baseline,
    load_baseline,
    snapshot_delta,
)
from .timeseries import TelemetryShipper, TimeSeriesStore  # noqa: F401
from .alerts import (  # noqa: F401
    KNOWN_LABEL_KEYS,
    AlertEngine,
    AlertRule,
    parse_rules,
)

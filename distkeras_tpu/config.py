"""Config layer — dataclass + YAML/CLI (SURVEY.md §5.6 "TPU equivalent").

The reference configures everything through trainer constructor kwargs
(``distkeras/trainers.py`` — no config files, no flags); that stays our
API.  This module is the one layer on top the survey prescribes for the
benchmark harness: a ``RunConfig`` dataclass, YAML loading, and a CLI so a
single checked-in file reproduces a whole benchmark table
(``configs/bench_all.yaml`` ↔ ``scripts/bench_all.py``) or packages the
same run as a deployable ``Job``.

YAML shape (one mapping per run; a top-level ``configs:`` list holds
several)::

    name: ADAG ConvNet/CIFAR-10
    trainer: ADAG                    # class in distkeras_tpu.trainers
    model: convnet_cifar10           # factory in distkeras_tpu.models.zoo
    model_kwargs: {num_classes: 10}
    dataset: load_cifar10            # loader in distkeras_tpu.data.datasets
    dataset_kwargs: {n_train: 8192}
    onehot: 10                       # one-hot "label" -> "label_onehot"
    test_take: 1024                  # null -> skip accuracy eval
    trainer_kwargs: {num_workers: 8, batch_size: 64, num_epoch: 5}
    quick: {dataset_kwargs: {n_train: 2048}, trainer_kwargs: {num_epoch: 2}}

``python -m distkeras_tpu.config FILE [--quick] [--job OUT.job]``.
"""

from __future__ import annotations

import argparse
import dataclasses
import sys
import time
from typing import Any, Optional

import numpy as np

from .obs import emit

_DEFAULT_TRAINER_KW = dict(loss="categorical_crossentropy",
                           features_col="features",
                           label_col="label_onehot")


@dataclasses.dataclass
class RunConfig:
    """One benchmark/training run, fully reproducible from data."""

    name: str
    trainer: str = "SingleTrainer"
    model: str = "mlp_mnist"
    model_kwargs: dict = dataclasses.field(default_factory=dict)
    dataset: str = "load_mnist"
    dataset_kwargs: dict = dataclasses.field(default_factory=dict)
    onehot: Optional[int] = 10
    test_take: Optional[int] = 1024
    #: spill the train split to disk shards and stream it
    #: (``data.streaming.ShardedFileDataset``) instead of training from
    #: RAM — the BASELINE config-5 "ImageNet-scale input" story.  An int
    #: is rows per shard; ``true`` uses the default shard size.
    streaming: Any = None
    trainer_kwargs: dict = dataclasses.field(default_factory=dict)
    quick: dict = dataclasses.field(default_factory=dict)

    @classmethod
    def from_dict(cls, d: dict) -> "RunConfig":
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - known
        if unknown:
            raise ValueError(f"unknown RunConfig keys {sorted(unknown)} "
                             f"(known: {sorted(known)})")
        return cls(**d)

    def with_quick(self) -> "RunConfig":
        """Apply the config's ``quick`` overrides (smaller data / fewer
        epochs for smoke runs); dict fields merge, scalars replace."""
        if not self.quick:
            return self
        d = dataclasses.asdict(self)
        q = d.pop("quick")
        for k, v in q.items():
            if isinstance(v, dict) and isinstance(d.get(k), dict):
                d[k] = {**d[k], **v}
            else:
                d[k] = v
        return RunConfig(**d, quick={})


def load_file(path: str) -> list:
    """YAML file -> list of RunConfig (single mapping or ``configs:`` list)."""
    import yaml
    with open(path) as f:
        doc = yaml.safe_load(f)
    entries = doc["configs"] if isinstance(doc, dict) and "configs" in doc \
        else [doc]
    return [RunConfig.from_dict(e) for e in entries]


def build(cfg: RunConfig):
    """RunConfig -> (trainer, train_dataset, test_dataset_or_None)."""
    import distkeras_tpu as dk
    from .data.transformers import OneHotTransformer

    model = getattr(dk.zoo, cfg.model)(**cfg.model_kwargs)
    train, test, _meta = getattr(dk.datasets, cfg.dataset)(
        **cfg.dataset_kwargs)
    if cfg.onehot:
        enc = OneHotTransformer(int(cfg.onehot), "label", "label_onehot")
        train = enc.transform(train)
        test = enc.transform(test)
    test = test.take(int(cfg.test_take)) if cfg.test_take else None

    kw = {**_DEFAULT_TRAINER_KW, **cfg.trainer_kwargs}
    if kw.get("num_workers") == "auto":
        # as many workers as the machine has devices, capped at 8 (the
        # reference examples' worker count) — lets one YAML run on a
        # single chip and on an 8-device mesh alike
        import jax
        kw["num_workers"] = min(8, len(jax.devices()))

    trainer_cls = getattr(dk, cfg.trainer)
    if cfg.streaming:
        import atexit
        import shutil
        import tempfile
        from .data.streaming import ShardedFileDataset
        from .trainers import (DistributedTrainer, SingleTrainer,
                               SpmdTrainer)
        if not issubclass(trainer_cls, (SingleTrainer, DistributedTrainer,
                                        SpmdTrainer)):
            # fail at build time with a clear message, not mid-train
            raise ValueError(
                f"streaming: trainer {cfg.trainer!r} has no "
                f"ShardedFileDataset path (supported: SingleTrainer, "
                f"SpmdTrainer and the distributed trainer family)")
        if isinstance(cfg.streaming, int) and \
                not isinstance(cfg.streaming, bool):
            rows = cfg.streaming
        else:
            # default shard size, capped so a distributed trainer gets at
            # least one shard per worker (partition == worker);
            # EnsembleTrainer sizes its workers from num_ensembles
            nw = int(kw.get("num_workers") or kw.get("num_ensembles") or 1)
            rows = min(4096, max(1, train.num_rows // max(1, nw)))
        spill_dir = tempfile.mkdtemp(prefix="dk_stream_")
        # the spill is run-scoped scratch, not a dataset the user keeps:
        # run() removes it eagerly; atexit covers direct build() callers
        atexit.register(shutil.rmtree, spill_dir, ignore_errors=True)
        train = ShardedFileDataset.write(train, spill_dir,
                                         rows_per_shard=rows)

    return trainer_cls(model, **kw), train, test


def run(cfg: RunConfig, repeat: int = 1) -> dict:
    """Build + train + evaluate; returns the measured row as a dict.

    ``repeat`` > 1 re-runs ``trainer.train()`` that many times on the
    SAME trainer (compiled programs cached on it survive across calls)
    and reports the MEDIAN samples/sec with the min–max spread — the
    single-clean-run methodology could not tell a real regression from
    host noise (VERDICT r4 weak #3: ±20–30% swings recorded as shrugs).
    """
    import distkeras_tpu as dk

    trainer, train, test = build(cfg)
    rates, walls = [], []
    model = None
    try:
        for _ in range(max(1, int(repeat))):
            n0 = len(trainer.metrics.records)
            h0 = len(trainer.get_history())
            t0 = time.time()
            model = trainer.train(train)
            wall = time.time() - t0
            walls.append(wall)
            recs = list(trainer.metrics.records)[n0:]  # deque: no slicing
            epochs = [r for r in recs if r["event"] == "epoch"]
            if len(epochs) > 1:
                # last epoch of the call: post-compile by construction
                rates.append((epochs[-1]["samples_per_sec"], "last epoch"))
            else:
                # THIS call's history only: the trainer accumulates
                # history across train() calls, and cumulative samples
                # over per-call wall would inflate every warm repeat
                samples = sum(np.size(h)
                              for h in trainer.get_history()[h0:]) \
                    * trainer.batch_size
                rates.append((samples / wall, "incl. compile"))
    finally:
        if cfg.streaming:  # the spill is scratch; free the disk now
            import shutil
            shutil.rmtree(train.directory, ignore_errors=True)
    if isinstance(model, list):  # EnsembleTrainer
        model = model[0]
    # repeats after the first are fully warm: median over those when
    # available, else the single measurement.  Spread is over the WARM
    # runs only (the cold call's compile time is not "spread"), and only
    # reported when there are >= 2 of them — with repeat=2 there is ONE
    # warm run: label it as such instead of a misleading "median of 1"
    # and leave the spread empty (ISSUE 4 satellite).
    vals = [r for r, _ in (rates[1:] if len(rates) > 1 else rates)]
    if len(rates) == 1:
        note = rates[-1][1]
    elif len(vals) == 1:
        note = "single warm run, cold excluded"
    else:
        note = f"median of {len(vals)} warm runs"
    spread = (float(np.min(vals)), float(np.max(vals))) \
        if len(vals) > 1 else None
    acc = None
    if test is not None:
        pred = dk.ModelPredictor(model, "features").predict(test)
        acc = dk.AccuracyEvaluator("prediction", "label").evaluate(pred)
    return {"name": cfg.name,
            "samples_per_sec": float(np.median(vals)),
            "spread": spread,  # (min, max) over warm runs; None if < 2
            "rates": [float(r) for r, _ in rates],  # per-call, run order
            "note": note, "accuracy": acc,
            "wall_seconds": float(np.sum(walls))}


def to_job(cfg: RunConfig, punchcard=None):
    """RunConfig -> deployable ``job_deployment.Job`` (same spec)."""
    from .job_deployment import Job
    import distkeras_tpu as dk

    model = getattr(dk.zoo, cfg.model)(**cfg.model_kwargs)
    kw = {**_DEFAULT_TRAINER_KW, **cfg.trainer_kwargs}
    return Job(cfg.name.replace(" ", "-").replace("/", "-"), model,
               trainer_spec={"class": cfg.trainer, "kwargs": kw},
               dataset_spec={"loader": cfg.dataset,
                             "kwargs": cfg.dataset_kwargs},
               punchcard=punchcard)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="run every config in a YAML file, print a table")
    ap.add_argument("file")
    ap.add_argument("--quick", action="store_true",
                    help="apply each config's quick: overrides")
    ap.add_argument("--repeat", type=int, default=1, metavar="N",
                    help="train() calls per config; N>1 reports the "
                         "median of the warm (post-compile) runs with "
                         "min-max spread")
    ap.add_argument("--job", metavar="OUT",
                    help="package the (single) config as a Job file "
                         "instead of running it")
    args = ap.parse_args(argv)

    cfgs = load_file(args.file)
    if args.quick:
        cfgs = [c.with_quick() for c in cfgs]
    if args.job:
        if len(cfgs) != 1:
            emit("--job needs a file with exactly one config", err=True)
            return 2
        with open(args.job, "wb") as f:
            f.write(to_job(cfgs[0]).package())
        emit(f"wrote job package {args.job}")
        return 0

    emit("| config | samples/sec/chip | spread | accuracy | wall |")
    emit("|---|---|---|---|---|")
    for cfg in cfgs:
        row = run(cfg, repeat=args.repeat)
        acc = f"{row['accuracy']:.3f}" if row["accuracy"] is not None else "—"
        if row["spread"] is None:  # < 2 warm runs: no meaningful spread
            spread = "—"
        else:
            lo, hi = row["spread"]
            spread = f"{lo:,.0f}–{hi:,.0f}"
        emit(f"| {row['name']} | {row['samples_per_sec']:,.0f} "
             f"({row['note']}) | {spread} | {acc} "
             f"| {row['wall_seconds']:.1f}s |")
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Parallelism: device meshes, SPMD sync engine, collective helpers.

This package is the TPU-native replacement for the reference's entire
distribution substrate (Spark executors + socket parameter server; reference
``distkeras/parameter_servers.py``, ``distkeras/networking.py``).  The sync
path formulates every dist-keras algorithm as an SPMD program over a
``jax.sharding.Mesh``: local shard training inside ``shard_map`` +
XLA collectives (``psum``/``pmean``) at communication-window edges — the
pull/commit round-trip of the reference collapses into one fused allreduce
riding ICI.
"""

from .mesh import make_mesh, shard_map  # noqa: F401
from .sync import (  # noqa: F401
    SyncEngine,
    AdagSync,
    DownpourSync,
    DynSgdSync,
    EasgdSync,
    NoCommSync,
)

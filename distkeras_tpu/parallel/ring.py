"""Ring attention — sequence/context parallelism over the ``sp`` mesh axis.

Absent from the reference (SURVEY.md §5.7) but first-class here: sequences
too long for one chip are sharded over the mesh's sequence axis; each
device keeps its Q shard resident and the K/V shards rotate around the
ring via ``lax.ppermute`` (one neighbor hop per step — bandwidth rides
ICI, never a host).  Softmax is computed *online* (running max/denominator
in f32, the flash-attention recurrence), so the full attention matrix is
never materialized: memory is O(T_local²) per step instead of O(T²).

Ref: Liu, Zaharia, Abbeel — "Ring Attention with Blockwise Transformers
for Near-Infinite Context" (2023); math identical to our single-device
``ops.attention.dot_product_attention`` (tested equal).
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from .mesh import shard_map
from .sync import _shard_map_kw

_NEG = -1e30  # finite -inf stand-in: keeps the online-softmax exp() NaN-free


def ring_attention(q, k, v, axis_name: str, *, causal: bool = False,
                   impl: str = "blockwise"):
    """Blockwise ring attention; call INSIDE ``shard_map``.

    q/k/v: per-device sequence shards (B, T_loc, H, Dh), sharded on T over
    ``axis_name``.  Returns the attention output shard (B, T_loc, H, Dh).

    ``impl="flash"`` runs the fused Pallas kernel per ring hop and merges
    hops via the exposed logsumexp (``ops.pallas_attention.
    flash_attention_lse``): per-hop memory drops from O(T_loc²) score
    blocks to O(T_loc·D), so the per-chip shard length is HBM-bound like
    single-chip flash — the sp × flash composition for genuinely long
    context.  ``"blockwise"`` keeps the einsum formulation (exact,
    runs anywhere)."""
    if impl == "flash":
        return _ring_attention_flash(q, k, v, axis_name, causal=causal)
    if impl != "blockwise":
        raise ValueError(f"impl must be blockwise|flash, got {impl!r}")
    p_size = lax.axis_size(axis_name)
    my_idx = lax.axis_index(axis_name)
    b, t_loc, h, dh = q.shape
    scale = 1.0 / math.sqrt(dh)

    # f32 accumulators (numerics survive bf16 inputs)
    o = jnp.zeros((b, t_loc, h, dh), jnp.float32)
    l = jnp.zeros((b, h, t_loc), jnp.float32)
    m = jnp.full((b, h, t_loc), _NEG, jnp.float32)

    perm = [(j, (j + 1) % p_size) for j in range(p_size)]
    q_pos = my_idx * t_loc + jnp.arange(t_loc)

    def step(i, carry):
        o, l, m, kb, vb = carry
        # kv block i originated on device (my_idx - i) mod p
        src = (my_idx - i) % p_size
        s = jnp.einsum("bqhd,bkhd->bhqk", q, kb,
                       preferred_element_type=jnp.float32) * scale
        if causal:
            k_pos = src * t_loc + jnp.arange(t_loc)
            mask = k_pos[None, :] <= q_pos[:, None]        # (Tq, Tk)
            s = jnp.where(mask[None, None], s, _NEG)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[..., None])
        if causal:
            p = jnp.where(mask[None, None], p, 0.0)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1)
        pv = jnp.einsum("bhqk,bkhd->bqhd", p, vb.astype(jnp.float32))
        o_new = o * corr.transpose(0, 2, 1)[..., None] + pv
        kb = lax.ppermute(kb, axis_name, perm)
        vb = lax.ppermute(vb, axis_name, perm)
        return o_new, l_new, m_new, kb, vb

    o, l, m, _, _ = lax.fori_loop(0, p_size, step, (o, l, m, k, v))
    out = o / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def _ring_attention_flash(q, k, v, axis_name: str, *, causal: bool):
    """Flash-kernel ring: hop 0 is the home (diagonal) block — the causal
    kernel when masking; later hops are fully-visible or fully-masked
    whole blocks (never diagonal), so they run the unmasked kernel and a
    per-hop scalar folds invisible blocks out through the lse merge
    (exp(_NEG − lse) ≡ 0 — no NaNs, exact zero weight)."""
    from ..ops.pallas_attention import flash_attention_lse

    p_size = lax.axis_size(axis_name)
    my_idx = lax.axis_index(axis_name)
    perm = [(j, (j + 1) % p_size) for j in range(p_size)]

    def merge(o_acc, lse_acc, o_i, lse_i):
        lse_new = jnp.logaddexp(lse_acc, lse_i)
        w_a = jnp.exp(lse_acc - lse_new).transpose(0, 2, 1)[..., None]
        w_i = jnp.exp(lse_i - lse_new).transpose(0, 2, 1)[..., None]
        return (o_acc.astype(jnp.float32) * w_a
                + o_i.astype(jnp.float32) * w_i), lse_new

    # hop 0: the home block (diagonal when causal)
    o_acc, lse_acc = flash_attention_lse(q, k, v, causal)
    o_acc = o_acc.astype(jnp.float32)
    kb, vb = k, v
    for i in range(1, p_size):  # p_size is static: unrolled schedule
        kb = lax.ppermute(kb, axis_name, perm)
        vb = lax.ppermute(vb, axis_name, perm)
        o_i, lse_i = flash_attention_lse(q, kb, vb, False)
        if causal:
            src = (my_idx - i) % p_size
            # whole-block visibility: block src strictly before my shard
            lse_i = jnp.where(src < my_idx, lse_i, _NEG)
        o_acc, lse_acc = merge(o_acc, lse_acc, o_i, lse_i)
    return o_acc.astype(q.dtype)


def ulysses_attention(q, k, v, axis_name: str, *, causal: bool = False,
                      impl: str = "auto"):
    """All-to-all sequence parallelism (the DeepSpeed-Ulysses shape);
    call INSIDE ``shard_map``.

    Instead of rotating K/V around a ring, one ``all_to_all`` re-shards
    the inputs from sequence-sharded (B, T/P, H, Dh) to HEAD-sharded
    (B, T, H/P, Dh); each device then runs ordinary FULL-sequence
    attention over its head group (the fused flash kernel on TPU), and a
    second ``all_to_all`` restores sequence sharding.  Exact — no online
    merging — with two collectives total per call vs the ring's P−1
    ppermute hops; the trade is O(T) activation memory per device during
    the attention (the ring stays O(T/P)).  Heads must divide the axis
    size.  Ref (pattern): DeepSpeed-Ulysses (Jacobs et al. 2023) /
    PAPERS.md; no reference-code equivalent (SURVEY.md §2: strategy
    ABSENT upstream).
    """
    p_size = lax.axis_size(axis_name)
    h = q.shape[2]
    if h % p_size:
        raise ValueError(f"ulysses needs heads ({h}) divisible by the "
                         f"{axis_name!r} axis size ({p_size}); use the "
                         f"ring path for head counts below the mesh")
    # (B, T/P, H, D) -> (B, T, H/P, D): split heads, concat sequence
    qh, kh, vh = (lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                 tiled=True) for x in (q, k, v))
    if impl == "auto":
        from ..ops.pallas_attention import _HAS_PLTPU
        impl = "flash" if _HAS_PLTPU else "dense"
    if impl == "flash":
        from ..ops.pallas_attention import flash_attention
        o = flash_attention(qh, kh, vh, causal)
    elif impl == "dense":
        from ..ops.attention import dot_product_attention
        o = dot_product_attention(qh, kh, vh, causal=causal)
    else:
        raise ValueError(f"impl must be auto|flash|dense, got {impl!r}")
    # (B, T, H/P, D) -> (B, T/P, H, D)
    return lax.all_to_all(o, axis_name, split_axis=1, concat_axis=2,
                          tiled=True)


def ring_attention_sharded(mesh: Mesh, q, k, v, *, axis: str = "sp",
                           batch_axis: str = None, causal: bool = False,
                           impl: str = "blockwise"):
    """Whole-array entry point: shards q/k/v on the sequence (T) axis over
    ``mesh[axis]`` and runs ring attention.  q/k/v: (B, T, H, Dh).

    ``batch_axis`` additionally shards the batch dimension over another
    mesh axis (dp×sp composition: each dp replica runs its own sequence
    ring over its batch shard — the K/V rotation stays within the sp
    axis, so rings never cross data-parallel replicas).  ``impl``: see
    :func:`ring_attention` (``"flash"`` = fused Pallas kernel per hop),
    plus ``"ulysses"`` for the all-to-all head-sharded formulation
    (:func:`ulysses_attention` — two collectives instead of a ring)."""
    spec = P(batch_axis, axis)
    if impl == "ulysses":
        inner = partial(ulysses_attention, axis_name=axis, causal=causal)
    else:
        inner = partial(ring_attention, axis_name=axis, causal=causal,
                        impl=impl)
    fn = shard_map(
        inner,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        **_shard_map_kw())
    return fn(q, k, v)

"""Ring attention — sequence/context parallelism over the ``sp`` mesh axis.

Absent from the reference (SURVEY.md §5.7) but first-class here: sequences
too long for one chip are sharded over the mesh's sequence axis; each
device keeps its Q shard resident and the K/V shards rotate around the
ring via ``lax.ppermute`` (one neighbor hop per step — bandwidth rides
ICI, never a host).  Softmax is computed *online* (running max/denominator
in f32, the flash-attention recurrence), so the full attention matrix is
never materialized: memory is O(T_local²) per step instead of O(T²).

Ref: Liu, Zaharia, Abbeel — "Ring Attention with Blockwise Transformers
for Near-Infinite Context" (2023); math identical to our single-device
``ops.attention.dot_product_attention`` (tested equal).
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from .mesh import shard_map
from .sync import _shard_map_kw

_NEG = -1e30  # finite -inf stand-in: keeps the online-softmax exp() NaN-free


def ring_attention(q, k, v, axis_name: str, *, causal: bool = False):
    """Blockwise ring attention; call INSIDE ``shard_map``.

    q/k/v: per-device sequence shards (B, T_loc, H, Dh), sharded on T over
    ``axis_name``.  Returns the attention output shard (B, T_loc, H, Dh).
    """
    p_size = lax.axis_size(axis_name)
    my_idx = lax.axis_index(axis_name)
    b, t_loc, h, dh = q.shape
    scale = 1.0 / math.sqrt(dh)

    # f32 accumulators (numerics survive bf16 inputs)
    o = jnp.zeros((b, t_loc, h, dh), jnp.float32)
    l = jnp.zeros((b, h, t_loc), jnp.float32)
    m = jnp.full((b, h, t_loc), _NEG, jnp.float32)

    perm = [(j, (j + 1) % p_size) for j in range(p_size)]
    q_pos = my_idx * t_loc + jnp.arange(t_loc)

    def step(i, carry):
        o, l, m, kb, vb = carry
        # kv block i originated on device (my_idx - i) mod p
        src = (my_idx - i) % p_size
        s = jnp.einsum("bqhd,bkhd->bhqk", q, kb,
                       preferred_element_type=jnp.float32) * scale
        if causal:
            k_pos = src * t_loc + jnp.arange(t_loc)
            mask = k_pos[None, :] <= q_pos[:, None]        # (Tq, Tk)
            s = jnp.where(mask[None, None], s, _NEG)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[..., None])
        if causal:
            p = jnp.where(mask[None, None], p, 0.0)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1)
        pv = jnp.einsum("bhqk,bkhd->bqhd", p, vb.astype(jnp.float32))
        o_new = o * corr.transpose(0, 2, 1)[..., None] + pv
        kb = lax.ppermute(kb, axis_name, perm)
        vb = lax.ppermute(vb, axis_name, perm)
        return o_new, l_new, m_new, kb, vb

    o, l, m, _, _ = lax.fori_loop(0, p_size, step, (o, l, m, k, v))
    out = o / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ring_attention_sharded(mesh: Mesh, q, k, v, *, axis: str = "sp",
                           batch_axis: str = None, causal: bool = False):
    """Whole-array entry point: shards q/k/v on the sequence (T) axis over
    ``mesh[axis]`` and runs ring attention.  q/k/v: (B, T, H, Dh).

    ``batch_axis`` additionally shards the batch dimension over another
    mesh axis (dp×sp composition: each dp replica runs its own sequence
    ring over its batch shard — the K/V rotation stays within the sp
    axis, so rings never cross data-parallel replicas)."""
    spec = P(batch_axis, axis)
    fn = shard_map(
        partial(ring_attention, axis_name=axis, causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        **_shard_map_kw())
    return fn(q, k, v)

"""Ring attention — sequence/context parallelism over the ``sp`` mesh axis.

Absent from the reference (SURVEY.md §5.7) but first-class here: sequences
too long for one chip are sharded over the mesh's sequence axis; each
device keeps its Q shard resident and the K/V shards rotate around the
ring via ``lax.ppermute`` (one neighbor hop per step — bandwidth rides
ICI, never a host).  Softmax is computed *online* (running max/denominator
in f32, the flash-attention recurrence), so the full attention matrix is
never materialized: memory is O(T_local²) per step instead of O(T²).

Ref: Liu, Zaharia, Abbeel — "Ring Attention with Blockwise Transformers
for Near-Infinite Context" (2023); math identical to our single-device
``ops.attention.dot_product_attention`` (tested equal).
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from .mesh import shard_map
from .sync import _shard_map_kw

_NEG = -1e30  # finite -inf stand-in: keeps the online-softmax exp() NaN-free


def _merge_lse(o_a, lse_a, o_b, lse_b):
    """Exactly combine two attention partials over disjoint key blocks via
    their logsumexps: out = Σ o_i·exp(lse_i − lse_tot).  Shapes:
    o (B, T, H, Dh) f32, lse (B, H, T) f32."""
    lse = jnp.logaddexp(lse_a, lse_b)
    w_a = jnp.exp(lse_a - lse).transpose(0, 2, 1)[..., None]
    w_b = jnp.exp(lse_b - lse).transpose(0, 2, 1)[..., None]
    return (o_a.astype(jnp.float32) * w_a
            + o_b.astype(jnp.float32) * w_b), lse


def _dense_lse(q, k, v, causal: bool):
    """One einsum attention hop returning (o_f32, lse) — the blockwise
    counterpart of ``ops.pallas_attention.flash_attention_lse`` for
    meshes/builds without the fused kernel.  Rectangular q/k lengths are
    the zigzag hop shape; causal (equal lengths) masks the local lower
    triangle."""
    dh = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) / math.sqrt(dh)
    if causal:
        t = q.shape[1]
        if k.shape[1] != t:
            raise ValueError("causal hop needs equal q/k lengths")
        mask = jnp.arange(t)[None, :] <= jnp.arange(t)[:, None]
        s = jnp.where(mask[None, None], s, _NEG)
    m = jnp.max(s, axis=-1)
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)
    o = jnp.einsum("bhqk,bkhd->bqhd", p / l[..., None],
                   v.astype(jnp.float32))
    return o, m + jnp.log(l)


def _hop_att(impl: str):
    """Per-hop attention primitive for the zigzag schedule: (q, k, v,
    causal) → (o f32, lse f32)."""
    if impl == "flash":
        from ..ops.pallas_attention import flash_attention_lse

        def att(q, k, v, causal):
            o, lse = flash_attention_lse(q, k, v, causal)
            return o.astype(jnp.float32), lse
        return att
    if impl != "blockwise":
        raise ValueError(f"impl must be blockwise|flash, got {impl!r}")
    return _dense_lse


def ring_attention(q, k, v, axis_name: str, *, causal: bool = False,
                   impl: str = "blockwise"):
    """Blockwise ring attention; call INSIDE ``shard_map``.

    q/k/v: per-device sequence shards (B, T_loc, H, Dh), sharded on T over
    ``axis_name``.  Returns the attention output shard (B, T_loc, H, Dh).

    ``impl="flash"`` runs the fused Pallas kernel per ring hop and merges
    hops via the exposed logsumexp (``ops.pallas_attention.
    flash_attention_lse``): per-hop memory drops from O(T_loc²) score
    blocks to O(T_loc·D), so the per-chip shard length is HBM-bound like
    single-chip flash — the sp × flash composition for genuinely long
    context.  ``"blockwise"`` keeps the einsum formulation (exact,
    runs anywhere)."""
    if impl == "flash":
        return _ring_attention_flash(q, k, v, axis_name, causal=causal)
    if impl != "blockwise":
        raise ValueError(f"impl must be blockwise|flash, got {impl!r}")
    p_size = lax.axis_size(axis_name)
    my_idx = lax.axis_index(axis_name)
    b, t_loc, h, dh = q.shape
    scale = 1.0 / math.sqrt(dh)

    # f32 accumulators (numerics survive bf16 inputs)
    o = jnp.zeros((b, t_loc, h, dh), jnp.float32)
    l = jnp.zeros((b, h, t_loc), jnp.float32)
    m = jnp.full((b, h, t_loc), _NEG, jnp.float32)

    perm = [(j, (j + 1) % p_size) for j in range(p_size)]
    q_pos = my_idx * t_loc + jnp.arange(t_loc)

    def step(i, carry):
        o, l, m, kb, vb = carry
        # kv block i originated on device (my_idx - i) mod p
        src = (my_idx - i) % p_size

        def compute(o, l, m, kb, vb):
            s = jnp.einsum("bqhd,bkhd->bhqk", q, kb,
                           preferred_element_type=jnp.float32) * scale
            if causal:
                k_pos = src * t_loc + jnp.arange(t_loc)
                mask = k_pos[None, :] <= q_pos[:, None]    # (Tq, Tk)
                s = jnp.where(mask[None, None], s, _NEG)
            m_new = jnp.maximum(m, jnp.max(s, axis=-1))
            p = jnp.exp(s - m_new[..., None])
            if causal:
                p = jnp.where(mask[None, None], p, 0.0)
            corr = jnp.exp(m - m_new)
            l_new = l * corr + jnp.sum(p, axis=-1)
            pv = jnp.einsum("bhqk,bkhd->bqhd", p, vb.astype(jnp.float32))
            o_new = o * corr.transpose(0, 2, 1)[..., None] + pv
            return o_new, l_new, m_new

        if causal:
            # hop skipping: with causal masking, blocks from devices
            # strictly AFTER this shard are fully masked — skip their
            # einsums entirely (the ppermute below still rotates them on).
            # NOTE: the ring is bulk-synchronous, so with the CONTIGUOUS
            # layout this saves FLOPs/energy but not wall-clock (the last
            # shard still computes every hop); layout="zigzag" is what
            # balances the work (see zigzag_ring_attention)
            o, l, m = lax.cond(src <= my_idx, compute,
                               lambda o, l, m, kb, vb: (o, l, m),
                               o, l, m, kb, vb)
        else:
            o, l, m = compute(o, l, m, kb, vb)
        kb = lax.ppermute(kb, axis_name, perm)
        vb = lax.ppermute(vb, axis_name, perm)
        return o, l, m, kb, vb

    o, l, m, _, _ = lax.fori_loop(0, p_size, step, (o, l, m, k, v))
    out = o / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def _ring_attention_flash(q, k, v, axis_name: str, *, causal: bool):
    """Flash-kernel ring: hop 0 is the home (diagonal) block — the causal
    kernel when masking; later hops are fully-visible or fully-masked
    whole blocks (never diagonal), so they run the unmasked kernel and a
    per-hop scalar folds invisible blocks out through the lse merge
    (exp(_NEG − lse) ≡ 0 — no NaNs, exact zero weight)."""
    from ..ops.pallas_attention import flash_attention_lse

    p_size = lax.axis_size(axis_name)
    my_idx = lax.axis_index(axis_name)
    perm = [(j, (j + 1) % p_size) for j in range(p_size)]

    # hop 0: the home block (diagonal when causal)
    o_acc, lse_acc = flash_attention_lse(q, k, v, causal)
    o_acc = o_acc.astype(jnp.float32)
    kb, vb = k, v
    for i in range(1, p_size):  # p_size is static: unrolled schedule
        kb = lax.ppermute(kb, axis_name, perm)
        vb = lax.ppermute(vb, axis_name, perm)
        if causal:
            src = (my_idx - i) % p_size

            def run(q, kb, vb):
                o_i, lse_i = flash_attention_lse(q, kb, vb, False)
                return o_i.astype(jnp.float32), lse_i

            def skip(q, kb, vb):
                # block src strictly after my shard: fully masked — skip
                # the kernel entirely (lse=_NEG folds it out of the
                # merge; exp(_NEG − lse) ≡ 0, no NaNs).  Same
                # wall-clock caveat as the blockwise path: only the
                # zigzag layout turns skipped hops into time saved
                b, t_loc, h, dh = q.shape
                return (jnp.zeros((b, t_loc, h, dh), jnp.float32),
                        jnp.full((b, h, t_loc), _NEG, jnp.float32))

            o_i, lse_i = lax.cond(src < my_idx, run, skip, q, kb, vb)
        else:
            o_i, lse_i = flash_attention_lse(q, kb, vb, False)
        o_acc, lse_acc = _merge_lse(o_acc, lse_acc, o_i, lse_i)
    return o_acc.astype(q.dtype)


# ---------------------------------------------------------------------------
# zigzag (striped) layout: load-balanced CAUSAL ring attention
# ---------------------------------------------------------------------------
#
# With the contiguous layout, causal masking makes the ring imbalanced:
# shard 0's queries see 1 of the P K/V blocks, shard P−1's see all P — and
# since every hop is a bulk-synchronous ppermute step, the LAST shard's
# work gates the wall clock: the mesh spends ~2× the necessary attention
# FLOPs (VERDICT r4 weak #1).  The zigzag layout splits the sequence into
# 2P chunks and gives device d the pair (d, 2P−1−d) — one early chunk E_d
# and one late chunk L_d — so every device owns an equal mix of
# early and late positions.  Causal visibility between shards then
# decomposes into HALF-blocks with no partial masks off the diagonal:
#
#   source s earlier than mine (s < d): E_d and L_d both see E_s fully,
#       neither sees L_s           → attend (q_full × k_early), cost ½
#   source s later than mine (s > d): only L_d sees anything — E_s and
#       L_s, both fully            → attend (q_late × k_full), cost ½
#   home hop (s = d): E×E diagonal + L×E full + L×L diagonal → 3 half-
#       sized calls, cost ½–¾
#
# Every device therefore executes the SAME flop count every hop —
# (P−1)·½ + home ≈ (P+1)/2P of the naive all-hops schedule — and the ring
# stays latency-balanced.  Ref (pattern): striped/zigzag attention
# (Brandon et al. 2023, "Striped Attention"); PAPERS.md.


def zigzag_order(p_size: int) -> np.ndarray:
    """Chunk permutation putting the 2P sequence chunks into zigzag
    layout: device d's shard = chunks (d, 2P−1−d)."""
    order = np.empty(2 * p_size, np.int64)
    order[0::2] = np.arange(p_size)
    order[1::2] = 2 * p_size - 1 - np.arange(p_size)
    return order


def zigzag_shuffle(x, p_size: int, axis: int = 1):
    """Reorder the sequence ``axis`` (length divisible by 2P) into zigzag
    layout; inverse of :func:`zigzag_unshuffle`."""
    t = x.shape[axis]
    if t % (2 * p_size):
        raise ValueError(f"zigzag needs the sequence length ({t}) "
                         f"divisible by 2·axis_size ({2 * p_size})")
    c = t // (2 * p_size)
    shape = x.shape[:axis] + (2 * p_size, c) + x.shape[axis + 1:]
    chunked = jnp.take(x.reshape(shape), jnp.asarray(zigzag_order(p_size)),
                       axis=axis)
    return chunked.reshape(x.shape)


def zigzag_unshuffle(x, p_size: int, axis: int = 1):
    t = x.shape[axis]
    c = t // (2 * p_size)
    inv = np.argsort(zigzag_order(p_size))
    shape = x.shape[:axis] + (2 * p_size, c) + x.shape[axis + 1:]
    chunked = jnp.take(x.reshape(shape), jnp.asarray(inv), axis=axis)
    return chunked.reshape(x.shape)


def zigzag_ring_attention(q, k, v, axis_name: str, *,
                          impl: str = "blockwise"):
    """Load-balanced CAUSAL ring attention over the zigzag layout; call
    INSIDE ``shard_map`` with shards already zigzag-ordered (device d
    holds [chunk d ; chunk 2P−1−d] — see :func:`zigzag_shuffle`).

    q/k/v: (B, 2c, H, Dh) per-device shards.  Returns the output shard in
    the same zigzag order.  Every hop costs exactly half a full block on
    EVERY device (see the module comment), so causal long-context
    training does ≈(P+1)/2P of the contiguous schedule's FLOPs with no
    straggler shard."""
    p_size = lax.axis_size(axis_name)
    my_idx = lax.axis_index(axis_name)
    b, t2, h, dh = q.shape
    if t2 % 2:
        raise ValueError(f"zigzag shard length must be even, got {t2}")
    c = t2 // 2
    att = _hop_att(impl)
    perm = [(j, (j + 1) % p_size) for j in range(p_size)]

    def split(x):
        return x[:, :c], x[:, c:]

    q_e, q_l = split(q)
    k_e, k_l = split(k)
    v_e, v_l = split(v)

    # home hop: E×E diagonal, L×E full (L is globally later), L×L diagonal
    o_e, lse_e = att(q_e, k_e, v_e, True)
    o_l1, lse_l1 = att(q_l, k_e, v_e, False)
    o_l2, lse_l2 = att(q_l, k_l, v_l, True)
    o_l, lse_l = _merge_lse(o_l1, lse_l1, o_l2, lse_l2)
    o_acc = jnp.concatenate([o_e.astype(jnp.float32),
                             o_l.astype(jnp.float32)], axis=1)
    lse_acc = jnp.concatenate([lse_e, lse_l], axis=2)

    def earlier_src(q, q_l, kb, vb):
        # source shard strictly earlier: both my chunks see its EARLY
        # chunk fully, neither sees its late chunk — ONE rectangular
        # (2c × c) attention call (full q rows keep the kernel's grid as
        # deep as a full hop's, so the MXU efficiency doesn't drop with
        # the halved FLOPs)
        return att(q, kb[:, :c], vb[:, :c], False)

    def later_src(q, q_l, kb, vb):
        # source shard strictly later: only my LATE chunk attends — its
        # early chunk fully and its late chunk fully (L_s earlier than
        # L_d exactly when s > d) — ONE rectangular (c × 2c) call
        o_h, lse_h = att(q_l, kb, vb, False)
        return (jnp.concatenate([jnp.zeros_like(o_h), o_h], axis=1),
                jnp.concatenate([jnp.full_like(lse_h, _NEG), lse_h],
                                axis=2))

    kb, vb = k, v
    for i in range(1, p_size):  # static, unrolled schedule
        kb = lax.ppermute(kb, axis_name, perm)
        vb = lax.ppermute(vb, axis_name, perm)
        src = (my_idx - i) % p_size
        # either branch runs ONE half-block (2c·c score-element) call —
        # equal cost, so the SPMD program is balanced whichever branch
        # each device takes
        o_i, lse_i = lax.cond(src < my_idx, earlier_src, later_src,
                              q, q_l, kb, vb)
        o_acc, lse_acc = _merge_lse(o_acc, lse_acc, o_i, lse_i)
    return o_acc.astype(q.dtype)


def ring_schedule_flops(p_size: int, t_loc: int, *, causal: bool,
                        layout: str = "contiguous"):
    """Score-element counts (q·k pairs whose dot products are computed)
    per device for one ring pass — the accounting behind the zigzag
    claim.  Returns a list of P per-device totals.  Mirrors exactly what
    the implementations execute: contiguous+causal skips fully-masked
    hops via ``lax.cond`` (devices are IMBALANCED — the last computes P
    blocks); zigzag runs 3 half-blocks home + 2 half-blocks per further
    hop on EVERY device."""
    full = t_loc * t_loc
    if layout == "zigzag":
        if not causal:
            return [p_size * full] * p_size  # falls back to the plain ring
        half = (t_loc // 2) * (t_loc // 2)
        return [3 * half + (p_size - 1) * 2 * half] * p_size
    if layout != "contiguous":
        raise ValueError(f"layout must be contiguous|zigzag, got {layout!r}")
    if not causal:
        return [p_size * full] * p_size
    return [(d + 1) * full for d in range(p_size)]


def ulysses_attention(q, k, v, axis_name: str, *, causal: bool = False,
                      impl: str = "auto"):
    """All-to-all sequence parallelism (the DeepSpeed-Ulysses shape);
    call INSIDE ``shard_map``.

    Instead of rotating K/V around a ring, one ``all_to_all`` re-shards
    the inputs from sequence-sharded (B, T/P, H, Dh) to HEAD-sharded
    (B, T, H/P, Dh); each device then runs ordinary FULL-sequence
    attention over its head group (the fused flash kernel on TPU), and a
    second ``all_to_all`` restores sequence sharding.  Exact — no online
    merging — with two collectives total per call vs the ring's P−1
    ppermute hops; the trade is O(T) activation memory per device during
    the attention (the ring stays O(T/P)).  Heads must divide the axis
    size.  Ref (pattern): DeepSpeed-Ulysses (Jacobs et al. 2023) /
    PAPERS.md; no reference-code equivalent (SURVEY.md §2: strategy
    ABSENT upstream).
    """
    p_size = lax.axis_size(axis_name)
    h = q.shape[2]
    if h % p_size:
        raise ValueError(f"ulysses needs heads ({h}) divisible by the "
                         f"{axis_name!r} axis size ({p_size}); use the "
                         f"ring path for head counts below the mesh")
    # (B, T/P, H, D) -> (B, T, H/P, D): split heads, concat sequence
    qh, kh, vh = (lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                 tiled=True) for x in (q, k, v))
    if impl == "auto":
        from ..ops.pallas_attention import _HAS_PLTPU
        impl = "flash" if _HAS_PLTPU else "dense"
    if impl == "flash":
        from ..ops.pallas_attention import flash_attention
        o = flash_attention(qh, kh, vh, causal)
    elif impl == "dense":
        from ..ops.attention import dot_product_attention
        o = dot_product_attention(qh, kh, vh, causal=causal)
    else:
        raise ValueError(f"impl must be auto|flash|dense, got {impl!r}")
    # (B, T, H/P, D) -> (B, T/P, H, D)
    return lax.all_to_all(o, axis_name, split_axis=1, concat_axis=2,
                          tiled=True)


def ring_attention_sharded(mesh: Mesh, q, k, v, *, axis: str = "sp",
                           batch_axis: str = None, causal: bool = False,
                           impl: str = "blockwise",
                           layout: str = "contiguous",
                           pre_shuffled: bool = False):
    """Whole-array entry point: shards q/k/v on the sequence (T) axis over
    ``mesh[axis]`` and runs ring attention.  q/k/v: (B, T, H, Dh).

    ``batch_axis`` additionally shards the batch dimension over another
    mesh axis (dp×sp composition: each dp replica runs its own sequence
    ring over its batch shard — the K/V rotation stays within the sp
    axis, so rings never cross data-parallel replicas).  ``impl``: see
    :func:`ring_attention` (``"flash"`` = fused Pallas kernel per hop),
    plus ``"ulysses"`` for the all-to-all head-sharded formulation
    (:func:`ulysses_attention` — two collectives instead of a ring).

    ``layout="zigzag"`` (causal only; T divisible by 2·axis size)
    re-stripes the sequence so every device holds an equal early+late mix
    and runs the load-balanced schedule (:func:`zigzag_ring_attention`):
    ≈half the attention FLOPs of the contiguous causal ring with no
    straggler shard.  The shuffle/unshuffle here is one gather each way;
    a training pipeline that keeps activations zigzag-ordered end-to-end
    pays it once per batch instead — pass ``pre_shuffled=True`` when
    q/k/v already arrive in zigzag order (the output stays zigzag; see
    ``models.optimize.zigzag_wrap``)."""
    spec = P(batch_axis, axis)
    p_size = mesh.shape[axis]
    if pre_shuffled and layout != "zigzag":
        raise ValueError("pre_shuffled=True only makes sense with "
                         "layout='zigzag'")
    if layout == "zigzag":
        if impl == "ulysses":
            raise ValueError("layout='zigzag' is a ring schedule; the "
                             "ulysses all-to-all path is already balanced")
        if not causal and pre_shuffled:
            raise ValueError("pre_shuffled zigzag requires causal=True "
                             "(non-causal rings don't use the stripe)")
        if causal:
            if not pre_shuffled:
                q = zigzag_shuffle(q, p_size)
                k = zigzag_shuffle(k, p_size)
                v = zigzag_shuffle(v, p_size)
            inner = partial(zigzag_ring_attention, axis_name=axis,
                            impl=impl)
            fn = shard_map(inner, mesh=mesh, in_specs=(spec, spec, spec),
                           out_specs=spec, **_shard_map_kw())
            out = fn(q, k, v)
            return out if pre_shuffled else zigzag_unshuffle(out, p_size)
        # non-causal attention is permutation-invariant over keys and has
        # no masked hops to balance: the plain ring IS the zigzag schedule
        layout = "contiguous"
    elif layout != "contiguous":
        raise ValueError(f"layout must be contiguous|zigzag, got {layout!r}")
    if impl == "ulysses":
        inner = partial(ulysses_attention, axis_name=axis, causal=causal)
    else:
        inner = partial(ring_attention, axis_name=axis, causal=causal,
                        impl=impl)
    fn = shard_map(
        inner,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        **_shard_map_kw())
    return fn(q, k, v)

"""Mesh construction and shard_map compatibility helpers.

The reference's "cluster" is a Spark app: N executor JVMs plus a driver
(reference ``distkeras/trainers.py:DistributedTrainer``).  Ours is a
``jax.sharding.Mesh``: the ``workers`` axis plays the role of Spark
executors; additional axes (``mp`` for tensor parallelism, ``sp`` for
sequence parallelism) are available to the model layer even though the
reference never had them.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.4.35 promotes shard_map to the top level
    shard_map = jax.shard_map
except AttributeError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map  # type: ignore


def make_mesh(num_workers: Optional[int] = None,
              axis_names: Sequence[str] = ("workers",),
              shape: Optional[Sequence[int]] = None,
              devices=None) -> Mesh:
    """Build a mesh over available devices.

    Default: a 1-D ``("workers",)`` mesh of ``num_workers`` devices — the
    data-parallel topology matching the reference's one-partition-per-worker
    contract.  Pass ``axis_names``/``shape`` for multi-axis (dp × mp × sp)
    meshes.
    """
    if devices is None:
        devices = jax.devices()
    if shape is None:
        n = num_workers if num_workers is not None else len(devices)
        shape = (n,)
    total = int(np.prod(shape))
    if total > len(devices):
        raise ValueError(
            f"mesh shape {tuple(shape)} needs {total} devices, "
            f"have {len(devices)}")
    arr = np.asarray(devices[:total]).reshape(shape)
    return Mesh(arr, tuple(axis_names))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def sharded_on(mesh: Mesh, axis: str = "workers") -> NamedSharding:
    """Leading-dim sharding along ``axis``."""
    return NamedSharding(mesh, P(axis))


def host_to_mesh(mesh: Mesh, tree, axis: str = "workers"):
    """Commit a host pytree with its leading dim sharded over ``axis``.

    One transfer per leaf: the TPU equivalent of Spark shipping each
    partition to its executor.  On a mesh SPANNING ``jax.distributed``
    processes each process contributes only the partitions its own
    devices hold (``spmd.put``) — executor-gets-its-partition for the
    sync dp trainers too (r5)."""
    from .spmd import put
    sh = sharded_on(mesh, axis)
    return jax.tree_util.tree_map(lambda x: put(x, sh), tree)


def broadcast_to_mesh(mesh: Mesh, tree):
    """Commit a host pytree fully replicated (the 'pull' of the center
    variable down to every worker, amortized to one transfer; multi-host
    aware like :func:`host_to_mesh`)."""
    from .spmd import put
    sh = replicated(mesh)
    return jax.tree_util.tree_map(lambda x: put(x, sh), tree)

"""Synchronous SPMD training engine — the TPU formulation of dist-keras.

Every reference algorithm (reference ``distkeras/workers.py`` +
``distkeras/parameter_servers.py``) is re-expressed here as:

  * a **local rule**: w minibatch steps of local optimization inside a
    ``lax.scan`` (w = the reference's ``communication_window``), and
  * a **communication rule** at the window edge: one XLA collective
    (``pmean``/``psum``) over the ``workers`` mesh axis replacing the entire
    socket pull/commit round-trip of the reference's parameter server.

The whole epoch — windows × local steps × collectives — is ONE jit-compiled
program: no host round-trips, collectives ride ICI, XLA overlaps the
allreduce with adjacent compute.  Staleness is identically zero in this
formulation (every window edge is a barrier), which is the synchronous limit
of each algorithm; the faithful staleness-preserving semantics live in
``distkeras_tpu.ps`` (async host parameter server).

Center/local variables are FULL variable pytrees (params + mutable state),
mirroring the reference where Keras ``get_weights()`` — the unit of
pull/commit — includes BatchNorm running statistics.
"""

from __future__ import annotations

import functools
import inspect
from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from .mesh import make_mesh, shard_map

Tree = Any


# ---------------------------------------------------------------------------
# pytree helpers
# ---------------------------------------------------------------------------

def tmap(f, *trees):
    return jax.tree_util.tree_map(f, *trees)


def tree_sub(a, b):
    return tmap(lambda x, y: x - y, a, b)


def tree_add(a, b):
    return tmap(lambda x, y: x + y, a, b)


def tree_scale(a, s):
    return tmap(lambda x: x * s, a)


def _inexact(x) -> bool:
    """Communication rules act on floating-point leaves only: integer/bool
    variable state (Keras SeedGenerator counters, step counters, ...) has no
    meaningful average/sum and must keep its dtype and worker-local value
    across window edges.  Works on jnp and np leaves alike — the async PS
    (``ps.servers`` / ``ps.workers``) shares this predicate."""
    return jnp.issubdtype(jnp.asarray(x).dtype, jnp.inexact)


def adopt_float_leaves(source: Tree, local: Tree) -> Tree:
    """``local`` with its floating leaves replaced by ``source``'s; integer/
    bool leaves keep the local value (see ``_inexact``).  The single merge
    rule for every window-edge/pull site (sync algorithms, async workers)."""
    return tmap(lambda s, l: s if _inexact(l) else l, source, local)


def _squeeze0(tree):
    return tmap(lambda x: x[0], tree)


def _expand0(tree):
    return tmap(lambda x: x[None], tree)


def _shard_map_kw():
    """jax renamed check_rep -> check_vma; pick whichever exists."""
    params = inspect.signature(shard_map).parameters
    if "check_vma" in params:
        return {"check_vma": False}
    return {"check_rep": False}


# ---------------------------------------------------------------------------
# the local minibatch step (shared by sync engine and async PS workers)
# ---------------------------------------------------------------------------

def aux_losses(state: Tree) -> list:
    """Collect every ``aux_loss`` leaf from a variables-state tree (each
    ``MoEDense`` writes its router load-balance scalar there)."""
    from jax.tree_util import DictKey, tree_flatten_with_path
    return [leaf for path, leaf in tree_flatten_with_path(state)[0]
            if path and isinstance(path[-1], DictKey)
            and path[-1].key == "aux_loss"]


def make_local_step(model, loss_fn: Callable,
                    optimizer: optax.GradientTransformation,
                    compute_dtype=None, remat: bool = False,
                    aux_weight: float = 0.0):
    """One minibatch of local optimization as a pure scan-able function:
    ``step((variables, opt_state, rng), (x, y)) -> (carry', loss)``.

    This is the reference's ``model.train_on_batch`` (reference
    ``distkeras/workers.py``) as a jit-compiled value_and_grad + optax
    update — the MXU hot loop.

    ``remat=True`` wraps the forward in ``jax.checkpoint``: activations
    are recomputed during the backward pass instead of living in HBM for
    the whole step — the standard FLOPs-for-memory trade for models whose
    activation footprint, not weights, is what OOMs.

    ``aux_weight > 0`` folds ``aux_weight * Σ state['aux_loss']`` (the
    MoE router load-balance losses) into the objective — the opt-in
    mitigation for router/expert collapse in long MoE runs (ADVICE r3);
    the default keeps the reference-parity task-loss-only behavior.
    """

    def forward(params, state, x, rng):
        return model.layer.apply(params, state, x, train=True, rng=rng)

    if remat:
        forward = jax.checkpoint(forward)

    def cast_floats(tree):
        return jax.tree_util.tree_map(
            lambda a: a.astype(compute_dtype)
            if jnp.issubdtype(a.dtype, jnp.floating) else a, tree)

    def step(carry, batch):
        variables, opt_state, rng = carry
        x, y = batch
        if compute_dtype is not None and \
                jnp.issubdtype(x.dtype, jnp.floating):
            x = x.astype(compute_dtype)
        rng, sub = jax.random.split(rng)

        def loss_of(params):
            # mixed precision: master params stay f32 in the optimizer;
            # the forward sees compute_dtype copies (covers token-input
            # models too, where no float x exists to derive dtype from —
            # layers cast their weights to the activation dtype)
            fwd_params = cast_floats(params) if compute_dtype is not None \
                else params
            out, new_state = forward(fwd_params, variables["state"], x, sub)
            loss_val = loss_fn(out, y)
            if aux_weight:
                aux = aux_losses(new_state)
                if aux:
                    loss_val = loss_val + aux_weight * sum(aux)
            return loss_val, new_state

        (loss_val, new_state), grads = jax.value_and_grad(
            loss_of, has_aux=True)(variables["params"])
        updates, opt_state = optimizer.update(
            grads, opt_state, variables["params"])
        params = optax.apply_updates(variables["params"], updates)
        return ({"params": params, "state": new_state}, opt_state, rng), loss_val

    return step


def make_window_fn(model, loss_fn, optimizer, compute_dtype=None,
                   remat: bool = False, aux_weight: float = 0.0):
    """jit-compiled window scan: ``(variables, opt_state, rng, xs, ys) ->
    (variables, opt_state, rng, losses)`` over the leading (steps) axis —
    the unit of work between two parameter-server interactions.

    Carry buffers are donated: params/opt-state update in place in HBM
    (callers all rebind to the outputs, measured ~4% on ResNet-20).
    """
    step = make_local_step(model, loss_fn, optimizer, compute_dtype, remat,
                           aux_weight)

    @functools.partial(jax.jit, donate_argnums=(0, 1, 2))
    def run(variables, opt_state, rng, xs, ys):
        (variables, opt_state, rng), losses = lax.scan(
            step, (variables, opt_state, rng), (xs, ys))
        return variables, opt_state, rng, losses

    return run


# ---------------------------------------------------------------------------
# communication rules (one per reference algorithm)
# ---------------------------------------------------------------------------

class SyncAlgorithm:
    """Window-edge communication rule.

    ``communicate(center, local, axis)`` runs inside ``shard_map`` (per
    device, collectives available) and returns ``(new_center, new_local)``.
    """

    #: whether workers restart each window from the (new) center variable
    name = "base"

    def communicate(self, center: Tree, local: Tree, axis: str):
        raise NotImplementedError


class NoCommSync(SyncAlgorithm):
    """No inter-worker communication (AveragingTrainer / EnsembleTrainer):
    workers train fully independently; any averaging happens after training
    (reference ``distkeras/trainers.py:AveragingTrainer.average_models``)."""

    name = "none"

    def communicate(self, center, local, axis):
        return center, local


class AdagSync(SyncAlgorithm):
    """ADAG (reference ``ADAGWorker`` + ``ADAGParameterServer``): workers
    accumulate a window of updates, commit the accumulated delta normalized
    by the worker count.  Synchronous limit: center ← center +
    mean_k(local_k − center) ≡ pmean of worker models; workers re-pull the
    new center.  This is allreduce-mean windowed SGD — the flagship mapping
    onto the MXU/ICI."""

    name = "adag"

    def communicate(self, center, local, axis):
        new_center = tmap(
            lambda c, l: lax.pmean(l, axis) if _inexact(l) else c,
            center, local)
        return new_center, adopt_float_leaves(new_center, local)


class DownpourSync(SyncAlgorithm):
    """DOWNPOUR (reference ``DOWNPOURWorker`` + ``DeltaParameterServer``):
    each worker commits Δ_k = local_k − center and the server adds every
    commit in full (no normalization).  Synchronous limit: center ← center +
    Σ_k Δ_k; workers re-pull."""

    name = "downpour"

    def communicate(self, center, local, axis):
        new_center = tmap(
            lambda c, l: c + lax.psum(l - c, axis) if _inexact(l) else c,
            center, local)
        return new_center, adopt_float_leaves(new_center, local)


class DynSgdSync(SyncAlgorithm):
    """DynSGD (reference ``DynSGDParameterServer``): commit scaled by
    1/(staleness+1).  Every window edge is a barrier here, so staleness ≡ 0
    and the scale is 1 — documented explicitly rather than silently; the
    staleness-sensitive behavior is exercised by the async PS path."""

    name = "dynsgd"
    staleness = 0

    def communicate(self, center, local, axis):
        scale = 1.0 / (self.staleness + 1)
        new_center = tmap(
            lambda c, l: c + lax.psum((l - c) * scale, axis)
            if _inexact(l) else c,
            center, local)
        return new_center, adopt_float_leaves(new_center, local)


class EasgdSync(SyncAlgorithm):
    """EASGD elastic averaging (reference ``AEASGDWorker`` /
    ``EAMSGDWorker``; Zhang, Choromanska, LeCun 2015): every τ steps the
    elastic force E_k = α(local_k − center) pulls the worker toward the
    center and the center toward the workers:
        local_k ← local_k − E_k ;  center ← center + Σ_k E_k.
    Workers KEEP their local model across windows (exploration) — this is
    the one family where local ≠ center by design.  EAMSGD differs only in
    the local optimizer (Nesterov momentum), not in this rule."""

    name = "easgd"

    def __init__(self, alpha: float):
        self.alpha = float(alpha)

    def communicate(self, center, local, axis):
        new_center = tmap(
            lambda c, l: c + lax.psum(self.alpha * (l - c), axis)
            if _inexact(l) else c,
            center, local)
        new_local = tmap(
            lambda c, l: l - self.alpha * (l - c) if _inexact(l) else l,
            center, local)
        return new_center, new_local


# ---------------------------------------------------------------------------
# engine
# ---------------------------------------------------------------------------

class EpochResult(NamedTuple):
    center: Tree      # variables pytree (replicated)
    local: Tree       # variables pytree, leading axis = workers
    opt_state: Tree   # leading axis = workers
    rngs: jnp.ndarray
    losses: jnp.ndarray  # (workers, n_windows, window)


class SyncEngine:
    """Builds jit-compiled epoch programs for a (model, loss, optimizer,
    algorithm) tuple over a worker mesh."""

    def __init__(self, model, loss_fn: Callable, optimizer: optax.GradientTransformation,
                 algo: SyncAlgorithm, num_workers: int, window: int,
                 mesh: Optional[Mesh] = None, axis: str = "workers",
                 compute_dtype=None, remat: bool = False,
                 aux_weight: float = 0.0):
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.algo = algo
        self.num_workers = int(num_workers)
        self.window = int(window)
        self.axis = axis
        self.mesh = mesh if mesh is not None else make_mesh(num_workers, (axis,))
        self.compute_dtype = compute_dtype
        self._local_step = make_local_step(model, loss_fn, optimizer,
                                           compute_dtype, remat, aux_weight)

    # -- distributed epoch --------------------------------------------------
    def epoch_fn(self):
        """jit-compiled: (center, local, opt_state, rngs, xs, ys) -> EpochResult.

        Global shapes: center replicated; local/opt_state leading axis =
        workers; rngs (workers, 2); xs/ys (workers, n_windows, window,
        batch, ...).
        """
        axis = self.axis

        def per_device(center, local, opt_state, rng, xs, ys):
            local, opt_state, rng = (_squeeze0(local), _squeeze0(opt_state),
                                     rng[0])
            xs, ys = xs[0], ys[0]

            def window_step(carry, batch_window):
                center, local, opt_state, rng = carry
                wx, wy = batch_window
                (local, opt_state, rng), losses = lax.scan(
                    self._local_step, (local, opt_state, rng), (wx, wy))
                center, local = self.algo.communicate(center, local, axis)
                return (center, local, opt_state, rng), losses

            (center, local, opt_state, rng), losses = lax.scan(
                window_step, (center, local, opt_state, rng), (xs, ys))
            return (center, _expand0(local), _expand0(opt_state),
                    rng[None], losses[None])

        mapped = shard_map(
            per_device, mesh=self.mesh,
            in_specs=(P(), P(axis), P(axis), P(axis), P(axis), P(axis)),
            out_specs=(P(), P(axis), P(axis), P(axis), P(axis)),
            **_shard_map_kw())

        @jax.jit
        def run(center, local, opt_state, rngs, xs, ys):
            return EpochResult(*mapped(center, local, opt_state, rngs, xs, ys))

        return run

    # -- streaming window ---------------------------------------------------
    def window_fn(self):
        """jit-compiled SINGLE window: (center, local, opt_state, rngs, wx,
        wy) -> EpochResult with losses (workers, window).

        The disk-streaming trainers drive this once per communication
        window (the host assembles window w+1 while the devices train
        window w); the collective at the window edge is identical to the
        epoch program's.  Model/opt state is donated — it updates in place
        in HBM across the host loop.
        """
        axis = self.axis

        def per_device(center, local, opt_state, rng, wx, wy):
            local, opt_state, rng = (_squeeze0(local), _squeeze0(opt_state),
                                     rng[0])
            (local, opt_state, rng), losses = lax.scan(
                self._local_step, (local, opt_state, rng), (wx[0], wy[0]))
            center, local = self.algo.communicate(center, local, axis)
            return (center, _expand0(local), _expand0(opt_state),
                    rng[None], losses[None])

        mapped = shard_map(
            per_device, mesh=self.mesh,
            in_specs=(P(), P(axis), P(axis), P(axis), P(axis), P(axis)),
            out_specs=(P(), P(axis), P(axis), P(axis), P(axis)),
            **_shard_map_kw())

        @functools.partial(jax.jit, donate_argnums=(0, 1, 2, 3))
        def run(center, local, opt_state, rngs, wx, wy):
            return EpochResult(*mapped(center, local, opt_state, rngs, wx, wy))

        return run

